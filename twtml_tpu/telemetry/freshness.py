"""End-to-end freshness plane — watermarks, critical path, SLOs (ISSUE 16).

Consumes the per-batch lineage records (telemetry/lineage.py) the existing
seams already stamp — pure host arithmetic over rolling windows, ZERO added
host fetches and ZERO added collectives (the PR 1/5/8 law, asserted by the
counting tests) — and derives the freshness story wall-clock stage gauges
cannot answer under the tunnel's ~10-minute health phases:

- **event-time watermarks**: ``freshness.event_lag_ms`` p50/p95/p99 from
  tweet ``created_at_ms`` to fetch delivery (exact percentiles over a
  rolling window, not histogram buckets — the buckets are seconds-scale),
  the same lag to stats publish, and a per-tick low watermark
  (now − oldest event-time still in flight) that rides the sideband vector
  to every host with no new allgather.
- **per-batch critical path**: the dominant seam-to-seam stage delta
  between open and delivery, rolled into ``freshness.critical.<edge>.ticks``
  counters — the r-series bottleneck-ladder verdicts, automated. The
  attribution is approximate under overlapped batches (stage clocks are
  cumulative across concurrent work) but names the binding rung.
- **SLO gate**: ``--freshnessSloMs`` with a sustained-breach run; the
  delivery adapter (apps/common.FreshnessGuard) turns a sustained run into
  blackbox events and ONE forced verified checkpoint per episode — the
  PR 8 early-warning shape, warn-only, sentinel untouched.

Mirrors the modelwatch module pattern: ``record_delivery`` is called by the
delivery adapter, ``record_publish`` by SessionStats, ``last_freshness``
feeds /api/freshness and the dashboard tiles, ``snapshot_for_checkpoint``
stamps verified checkpoints. Everything is a no-op until ``configure``
enables the plane; jax-free.
"""

from __future__ import annotations

import threading
from collections import deque

from ..utils import get_logger
from ..utils.clock import now_ms
from . import blackbox as _blackbox
from . import lineage as _lineage
from . import metrics as _metrics

log = get_logger("telemetry.freshness")

# rolling exact-percentile windows (per-batch lags; 512 batches ≈ minutes)
LAG_WINDOW = 512
# watermark sparkline shipped to the dashboard (Freshness.watermark)
SPARK_WINDOW = 64
# delivered-but-unpublished event stamps awaiting the next publish tick
PUBLISH_PENDING_MAX = 1024
# sustained-breach window (delivered batches over SLO before an episode
# fires) — the burn-rate analog of modelwatch's alert_run window
BREACH_WINDOW = 8

# ms-scale histogram bounds (1 ms .. ~2.3 h); the registry default bounds
# are seconds-geometry and would saturate at ~0.5 s
LAG_BOUNDS = tuple(1.0 * (2.0 ** i) for i in range(24))


def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return -1.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return float(sorted_vals[idx])


class FreshnessPlane:
    """Rolling freshness state for one process. Thread-safe: deliveries
    arrive on the fetch-pipeline worker threads, publishes on the stats
    path, views on the web publisher."""

    def __init__(self, slo_ms: float = 0.0, window: int = BREACH_WINDOW):
        self.slo_ms = float(slo_ms)
        self.window = int(window)
        self._lock = threading.Lock()
        self._lags: deque = deque(maxlen=LAG_WINDOW)
        self._publish_lags: deque = deque(maxlen=LAG_WINDOW)
        self._spark: deque = deque(maxlen=SPARK_WINDOW)
        self._pending_publish: deque = deque(maxlen=PUBLISH_PENDING_MAX)
        self._edge_ticks: dict = {}
        self._batches = 0
        self._rows = 0
        self._last_lag = -1.0
        self._last_watermark = -1.0
        self._critical = ""
        self._breach_run = 0
        self._breaches = 0
        self._in_episode = False

    # -- recording hooks -----------------------------------------------------
    def record_delivery(self) -> "dict | None":
        """Pop the oldest in-flight lineage record at fetch delivery and
        fold it into the rolling view. Returns the SLO verdict for the
        delivery adapter (None for blank/absent records)."""
        rec = _lineage.pop_delivery()
        if rec is None:
            return None
        delivered = rec["delivered_ms"]
        event_hi = rec.get("event_max_ms", 0)
        lag = float(delivered - event_hi) if event_hi > 0 else -1.0
        floor = _lineage.open_event_floor()
        if floor == 0:
            floor = rec.get("event_min_ms", 0)
        watermark = float(delivered - floor) if floor > 0 else lag
        critical = rec.get("critical", "")
        with self._lock:
            self._batches += 1
            self._rows += rec.get("rows", 0)
            self._critical = critical
            self._last_lag = lag
            if lag >= 0.0:
                self._lags.append(lag)
            self._last_watermark = watermark
            if watermark >= 0.0:
                self._spark.append(watermark)
            if critical:
                self._edge_ticks[critical] = (
                    self._edge_ticks.get(critical, 0) + 1
                )
            if event_hi > 0:
                self._pending_publish.append(event_hi)
            breach = self.slo_ms > 0.0 and lag >= 0.0 and lag > self.slo_ms
            if breach:
                self._breach_run += 1
            else:
                self._breach_run = 0
                self._in_episode = False
            run = self._breach_run
            sustained = False
            if run >= self.window and not self._in_episode:
                self._in_episode = True
                self._breaches += 1
                sustained = True
            in_episode = self._in_episode
            lags_sorted = sorted(self._lags)
        self._publish_gauges(lag, watermark, critical, lags_sorted)
        if sustained:
            _metrics.get_registry().counter("freshness.slo_breaches").inc()
            _blackbox.record(
                "freshness_slo_breach", lag_ms=round(lag, 1),
                slo_ms=self.slo_ms, run=run, critical=critical,
            )
            log.warning(
                "freshness SLO breach sustained: event lag %.0f ms > %.0f ms"
                " for %d batches (critical edge: %s)",
                lag, self.slo_ms, run, critical or "?",
            )
        return {
            "event_lag_ms": lag,
            "watermark_lag_ms": watermark,
            "critical": critical,
            "breach": breach,
            "breach_run": run,
            "sustained": sustained,
            "in_episode": in_episode,
        }

    def record_publish(self) -> None:
        """Stamp event→publish lag for every batch delivered since the last
        stats-publish tick (SessionStats calls this on its publish path)."""
        with self._lock:
            if not self._pending_publish:
                return
            ms = now_ms()
            while self._pending_publish:
                self._publish_lags.append(
                    float(ms - self._pending_publish.popleft())
                )
            pub_sorted = sorted(self._publish_lags)
        _metrics.get_registry().gauge("freshness.publish_lag_p95_ms").set(
            round(_pct(pub_sorted, 0.95), 1)
        )

    def _publish_gauges(self, lag, watermark, critical, lags_sorted) -> None:
        reg = _metrics.get_registry()
        if lag >= 0.0:
            reg.histogram("freshness.event_lag_ms", bounds=LAG_BOUNDS).observe(
                lag
            )
            reg.gauge("freshness.event_lag_p50_ms").set(
                round(_pct(lags_sorted, 0.50), 1)
            )
            reg.gauge("freshness.event_lag_p95_ms").set(
                round(_pct(lags_sorted, 0.95), 1)
            )
            reg.gauge("freshness.event_lag_p99_ms").set(
                round(_pct(lags_sorted, 0.99), 1)
            )
        if watermark >= 0.0:
            reg.gauge("freshness.watermark_lag_ms").set(round(watermark, 1))
        if critical:
            reg.counter(f"freshness.critical.{critical}.ticks").inc()

    # -- views ---------------------------------------------------------------
    def last_event_lag_ms(self) -> float:
        """Most recent delivery's event lag (the sideband column; 0 before
        the first delivery with a known event time)."""
        with self._lock:
            return self._last_lag if self._last_lag >= 0.0 else 0.0

    def view(self) -> "dict | None":
        """The dashboard/web view (None until a delivery was recorded)."""
        with self._lock:
            if self._batches == 0:
                return None
            lags = sorted(self._lags)
            pubs = sorted(self._publish_lags)
            return {
                "batches": self._batches,
                "rows": self._rows,
                "eventLagMs": round(self._last_lag, 1),
                "eventLagP50Ms": round(_pct(lags, 0.50), 1),
                "eventLagP95Ms": round(_pct(lags, 0.95), 1),
                "eventLagP99Ms": round(_pct(lags, 0.99), 1),
                "publishLagP95Ms": round(_pct(pubs, 0.95), 1),
                "watermarkLagMs": round(self._last_watermark, 1),
                "watermark": [round(v, 1) for v in self._spark],
                "critical": self._critical,
                "criticalTicks": dict(self._edge_ticks),
                "sloMs": self.slo_ms,
                "breachRun": self._breach_run,
                "breaches": self._breaches,
            }

    def checkpoint_snapshot(self) -> "dict | None":
        """Compact freshness stamp for a verified checkpoint's meta (plain
        floats, json-safe; None before the first delivery)."""
        with self._lock:
            if self._batches == 0:
                return None
            lags = sorted(self._lags)
            return {
                "event_lag_p95_ms": round(_pct(lags, 0.95), 1),
                "watermark_lag_ms": round(self._last_watermark, 1),
                "critical": self._critical,
                "batches": self._batches,
                "breaches": self._breaches,
            }


# -- process-wide plane -------------------------------------------------------

_lock = threading.Lock()
_PLANE: "FreshnessPlane | None" = None
_ON = False


def configure(conf=None, *, on=None, slo_ms=None, window=None) -> None:
    """Install the plane from a Config (apps call this at run() start) or
    from explicit knobs (tests/benches). ``--freshness off`` disables the
    lineage FIFOs too, making the off arm bit-identical to HEAD."""
    global _PLANE, _ON
    if conf is not None:
        on = getattr(conf, "freshness", "on") == "on" if on is None else on
        slo_ms = (
            float(getattr(conf, "freshnessSloMs", 0.0))
            if slo_ms is None else slo_ms
        )
    enabled = bool(on) if on is not None else True
    with _lock:
        _ON = enabled
        _PLANE = FreshnessPlane(
            slo_ms=slo_ms or 0.0,
            window=window or BREACH_WINDOW,
        ) if enabled else None
    _lineage.configure(enabled)


def enabled() -> bool:
    return _ON


def get_plane() -> "FreshnessPlane | None":
    with _lock:
        return _PLANE


def record_delivery() -> "dict | None":
    plane = get_plane()
    return plane.record_delivery() if plane is not None else None


def record_publish() -> None:
    plane = get_plane()
    if plane is not None:
        plane.record_publish()


def last_event_lag_ms() -> float:
    plane = get_plane()
    return plane.last_event_lag_ms() if plane is not None else 0.0


def last_freshness() -> "dict | None":
    """Latest freshness view for /api/freshness and SessionStats; None when
    the plane is off or nothing was delivered."""
    plane = get_plane()
    return plane.view() if plane is not None else None


def snapshot_for_checkpoint() -> "dict | None":
    plane = get_plane()
    return plane.checkpoint_snapshot() if plane is not None else None


def reset_for_tests() -> None:
    global _PLANE, _ON
    with _lock:
        _PLANE = None
        _ON = False
    _lineage.reset_for_tests()
