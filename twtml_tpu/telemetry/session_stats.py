"""Per-session stats publishing (reference: SessionStats.scala:9-63).

Opens a 4-series Lightning streaming line chart (real=blue, pred=yellow, with
lighter "detail" shades, SessionStats.scala:15-20,49-52), registers the
session with the twtml web server (``web.config``), and pushes per-batch
stats to both. Every network call is best-effort (``Try`` in the reference,
SessionStats.scala:29-33,60): the ML loop must survive telemetry outages.

Best-effort hardened (r7): each endpoint sits behind a circuit breaker
(telemetry/breaker.py) — a dead dashboard stops costing the hot path its
full ``--webTimeout`` per publish after ``FAILURE_THRESHOLD`` consecutive
failures (drop-and-count, half-open probe re-admits it) — and when the
tunnel-health monitor reports a DEGRADED transport, the per-batch series
frames (the biggest payload) shed to every ``SERIES_SHED_EVERY``-th batch
while the scalar stats keep full resolution. Neither mechanism changes the
reference parity: publishes still never raise into the ML loop.
"""

from __future__ import annotations

import numpy as np

from ..utils import get_logger, round_half_up
from . import metrics as _metrics
from . import sideband as _sideband
from . import trace as _trace
from .breaker import CircuitBreaker
from .lightning import CHART_MAX_POINTS, Lightning, Visualization
from .web_client import WebClient

log = get_logger("telemetry.session")

# per-batch cap on chart series points shipped to the dashboard (shared
# with every streaming chart — telemetry/lightning.py)
SERIES_MAX_POINTS = CHART_MAX_POINTS

# publish a pipeline-metrics snapshot every N stats updates: counters move
# every batch but the dashboard panel doesn't need per-batch resolution,
# and each publish is one more best-effort HTTP POST on the hot path
METRICS_EVERY = 8

# degraded-tunnel load shedding: ship only every Nth batch's series frame
# while the health monitor reports a degraded transport
SERIES_SHED_EVERY = 8

# host-process gauges (ISSUE 8 satellite): uptime is measured from this
# module's import — the app imports it at startup, so the gauge tracks the
# process lifetime the axon-client RSS retention grows over
import time as _time_mod

_PROCESS_START_S = _time_mod.monotonic()

# SessionStats.scala:15-20
REAL_COLOR_DET = [173.0, 216.0, 230.0]  # light blue
REAL_COLOR = [30.0, 144.0, 255.0]  # blue
PRED_COLOR_DET = [238.0, 232.0, 170.0]  # pale yellow
PRED_COLOR = [255.0, 215.0, 0.0]  # gold


class SessionStats:
    def __init__(self, conf):
        self.conf = conf
        self.lgn = Lightning(host=conf.lightning)
        self.web = WebClient(
            conf.twtweb, timeout=float(getattr(conf, "webTimeout", 2.0))
        )
        self.viz: Visualization | None = None
        self._updates = 0
        # one breaker per endpoint: the web dashboard and Lightning fail
        # independently (PARITY: the reference's Try semantics are
        # preserved — the breaker only decides whether the best-effort
        # attempt is MADE, never raises into the ML loop)
        self._web_breaker = CircuitBreaker("web")
        self._lgn_breaker = CircuitBreaker("lightning")
        # rolling (monotonic_s, rss_mb) samples, one per publish tick, for
        # the continuous leak-rate gauge (ISSUE 16 satellite — the
        # tools/soak.py least-squares slope, live instead of offline)
        import collections

        self._rss_samples: collections.deque = collections.deque(maxlen=256)

    def open(self) -> "SessionStats":
        log.info("Initializing plot on lightning server: %s", self.conf.lightning)
        try:
            self.viz = self.lgn.line_streaming(
                series=[[0.0]] * 4,
                size=[1.0, 1.0, 2.0, 2.0],
                color=[REAL_COLOR_DET, PRED_COLOR_DET, REAL_COLOR, PRED_COLOR],
            )
            log.info(
                "lightning session: %s/sessions/%s — %s/visualizations/%s/pym",
                self.conf.lightning, self.viz.session,
                self.conf.lightning, self.viz.id,
            )
        except Exception as exc:
            log.warning("lightning unavailable (%s); charts disabled", exc)

        log.info("Initializing config on web server: %s", self.conf.twtweb)
        try:
            self.web.config(
                self.viz.session if self.viz else "",
                self.lgn.host,
                [self.viz.id] if self.viz else [],
            )
        except Exception as exc:
            log.warning("twtml-web unavailable (%s); dashboard disabled", exc)
        return self

    def update(
        self,
        count: int,
        batch: int,
        mse: float,
        real_stdev: float,
        pred_stdev: float,
        real: np.ndarray,
        pred: np.ndarray,
    ) -> None:
        """Push one batch of stats — same call shape as SessionStats.update
        (SessionStats.scala:22-34); mse/stdevs arrive already HALF_UP-rounded
        and are truncated to int for the dashboard like ``.toLong``. Timed
        unconditionally (per batch) for the sideband's publish stage."""
        import time as _time

        tr = _trace.get()
        t0 = _time.perf_counter()
        if not tr.enabled:
            self._update(count, batch, mse, real_stdev, pred_stdev, real, pred)
            _sideband.record_stage(
                "stats_publish", _time.perf_counter() - t0
            )
            return
        with tr.span("stats_publish", batch=int(batch)):
            self._update(count, batch, mse, real_stdev, pred_stdev, real, pred)
        _sideband.record_stage("stats_publish", _time.perf_counter() - t0)

    def _series_due(self) -> bool:
        """Degraded-tunnel load shedding: the per-batch series frame is the
        biggest publish payload; while the health monitor reports a
        DEGRADED transport, ship only every ``SERIES_SHED_EVERY``-th one
        (the scalar stats above keep full per-batch resolution)."""
        monitor = _metrics.get_health_monitor()
        if monitor.phase != monitor.DEGRADED:
            return True
        if self._updates % SERIES_SHED_EVERY == 0:
            return True
        _metrics.get_registry().counter("publish.series_shed").inc()
        return False

    def _update(
        self, count, batch, mse, real_stdev, pred_stdev, real, pred
    ) -> None:
        stats_ok = False
        if self._web_breaker.allow():
            try:
                self.web.stats(
                    count, batch, int(mse), int(real_stdev), int(pred_stdev)
                )
                self._web_breaker.record_success()
                stats_ok = True
            except Exception:
                self._web_breaker.record_failure()
                log.debug("web.stats failed", exc_info=True)
        if stats_ok and self._series_due():
            # feed the built-in dashboard chart (Lightning-free path); the
            # chart window keeps ~400 points, so huge bench-scale batches are
            # subsampled before paying the JSON encode on the hot path
            try:
                self.web.series(
                    list(real[:SERIES_MAX_POINTS]),
                    list(pred[:SERIES_MAX_POINTS]),
                    real_stdev, pred_stdev,
                )
                self._web_breaker.record_success()
            except Exception:
                self._web_breaker.record_failure()
                log.debug("web.series failed", exc_info=True)
        if self.viz is not None and self._lgn_breaker.allow():
            try:
                real_stdev_arr = [real_stdev] * int(batch)
                pred_stdev_arr = [pred_stdev] * int(batch)
                self.lgn.line_streaming(
                    series=[list(real), list(pred), real_stdev_arr, pred_stdev_arr],
                    viz=self.viz,
                )
                self._lgn_breaker.record_success()
            except Exception:
                self._lgn_breaker.record_failure()
                log.debug("lightning append failed", exc_info=True)
        # freshness plane (ISSUE 16): stamp the event→publish lag for every
        # batch delivered since the last stats push — a host-clock read over
        # already-collected lineage records, inside the timed stats_publish
        # window (zero device traffic, no-op when --freshness off)
        from . import freshness as _freshness

        _freshness.record_publish()
        self._updates += 1
        if self._updates % METRICS_EVERY == 0:
            self.publish_metrics()

    def publish_metrics(self) -> None:
        """Best-effort push of the process metrics registry + tunnel-health
        summary to the dashboard's observability panel (/api/metrics) —
        with derived per-histogram p50/p95/p99 (the latency tile), and the
        per-host ``Hosts`` view when a lockstep sideband is live."""
        # host-process gauges, sampled per publish tick (ISSUE 8 satellite):
        # makes the known axon-client RSS growth (BENCHMARKS r3 soak)
        # visible on every /api/metrics payload and post-mortem bundle —
        # statm reads, no device traffic
        try:
            from ..utils.rss import rss_mb, slope_mb_per_min

            reg = _metrics.get_registry()
            cur_mb = rss_mb()
            reg.gauge("host.rss_mb").set(round(cur_mb, 1))
            reg.gauge("host.uptime_s").set(
                round(_time_mod.monotonic() - _PROCESS_START_S, 1)
            )
            # continuous leak-rate gauge (ISSUE 16 satellite): least-squares
            # MB/min over the rolling publish-tick samples — the soak
            # estimator, live, so the axon-client retention (BENCHMARKS r3
            # soak) shows as a rate without a dedicated soak run
            self._rss_samples.append((_time_mod.monotonic(), cur_mb))
            reg.gauge("host.rss_slope_mb_per_min").set(
                round(slope_mb_per_min(self._rss_samples), 3)
            )
        except Exception:
            pass
        # telemetry historian (ISSUE 20): THE sampling seam — lawcheck
        # TW010 pins historian.sample() to this method. It snapshots the
        # registry/health/stage views this publish tick already computed
        # (pure host reads, zero device traffic); no-op when --history off.
        # BEFORE the breaker gate: the historian writes to local disk, so a
        # dead dashboard must not stop the durable timeline
        from . import historian as _historian

        _historian.sample()
        if not self._web_breaker.allow():
            return
        try:
            snap = _metrics.get_registry().snapshot()
            # ship the derived quantiles, not the raw buckets: the
            # dashboard tile wants three numbers per histogram, and the
            # wire stays small
            hists = {
                name: {
                    k: h[k] for k in ("count", "mean", "p50", "p95", "p99")
                }
                for name, h in snap["histograms"].items()
            }
            self.web.metrics(
                snap["counters"], snap["gauges"],
                _metrics.get_health_monitor().summary(),
                histograms=hists,
            )
            self._web_breaker.record_success()
        except Exception:
            self._web_breaker.record_failure()
            log.debug("web.metrics failed", exc_info=True)
        view = _sideband.last_hosts()
        if view is not None and self._web_breaker.allow():
            try:
                # elastic membership summary rides the same Hosts frame
                # (registry gauges the membership plane maintains; zero
                # when the run is not elastic)
                msnap = _metrics.get_registry().snapshot()
                gauges = msnap["gauges"]
                counters = msnap["counters"]
                self.web.hosts(
                    view["hosts"], view["straggler"], view["stage"],
                    view["skew_ms"],
                    epoch=int(gauges.get("elastic.epoch", -1)),
                    live_hosts=int(gauges.get("elastic.live_hosts", 0)),
                    departed=int(counters.get("elastic.hosts_departed", 0)),
                    rejoined=int(counters.get("elastic.hosts_rejoined", 0)),
                    lead_uid=int(gauges.get("elastic.lead_uid", -1)),
                )
                self._web_breaker.record_success()
            except Exception:
                self._web_breaker.record_failure()
                log.debug("web.hosts failed", exc_info=True)
        # per-tenant model-plane view (telemetry/tenants.py — recorded by
        # the tenant handle adapter from the already-fetched stacked
        # StepOutput; empty on single-tenant runs)
        from . import tenants as _tenants

        tview = _tenants.last_tenants()
        if tview is not None and self._web_breaker.allow():
            try:
                self.web.tenants(
                    tview["tenants"], tview["gating"], tview["active"],
                )
                self._web_breaker.record_success()
            except Exception:
                self._web_breaker.record_failure()
                log.debug("web.tenants failed", exc_info=True)
        # model-health view (telemetry/modelwatch.py — derived from the
        # in-step quality vector the pipeline already fetched; empty until
        # a --modelWatch tick has been recorded)
        from . import modelwatch as _modelwatch

        mview = _modelwatch.last_model()
        if mview is not None and self._web_breaker.allow():
            try:
                self.web.model_health(
                    level=mview["level"],
                    drift_score=mview["drift_score"],
                    loss_trend=mview["loss_trend"],
                    weight_norm=mview["weight_norm"],
                    update_norm=mview["update_norm"],
                    grad_norm=mview["grad_norm"],
                    mse=mview["mse"],
                    tenants=mview["tenants"],
                    episodes=mview["episodes"],
                )
                self._web_breaker.record_success()
            except Exception:
                self._web_breaker.record_failure()
                log.debug("web.model_health failed", exc_info=True)
        # end-to-end freshness view (telemetry/freshness.py — derived from
        # lineage records stamped at seams the pipeline already crosses;
        # None until a delivery has been observed or when --freshness off)
        from . import freshness as _freshness

        fview = _freshness.last_freshness()
        if fview is not None and self._web_breaker.allow():
            try:
                self.web.freshness(fview)
                self._web_breaker.record_success()
            except Exception:
                self._web_breaker.record_failure()
                log.debug("web.freshness failed", exc_info=True)
        hview = _historian.last_history()
        if hview is not None and self._web_breaker.allow():
            try:
                self.web.history(hview)
                self._web_breaker.record_success()
            except Exception:
                self._web_breaker.record_failure()
                log.debug("web.history failed", exc_info=True)
