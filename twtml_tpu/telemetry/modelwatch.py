"""Model & data observability plane — the host half (ISSUE 8).

Consumes the in-step quality vector (ops/quality.py) the pipeline ALREADY
fetched as a StepOutput leaf — pure host numpy over rolling windows, ZERO
added host fetches and ZERO added collectives (the PR 1/5 law, asserted by
the counting tests) — and derives the streaming health story the serving
plane's promotion gate needs long before NaN:

- **drift scores**: per monitored moment (prediction/label/residual means,
  the 4 dense-feature means, the hash-bucket skew proxy), the z-shift of a
  RECENT window's mean against a rolling REFERENCE window
  (``|mean(recent) − mean(ref)| / std(ref)``). The reference LAGS the
  recent window (values graduate from recent into reference), and it
  FREEZES while the level is not ok — so a sustained shift stays an alert
  instead of silently becoming the new baseline, and the level recovers
  exactly when the stream returns to the pre-shift distribution. The
  model's drift score is the max over fields; no verdict until
  ``min_ref`` reference ticks exist.
- **loss trend**: fast/slow EWMAs of the per-batch mse; the trend is the
  fast EWMA's relative elevation over the slow one — a streaming slope
  that ignores the absolute loss scale.
- **graduated health levels**: ok → warn → alert on fixed z/trend
  thresholds; a non-finite quality entry is an immediate alert (the
  sentinel's rollback machinery stays the enforcement arm — levels are
  telemetry-only, PARITY.md).

Mirrors the sideband/tenants module pattern: ``record_tick`` is called by
the model-watch delivery adapter (apps/common.ModelWatchGuard),
``last_model`` exposes the rolling view the dashboard's "model · drift"
tiles and ``/api/model`` render, level flips and drift-episode starts land
in the flight-recorder ring, and ``snapshot_for_checkpoint`` stamps the
current quality picture into every verified checkpoint's meta
(tools/model_report.py renders the history — the promotion-gate substrate).

The stacked tenant plane records one track per tenant from the [M, Q]
quality leaf (per-tenant drift for free through the PR 7 adapter); the
model-level view is then the worst tenant's level/drift and the
row-weighted mean of the norms.
"""

from __future__ import annotations

import math
import threading
from collections import deque

import numpy as np

from ..utils import get_logger
from . import blackbox as _blackbox
from . import metrics as _metrics
from ..ops.quality import QUALITY_INDEX, QUALITY_WIDTH

log = get_logger("telemetry.modelwatch")

LEVELS = ("ok", "warn", "alert")
LEVEL_RANK = {name: i for i, name in enumerate(LEVELS)}

# rolling-window geometry: the reference window is the "what normal looks
# like" memory, the recent window the "what is happening now" probe
REF_WINDOW = 96
RECENT_WINDOW = 16
MIN_REF = 24

# drift thresholds (z of recent mean vs reference distribution); wide on
# purpose — a stationary stream's recent means sit within ~1σ/√RECENT of
# the reference mean, so 4σ/8σ only fire on real shifts
WARN_Z = 4.0
ALERT_Z = 8.0

# loss-trend EWMAs: trend = fast/slow − 1 (relative elevation)
TREND_FAST_ALPHA = 0.2
TREND_SLOW_ALPHA = 0.02
TREND_WARN = 0.25
TREND_ALERT = 1.0

# the quality fields whose z-shift constitutes data/model drift (means and
# the bucket-skew proxy; variances ride the view but don't score — a
# variance shift moves the mean z denominators already)
DRIFT_FIELDS = (
    "pred_mean",
    "label_mean",
    "resid_mean",
    "num_mean_0",
    "num_mean_1",
    "num_mean_2",
    "num_mean_3",
    "bucket_top_share",
)

# loss-sparkline window shipped to the dashboard (ModelHealth.mse)
SPARK_WINDOW = 64


class _Track:
    """Rolling drift/trend state for ONE model (one tenant, or the single
    model). Pure host arithmetic; deterministic given the tick stream."""

    def __init__(self, watch: "ModelWatch"):
        self._w = watch
        self.ref = {
            f: deque(maxlen=watch.ref_window) for f in DRIFT_FIELDS
        }
        self.recent = {
            f: deque(maxlen=watch.recent_window) for f in DRIFT_FIELDS
        }
        self.ewma_fast: float | None = None
        self.ewma_slow: float | None = None
        self.level = "ok"
        self.drift = 0.0
        self.trend = 0.0
        self.drift_field = ""
        self.alert_run = 0
        self.ticks = 0
        self.last_q: np.ndarray | None = None

    def observe(self, q: np.ndarray, mse: float) -> None:
        w = self._w
        self.ticks += 1
        self.last_q = q
        finite = bool(np.isfinite(q).all()) and math.isfinite(mse)
        if finite:
            # two-window drift state: fresh values enter the RECENT probe,
            # and the value falling out of it graduates into the lagged
            # REFERENCE — but only while the level is ok (the baseline
            # freezes during an episode, so a sustained shift stays an
            # alert instead of becoming the new normal)
            frozen = self.level != "ok"
            for f in DRIFT_FIELDS:
                rec = self.recent[f]
                if len(rec) == rec.maxlen and not frozen:
                    self.ref[f].append(rec[0])
                rec.append(float(q[QUALITY_INDEX[f]]))
            if self.ewma_fast is None:
                self.ewma_fast = self.ewma_slow = mse
            else:
                self.ewma_fast += w.trend_fast * (mse - self.ewma_fast)
                self.ewma_slow += w.trend_slow * (mse - self.ewma_slow)
            self.trend = (
                self.ewma_fast / max(self.ewma_slow, 1e-12) - 1.0
                if self.ewma_slow and self.ewma_slow > 0
                else 0.0
            )
            self.drift, self.drift_field = self._drift_score()
        level = self._level(finite)
        if level == "alert":
            self.alert_run += 1
        else:
            self.alert_run = 0
        self.level = level

    def _drift_score(self) -> "tuple[float, str]":
        w = self._w
        best, best_field = 0.0, ""
        for f in DRIFT_FIELDS:
            ref, recent = self.ref[f], self.recent[f]
            if len(ref) < w.min_ref or len(recent) < recent.maxlen:
                continue
            rv = np.asarray(ref, np.float64)
            ref_mean = float(rv.mean())
            # the z floor keeps a near-constant reference column (std ~ 0)
            # from turning float noise into infinite z
            scale = max(
                float(rv.std()), 1e-3 * abs(ref_mean), 1e-9
            )
            z = abs(
                float(np.asarray(recent, np.float64).mean()) - ref_mean
            ) / scale
            if z > best:
                best, best_field = z, f
        return best, best_field

    def _level(self, finite: bool) -> str:
        w = self._w
        if not finite:
            return "alert"
        if self.drift >= w.alert_z or self.trend >= w.trend_alert:
            return "alert"
        if self.drift >= w.warn_z or self.trend >= w.trend_warn:
            return "warn"
        return "ok"


class ModelWatch:
    """The per-process watcher: one ``_Track`` per model (grown lazily to
    the tenant count), registry gauges/counters, flight-recorder events,
    and the rolling dashboard/checkpoint views. Thresholds are injectable
    for tests; the module-level singleton below uses the defaults."""

    def __init__(
        self,
        ref_window: int = REF_WINDOW,
        recent_window: int = RECENT_WINDOW,
        min_ref: int = MIN_REF,
        warn_z: float = WARN_Z,
        alert_z: float = ALERT_Z,
        trend_fast: float = TREND_FAST_ALPHA,
        trend_slow: float = TREND_SLOW_ALPHA,
        trend_warn: float = TREND_WARN,
        trend_alert: float = TREND_ALERT,
    ):
        self.ref_window = ref_window
        self.recent_window = recent_window
        self.min_ref = min_ref
        self.warn_z = warn_z
        self.alert_z = alert_z
        self.trend_fast = trend_fast
        self.trend_slow = trend_slow
        self.trend_warn = trend_warn
        self.trend_alert = trend_alert
        self._tracks: list[_Track] = []
        self._mse_hist: deque[float] = deque(maxlen=SPARK_WINDOW)
        self._level = "ok"
        self._episodes = 0
        self._flips = 0
        self._ticks = 0
        self._last_norms = (0.0, 0.0, 0.0)
        self._lock = threading.Lock()

    # -- recording -----------------------------------------------------------
    def observe(self, quality, count, mse) -> dict:
        """One delivered tick's quality — ``quality`` is [Q] (single model)
        or [M, Q] (tenant plane); ``count``/``mse`` scalars or [M]. Returns
        the verdict dict the delivery adapter acts on."""
        q = np.asarray(quality, np.float64)
        if q.ndim == 1:
            q = q[None, :]
        counts = np.atleast_1d(np.asarray(count, np.float64))
        mses = np.atleast_1d(np.asarray(mse, np.float64))
        if q.shape[1] != QUALITY_WIDTH:
            raise ValueError(
                f"quality vector width {q.shape[1]} != {QUALITY_WIDTH}"
            )
        m = q.shape[0]
        with self._lock:
            while len(self._tracks) < m:
                self._tracks.append(_Track(self))
            prev_level = self._level
            for i in range(m):
                if counts[i] > 0:
                    self._tracks[i].observe(q[i], float(mses[i]))
            self._ticks += 1
            total = float(counts.sum())
            agg_mse = (
                float((counts * mses).sum() / total) if total > 0 else 0.0
            )
            if total > 0 and math.isfinite(agg_mse):
                self._mse_hist.append(agg_mse)
            # model-level verdict: the worst tenant; norms are the
            # row-weighted means over tenants active this tick
            worst = max(
                self._tracks[:m], key=lambda t: LEVEL_RANK[t.level]
            )
            self._level = worst.level
            active = counts > 0
            wn = un = gn = 0.0
            if active.any():
                aw = counts[active] / counts[active].sum()
                iw, iu, ig = (
                    QUALITY_INDEX["weight_norm"],
                    QUALITY_INDEX["update_norm"],
                    QUALITY_INDEX["grad_norm"],
                )
                qa = q[active]
                wn = float((aw * qa[:, iw]).sum())
                un = float((aw * qa[:, iu]).sum())
                gn = float((aw * qa[:, ig]).sum())
            self._last_norms = (wn, un, gn)
            drift = max((t.drift for t in self._tracks[:m]), default=0.0)
            trend = max((t.trend for t in self._tracks[:m]), default=0.0)
            alert_run = max(
                (t.alert_run for t in self._tracks[:m]), default=0
            )
            flipped = self._level != prev_level
            episode = flipped and LEVEL_RANK[self._level] > LEVEL_RANK[
                prev_level
            ] and prev_level == "ok"
            if flipped:
                self._flips += 1
            if episode:
                self._episodes += 1
            level = self._level
        self._publish(m, level, drift, trend, wn, un, gn)
        if flipped:
            _blackbox.record(
                "model_health", level=level, prev=prev_level,
                drift=round(drift, 3), trend=round(trend, 4),
            )
            (log.warning if level != "ok" else log.info)(
                "model health %s -> %s (drift z=%.2f, loss trend %+.1f%%)",
                prev_level, level, drift, trend * 100.0,
            )
        if episode:
            _metrics.get_registry().counter("model.drift_episodes").inc()
            _blackbox.record(
                "drift_episode", drift=round(drift, 3),
                field=max(
                    self._tracks[:m], key=lambda t: t.drift
                ).drift_field,
            )
        return {
            "level": level,
            "drift_score": drift,
            "loss_trend": trend,
            "alert_run": alert_run,
            "flipped": flipped,
        }

    def _publish(self, m, level, drift, trend, wn, un, gn) -> None:
        reg = _metrics.get_registry()
        reg.gauge("model.weight_norm").set(round(wn, 4))
        reg.gauge("model.update_norm").set(round(un, 4))
        reg.gauge("model.grad_norm").set(round(gn, 4))
        reg.gauge("model.drift_score").set(round(drift, 4))
        reg.gauge("model.loss_trend").set(round(trend, 4))
        reg.gauge("model.health_level").set(LEVEL_RANK[level])
        if m > 1:
            for i, t in enumerate(self._tracks[:m]):
                reg.gauge(f"tenant.{i}.drift_score").set(round(t.drift, 4))
                reg.gauge(f"tenant.{i}.health_level").set(
                    LEVEL_RANK[t.level]
                )

    # -- views ---------------------------------------------------------------
    def view(self) -> "dict | None":
        """The dashboard/web view (None until a tick was recorded)."""
        with self._lock:
            if self._ticks == 0:
                return None
            wn, un, gn = self._last_norms
            m = len(self._tracks)
            drift = max((t.drift for t in self._tracks), default=0.0)
            trend = max((t.trend for t in self._tracks), default=0.0)
            return {
                "level": self._level,
                "drift_score": round(drift, 3),
                "loss_trend": round(trend, 4),
                "weight_norm": round(wn, 3),
                "update_norm": round(un, 4),
                "grad_norm": round(gn, 3),
                "mse": [round(v, 3) for v in self._mse_hist],
                "tenants": [
                    {
                        "tenant": i,
                        "level": t.level,
                        "drift": round(t.drift, 3),
                        "trend": round(t.trend, 4),
                    }
                    for i, t in enumerate(self._tracks)
                ] if m > 1 else [],
                "episodes": self._episodes,
                "ticks": self._ticks,
            }

    def checkpoint_snapshot(self) -> "dict | None":
        """The compact quality stamp a verified checkpoint's meta carries
        (plain floats — json-safe; None before the first tick)."""
        with self._lock:
            if self._ticks == 0:
                return None
            wn, un, gn = self._last_norms
            stamp = {
                "level": self._level,
                "drift_score": round(
                    max((t.drift for t in self._tracks), default=0.0), 4
                ),
                "loss_trend": round(
                    max((t.trend for t in self._tracks), default=0.0), 4
                ),
                "weight_norm": round(wn, 4),
                "update_norm": round(un, 4),
                "grad_norm": round(gn, 4),
                "mse": round(self._mse_hist[-1], 4) if self._mse_hist else -1.0,
                "ticks": self._ticks,
                "episodes": self._episodes,
            }
            if len(self._tracks) > 1:
                # per-tenant stamps (ISSUE 11): the champion/challenger
                # promotion rule compares variants by the ONLINE score the
                # trainer already computes — level, drift, trend, and the
                # fast loss EWMA — so A/B verdicts ride the checkpoint
                # handoff with zero new surfaces
                stamp["tenants"] = [
                    {
                        "tenant": i,
                        "level": t.level,
                        "drift_score": round(t.drift, 4),
                        "loss_trend": round(t.trend, 4),
                        "loss": (
                            round(t.ewma_fast, 4)
                            if t.ewma_fast is not None else -1.0
                        ),
                    }
                    for i, t in enumerate(self._tracks)
                ]
            return stamp


# -- process-wide watcher ----------------------------------------------------

_lock = threading.Lock()
_WATCH: "ModelWatch | None" = None


def get_watch() -> ModelWatch:
    global _WATCH
    with _lock:
        if _WATCH is None:
            _WATCH = ModelWatch()
        return _WATCH


def record_tick(quality, count, mse) -> dict:
    """Module-level recording hook (the delivery adapter's entry point)."""
    return get_watch().observe(quality, count, mse)


def last_model() -> "dict | None":
    """Latest model-health view for /api/model and SessionStats; None when
    nothing has been recorded (single source of truth: the watcher)."""
    with _lock:
        watch = _WATCH
    return watch.view() if watch is not None else None


def snapshot_for_checkpoint() -> "dict | None":
    with _lock:
        watch = _WATCH
    return watch.checkpoint_snapshot() if watch is not None else None


def reset_for_tests() -> None:
    global _WATCH
    with _lock:
        _WATCH = None
