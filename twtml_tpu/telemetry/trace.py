"""Pipeline tracing: Chrome-trace-event JSONL spans over the per-batch stages.

The r2/r3 bottleneck ladder (tunnel uploads > host parse > host featurize >
device step) was reconstructed by hand from ad-hoc bench scripts; a ``--trace
PATH`` run writes it directly: every stage of every batch becomes a span
carrying bytes-on-wire, batch size, and fetch depth, so
``tools/trace_report.py`` (or Perfetto) reproduces the per-stage time budget
from the file alone.

File format: the Chrome JSON **array** trace format, written incrementally —
a ``[`` line followed by one complete event object per line (trailing
comma). The spec makes the closing ``]`` optional exactly so writers can
append and crashes lose nothing, which also makes the file line-parseable as
JSONL after stripping the decoration (``tools/trace_report.py`` does). Loads
as-is in Perfetto / ``chrome://tracing``.

Measurement-integrity constraints (BENCHMARKS.md): tracing adds **no**
``device_get``/``block_until_ready`` calls and no non-main-thread
``device_put`` — spans only time work the pipeline already does. Off is the
default and must stay ~free on the hot path: ``get()`` returns a null tracer
whose ``enabled`` is False and whose ``span()`` hands back one shared no-op
context manager — instrumentation sites guard-check ``enabled`` before doing
any argument computation.

Threading: spans are written from the main thread AND the fetch pool
(apps/common.FetchPipeline) — one lock around the line write keeps events
intact; ``tid`` records the emitting thread so Perfetto lanes stay honest.

Growth cap (r8): a ``--trace`` file grows without bound over a 600 s bench
or a multi-hour soak, so the writer rotates on size — when the active file
crosses ``max_bytes`` it becomes ``PATH.1`` (replacing any previous
``PATH.1``, whose events are the DROPPED ones — counted in the
``trace.dropped_events`` registry counter) and a fresh ``PATH`` segment
starts. ``tools/trace_report.py`` stitches ``PATH.1`` + ``PATH`` back into
one report. ``--traceMaxMb 0`` disables rotation.

Event sink (r8): the crash flight recorder (telemetry/blackbox.py) attaches
via ``set_event_sink`` so recent spans ride its bounded in-memory ring —
one callback per written event, no second file, nothing when tracing is
off.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time

from ..utils import get_logger

log = get_logger("telemetry.trace")

# the per-batch pipeline stages (the instrumentation contract — tests and
# trace_report key on these names)
STAGES = (
    "source_read",   # queue drain on the batch scheduler
    "parse",         # bytes/lines → Status/ParsedBlock, on the source thread
    "featurize",     # host featurize incl. wire build (FeatureStream)
    "wire_pack",     # one-buffer pack of the ragged wire (when --wire
                     # ragged); carries a ``mode`` attribute — "single"
                     # (the k=1 pack) or "group" (the coalesced superbatch
                     # wire, --wirePack group) — plus ``wire_bytes``, so
                     # trace reports show the Lean-wire-v2 layout in use
    "dispatch",      # model.step dispatch — argument uploads ride this
    "fetch",         # pipelined StepOutput host fetch (FetchPipeline pool)
    "stats_publish", # telemetry POSTs (SessionStats)
)


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _NullTrace:
    """The off-by-default tracer: every operation is a guard-checked no-op."""

    enabled = False
    path = ""

    def span(self, name, **args):
        return _NULL_SPAN

    def complete(self, name, t0_s, dur_s, **args):
        pass

    def instant(self, name, **args):
        pass

    def counter(self, name, **values):
        pass

    def close(self):
        pass


_NULL = _NullTrace()


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_trace", "_name", "_args", "_t0")

    def __init__(self, trace: "PipelineTrace", name: str, args: dict):
        self._trace = trace
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def add(self, **args) -> None:
        """Attach args discovered mid-span (e.g. rows known only after
        featurize returns)."""
        self._args.update(args)

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._args["error"] = exc_type.__name__
        self._trace.complete(
            self._name, self._t0, time.perf_counter() - self._t0,
            **self._args,
        )
        return False


class PipelineTrace:
    """Chrome-trace-event writer. ``ts`` is ``time.perf_counter`` µs (one
    monotonic timebase across threads); writes are line-buffered so a crash
    loses at most the event being formatted. ``max_bytes`` arms size-based
    rotation (module docstring; 0 = unbounded)."""

    enabled = True

    def __init__(self, path: str, max_bytes: int = 0):
        self.path = path
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._bytes = 0
        self._events_in_file = 0
        self._rotated_events = 0  # events in OUR current PATH.1 segment
        # buffering=1: every event line reaches the OS immediately — the
        # crash-flush guarantee without an explicit flush per event
        self._fh = open(path, "w", encoding="utf-8", buffering=1)
        self._fh.write("[\n")
        self._write_meta()

    def _write_meta(self) -> None:
        self._event(
            {"name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
             "args": {"name": "twtml-tpu pipeline"}}
        )

    # -- event plumbing ------------------------------------------------------
    def _event(self, ev: dict) -> None:
        line = json.dumps(ev, separators=(",", ":"))
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(line + ",\n")
            self._bytes += len(line) + 2
            self._events_in_file += 1
            if self.max_bytes and self._bytes >= self.max_bytes:
                self._rotate_locked()
        sink = _SINK
        if sink is not None:
            try:
                sink(ev)
            except Exception:  # a sick sink must never kill the pipeline
                log.debug("trace event sink failed", exc_info=True)

    def _rotate_locked(self) -> None:
        """Size rotation (caller holds the lock): the active segment becomes
        PATH.1; a previous PATH.1's events fall off the end and are counted
        as dropped — the bounded two-segment policy keeps worst-case disk
        at ~2 x max_bytes for arbitrarily long runs."""
        self._fh.close()
        rotated = self.path + ".1"
        if self._rotated_events:
            from . import metrics as _metrics

            _metrics.get_registry().counter("trace.dropped_events").inc(
                self._rotated_events
            )
            log.warning(
                "trace rotation dropped %d event(s) from the oldest "
                "segment (%s)", self._rotated_events, rotated,
            )
        os.replace(self.path, rotated)
        self._rotated_events = self._events_in_file
        self._bytes = 0
        self._events_in_file = 0
        self._fh = open(self.path, "w", encoding="utf-8", buffering=1)
        self._fh.write("[\n")
        # re-emit the metadata so the fresh segment stands alone in Perfetto
        meta = {"name": "process_name", "ph": "M", "pid": self._pid,
                "tid": 0, "args": {"name": "twtml-tpu pipeline"}}
        line = json.dumps(meta, separators=(",", ":"))
        self._fh.write(line + ",\n")
        self._bytes += len(line) + 2
        self._events_in_file += 1

    def _base(self, name: str) -> dict:
        return {
            "name": name,
            "cat": "pipeline",
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
        }

    # -- public API ----------------------------------------------------------
    def span(self, name: str, **args) -> _Span:
        """``with trace.span("featurize", rows=...):`` — one complete event
        spanning the with-block. Nest freely; Chrome's viewer nests X events
        by time containment per thread."""
        return _Span(self, name, args)

    def complete(self, name: str, t0_s: float, dur_s: float, **args) -> None:
        """Record a complete event from an already-taken (start, duration)
        pair — for call sites that need the duration themselves (the fetch
        wrapper feeds it to the health monitor too)."""
        ev = self._base(name)
        ev["ph"] = "X"
        ev["ts"] = round(t0_s * 1e6, 1)
        ev["dur"] = round(dur_s * 1e6, 1)
        if args:
            ev["args"] = args
        self._event(ev)

    def instant(self, name: str, **args) -> None:
        """Zero-duration mark (health-phase transitions)."""
        ev = self._base(name)
        ev["ph"] = "i"
        ev["ts"] = round(time.perf_counter() * 1e6, 1)
        ev["s"] = "p"  # process-scoped mark
        if args:
            ev["args"] = args
        self._event(ev)

    def counter(self, name: str, **values) -> None:
        """Chrome counter track (e.g. fetch queue depth over time)."""
        ev = self._base(name)
        ev["ph"] = "C"
        ev["ts"] = round(time.perf_counter() * 1e6, 1)
        ev["args"] = values
        self._event(ev)

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()


# -- module-level active tracer ---------------------------------------------
# One active tracer per process, installed by the app entry points from
# ``--trace PATH``. Instrumentation sites call ``get()`` and guard on
# ``.enabled`` — with no tracer installed that is one attribute read.

_active: "PipelineTrace | _NullTrace" = _NULL

# optional per-event callback (the flight recorder's ring — blackbox.py);
# one attribute read per written event, None when nothing listens
_SINK = None


def set_event_sink(sink) -> None:
    """Attach/detach the per-event callback (``None`` detaches). Events
    only flow while a real tracer is installed — the sink never turns
    tracing on by itself."""
    global _SINK
    _SINK = sink


def install(path: str, max_bytes: int = 0) -> "PipelineTrace | _NullTrace":
    """Activate tracing to ``path`` (empty path → stays off). Closes any
    previously installed tracer; registered atexit so a crash still flushes
    and closes the file. ``max_bytes`` arms size rotation (0 = off)."""
    global _active
    if not path:
        return _active
    if _active.enabled:
        _active.close()
    _active = PipelineTrace(path, max_bytes=max_bytes)
    atexit.register(_active.close)
    log.info("pipeline trace → %s (Perfetto-loadable)", path)
    return _active


def uninstall() -> None:
    """Deactivate and close the active tracer (app shutdown path)."""
    global _active
    if _active.enabled:
        _active.close()
    _active = _NULL


def get() -> "PipelineTrace | _NullTrace":
    return _active
