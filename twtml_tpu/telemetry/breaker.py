"""Publish circuit breaker — keeps a dead dashboard off the hot path.

Every telemetry publish is best-effort (the reference wraps them in ``Try``,
SessionStats.scala:29-33,60) — but "best-effort" still means each FAILED
attempt blocks the batch handler for up to the full client timeout
(``--webTimeout``, default 2 s): a dead dashboard taxes every batch. The
breaker preserves the reference's parity exactly — a publish never raises
into the ML loop — while deciding whether the attempt is MADE at all:

- CLOSED: publishes flow; ``failure_threshold`` CONSECUTIVE failures open it.
- OPEN: publishes are dropped-and-counted (no socket, no timeout wait) for
  ``cooldown_s``.
- HALF-OPEN: after the cooldown, exactly ONE probe publish is admitted;
  success re-closes the breaker (the dashboard is back), failure re-opens
  it for another cooldown.

State transitions are stamped into the metrics registry
(``publish.<name>.breaker_open`` gauge, ``.failures``/``.dropped`` counters)
and the active trace, so an operator sees WHEN the dashboard vanished and
when it came back. ``now`` is injectable for deterministic tests.
"""

from __future__ import annotations

import threading
import time

from ..utils import get_logger
from . import metrics as _metrics
from . import trace as _trace

log = get_logger("telemetry.breaker")

FAILURE_THRESHOLD = 5  # consecutive failures that open the breaker
COOLDOWN_S = 30.0  # open duration before the half-open probe


class CircuitBreaker:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(
        self,
        name: str,
        failure_threshold: int = FAILURE_THRESHOLD,
        cooldown_s: float = COOLDOWN_S,
        registry: "object | None" = None,
        now=time.monotonic,
    ):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self._now = now
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        reg = registry if registry is not None else _metrics.get_registry()
        self._open_gauge = reg.gauge(f"publish.{name}.breaker_open")
        self._dropped = reg.counter(f"publish.{name}.dropped")
        self._failures = reg.counter(f"publish.{name}.failures")

    def allow(self) -> bool:
        """Whether the caller should attempt its publish now. While OPEN,
        returns False and counts a drop — until the cooldown elapses, when
        exactly one probe is admitted (HALF-OPEN); further calls keep
        dropping until that probe's outcome is recorded."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN and (
                self._now() - self._opened_at >= self.cooldown_s
            ):
                self.state = self.HALF_OPEN
                self._transition("probing the endpoint after cooldown")
                return True
            self._dropped.inc()
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self.state != self.CLOSED:
                self.state = self.CLOSED
                self._open_gauge.set(0)
                self._transition("endpoint recovered; publishes re-admitted")

    def record_failure(self) -> None:
        with self._lock:
            self._failures.inc()
            self._consecutive += 1
            if self.state == self.HALF_OPEN or (
                self.state == self.CLOSED
                and self._consecutive >= self.failure_threshold
            ):
                reopened = self.state == self.HALF_OPEN
                self.state = self.OPEN
                self._opened_at = self._now()
                self._open_gauge.set(1)
                self._transition(
                    "probe failed; re-opened for %gs" % self.cooldown_s
                    if reopened
                    else "opened after %d consecutive failures; publishes "
                    "dropped for %gs then probed"
                    % (self._consecutive, self.cooldown_s)
                )

    def _transition(self, why: str) -> None:
        # called under the lock: metric writes take their own locks and the
        # trace writer serializes internally — no lock-order cycle
        log.warning("publish breaker %r %s: %s", self.name, self.state, why)
        _trace.get().instant(
            "publish_breaker", breaker=self.name, state=self.state
        )
