"""Per-host telemetry sideband for multi-host lockstep runs.

The fleet was observationally blind: the lockstep scheduler
(streaming/context._lockstep_loop) gates every tick on the slowest host,
but nothing recorded WHICH host gated or WHAT stage of its pipeline was
slow. This module is the fix, under the measurement law that made PR 1
honest (BENCHMARKS.md "Measurement integrity"): **zero added host fetches
and zero added collectives** — the sideband is a compact fixed-width float
vector of host-side bookkeeping that rides the EXISTING per-tick cadence
allgather (the flags array widens; no new collective is ever issued), and
every value in it is read from state the pipeline already maintains
(the stage clock below, the metrics registry, the tunnel-health monitor).

Three pieces:

- **stage clock** (``record_stage``): cumulative per-stage wall seconds,
  fed by the instrumentation sites that already take timings (the pooled
  fetch wraps its one ``device_get``; dispatch/featurize/source-read wrap
  work the batch loop already does). Per-BATCH cost is a handful of
  ``perf_counter`` reads and one dict add — no device traffic, no threads.
  The per-tweet object-parse path stays trace-gated (two clock reads per
  tweet would tax the ~1.2M tweets/s parser measurably), so ``parse``
  attribution on object ingest needs ``--trace``; the block parser times
  per MB-scale chunk and always contributes.
- **SidebandCollector**: turns the clock deltas + registry gauges +
  health summary into the fixed ``FIELDS`` vector each tick.
- **LockstepTelemetry**: the context-side driver — builds this host's
  vector, ingests the gathered ``[hosts, WIDTH]`` matrix, feeds the
  straggler attributor (telemetry/straggler.py), and publishes the
  ``hosts[]`` view the dashboard and the flight recorder read
  (``last_hosts``).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..utils import get_logger

log = get_logger("telemetry.sideband")

# The fixed sideband layout. Every host MUST ship exactly this vector —
# the cadence allgather concatenates it after the 4 lockstep flags, so the
# wire shape is part of the collective program contract.
FIELDS = (
    "tick_prep_ms",     # wall ms this host spent between cadence allgathers
                        # (its own work: the direct gating measure)
    "source_read_ms",   # per-stage wall ms accumulated since the last tick
    "parse_ms",
    "featurize_ms",
    "dispatch_ms",      # argument uploads ride the dispatch (r2)
    "fetch_ms",
    "publish_ms",
    "queue_rows",       # intake queue depth (ingest.queue_rows gauge)
    "fetch_rtt_ms",     # tunnel-health rolling median
    "rollbacks",        # divergence-sentinel rollbacks (model.rollbacks)
    "rows_shed",        # ingest.rows_shed counter
    "health_degraded",  # 0 healthy / 1 degraded
    "wire_pack_ms",     # per-tick delta like the other stage columns (r16)
    "event_lag_ms",     # freshness plane: last event→delivery lag on this
                        # host — the fleet's low watermark rides the
                        # EXISTING cadence allgather, never a new one (r16)
)
WIDTH = len(FIELDS)

# FIELDS entries that are per-tick deltas of the stage clock
STAGE_FIELDS = {
    "source_read_ms": "source_read",
    "parse_ms": "parse",
    "featurize_ms": "featurize",
    "dispatch_ms": "dispatch",
    "fetch_ms": "fetch",
    "publish_ms": "stats_publish",
    "wire_pack_ms": "wire_pack",
}


# -- stage clock -------------------------------------------------------------
# Cumulative wall seconds per pipeline stage, always on: the contributing
# sites run at batch cadence (or chunk cadence for the block parser), so the
# cost is one lock + one float add per stage per batch. ``_CLOCK_ON`` exists
# only so the observability-overhead bench can measure an honest "all off"
# control arm (tools/bench_observability.py).

_STAGE_LOCK = threading.Lock()
_STAGE_SECONDS: "dict[str, float]" = {}
_CLOCK_ON = True


def record_stage(stage: str, dur_s: float) -> None:
    """Accumulate one stage timing (seconds). Pool threads call this for
    ``fetch`` concurrently, so cumulative fetch seconds may exceed wall
    time — fine for attribution, which compares a host against itself."""
    if not _CLOCK_ON:
        return
    with _STAGE_LOCK:
        _STAGE_SECONDS[stage] = _STAGE_SECONDS.get(stage, 0.0) + dur_s


def stage_seconds() -> "dict[str, float]":
    with _STAGE_LOCK:
        return dict(_STAGE_SECONDS)


def set_stage_clock(on: bool) -> None:
    """Bench hook (tools/bench_observability.py): the control arm must not
    pay even the per-batch dict adds."""
    global _CLOCK_ON
    _CLOCK_ON = bool(on)


# -- per-tick collection -----------------------------------------------------


class SidebandCollector:
    """Builds this host's sideband vector each lockstep tick. Everything is
    host-side state: the stage clock, the metrics registry, and the health
    monitor — no ``device_get``, no collective (asserted by
    tests/test_observability.py the way the --trace tests assert it)."""

    def __init__(self):
        self._prev_stages = stage_seconds()
        self._prev_tick = time.perf_counter()

    def collect(self, rollbacks: int = 0) -> np.ndarray:
        from . import metrics as _metrics

        now = time.perf_counter()
        cur = stage_seconds()
        reg = _metrics.get_registry()
        health = _metrics.get_health_monitor()
        vec = np.zeros((WIDTH,), dtype=np.float64)
        for i, name in enumerate(FIELDS):
            stage = STAGE_FIELDS.get(name)
            if stage is not None:
                vec[i] = (
                    cur.get(stage, 0.0) - self._prev_stages.get(stage, 0.0)
                ) * 1e3
        vec[FIELDS.index("tick_prep_ms")] = (now - self._prev_tick) * 1e3
        vec[FIELDS.index("queue_rows")] = reg.gauge(
            "ingest.queue_rows"
        ).snapshot()
        vec[FIELDS.index("fetch_rtt_ms")] = health.median_ms()
        vec[FIELDS.index("rollbacks")] = float(rollbacks)
        vec[FIELDS.index("rows_shed")] = reg.counter(
            "ingest.rows_shed"
        ).snapshot()
        vec[FIELDS.index("health_degraded")] = (
            1.0 if health.phase == health.DEGRADED else 0.0
        )
        # lazy import: freshness imports this module for the stage clock
        from . import freshness as _freshness

        vec[FIELDS.index("event_lag_ms")] = _freshness.last_event_lag_ms()
        self._prev_stages = cur
        # non-finite values must never ride the collective (they would
        # poison every peer's view)
        np.nan_to_num(vec, copy=False, posinf=0.0, neginf=0.0)
        return vec

    def tick_done(self) -> None:
        """Mark the cadence allgather's return: the next tick_prep_ms
        window starts here, so time spent WAITING in the collective (the
        fast hosts' idle time) never counts as the host's own work."""
        self._prev_tick = time.perf_counter()


# -- the published hosts[] view ---------------------------------------------
# Last gathered per-host matrix + straggler verdict, published for the
# dashboard (SessionStats → Hosts message), the flight recorder, and tests.

_VIEW_LOCK = threading.Lock()
_LAST_VIEW: "dict | None" = None


def publish_hosts(view: dict) -> None:
    global _LAST_VIEW
    with _VIEW_LOCK:
        _LAST_VIEW = view


def last_hosts() -> "dict | None":
    with _VIEW_LOCK:
        return None if _LAST_VIEW is None else dict(_LAST_VIEW)


def reset_for_tests() -> None:
    global _LAST_VIEW, _CLOCK_ON
    with _VIEW_LOCK:
        _LAST_VIEW = None
    with _STAGE_LOCK:
        _STAGE_SECONDS.clear()
    _CLOCK_ON = True


class LockstepTelemetry:
    """The lockstep scheduler's sideband driver: one instance per
    ``_lockstep_loop``. ``vector()`` before the allgather, ``tick_done()``
    right after it returns, ``ingest(matrix)`` on the gathered rows."""

    def __init__(self, process_index: int = 0, num_processes: int = 1):
        from . import metrics as _metrics
        from .straggler import StragglerAttributor

        self.process_index = process_index
        self.num_processes = num_processes
        self._collector = SidebandCollector()
        self._attributor = StragglerAttributor()
        self._ticks = _metrics.get_registry().counter("lockstep.ticks")

    def vector(self, rollbacks: int = 0) -> np.ndarray:
        return self._collector.collect(rollbacks=rollbacks)

    def tick_done(self) -> None:
        self._collector.tick_done()

    def ingest(self, matrix: np.ndarray) -> None:
        """Consume the gathered ``[hosts, WIDTH]`` sideband block: classify
        the straggler, publish the hosts[] view, and feed the flight
        recorder's ring. Pure host-side bookkeeping."""
        self._ticks.inc()
        verdict = self._attributor.observe(matrix)
        hosts = []
        for h in range(matrix.shape[0]):
            row = {"host": h}
            for i, name in enumerate(FIELDS):
                row[name] = round(float(matrix[h, i]), 3)
            hosts.append(row)
        view = {
            "hosts": hosts,
            "straggler": verdict["host"],
            "stage": verdict["stage"],
            "skew_ms": verdict["skew_ms"],
        }
        publish_hosts(view)
        from . import blackbox as _blackbox

        _blackbox.record(
            "sideband",
            straggler=verdict["host"], stage=verdict["stage"],
            skew_ms=verdict["skew_ms"],
            prep_ms=[round(float(v), 2) for v in matrix[:, 0]],
        )
