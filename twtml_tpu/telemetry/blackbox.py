"""Crash flight recorder: a bounded in-memory ring of recent telemetry,
dumped as ONE post-mortem JSON bundle when a run aborts.

All four abort paths the runtime guards added (fetch-watchdog exhaustion,
sentinel rollback-budget, lockstep peer death, cadence disagreement) used
to die leaving nothing to debug a chaos-soak failure with but stdout. They
all funnel through ``StreamingContext.request_abort`` now; that funnel (and
a SIGTERM) triggers ``abort_dump``, which writes the bundle next to the
checkpoint directory: config snapshot, last-verified-checkpoint note, the
event ring (trace spans when ``--trace`` is live, health transitions, chaos
firings, guard events, per-tick sideband rows), a metrics-registry
snapshot, the tunnel-health summary, and the last per-host sideband view.
``tools/postmortem_report.py`` renders it (exit 2 on malformed bundles,
like trace_report).

Measurement integrity: recording is host-side ring appends (one lock, one
deque append); the dump happens once, on the way DOWN — never on the hot
path. No ``device_get``, no collective, ever.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque

from ..utils import get_logger

log = get_logger("telemetry.blackbox")

BUNDLE_KIND = "twtml-postmortem"
BUNDLE_VERSION = 1
DEFAULT_CAPACITY = 512

# keys a bundle MUST carry to be parseable (postmortem_report checks)
REQUIRED_KEYS = (
    "kind", "version", "reason", "time_unix", "config", "events", "metrics",
)


class FlightRecorder:
    def __init__(self, config: "dict | None" = None, out_dir: str = "",
                 process_index: int = 0, capacity: int = DEFAULT_CAPACITY):
        self.config = dict(config or {})
        self.out_dir = out_dir or os.getcwd()
        self.process_index = int(process_index)
        self._ring: deque = deque(maxlen=capacity)
        self._dropped = 0
        self._notes: dict = {}
        self._lock = threading.Lock()
        self.last_dump_path: "str | None" = None
        self._dumped = False

    # -- recording (hot-path-safe: one lock + one append) --------------------
    def record(self, kind: str, **payload) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(
                {"t": round(time.time(), 3), "kind": kind, **payload}
            )

    def note(self, key: str, value) -> None:
        """Sticky context that should survive however old the ring gets
        (e.g. the last verified checkpoint id)."""
        with self._lock:
            self._notes[key] = value

    def on_trace_event(self, ev: dict) -> None:
        """Trace-writer sink (telemetry/trace.py): complete spans and
        instants join the ring in compact form; metadata/counter tracks are
        skipped — the ring wants the last N meaningful things that
        happened, not a second trace file."""
        ph = ev.get("ph")
        if ph == "X":
            self.record(
                "span", name=ev.get("name"),
                dur_ms=round(float(ev.get("dur", 0.0)) / 1e3, 3),
                **(ev.get("args") or {}),
            )
        elif ph == "i":
            self.record("instant", name=ev.get("name"),
                        **(ev.get("args") or {}))

    # -- the bundle ----------------------------------------------------------
    def bundle(self, reason: str) -> dict:
        from . import historian as _historian
        from . import metrics as _metrics
        from . import sideband as _sideband

        with self._lock:
            events = list(self._ring)
            notes = dict(self._notes)
            dropped = self._dropped
        return {
            "kind": BUNDLE_KIND,
            "version": BUNDLE_VERSION,
            "reason": reason,
            "time_unix": round(time.time(), 3),
            "process_index": self.process_index,
            "config": self.config,
            "notes": notes,
            "events": events,
            "events_dropped": dropped,
            "metrics": _metrics.get_registry().snapshot(),
            "health": _metrics.get_health_monitor().summary(),
            "hosts": _sideband.last_hosts(),
            # the minutes BEFORE death: the historian's in-memory tail
            # (samples + phase transitions), None when --history off
            "history": _historian.bundle_tail(),
        }

    def dump(self, reason: str, out_dir: "str | None" = None,
             force: bool = False) -> "str | None":
        """Write the post-mortem bundle; returns its path. ONE bundle per
        process per failure (the abort funnel and the SIGTERM handler can
        both fire on the same shutdown) — ``force`` re-dumps for artifact
        collection (tools/chaos_soak.py)."""
        with self._lock:
            if self._dumped and not force:
                return self.last_dump_path
            self._dumped = True
        target_dir = out_dir or self.out_dir
        path = os.path.join(
            target_dir,
            f"postmortem.p{self.process_index}.{os.getpid()}.json",
        )
        try:
            os.makedirs(target_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.bundle(reason), fh, default=_json_default)
            os.replace(tmp, path)  # a torn bundle must never shadow a good one
        except Exception:
            log.exception("post-mortem bundle write failed (%s)", path)
            return None
        self.last_dump_path = path
        log.critical("post-mortem bundle written: %s (reason: %s)", path,
                     reason)
        return path


def _json_default(obj):
    """Bundles carry whatever rode the ring — numpy scalars/arrays from
    metrics payloads must serialize, not kill the dump."""
    import numpy as np

    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return repr(obj)


# -- process-wide recorder ---------------------------------------------------

_RECORDER: "FlightRecorder | None" = None
_PREV_SIGTERM = None
_SIGTERM_INSTALLED = False


def install(config: "dict | None" = None, out_dir: str = "",
            process_index: int = 0,
            capacity: int = DEFAULT_CAPACITY) -> FlightRecorder:
    """Activate the flight recorder process-wide (re-install resets the
    ring — each app run records its own story) and hook the trace writer so
    ``--trace`` spans join the ring."""
    global _RECORDER
    _RECORDER = FlightRecorder(
        config=config, out_dir=out_dir, process_index=process_index,
        capacity=capacity,
    )
    from . import trace as _trace

    _trace.set_event_sink(_RECORDER.on_trace_event)
    return _RECORDER


def uninstall() -> None:
    global _RECORDER
    _RECORDER = None
    from . import trace as _trace

    _trace.set_event_sink(None)


def get() -> "FlightRecorder | None":
    return _RECORDER


def record(kind: str, **payload) -> None:
    """Module-level ring append — one None check when no recorder is
    installed (the default: tests and library embedding)."""
    if _RECORDER is not None:
        _RECORDER.record(kind, **payload)


def note(key: str, value) -> None:
    if _RECORDER is not None:
        _RECORDER.note(key, value)


def abort_dump(reason: str) -> "str | None":
    """The abort funnel (StreamingContext.request_abort): record the abort
    and dump the single post-mortem bundle."""
    if _RECORDER is None:
        return None
    _RECORDER.record("abort", reason=reason)
    return _RECORDER.dump(reason)


def last_dump_path() -> "str | None":
    return _RECORDER.last_dump_path if _RECORDER is not None else None


def dump(reason: str, out_dir: "str | None" = None,
         force: bool = False) -> "str | None":
    if _RECORDER is None:
        return None
    return _RECORDER.dump(reason, out_dir=out_dir, force=force)


def _on_sigterm(signum, frame, _prev=None) -> None:
    """Dump on SIGTERM, then chain to whatever handler was there before
    (default: terminate). A kill -TERM mid-soak leaves a bundle behind."""
    if _RECORDER is not None:
        _RECORDER.record("sigterm")
        _RECORDER.dump("SIGTERM")
    prev = _prev if _prev is not None else _PREV_SIGTERM
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def install_signal_handler() -> bool:
    """Best-effort SIGTERM hook (main thread only — signal.signal raises
    elsewhere). Installed once per process; re-installs are no-ops so
    repeated app runs (tools/chaos_soak.py) never chain handlers into a
    loop."""
    global _PREV_SIGTERM, _SIGTERM_INSTALLED
    if _SIGTERM_INSTALLED:
        return True
    try:
        _PREV_SIGTERM = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        return False  # not the main thread
    _SIGTERM_INSTALLED = True
    return True
