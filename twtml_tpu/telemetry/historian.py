"""Telemetry historian — durable long-horizon time series + phase-segmented
cross-run perf regression sentinel (ISSUE 20).

Every observability surface before this one (trace spans, the metrics
registry, the sideband, modelwatch, freshness) is instantaneous or a bounded
in-memory ring — nothing survives the process, so the long-horizon questions
(the axon RSS-retention curve, the tunnel's ~10-minute health phases, the
run-over-run perf trajectory) could not be answered from a run's leftovers.
The historian closes that gap with the cheapest possible sampling:

- **Sampled at the EXISTING stats-publish cadence.** ``sample()`` is called
  from exactly one place — ``SessionStats.publish_metrics`` (lawcheck TW010
  pins the seam the way TW009 pins the journal seam) — and snapshots the
  ALREADY-COMPUTED registry/health/stage views. Zero added host fetches,
  zero added collectives (counted in tests/test_history.py like PR 5/8/16).
- **The journal's durability discipline.** CRC32-framed JSON records in
  fixed-size rotated segments (``seg-<seq>.twh``); a kill -9 mid-write fails
  the CRC and the torn tail truncates LOUDLY (``history.torn_tails``);
  ``--historyMaxMb`` is a hard ceiling enforced by dropping the OLDEST
  segments (counted). A restart appends after the recovered tail, so one
  directory accumulates a multi-run timeline.
- **Phase segmentation.** The PR 1 tunnel-health classifier's transitions
  persist as labeled records, so every derived statistic is phase-matched —
  a degraded-phase stall never pollutes a healthy-phase baseline.
- **Long-horizon derivations.** Hours-scale least-squares RSS slope (the
  soak gate's estimator, ``utils.rss.slope_mb_per_min``, over any run's
  leftovers), per-phase throughput / fetch-RTT trends — all computable from
  the raw segments alone (``read_series`` + the ``phase_intervals`` /
  ``rss_slope`` helpers; tools/history_report.py is the CLI).
- **Cross-run regression sentinel** (``--perfGuard warn|off``): per-stage
  stage-clock medians over HEALTHY-phase samples are stamped into
  ``<dir>/baseline.json`` at clean shutdown; the next run compares its
  healthy-phase per-tick stage costs against the baseline and a SUSTAINED
  regression (> ``--perfGuardRatio`` for ``GUARD_WINDOW`` consecutive
  healthy samples) raises ONE warn-only blackbox event per episode +
  ``perf.regressions`` counters. Never aborts — the sentinel is a narrator,
  not a gate.

``--history off`` is bit-exact HEAD: no module state, no file handles, the
sample hook no-ops (tests byte-compare weights; tools/bench_history.py gates
the paired on/off overhead at >= 0.97x).

Frame format (little-endian): ``b"TWTH" | u32 payload_len | u32
crc32(payload) | payload`` where payload is one UTF-8 JSON object with a
``"k"`` kind tag: ``"r"`` run header (run id + config fingerprint — joins
segments to BENCH_*.json rows), ``"s"`` sample, ``"p"`` phase transition.
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import zlib
from collections import deque

from ..utils import get_logger
from ..utils.clock import now_ms
from . import metrics as _metrics
from . import sideband as _sideband

log = get_logger("telemetry.historian")

MAGIC = b"TWTH"
_FRAME = struct.Struct("<4sII")  # magic, payload_len, crc32(payload)
_SEG_RE = re.compile(r"^seg-(\d{20})\.twh$")
_PAYLOAD_MAX = 1 << 31  # sanity bound when scanning possibly-garbage tails

# segments rotate at this size unless --historyMaxMb forces smaller (the
# drop granularity under the disk ceiling: segments retire whole)
_SEGMENT_BYTES_DEFAULT = 4 * 1024 * 1024

BASELINE_NAME = "baseline.json"

# sustained-regression window: consecutive HEALTHY-phase samples a stage
# must sit above ratio x baseline before ONE episode fires (the freshness
# BREACH_WINDOW shape — burst noise never pages)
GUARD_WINDOW = 8
# stages cheaper than this per tick are below timing-noise scale on the
# one-core host; the sentinel ignores them (a 0.01 ms -> 0.03 ms "3x
# regression" is jitter, not a verdict)
GUARD_MIN_BASELINE_MS = 0.5
# healthy samples required before a baseline stamp is meaningful
BASELINE_MIN_SAMPLES = GUARD_WINDOW
# per-stage healthy-sample history kept for the shutdown baseline stamp
_STAGE_HISTORY = 4096
# in-memory tail ring: the blackbox bundle's "minutes before death" and the
# dashboard sparklines read this, never the disk
TAIL_RING = 256
# samples shipped per view/bundle
TAIL_SAMPLES = 64


def _median(vals) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    n = len(s)
    if n % 2:
        return float(s[n // 2])
    return (s[n // 2 - 1] + s[n // 2]) / 2.0


class Historian:
    """Bounded on-disk time-series historian for one process.

    Thread-safety: ``sample()`` runs on the stats-publish path only (the
    TW010 seam), but views/bundle reads arrive from web/blackbox threads —
    the lock guards the cheap bookkeeping; the file handle is touched only
    under it.
    """

    def __init__(
        self,
        directory: str,
        max_mb: int = 256,
        perf_guard: bool = True,
        guard_ratio: float = 1.5,
        run_id: int = 0,
        fingerprint: str = "",
    ):
        self.directory = directory
        self.max_bytes = max(1, int(max_mb)) * 1024 * 1024
        self.segment_bytes = max(
            64 * 1024, min(_SEGMENT_BYTES_DEFAULT, self.max_bytes // 4)
        )
        self.perf_guard = bool(perf_guard)
        self.guard_ratio = float(guard_ratio)
        self.run_id = int(run_id)
        self.fingerprint = str(fingerprint)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None
        self._active_size = 0
        reg = _metrics.get_registry()
        self._samples_c = reg.counter("history.samples")
        self._torn = reg.counter("history.torn_tails")
        self._dropped_segments = reg.counter("history.segments_dropped")
        self._regressions = reg.counter("perf.regressions")
        self._disk_gauge = reg.gauge("history.disk_mb")
        self.next_seq = 0
        self._recover_tail()
        self._disk_bytes = self.disk_bytes()
        self._update_disk_gauge()
        # previous cumulative stage clock: per-sample deltas are the
        # per-publish-tick stage costs the sentinel compares (the sideband
        # collector keeps its own prev — the historian must not share it)
        self._prev_stages: "dict[str, float]" = dict(
            _sideband.stage_seconds()
        )
        self._seen_transitions = 0
        self._tail: deque = deque(maxlen=TAIL_RING)
        # healthy-phase per-stage history for the shutdown baseline stamp
        self._stage_hist: "dict[str, deque]" = {}
        self._healthy_samples = 0
        # sentinel state: per-stage consecutive-breach runs + episode latch
        self._breach_run: "dict[str, int]" = {}
        self._in_episode: "dict[str, bool]" = {}
        self.baseline: "dict | None" = self._load_baseline()
        # the run header joins these segments to BENCH_*.json rows and the
        # next run's baseline provenance
        self._write({
            "k": "r", "t_ms": now_ms(), "run_id": self.run_id,
            "fingerprint": self.fingerprint,
            "phase": _metrics.get_health_monitor().phase,
        })

    # ---------------------------------------------------------------- disk

    def _segments(self) -> "list[tuple[int, str]]":
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                out.append(
                    (int(m.group(1)), os.path.join(self.directory, name))
                )
        out.sort()
        return out

    def _seg_path(self, first_seq: int) -> str:
        return os.path.join(self.directory, f"seg-{first_seq:020d}.twh")

    def _recover_tail(self) -> None:
        """Find the append position from the newest segment with a valid
        frame, truncating a torn tail LOUDLY (kill -9 mid-append)."""
        for first_seq, path in reversed(self._segments()):
            size = os.path.getsize(path)
            valid_end = 0
            count = 0
            for _rec, end in _scan_segment(path):
                valid_end = end
                count += 1
            if valid_end < size:
                self._torn.inc()
                log.error(
                    "historian: TORN TAIL in %s — %d byte(s) after the "
                    "last CRC-valid frame truncated (a kill mid-append); "
                    "every complete record before it survives",
                    path, size - valid_end,
                )
                with open(path, "r+b") as fh:
                    fh.truncate(valid_end)
            if count:
                self.next_seq = first_seq + count
                return
            if valid_end == 0 and first_seq != 0:
                os.unlink(path)  # fully-torn husk; position is below it
                continue
            self.next_seq = first_seq
            return

    def _rotate_if_needed(self) -> None:
        if self._fh is not None and self._active_size < self.segment_bytes:
            return
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        path = self._seg_path(self.next_seq)
        self._fh = open(path, "ab")
        self._active_size = self._fh.tell()

    def disk_bytes(self) -> int:
        return sum(os.path.getsize(p) for _, p in self._segments())

    def _update_disk_gauge(self) -> None:
        self._disk_gauge.set(round(self._disk_bytes / (1024 * 1024), 3))

    def _enforce_max_bytes(self) -> None:
        """--historyMaxMb is a HARD ceiling: drop the oldest whole segments
        (never the active one) until under it — loudly; dropped samples are
        history a later report can no longer see."""
        if self._disk_bytes <= self.max_bytes:
            return
        for _, path in self._segments()[:-1]:
            if self._disk_bytes <= self.max_bytes:
                break
            size = os.path.getsize(path)
            os.unlink(path)
            self._disk_bytes -= size
            self._dropped_segments.inc()
            log.warning(
                "historian: disk ceiling --historyMaxMb exceeded — dropped "
                "oldest segment %s (%d bytes); its samples are gone from "
                "the timeline (counted in history.segments_dropped)",
                os.path.basename(path), size,
            )

    def _write(self, rec: dict) -> None:
        """Append one CRC-framed JSON record (caller holds no lock — this
        runs from __init__ and from sample() which serializes itself)."""
        payload = json.dumps(
            rec, separators=(",", ":"), ensure_ascii=False
        ).encode("utf-8")
        with self._lock:
            self._rotate_if_needed()
            self._fh.write(
                _FRAME.pack(MAGIC, len(payload), zlib.crc32(payload))
            )
            self._fh.write(payload)
            self._fh.flush()
            self._active_size += _FRAME.size + len(payload)
            self._disk_bytes += _FRAME.size + len(payload)
            self.next_seq += 1
            if self._active_size >= self.segment_bytes:
                self._enforce_max_bytes()
            self._update_disk_gauge()

    # -------------------------------------------------------------- sample

    def sample(self) -> None:
        """Snapshot the already-computed telemetry views into one durable
        record. Called ONLY from SessionStats.publish_metrics (TW010) —
        pure host-side reads: registry snapshot, health-monitor summary,
        cumulative stage clock, /proc statm. No device traffic."""
        from ..utils.rss import rss_mb

        monitor = _metrics.get_health_monitor()
        # persist phase transitions the classifier recorded since the last
        # sample — the labeled intervals every derivation is matched on
        with monitor._lock:
            transitions = list(monitor.transitions)
            phase = monitor.phase
        for t, ph in transitions[self._seen_transitions:]:
            self._write({"k": "p", "t_ms": int(t * 1000.0), "phase": ph})
        self._seen_transitions = len(transitions)

        stages = _sideband.stage_seconds()
        deltas = {
            k: round((v - self._prev_stages.get(k, 0.0)) * 1000.0, 3)
            for k, v in stages.items()
        }
        self._prev_stages = stages
        snap = _metrics.get_registry().snapshot()
        summary = monitor.summary()
        rec = {
            "k": "s",
            "seq": self.next_seq,
            "t_ms": now_ms(),
            "run_id": self.run_id,
            "phase": phase,
            "rss_mb": round(rss_mb(), 2),
            "rtt_ms": summary["rtt_ms"],
            "stages_ms": deltas,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }
        self._write(rec)
        self._samples_c.inc()
        with self._lock:
            self._tail.append({
                "t_ms": rec["t_ms"], "phase": phase,
                "rss_mb": rec["rss_mb"], "rtt_ms": rec["rtt_ms"],
                "stages_ms": deltas,
            })
            if phase == monitor.HEALTHY:
                self._healthy_samples += 1
                for stage, ms in deltas.items():
                    self._stage_hist.setdefault(
                        stage, deque(maxlen=_STAGE_HISTORY)
                    ).append(ms)
        if self.perf_guard and phase == monitor.HEALTHY:
            self._guard_check(deltas)

    # ------------------------------------------------ regression sentinel

    def _guard_check(self, deltas: "dict[str, float]") -> None:
        """Phase-matched sustained-regression detection against the prior
        run's baseline. Warn-only by construction: one blackbox event +
        counter per episode, never a raise into the publish path."""
        base = self.baseline
        if not base:
            return
        for stage, base_ms in base.get("stages_ms", {}).items():
            if base_ms < GUARD_MIN_BASELINE_MS:
                continue
            cur = deltas.get(stage)
            if cur is None:
                continue
            if cur > self.guard_ratio * base_ms:
                run = self._breach_run.get(stage, 0) + 1
                self._breach_run[stage] = run
            else:
                self._breach_run[stage] = 0
                self._in_episode[stage] = False
                continue
            if run >= GUARD_WINDOW and not self._in_episode.get(stage):
                self._in_episode[stage] = True
                self._regressions.inc()
                ratio = round(cur / base_ms, 2)
                from . import blackbox as _blackbox

                _blackbox.record(
                    "perf_regression", stage=stage, ratio=ratio,
                    baseline_ms=round(base_ms, 3), current_ms=round(cur, 3),
                    window=run, baseline_run_id=base.get("run_id", -1),
                )
                log.warning(
                    "perfGuard: stage %r sustained at %.2fx the healthy-"
                    "phase baseline (%.3f ms -> %.3f ms per publish tick, "
                    "%d consecutive healthy samples; baseline from run %s)"
                    " — warn-only, counted in perf.regressions",
                    stage, ratio, base_ms, cur, run,
                    base.get("run_id", "?"),
                )

    def _load_baseline(self) -> "dict | None":
        path = os.path.join(self.directory, BASELINE_NAME)
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
            if isinstance(doc, dict) and isinstance(
                doc.get("stages_ms"), dict
            ):
                log.info(
                    "perfGuard: baseline loaded from %s (run %s, %d "
                    "healthy samples)", path, doc.get("run_id", "?"),
                    doc.get("samples", 0),
                )
                return doc
        except FileNotFoundError:
            pass
        except Exception:
            log.warning(
                "perfGuard: unreadable baseline %s ignored", path,
                exc_info=True,
            )
        return None

    def stamp_baseline(self) -> "dict | None":
        """Write per-stage healthy-phase medians as the next run's baseline
        (clean shutdown only — the app's finally block gates on a
        non-failed run). Atomic tmp+replace; returns the stamped doc or
        None when too few healthy samples exist to be a verdict."""
        with self._lock:
            if self._healthy_samples < BASELINE_MIN_SAMPLES:
                return None
            stages = {
                stage: round(_median(vals), 3)
                for stage, vals in self._stage_hist.items()
                if vals
            }
            samples = self._healthy_samples
        doc = {
            "version": 1,
            "run_id": self.run_id,
            "fingerprint": self.fingerprint,
            "samples": samples,
            "stages_ms": stages,
        }
        path = os.path.join(self.directory, BASELINE_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
        log.info(
            "perfGuard: baseline stamped to %s (%d healthy samples, "
            "%d stages)", path, samples, len(stages),
        )
        return doc

    # --------------------------------------------------------------- views

    def view(self) -> "dict | None":
        """The dashboard/web view (None until the first sample) — compact
        sparkline series from the in-memory tail ring, no disk reads."""
        with self._lock:
            if not self._tail:
                return None
            tail = list(self._tail)[-TAIL_SAMPLES:]
            disk_mb = round(self._disk_bytes / (1024 * 1024), 2)
        from ..utils.rss import slope_mb_per_min

        slope = slope_mb_per_min(
            [(t["t_ms"] / 1000.0, t["rss_mb"]) for t in tail]
        )
        return {
            "samples": int(self._samples_c.snapshot()),
            "runId": self.run_id,
            "phase": tail[-1]["phase"],
            "rssMb": tail[-1]["rss_mb"],
            "rssSlopeMbPerMin": round(slope, 3),
            "rttMs": tail[-1]["rtt_ms"],
            "diskMb": disk_mb,
            "regressions": int(self._regressions.snapshot()),
            "rss": [t["rss_mb"] for t in tail],
            "rtt": [t["rtt_ms"] for t in tail],
            "stageMs": [
                round(sum(t["stages_ms"].values()), 2) for t in tail
            ],
        }

    def bundle_tail(self, samples: int = TAIL_SAMPLES) -> dict:
        """The blackbox fold-in: the minutes before death (tail samples +
        every phase transition this process saw), straight from memory —
        the bundle writer must not pay disk reads mid-crash."""
        with self._lock:
            tail = list(self._tail)[-samples:]
        monitor = _metrics.get_health_monitor()
        with monitor._lock:
            transitions = [
                [int(t * 1000.0), ph] for t, ph in monitor.transitions
            ]
        return {
            "run_id": self.run_id,
            "fingerprint": self.fingerprint,
            "samples": tail,
            "transitions": transitions,
            "baseline": self.baseline,
        }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# ------------------------------------------------------------- raw readers
# module-level so tools/history_report.py can reconstruct the timeline from
# a SIGKILLed run's leftover segments with no live process state


def _scan_segment(path: str):
    """Yield (record_dict, end_offset) for every CRC-valid frame in one
    segment, stopping at the first invalid one (torn tail)."""
    with open(path, "rb") as fh:
        data = fh.read()
    pos = 0
    while pos + _FRAME.size <= len(data):
        magic, plen, crc = _FRAME.unpack_from(data, pos)
        if magic != MAGIC or plen == 0 or plen > _PAYLOAD_MAX:
            return
        end = pos + _FRAME.size + plen
        if end > len(data):
            return  # torn mid-payload
        payload = data[pos + _FRAME.size: end]
        if zlib.crc32(payload) != crc:
            return  # torn mid-frame / bit rot
        try:
            rec = json.loads(payload.decode("utf-8"))
        except ValueError:
            return
        yield rec, end
        pos = end


def read_series(directory: str) -> "list[dict]":
    """Every CRC-valid record across all segments, in append order — the
    offline entry point: works on a dead run's directory as-is (a torn
    tail is skipped, not an error; the live recovery truncates it)."""
    records: "list[dict]" = []
    names = []
    try:
        names = os.listdir(directory)
    except OSError:
        return records
    segs = sorted(
        (int(m.group(1)), os.path.join(directory, n))
        for n in names if (m := _SEG_RE.match(n))
    )
    for _first, path in segs:
        for rec, _end in _scan_segment(path):
            records.append(rec)
    return records


def phase_intervals(records: "list[dict]") -> "list[dict]":
    """Healthy/degraded episodes as labeled [start_ms, end_ms] intervals
    from run-header/phase/sample records alone (phase-matching for every
    derived statistic). Sample records vote too: a run that never flipped
    still yields its one interval."""
    out: "list[dict]" = []
    cur_phase = None
    cur_start = None
    last_t = None
    n_samples = 0

    def _close(end_ms):
        if cur_phase is not None and cur_start is not None:
            out.append({
                "phase": cur_phase,
                "start_ms": int(cur_start),
                "end_ms": int(end_ms),
                "samples": n_samples,
            })

    for rec in records:
        t = rec.get("t_ms")
        if t is None:
            continue
        kind = rec.get("k")
        phase = rec.get("phase")
        if kind == "s":
            last_t = t
        if not phase:
            continue
        if cur_phase is None:
            cur_phase, cur_start = phase, t
        elif phase != cur_phase:
            # "p" records carry the exact flip time; a sample or run header
            # with a new phase still flips the interval (robust to a torn
            # tail that ate the transition record)
            _close(t)
            cur_phase, cur_start = phase, t
            n_samples = 0
        if kind == "s":
            n_samples += 1
    _close(last_t if last_t is not None else cur_start)
    return out


def rss_slope(records: "list[dict]") -> float:
    """Least-squares RSS slope (MB/min) over every sample record — the
    soak gate's estimator, answerable from any run's leftovers."""
    from ..utils.rss import slope_mb_per_min

    return slope_mb_per_min([
        (rec["t_ms"] / 1000.0, rec["rss_mb"])
        for rec in records
        if rec.get("k") == "s" and "rss_mb" in rec
    ])


def phase_trends(records: "list[dict]") -> "dict[str, dict]":
    """Per-phase medians of the trend metrics (fetch RTT, per-tick stage
    costs, rows/s throughput from counter deltas) — the r-series verdicts,
    phase-matched so a degraded stall never dilutes the healthy numbers."""
    by_phase: "dict[str, dict]" = {}
    prev: "dict | None" = None
    for rec in records:
        if rec.get("k") != "s":
            continue
        bucket = by_phase.setdefault(rec.get("phase", "?"), {
            "samples": 0, "rtt_ms": [], "rss_mb": [], "stages_ms": {},
            "rows_per_s": [],
        })
        bucket["samples"] += 1
        if rec.get("rtt_ms", 0) > 0:
            bucket["rtt_ms"].append(rec["rtt_ms"])
        if "rss_mb" in rec:
            bucket["rss_mb"].append(rec["rss_mb"])
        for stage, ms in rec.get("stages_ms", {}).items():
            bucket["stages_ms"].setdefault(stage, []).append(ms)
        if prev is not None and prev.get("run_id") == rec.get("run_id"):
            dt_s = (rec["t_ms"] - prev["t_ms"]) / 1000.0
            rows = (
                rec.get("counters", {}).get("journal.appended_rows", 0)
                - prev.get("counters", {}).get("journal.appended_rows", 0)
            )
            if dt_s > 0 and rows > 0:
                bucket["rows_per_s"].append(rows / dt_s)
        prev = rec
    return {
        phase: {
            "samples": b["samples"],
            "rtt_ms": round(_median(b["rtt_ms"]), 3),
            "rss_mb": round(_median(b["rss_mb"]), 2),
            "rows_per_s": round(_median(b["rows_per_s"]), 1),
            "stages_ms": {
                stage: round(_median(vals), 3)
                for stage, vals in sorted(b["stages_ms"].items())
            },
        }
        for phase, b in by_phase.items()
    }


# ------------------------------------------------------- module-global face
# (the journal/blackbox idiom: entry points install once, THE seam calls
# sample(), tests uninstall)

_HISTORIAN: "Historian | None" = None


def configure(
    directory: str,
    max_mb: int = 256,
    perf_guard: bool = True,
    guard_ratio: float = 1.5,
    run_id: int = 0,
    fingerprint: str = "",
) -> Historian:
    global _HISTORIAN
    if _HISTORIAN is not None:
        _HISTORIAN.close()
    _HISTORIAN = Historian(
        directory, max_mb=max_mb, perf_guard=perf_guard,
        guard_ratio=guard_ratio, run_id=run_id, fingerprint=fingerprint,
    )
    log.info(
        "telemetry historian ON: %s (max %d MB, run_id=%d, perfGuard=%s, "
        "resumed at seq %d)", directory, max_mb, run_id,
        "warn" if perf_guard else "off", _HISTORIAN.next_seq,
    )
    return _HISTORIAN


def enabled() -> bool:
    return _HISTORIAN is not None


def get() -> "Historian | None":
    return _HISTORIAN


def sample() -> None:
    """THE sampling hook (lawcheck TW010: only SessionStats.publish_metrics
    may call this) — no-op when the historian is off so ``--history off``
    is bit-exact pre-historian behavior."""
    if _HISTORIAN is not None:
        _HISTORIAN.sample()


def last_history() -> "dict | None":
    """Latest historian view for /api/history and SessionStats; None when
    the historian is off or nothing was sampled."""
    return _HISTORIAN.view() if _HISTORIAN is not None else None


def bundle_tail() -> "dict | None":
    """The blackbox fold-in (the minutes before death); None when off."""
    return _HISTORIAN.bundle_tail() if _HISTORIAN is not None else None


def stamp_baseline() -> "dict | None":
    """Clean-shutdown hook: stamp this run's healthy-phase stage medians as
    the next run's perfGuard baseline."""
    if _HISTORIAN is not None and _HISTORIAN.perf_guard:
        return _HISTORIAN.stamp_baseline()
    return None


def uninstall() -> None:
    global _HISTORIAN
    if _HISTORIAN is not None:
        _HISTORIAN.close()
    _HISTORIAN = None


def reset_for_tests() -> None:
    uninstall()
