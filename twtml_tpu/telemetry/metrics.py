"""Process-local metrics registry + tunnel-health classification.

Why this exists: every r2/r3 perf conclusion (tunnel health phases, ~70–100 ms
fetch RTTs, upload-bandwidth regimes, axon-client RSS growth) was
reconstructed by hand from ad-hoc bench scripts. This registry makes the same
signals first-class per-run state: counters/gauges/histograms maintained on
the hot path (integer adds under a per-metric lock — no device traffic, no
host fetches, no threads), snapshot on demand, published to the dashboard as
a ``Metrics`` message (telemetry/api_types.py) and stamped into traces
(telemetry/trace.py).

Hard constraints (BENCHMARKS.md "Measurement integrity"): nothing in this
module may touch the device — no ``device_get``, no ``block_until_ready``,
no ``device_put``. Everything is host-side bookkeeping over timings the
pipeline already takes.

The ``TunnelHealthMonitor`` is the rolling RTT/throughput estimator: it
watches the fetch latencies the pipeline already measures (FetchPipeline's
pooled ``device_get``s, benchloop's per-pass completion fetch) and classifies
the tunnel into the ~10-minute healthy/degraded **health phases** the r2
benchmarks measured (2–3× rate swings). Classification is self-relative —
degraded means the rolling median latency sits ``degrade_factor``× above the
best latency this process has seen — because the same monitor must work at
RTT scale (~70 ms app fetches) and at pass scale (multi-second bench passes).
"""

from __future__ import annotations

import statistics
import threading
from collections import deque

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "TunnelHealthMonitor",
    "get_registry",
    "get_health_monitor",
    "reset_for_tests",
]


class Counter:
    """Monotonic add-only counter."""

    def __init__(self, name: str):
        self.name = name
        self.value: float = 0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1) -> None:
        with self._lock:
            self.value += amount

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Last-value gauge (set wins; ``add`` for up/down tracking)."""

    def __init__(self, name: str):
        self.name = name
        self.value: float = 0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value

    def add(self, amount: float) -> None:
        with self._lock:
            self.value += amount

    def snapshot(self) -> float:
        return self.value


# geometric latency buckets: 1 ms .. ~524 s doubling — wide enough for both
# the ~70 ms tunnel RTT regime and multi-second stall bursts
DEFAULT_BOUNDS = tuple(0.001 * (2.0 ** i) for i in range(20))


class Histogram:
    """Fixed-bound histogram with count/sum/min/max and a percentile
    estimator (linear within the winning bucket)."""

    def __init__(self, name: str, bounds: "tuple[float, ...]" = DEFAULT_BOUNDS):
        self.name = name
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +1: overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        import bisect

        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)

    def percentile(self, p: float) -> float:
        """Approximate p-quantile (0..1) from the bucket counts; the bucket's
        upper bound is the estimate (conservative for latencies)."""
        with self._lock:
            return self._percentile_locked(p)

    def _percentile_locked(self, p: float) -> float:
        if self.count == 0:
            return 0.0
        target = p * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                if i >= len(self.bounds):
                    return float(self.max)
                return self.bounds[i]
        return float(self.max)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "mean": (self.sum / self.count) if self.count else 0.0,
                # derived percentiles (r8): /api/metrics consumers and the
                # dashboard latency tile want p50/p95/p99 without
                # re-implementing the bucket walk client-side; identical to
                # Histogram.percentile by construction (one shared walk)
                "p50": self._percentile_locked(0.50),
                "p95": self._percentile_locked(0.95),
                "p99": self._percentile_locked(0.99),
                "buckets": [
                    [b, c] for b, c in zip(self.bounds, self.counts) if c
                ] + ([["inf", self.counts[-1]]] if self.counts[-1] else []),
            }


class MetricsRegistry:
    """Named metric store with get-or-create accessors and an isolated
    ``snapshot()`` (plain dicts/floats — later registry mutation never shows
    through a snapshot already taken)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name)
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name)
            return m

    def histogram(
        self, name: str, bounds: "tuple[float, ...]" = DEFAULT_BOUNDS
    ) -> Histogram:
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name, bounds)
            return m

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: m.snapshot() for k, m in counters.items()},
            "gauges": {k: m.snapshot() for k, m in gauges.items()},
            "histograms": {k: m.snapshot() for k, m in histograms.items()},
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


class TunnelHealthMonitor:
    """Classify the transport into healthy/degraded **health phases** from a
    stream of latency observations (seconds).

    Self-relative rule with hysteresis: with at least ``min_samples`` in the
    rolling window, the phase flips to DEGRADED when the window median
    exceeds ``degrade_factor`` × the best (minimum) latency ever observed,
    and back to HEALTHY when the median drops under ``recover_factor`` ×
    best. Latencies under ``floor_s`` are below tunnel-RTT scale and never
    count as degraded (keeps µs-scale CPU-backend jitter out of the
    classifier). Observations are attributed to the phase AFTER
    classification, so ``observations`` splits a run's samples into the two
    phases the way bench output wants them.

    Transitions are stamped into the active trace (an instant event) and the
    registry (``tunnel.phase_transitions`` counter + ``tunnel.degraded``
    gauge); callers never need to watch for them. ``now`` is injectable so
    tests can drive synthetic series deterministically.
    """

    HEALTHY = "healthy"
    DEGRADED = "degraded"

    def __init__(
        self,
        window: int = 16,
        min_samples: int = 5,
        degrade_factor: float = 2.5,
        recover_factor: float = 1.5,
        floor_s: float = 0.030,
        registry: "MetricsRegistry | None" = None,
    ):
        self._window: deque[float] = deque(maxlen=window)
        self.min_samples = min_samples
        self.degrade_factor = degrade_factor
        self.recover_factor = recover_factor
        self.floor_s = floor_s
        self.best: float | None = None
        self.phase = self.HEALTHY
        self.transitions: list[tuple[float, str]] = []
        self.observations = {self.HEALTHY: 0, self.DEGRADED: 0}
        self._registry = registry
        self._lock = threading.Lock()

    def observe(self, latency_s: float, now: "float | None" = None) -> str:
        """Feed one latency; returns the (possibly new) phase."""
        import time

        if now is None:
            now = time.time()
        with self._lock:
            self._window.append(latency_s)
            self.best = (
                latency_s if self.best is None else min(self.best, latency_s)
            )
            new_phase = self.phase
            if len(self._window) >= self.min_samples:
                med = statistics.median(self._window)
                base = max(self.best, 1e-9)
                if self.phase == self.HEALTHY:
                    if med > self.floor_s and med > self.degrade_factor * base:
                        new_phase = self.DEGRADED
                else:
                    if med <= self.floor_s or med <= self.recover_factor * base:
                        new_phase = self.HEALTHY
            flipped = new_phase != self.phase
            self.phase = new_phase
            self.observations[new_phase] += 1
            if flipped:
                self.transitions.append((now, new_phase))
        if flipped:
            self._stamp(now, new_phase, latency_s)
        return new_phase

    def _stamp(self, now: float, phase: str, latency_s: float) -> None:
        """Record a phase transition in the registry and the active trace
        (outside the lock — the trace writer takes its own)."""
        reg = self._registry if self._registry is not None else get_registry()
        reg.counter("tunnel.phase_transitions").inc()
        reg.gauge("tunnel.degraded").set(1 if phase == self.DEGRADED else 0)
        from . import trace as _trace

        _trace.get().instant(
            "health_phase", phase=phase, latency_ms=round(latency_s * 1e3, 3)
        )
        # flight-recorder ring (no-op unless a recorder is installed): a
        # phase flip is exactly the context a post-mortem wants
        from . import blackbox as _blackbox

        _blackbox.record(
            "health_phase", phase=phase, latency_ms=round(latency_s * 1e3, 3)
        )

    def median_ms(self) -> float:
        with self._lock:
            if not self._window:
                return 0.0
            return statistics.median(self._window) * 1e3

    def summary(self) -> dict:
        """The health block bench.py and the Metrics message publish."""
        with self._lock:
            return {
                "phase": self.phase,
                "transitions": len(self.transitions),
                "rtt_ms": round(
                    statistics.median(self._window) * 1e3, 3
                ) if self._window else 0.0,
                "best_ms": round(self.best * 1e3, 3) if self.best else 0.0,
                "observations": dict(self.observations),
            }


# -- process-wide defaults ---------------------------------------------------
# One registry + one health monitor per process: instrumentation points are
# scattered (sources, context, fetch pipeline, stats) and all feed the same
# run-level story the dashboard/bench surface.

_REGISTRY = MetricsRegistry()
_HEALTH = TunnelHealthMonitor(registry=_REGISTRY)


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def get_health_monitor() -> TunnelHealthMonitor:
    return _HEALTH


def reset_for_tests() -> None:
    """Clear the process-wide registry and health monitor (tests only — the
    hot path holds no references across calls, so swapping state is safe)."""
    global _HEALTH
    _REGISTRY.reset()
    _HEALTH = TunnelHealthMonitor(registry=_REGISTRY)
