from .api_types import Config, Metrics, Series, Stats, decode, encode
from .web_client import WebClient
from .session_stats import SessionStats
from . import metrics, trace

__all__ = [
    "Config", "Metrics", "Series", "Stats", "decode", "encode",
    "WebClient", "SessionStats", "metrics", "trace",
]
