from .api_types import Config, Stats, decode, encode
from .web_client import WebClient
from .session_stats import SessionStats

__all__ = ["Config", "Stats", "decode", "encode", "WebClient", "SessionStats"]
