from .api_types import Config, Hosts, Metrics, Series, Stats, decode, encode
from .web_client import WebClient
from .session_stats import SessionStats
from . import blackbox, metrics, sideband, straggler, trace

__all__ = [
    "Config", "Hosts", "Metrics", "Series", "Stats", "decode", "encode",
    "WebClient", "SessionStats", "blackbox", "metrics", "sideband",
    "straggler", "trace",
]
