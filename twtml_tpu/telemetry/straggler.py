"""Lockstep straggler attribution over the per-host sideband matrix.

Every lockstep tick the slowest host silently gates the whole group (the
cadence allgather is a barrier). Given the gathered ``[hosts,
sideband.WIDTH]`` matrix, this classifier names the gating host (largest
``tick_prep_ms`` — the wall time each host spent on its OWN work between
allgathers, waiting-in-collective excluded) and attributes it to a stage on
the r2/r3 bottleneck ladder:

    upload (dispatch — argument uploads ride it) > parse > featurize >
    fetch > device

Attribution rule: with enough history (``min_history`` ticks), the stage
whose current value deviates most ABOVE that host's own rolling median —
self-relative, like the tunnel-health classifier, so a host that is simply
configured slower than its peers doesn't drown the signal of what CHANGED.
Cold (or when no host stage moved), the largest absolute stage time wins;
and when the host's stage clocks account for almost none of its tick time,
the verdict falls back to ``device`` — time spent outside host-side stages
(the device step / collective interior), which host clocks cannot see.

Outputs are registry state (``lockstep.straggler_host``,
``lockstep.tick_skew_ms`` gauges + per-stage ``straggler.<stage>.ticks``
counters) and the verdict dict the sideband publishes to the dashboard's
``Hosts`` tile row. Pure host-side bookkeeping — no device traffic.
"""

from __future__ import annotations

import statistics
from collections import deque

import numpy as np

from ..utils import get_logger

log = get_logger("telemetry.straggler")

# sideband stage field → bottleneck-ladder name (dispatch is the upload
# carrier on this transport — BENCHMARKS.md r2)
LADDER = {
    "dispatch_ms": "upload",
    "parse_ms": "parse",
    "featurize_ms": "featurize",
    "fetch_ms": "fetch",
    "source_read_ms": "ingest",
    "publish_ms": "publish",
}

# below this tick skew (ms) no host is meaningfully gating — at CPU-test
# scale every host lands within scheduler noise of its peers
MIN_SKEW_MS = 5.0

# fraction of the gating host's tick time its host-side stages must explain
# before a stage verdict beats the "device" fallback
MIN_STAGE_SHARE = 0.2


class StragglerAttributor:
    def __init__(self, window: int = 64, min_history: int = 8):
        self.window = window
        self.min_history = min_history
        # history[host][field_index] -> deque of recent values
        self._history: "dict[int, dict[int, deque]]" = {}
        self.last: "dict | None" = None
        self.ticks = 0

    def _push(self, host: int, col: int, value: float) -> float:
        """Record a value and return the PRIOR rolling median (0 when no
        history yet) — the deviation baseline must not include the value
        being judged."""
        cols = self._history.setdefault(host, {})
        dq = cols.setdefault(col, deque(maxlen=self.window))
        med = statistics.median(dq) if len(dq) >= self.min_history else None
        dq.append(value)
        return med if med is not None else 0.0

    def observe(self, matrix: np.ndarray) -> dict:
        """One gathered sideband matrix → the tick's verdict dict
        ``{host, stage, skew_ms, prep_ms}``."""
        from . import metrics as _metrics
        from .sideband import FIELDS

        self.ticks += 1
        matrix = np.asarray(matrix, dtype=np.float64)
        prep = matrix[:, FIELDS.index("tick_prep_ms")]
        gate = int(np.argmax(prep))
        skew = float(prep.max() - prep.min()) if matrix.shape[0] > 1 else 0.0

        stage_cols = [
            (i, LADDER[name])
            for i, name in enumerate(FIELDS)
            if name in LADDER
        ]
        # update every host's rolling history (the baselines must advance
        # for all hosts every tick, not just the gating one)
        deviations: "dict[int, dict[str, tuple[float, float]]]" = {}
        for h in range(matrix.shape[0]):
            per = {}
            for col, ladder_name in stage_cols:
                v = float(matrix[h, col])
                med = self._push(h, col, v)
                per[ladder_name] = (v, v - med)
            deviations[h] = per

        stage = ""
        if matrix.shape[0] > 1 and skew >= MIN_SKEW_MS:
            per = deviations[gate]
            cold = self.ticks <= self.min_history
            # deviation-ranked once history exists; absolute-ranked cold
            key = (lambda kv: kv[1][0]) if cold else (lambda kv: kv[1][1])
            name, (value, _dev) = max(per.items(), key=key)
            total_stage_ms = sum(v for v, _ in per.values())
            prep_gate = float(prep[gate])
            if value <= 0 or (
                prep_gate > 0 and total_stage_ms < MIN_STAGE_SHARE * prep_gate
            ):
                # the host clocks explain almost none of the tick: the time
                # went to the device step / collective interior
                stage = "device"
            else:
                stage = name
            _metrics.get_registry().counter(
                f"straggler.{stage}.ticks"
            ).inc()
        gating = stage != ""
        reg = _metrics.get_registry()
        reg.gauge("lockstep.straggler_host").set(gate if gating else -1)
        reg.gauge("lockstep.tick_skew_ms").set(round(skew, 3))
        self.last = {
            "host": gate if gating else -1,
            "stage": stage,
            "skew_ms": round(skew, 3),
            "prep_ms": [round(float(v), 3) for v in prep],
        }
        return self.last

    def summary(self) -> dict:
        """Last verdict + per-host rolling stage medians (for reports)."""
        from .sideband import FIELDS

        medians: "dict[int, dict[str, float]]" = {}
        for host, cols in self._history.items():
            medians[host] = {
                LADDER[FIELDS[col]]: round(statistics.median(dq), 3)
                for col, dq in cols.items()
                if dq and FIELDS[col] in LADDER
            }
        return {"last": self.last, "ticks": self.ticks, "medians": medians}
