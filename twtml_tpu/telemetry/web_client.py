"""Minimal JSON/HTTP client for the twtml web API.

Same surface as the reference's scalaj-http client
(spark/.../web/WebClient.scala:9-56): POST Config/Stats to ``{server}/api``,
GET them back from ``/api/config`` and ``/api/stats``. stdlib urllib — no
external HTTP dependency; callers wrap calls best-effort like the reference
wraps them in ``Try`` (SessionStats.scala:29-33,60).
"""

from __future__ import annotations

import urllib.request

from .api_types import (
    Config, Fleet, Freshness, History, Hosts, Metrics, ModelHealth, Series,
    Serving, Stats, Tenants, decode, encode,
)

DEFAULT_SERVER = "http://localhost:8888"  # WebClient.scala:13


class WebClient:
    def __init__(self, server: str = "", timeout: float = 2.0):
        self.server = server or DEFAULT_SERVER
        self.timeout = timeout

    def _request(self, kind: str = "", data: bytes | None = None):
        # --chaos web injection point (streaming/faults.py): a dead or
        # slow dashboard, simulated before the socket. Lazy import — a
        # module-level one would cycle through streaming/__init__ while
        # telemetry/__init__ is still importing this module.
        from ..streaming import faults as _faults

        _faults.perturb("web")
        req = urllib.request.Request(
            self.server + "/api" + kind,
            data=data,
            headers={"content-type": "application/json", "accept": "application/json"},
            method="POST" if data is not None else "GET",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read().decode("utf-8")

    def _post(self, obj: Config | Stats) -> None:
        self._request(data=encode(obj).encode("utf-8"))

    # -- writes (WebClient.scala:31-38) --------------------------------------
    def config(self, id: str, host: str, viz: list[str]) -> None:
        self._post(Config(id=id, host=host, viz=list(viz)))

    def stats(
        self, count: int, batch: int, mse: int, real_stddev: int, pred_stddev: int
    ) -> None:
        self._post(
            Stats(
                count=int(count),
                batch=int(batch),
                mse=int(mse),
                realStddev=int(real_stddev),
                predStddev=int(pred_stddev),
            )
        )

    def series(
        self, real, pred, real_stddev: float, pred_stddev: float
    ) -> None:
        """Push one batch's real/pred series for the built-in live chart
        (additive message; no reference equivalent — Lightning held these)."""
        self._post(
            Series(
                real=[float(v) for v in real],
                pred=[float(v) for v in pred],
                realStddev=float(real_stddev),
                predStddev=float(pred_stddev),
            )
        )

    def metrics(self, counters: dict, gauges: dict, health: dict,
                histograms: "dict | None" = None) -> None:
        """Push a pipeline-metrics snapshot for the dashboard's
        observability panel (additive message; telemetry/metrics.py).
        ``histograms`` carries the derived p50/p95/p99 per histogram."""
        self._post(Metrics(counters=dict(counters), gauges=dict(gauges),
                           health=dict(health),
                           histograms=dict(histograms or {})))

    def hosts(self, hosts: list, straggler: int = -1, stage: str = "",
              skew_ms: float = 0.0, epoch: int = -1, live_hosts: int = 0,
              departed: int = 0, rejoined: int = 0,
              lead_uid: int = -1) -> None:
        """Push the per-host lockstep sideband view for the dashboard's
        Hosts tile row (additive message; telemetry/sideband.py), plus the
        elastic membership summary (epoch, live host count, cumulative
        departed/rejoined, and the current lead's uid — it moves at a won
        election; streaming/membership.py gauges)."""
        self._post(Hosts(hosts=list(hosts), straggler=int(straggler),
                         stage=str(stage), skewMs=float(skew_ms),
                         epoch=int(epoch), liveHosts=int(live_hosts),
                         departed=int(departed), rejoined=int(rejoined),
                         leadUid=int(lead_uid)))

    def tenants(self, tenants: list, gating: int = -1, active: int = 0) -> None:
        """Push the per-tenant model-plane view for the dashboard's Tenants
        tile row (additive message; telemetry/tenants.py)."""
        self._post(Tenants(tenants=list(tenants), gating=int(gating),
                           active=int(active)))

    def model_health(self, level: str = "ok", drift_score: float = 0.0,
                     loss_trend: float = 0.0, weight_norm: float = 0.0,
                     update_norm: float = 0.0, grad_norm: float = 0.0,
                     mse=None, tenants=None, episodes: int = 0) -> None:
        """Push the model-health view for the dashboard's "model · drift"
        tile row + loss sparkline (additive message;
        telemetry/modelwatch.py)."""
        self._post(ModelHealth(
            level=str(level), driftScore=float(drift_score),
            lossTrend=float(loss_trend), weightNorm=float(weight_norm),
            updateNorm=float(update_norm), gradNorm=float(grad_norm),
            mse=[float(v) for v in (mse or [])],
            tenants=list(tenants or []), episodes=int(episodes),
        ))

    def serving(self, view: dict) -> None:
        """Push the serving-plane view (``ServingPlane.stats()``) for the
        dashboard's Serving tile row (additive message; serving/plane.py)."""
        known = Serving.__dataclass_fields__
        self._post(Serving(**{k: v for k, v in view.items() if k in known}))

    def freshness(self, view: dict) -> None:
        """Push the end-to-end freshness view (telemetry/freshness.py
        ``last_freshness()``) for the dashboard's "freshness · e2e lag"
        tile row (additive message)."""
        known = Freshness.__dataclass_fields__
        self._post(Freshness(**{k: v for k, v in view.items() if k in known}))

    def history(self, view: dict) -> None:
        """Push the telemetry-historian view (telemetry/historian.py
        ``last_history()``) for the dashboard's "history · long horizon"
        sparkline tile row (additive message)."""
        known = History.__dataclass_fields__
        self._post(History(**{k: v for k, v in view.items() if k in known}))

    def fleet(self, view: dict) -> None:
        """Push the read-fleet view (``FleetRouter.stats()``) for the
        dashboard's fleet tile row (additive message; serving/fleet.py)."""
        known = Fleet.__dataclass_fields__
        self._post(Fleet(**{k: v for k, v in view.items() if k in known}))

    # -- reads (WebClient.scala:40-46) ---------------------------------------
    def get_config(self) -> Config:
        return decode(self._request("/config"))

    def get_stats(self) -> Stats:
        return decode(self._request("/stats"))
