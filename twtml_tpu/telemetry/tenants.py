"""Per-tenant telemetry view for the multi-tenant model plane (ISSUE 7).

Mirrors the sideband's ``Hosts`` pattern (telemetry/sideband.py →
``last_hosts`` → SessionStats.publish_metrics → /api/hosts): the tenant
handle adapter (apps/common.attach_tenant_plane) records one row per tenant
per delivered tick from the ALREADY-FETCHED stacked StepOutput — pure
host-side bookkeeping, ZERO added host fetches (the r2/r3 measurement law)
— and ``last_tenants`` exposes the rolling view the dashboard's ``Tenants``
tiles render. Registry state rides along: ``tenants.active`` (tenants with
rows this tick), per-tenant ``tenant.<m>.rows`` counters, and
``tenant.<m>.mse`` gauges, all visible on /api/metrics without a dashboard.

The *gating* tenant is the one with the most rows this tick — the tenant
that binds the shared row bucket's capacity (the analog of the straggler
host: where the next capacity problem will surface first).
"""

from __future__ import annotations

import threading

import numpy as np

from . import metrics as _metrics

_lock = threading.Lock()
_state: "dict | None" = None


def reset_for_tests() -> None:
    global _state
    with _lock:
        _state = None


def record_tick(counts, mses) -> None:
    """One delivered tick's per-tenant (row count, mse) — called by the
    tenant handle adapter with host-side numpy scalars."""
    global _state
    counts = np.asarray(counts, np.int64)
    mses = np.asarray(mses, np.float64)
    m = counts.shape[0]
    with _lock:
        st = _state
        if st is None or st["rows"].shape[0] != m:
            st = {
                "rows": np.zeros((m,), np.int64),
                "ticks": 0,
                "last_counts": np.zeros((m,), np.int64),
                "last_mses": np.zeros((m,), np.float64),
            }
        st["rows"] += counts
        st["ticks"] += 1
        st["last_counts"] = counts
        st["last_mses"] = mses
        _state = st
    reg = _metrics.get_registry()
    active = int((counts > 0).sum())
    reg.gauge("tenants.active").set(active)
    reg.gauge("tenants.configured").set(m)
    for i in range(m):
        if counts[i]:
            reg.counter(f"tenant.{i}.rows").inc(int(counts[i]))
            if np.isfinite(mses[i]):
                reg.gauge(f"tenant.{i}.mse").set(round(float(mses[i]), 3))


def last_tenants() -> "dict | None":
    """The dashboard view: one row per tenant (cumulative rows, last-tick
    rows/mse), the gating tenant (most rows this tick; -1 when all dry),
    and the active count. None until a tenant tick has been recorded."""
    with _lock:
        st = _state
        if st is None:
            return None
        counts = st["last_counts"]
        gating = int(np.argmax(counts)) if counts.any() else -1
        return {
            "tenants": [
                {
                    "tenant": i,
                    "rows": int(st["rows"][i]),
                    "batch": int(counts[i]),
                    "mse": (
                        round(float(st["last_mses"][i]), 3)
                        if np.isfinite(st["last_mses"][i]) else -1.0
                    ),
                }
                for i in range(st["rows"].shape[0])
            ],
            "gating": gating,
            "active": int((counts > 0).sum()),
            "ticks": int(st["ticks"]),
        }
