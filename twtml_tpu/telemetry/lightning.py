"""Lightning visualization-server client (line-streaming subset).

Replaces the vendored lightning-scala jar (spark/lib/lightning-scala_2.10-*.jar).
Only the API surface the reference actually uses is implemented
(SessionStats.scala:11,31-33,49-52 and KMeans.scala:86-87):

- ``Lightning(host)`` with lazy session creation (``create_session``);
- ``line_streaming(series, size=None, color=None)`` → new ``Visualization``
  (type ``line-streaming``) seeded with the given series;
- ``line_streaming(series, viz=viz)`` → append data to the live chart.

Endpoints follow the public Lightning REST protocol: ``POST /sessions/``,
``POST /sessions/{id}/visualizations/``, ``POST /visualizations/{id}/data/``.
All calls are plain stdlib HTTP; callers keep the reference's best-effort
``Try`` semantics (telemetry failures never stop training).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field


@dataclass
class Visualization:
    id: str
    session: str
    host: str


@dataclass
class Lightning:
    host: str = "http://localhost:3000"
    session: str = ""
    auth: tuple[str, str] | None = None
    timeout: float = 2.0

    def _post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            self.host.rstrip("/") + path,
            data=json.dumps(payload).encode("utf-8"),
            headers={"content-type": "application/json", "accept": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = resp.read().decode("utf-8")
        return json.loads(body) if body else {}

    def create_session(self, name: str = "") -> str:
        out = self._post("/sessions/", {"name": name} if name else {})
        self.session = str(out.get("id", ""))
        return self.session

    def line_streaming(
        self,
        series,
        size=None,
        color=None,
        viz: Visualization | None = None,
    ) -> Visualization:
        """Create (viz=None) or append to a streaming line chart — mirrors
        lightning-scala's ``lineStreaming`` used at SessionStats.scala:31-33
        (append) and :49-52 (create with size/color options)."""
        data: dict = {"series": [list(map(float, s)) for s in series]}
        if size is not None:
            data["size"] = list(map(float, size))
        if color is not None:
            data["color"] = [list(map(float, c)) for c in color]
        if viz is None:
            if not self.session:
                self.create_session()
            out = self._post(
                f"/sessions/{self.session}/visualizations/",
                {"type": "line-streaming", "data": data},
            )
            return Visualization(id=str(out.get("id", "")), session=self.session, host=self.host)
        self._post(f"/visualizations/{viz.id}/data/", {"data": data})
        return viz
