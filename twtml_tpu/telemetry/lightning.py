"""Lightning visualization-server client (streaming-chart subset).

Replaces the vendored lightning-scala jar (spark/lib/lightning-scala_2.10-*.jar).
The API surface covers what the reference uses plus what it sketched and
left commented out (SessionStats.scala:11,31-33,49-52; KMeans.scala:86-96,
129-132):

- ``Lightning(host)`` with lazy session creation (``create_session``);
- ``line_streaming(series, size=None, color=None)`` → new ``Visualization``
  (type ``line-streaming``) seeded with the given series;
- ``line_streaming(series, viz=viz)`` → append data to the live chart;
- ``scatter_streaming(x, y, label=None[, viz=viz])`` — the k-means cluster
  chart the reference's KMeans.scala:89,129-132 calls for but never enables.

Endpoints follow the public Lightning REST protocol: ``POST /sessions/``,
``POST /sessions/{id}/visualizations/``, ``POST /visualizations/{id}/data/``.
All calls are plain stdlib HTTP; callers keep the reference's best-effort
``Try`` semantics (telemetry failures never stop training).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field

# per-batch cap on chart points shipped to any streaming chart — huge
# bench-scale batches are subsampled before paying the JSON encode
CHART_MAX_POINTS = 200


@dataclass
class Visualization:
    id: str
    session: str
    host: str


@dataclass
class Lightning:
    host: str = "http://localhost:3000"
    session: str = ""
    auth: tuple[str, str] | None = None
    timeout: float = 2.0

    def _post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            self.host.rstrip("/") + path,
            data=json.dumps(payload).encode("utf-8"),
            headers={"content-type": "application/json", "accept": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = resp.read().decode("utf-8")
        return json.loads(body) if body else {}

    def create_session(self, name: str = "") -> str:
        out = self._post("/sessions/", {"name": name} if name else {})
        self.session = str(out.get("id", ""))
        return self.session

    def _create_or_append(
        self, viz_type: str, data: dict, viz: Visualization | None
    ) -> Visualization:
        """Shared streaming-chart flow: viz=None creates a visualization of
        ``viz_type`` seeded with ``data`` (lazily creating the session);
        otherwise appends ``data`` to the live chart."""
        if viz is None:
            if not self.session:
                self.create_session()
            out = self._post(
                f"/sessions/{self.session}/visualizations/",
                {"type": viz_type, "data": data},
            )
            return Visualization(
                id=str(out.get("id", "")), session=self.session, host=self.host
            )
        self._post(f"/visualizations/{viz.id}/data/", {"data": data})
        return viz

    def line_streaming(
        self,
        series,
        size=None,
        color=None,
        viz: Visualization | None = None,
    ) -> Visualization:
        """Create (viz=None) or append to a streaming line chart — mirrors
        lightning-scala's ``lineStreaming`` used at SessionStats.scala:31-33
        (append) and :49-52 (create with size/color options)."""
        data: dict = {"series": [list(map(float, s)) for s in series]}
        if size is not None:
            data["size"] = list(map(float, size))
        if color is not None:
            data["color"] = [list(map(float, c)) for c in color]
        return self._create_or_append("line-streaming", data, viz)

    def scatter_streaming(
        self,
        x,
        y,
        label=None,
        viz: Visualization | None = None,
    ) -> Visualization:
        """Create (viz=None) or append to a streaming scatter plot — the
        lightning-scala ``scatterstreaming`` the reference's k-means entry
        sketches at KMeans.scala:89 (create) and :129-132 (append, with
        per-point cluster labels)."""
        data: dict = {"x": list(map(float, x)), "y": list(map(float, y))}
        if label is not None:
            data["label"] = list(map(int, label))
        return self._create_or_append("scatter-streaming", data, viz)
