"""Device-mesh construction.

The reference's scale-out unit is a Spark executor fleet wired by Akka/Netty
(SURVEY.md §2.4); ours is a ``jax.sharding.Mesh`` whose collectives ride ICI.
Two axes cover this framework's needs:

- ``data`` — micro-batch rows are sharded across it; the per-iteration
  gradient reduce is a ``psum`` over it (the treeAggregate equivalent,
  SURVEY.md §3.3);
- ``model`` — optional: the hashed text-feature dimension is sharded across
  it for the 2^18-dim featurizer (BASELINE config #4), the analog the survey
  identifies for "long-context" scale (SURVEY.md §5.7: feature-dimension
  sharding, not sequence parallelism).

On a multi-host pod, ``jax.devices()`` spans all processes and the same mesh
code yields DCN+ICI-aware placement (jax fills the mesh devices in process
order); see distributed.py for process-group formation.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(
    num_data: int | None = None,
    num_model: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('data',) or ('data','model') mesh over the given devices
    (default: all). ``num_data=None`` uses every remaining device."""
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        if len(devices) % num_model:
            raise ValueError(
                f"{len(devices)} devices not divisible by num_model={num_model}"
            )
        num_data = len(devices) // num_model
    need = num_data * num_model
    if need > len(devices):
        raise ValueError(f"mesh {num_data}x{num_model} needs {need} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:need])
    if num_model == 1:
        return Mesh(arr.reshape(num_data), ("data",))
    return Mesh(arr.reshape(num_data, num_model), ("data", "model"))


def default_mesh(max_data: int | None = None) -> Mesh:
    """All-devices data-parallel mesh; ``max_data`` caps the shard count
    (the local[N] master hint, config.local_shards)."""
    devices = jax.devices()
    n = len(devices) if max_data is None else min(max_data, len(devices))
    return make_mesh(num_data=n, devices=devices[:n])
