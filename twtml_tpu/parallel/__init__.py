from .mesh import make_mesh, default_mesh
from .sharding import ParallelSGDModel, batch_pspecs, shard_batch
from . import distributed

__all__ = [
    "make_mesh",
    "default_mesh",
    "ParallelSGDModel",
    "batch_pspecs",
    "shard_batch",
    "distributed",
]
