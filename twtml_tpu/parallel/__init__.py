from .mesh import make_mesh, default_mesh
from .sharding import ParallelSGDModel, batch_pspecs, shard_batch
from .tenants import TenantStackModel, split_tenant_output
from . import distributed

__all__ = [
    "make_mesh",
    "default_mesh",
    "ParallelSGDModel",
    "TenantStackModel",
    "split_tenant_output",
    "batch_pspecs",
    "shard_batch",
    "distributed",
]
