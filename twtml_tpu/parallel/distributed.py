"""Multi-host process-group formation.

The reference's multi-node story is Spark cluster managers + Akka RPC
(README.md:40-55 `--master spark://...`; SURVEY.md §2.4). The TPU-native
equivalent is ``jax.distributed``: one Python controller per host joins a
process group over DCN, after which ``jax.devices()`` spans the pod and the
same Mesh/shard_map programs from sharding.py scale out — gradient psums ride
ICI within a slice and DCN across slices, with zero application-code change.

Stream intake is sharded by host (SURVEY.md §7 stage 5): each process runs
its own source/featurizer and contributes its rows of the global batch via
``host_local_batch_to_global``.
"""

from __future__ import annotations

import jax
import numpy as np

from ..features.batch import FeatureBatch, RaggedUnitBatch, UnitBatch
from ..utils import get_logger

log = get_logger("parallel.distributed")


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the jax.distributed process group (idempotent). With no args,
    reads the cluster env (TPU pod metadata / JAX_COORDINATOR_ADDRESS...).

    Must run before anything initializes the XLA backend (jax.distributed's
    own contract) — do NOT probe jax.process_count() first, that probe itself
    initializes the backend and forecloses pod formation."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        log.info(
            "joined process group: process %d/%d, %d global devices",
            jax.process_index(), jax.process_count(), jax.device_count(),
        )
    except RuntimeError as exc:
        # "already initialized" (re-entry) is fine; anything else on an
        # explicitly-requested pod is a real failure the caller must see.
        if "already" in str(exc).lower():
            log.debug("jax.distributed already initialized")
        elif coordinator_address is not None:
            raise
        else:
            log.debug("jax.distributed not initialized (%s); single-process", exc)
    except Exception as exc:  # auto-detection found no cluster env
        if coordinator_address is not None:
            raise
        log.debug("jax.distributed not initialized (%s); single-process mode", exc)


def local_rows(arr) -> np.ndarray:
    """This process's rows of a row-sharded global array, in global row
    order (shards sorted by their global offset). Per-shard device→host
    copies start async so they overlap each other; the fetch itself is
    synchronous."""
    shards = sorted(
        arr.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    for s in shards:
        s.data.copy_to_host_async()
    return np.concatenate([np.asarray(s.data) for s in shards])


def host_local_rows_to_global(arr: np.ndarray, mesh):
    """Plain per-host [B_local, ...] rows → one global row-sharded array —
    the dense-array sibling of ``host_local_batch_to_global`` (the k-means
    pipeline ships dense point matrices, not featurized batches). Requires
    the process-aligned data axis, like per-host batch intake."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = np.asarray(arr)
    spec = P(mesh.axis_names[0], *([None] * (arr.ndim - 1)))
    if jax.process_count() == 1:
        return jax.device_put(arr, NamedSharding(mesh, spec))
    global_shape = (arr.shape[0] * jax.process_count(),) + arr.shape[1:]
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), arr, global_shape
    )


def _ragged_local_aligned(batch: RaggedUnitBatch, mesh) -> RaggedUnitBatch:
    """uint16-harmonized, aligned-to-LOCAL-shards ragged batch with the
    per-shard sub-buffer capacity AGREED across processes by one tiny
    allgather-max — the one alignment rule every multi-host ragged path
    shares (global assembly, per-shard packing, and group preparation), so
    every host compiles identical program shapes. Callers must invoke it at
    deterministic points (the lockstep tick / dispatch path) so the
    collective always pairs."""
    import numpy as _np

    if batch.units.dtype != _np.uint16:
        batch = RaggedUnitBatch(
            _np.asarray(batch.units, _np.uint16), batch.offsets,
            batch.numeric, batch.label, batch.mask,
            row_len=batch.row_len, num_shards=batch.num_shards,
        )
    aligned, _codec = _ragged_local_aligned_codec(batch, mesh, codec="")
    return aligned


def _ragged_local_aligned_codec(
    batch: RaggedUnitBatch, mesh, codec: str = ""
) -> "tuple[RaggedUnitBatch, int]":
    """The alignment agreement, widened for the compressed wire (r16,
    ROADMAP item 3 REMAINING): the SAME one allgather that agrees the raw
    per-shard bucket also carries this host's codec eligibility (uint8
    units) and its encoded-segment maximum, so the cross-host COMPRESSED
    bucket needs zero additional collectives. Returns ``(aligned batch,
    agreed codec bucket)`` — 0 means the wire ships raw (codec off, a
    non-ASCII host, or an incompressible agreement).

    The agreed codec bucket must cover every host's segments AFTER
    re-alignment to the agreed raw bucket, which each host cannot encode
    locally (it doesn't know the agreed raw bucket yet). The bound that
    closes the loop without a second collective: growing a segment's
    capacity only extends its trailing zero run, and the greedy digram
    encode maps 2k extra zeros to k extra zero-pair codes (dictionary
    entry 0) plus at most one boundary byte — so every host derives the
    same agreed bucket as ``max over hosts of (enc_max_h +
    ceil((agreed_raw - raw_need_h) / 2) + 1)``, rounded to the codec
    multiple, from the one gathered [need, enc_max, eligible] triple.
    ``pack_ragged_sharded`` asserts the bound at encode time (a violation
    is a codec bug, never silent wire corruption)."""
    import numpy as _np
    from jax.experimental import multihost_utils

    from ..features.batch import align_ragged_shards, ragged_shard_bucket

    num_data = mesh.shape[mesh.axis_names[0]]
    local_shards = num_data // jax.process_count()
    if not codec or codec == "off":
        if batch.num_shards == local_shards > 1:
            # already local-aligned: on the multi-host path the only
            # producer of this layout is a prior call of this function,
            # whose per-shard capacity IS the agreed bucket — skip the
            # re-allgather (the superbatch partial-group step would
            # otherwise pay one redundant DCN round trip per batch, r5
            # review). local_shards == 1 cannot distinguish a fresh flat
            # batch from a prepared one, so that topology keeps the
            # collective.
            return batch, 0
        need = ragged_shard_bucket(batch, local_shards)
        agreed = int(
            multihost_utils.process_allgather(
                _np.array([need], _np.int64)
            ).max()
        )
        return align_ragged_shards(batch, local_shards, unit_bucket=agreed), 0

    from ..features.wirecodec import encode, encoded_bucket

    need = ragged_shard_bucket(batch, local_shards)
    eligible = int(batch.units.dtype == _np.uint8)
    enc_max = 0
    if eligible:
        # encode at LOCAL alignment; the agreed bound formula below lifts
        # it to the agreed raw bucket without re-encoding
        local = align_ragged_shards(batch, local_shards, unit_bucket=need)
        segs = _np.asarray(local.units).reshape(local_shards, -1)
        enc_max = max(int(encode(r).shape[0]) for r in segs)
    gathered = multihost_utils.process_allgather(
        _np.array([need, enc_max, eligible], _np.int64)
    )
    gathered = _np.atleast_2d(gathered)
    agreed_raw = int(gathered[:, 0].max())
    all_eligible = bool(gathered[:, 2].min())
    aligned = align_ragged_shards(batch, local_shards, unit_bucket=agreed_raw)
    if not all_eligible:
        # mixed dtypes across hosts: harmonize to the full uint16 schema
        # (the pre-codec rule) and ship raw — counted as a codec fallback
        # at the app seam
        if aligned.units.dtype != _np.uint16:
            aligned = RaggedUnitBatch(
                _np.asarray(aligned.units, _np.uint16), aligned.offsets,
                aligned.numeric, aligned.label, aligned.mask,
                row_len=aligned.row_len, num_shards=aligned.num_shards,
            )
        return aligned, 0
    per_host = gathered[:, 1] + (agreed_raw - gathered[:, 0] + 1) // 2 + 1
    agreed_codec = encoded_bucket(int(per_host.max()))
    if agreed_codec >= agreed_raw:
        return aligned, 0  # incompressible agreement: raw is smaller
    # the codec rides the uint8 wire; all hosts agreed eligibility, so the
    # narrow dtype is consistent fleet-wide (the uint16 harmonization is
    # exactly what the eligibility gather replaces)
    if aligned.units.dtype != _np.uint8:
        aligned = RaggedUnitBatch(
            _np.asarray(aligned.units, _np.uint8), aligned.offsets,
            aligned.numeric, aligned.label, aligned.mask,
            row_len=aligned.row_len, num_shards=aligned.num_shards,
        )
    return aligned, agreed_codec


class MultiHostSGDModel:
    """Per-host sharded intake over a multi-process mesh, with the same step
    surface the apps consume (apps/common.build_model): LOCAL host batches
    in, host-relevant outputs back.

    ``step`` assembles this host's featurized rows into the global
    row-sharded batch (``host_local_batch_to_global``), runs the inner
    mesh-sharded step (whose gradient psums ride ICI within a host and DCN
    across — the treeAggregate analog, SURVEY.md §3.3), and returns a
    StepOutput whose scalar stats are GLOBAL (psum over the whole data
    axis, identical on every host) while ``predictions`` is localized to
    THIS host's contributed rows — aligned with the local batch the app's
    handler already holds, so per-row telemetry (real/pred series) stays a
    host-local concern and no host ever fetches another host's rows."""

    def __init__(self, inner, mesh, rebuilder=None):
        self.inner = inner
        self.mesh = mesh
        self.num_data = inner.num_data
        self._lead = jax.process_index() == 0
        # elastic membership (--elastic on): how to rebuild the inner
        # mesh-sharded model for a re-formed epoch's mesh — a closure over
        # the conf, set by apps/common.build_model
        self._rebuilder = rebuilder
        # codec groups (r20): per-batch agreed codec buckets recorded at
        # prepare() time (the one allgather), consumed by
        # pack_group_for_wire. Keyed by id(batch) WITH the batch held, so
        # ids cannot be recycled while an entry is live; entries for
        # batches that never reach a group pack (shutdown flush) are the
        # only residue.
        self._group_buckets = {}

    def rebuild(self, mesh) -> "MultiHostSGDModel":
        """Swap in a fresh inner model on a NEW epoch's mesh IN PLACE —
        every holder of this wrapper (fetch pipelines, checkpoint
        closures, the sentinel) keeps working across an elastic membership
        change. Weights start at zeros; the caller restores them from the
        lead's broadcast checkpoint (the PR 4 path) before the next tick."""
        if self._rebuilder is None:
            raise RuntimeError(
                "MultiHostSGDModel.rebuild needs the rebuilder closure "
                "(set by apps/common.build_model)"
            )
        self.inner = self._rebuilder(mesh)
        # the rebuilder may substitute a mesh (a shrunken 1-device epoch
        # gets a synthesized 1-device data mesh) — the inner's is the truth
        self.mesh = self.inner.mesh
        self.num_data = self.inner.num_data
        self._lead = jax.process_index() == 0
        return self

    @property
    def latest_weights(self):
        return self.inner.latest_weights

    def set_initial_weights(self, weights) -> "MultiHostSGDModel":
        self.inner.set_initial_weights(weights)
        return self

    # the module-level helper, kept as a method name for call sites
    _local_rows = staticmethod(local_rows)

    # the ragged wire packs per shard on multi-host too (pack_for_wire);
    # the app-side pack opt-in keys off this (apps/common.py).
    # --wireCodec dict (r16, widened to groups in r20): the cross-host
    # compressed bucket rides the SAME alignment allgather the raw bucket
    # already pays (_ragged_local_aligned_codec) — zero added collectives,
    # asserted by the counted elastic acceptance test; set by
    # apps/common.build_model. Groups (--superBatch > 1): prepare()
    # records each batch's agreed bucket, pack_group_for_wire combines
    # them (raw-dominates, else max) with plain arithmetic.
    accepts_packed = True
    wire_codec = ""

    def step(self, local_batch):
        """Dispatch only — returns the StepOutput with predictions still
        GLOBAL (row-sharded). Localization + host transfer live in
        ``fetch_output`` so the main thread never blocks a transport round
        trip at dispatch time (r3 advisor: the synchronous lead-side
        ``local_rows`` here re-introduced exactly the per-batch sync the
        FetchPipeline exists to remove). A PackedBatch from
        ``pack_for_wire`` is already the assembled global wire — pass it
        straight to the mesh step."""
        from ..features.batch import PackedBatch

        if isinstance(local_batch, PackedBatch):
            return self.inner.step(local_batch)
        return self.inner.step(
            host_local_batch_to_global(local_batch, self.mesh)
        )

    def prepare(self, batch):
        """Pre-group hook (SuperBatcher calls it per batch BEFORE shape
        signatures/stacking): harmonize the units wire dtype across hosts
        and shard-align ragged batches to this host's local shards with the
        cross-process agreed bucket — so every host's group signatures,
        closure ticks, and stacked shapes are identical (the lockstep
        contract extended to groups). Runs at the scheduler tick, a
        deterministic point, so the agree collective always pairs.

        With ``wire_codec`` set (r20, codec groups), the SAME alignment
        allgather also agrees this batch's codec bucket — recorded here
        and consumed by ``pack_group_for_wire``, which combines the K
        batches' agreed buckets into the group bucket with ZERO additional
        collectives (the agreed values are fleet-identical, so the
        combine is plain arithmetic on every host)."""
        if isinstance(batch, RaggedUnitBatch):
            if self.wire_codec:
                aligned, bucket = _ragged_local_aligned_codec(
                    batch, self.mesh, codec=self.wire_codec
                )
                self._group_buckets[id(aligned)] = (aligned, bucket)
                return aligned
            return _ragged_local_aligned(batch, self.mesh)
        if isinstance(batch, UnitBatch) and batch.units.dtype != np.uint16:
            return batch._replace(units=batch.units.astype(np.uint16))
        return batch

    def pack_for_wire(self, local_batch):
        """The multi-host form of the one-buffer ragged wire: align this
        host's rows to its LOCAL shard segments (agreed bucket — uniform
        per-segment bytes on every host), pack them, and assemble the
        global per-shard buffer from every process's contribution. With
        ``wire_codec`` set, the compressed bucket is agreed on the SAME
        alignment allgather and every host packs identical codec segment
        shapes (or every host ships raw — the fallback decision is part of
        the agreement, never per-host)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..features.batch import PackedBatch, pack_ragged_sharded

        if not isinstance(local_batch, RaggedUnitBatch):
            raise TypeError(
                "pack_for_wire is the ragged wire's pack; padded batches "
                "assemble as plain arrays"
            )
        if self.wire_codec:
            got = self._group_buckets.pop(id(local_batch), None)
            if got is not None:
                # already prepared (a partial superbatch tail riding the
                # k=1 wire): alignment AND bucket were agreed at prepare()
                # time — no second collective, and the recorded bucket is
                # fleet-identical by construction
                aligned, codec_bucket = got
            else:
                aligned, codec_bucket = _ragged_local_aligned_codec(
                    local_batch, self.mesh, codec=self.wire_codec
                )
            pb = pack_ragged_sharded(
                aligned, num_shards_out=self.num_data,
                codec=self.wire_codec if codec_bucket else None,
                codec_bucket=codec_bucket or None,
            )
        else:
            aligned = _ragged_local_aligned(local_batch, self.mesh)
            pb = pack_ragged_sharded(aligned, num_shards_out=self.num_data)
        sharding = NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
        buf = jax.make_array_from_process_local_data(
            sharding, pb.buffer,
            (pb.buffer.shape[0] * jax.process_count(),),
        )
        # the local buffer's arena lease rides to the dispatch pipeline
        # (retired once the step's fetch delivers — apps/common.py)
        return PackedBatch(buf, pb.layout)._with_lease(pb._lease)

    def pack_group_for_wire(self, batches):
        """Multi-host form of the COALESCED superbatch wire: align each of
        the K local batches to this host's LOCAL shard segments (agreed
        bucket — uniform per-segment bytes on every host), pack them
        shard-major into one local buffer (``pack_ragged_group``), and
        assemble the global buffer from every process's contribution —
        exactly the ``pack_for_wire`` assembly, K segments deep. The
        per-process block is this host's local shards' [K, per-segment]
        bytes, so the shard-major global layout is contiguous per process
        and the data axis shards it like the single-group wire.

        With ``wire_codec`` set (r20), each batch's cross-host agreed
        bucket was recorded at ``prepare`` time; the group bucket is raw
        if ANY batch agreed raw, else the max agreed bucket (covers every
        batch's segments, and is computed from fleet-identical agreed
        values — zero collectives at pack time)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..features.batch import PackedBatch, pack_ragged_group

        if self.wire_codec:
            aligned, buckets = [], []
            for b in batches:
                got = self._group_buckets.pop(id(b), None)
                if got is None:
                    # not prepared through the codec agreement (a direct
                    # caller outside the SuperBatcher) — align raw, which
                    # forces the whole group raw on every host identically
                    aligned.append(_ragged_local_aligned(b, self.mesh))
                    buckets.append(0)
                else:
                    aligned.append(b)
                    buckets.append(got[1])
            group_bucket = 0 if 0 in buckets else max(buckets)
            pb = pack_ragged_group(
                aligned, num_shards_out=self.num_data,
                codec=self.wire_codec if group_bucket else None,
                codec_bucket=group_bucket or None,
            )
        else:
            aligned = [_ragged_local_aligned(b, self.mesh) for b in batches]
            pb = pack_ragged_group(aligned, num_shards_out=self.num_data)
        sharding = NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
        buf = jax.make_array_from_process_local_data(
            sharding, pb.buffer,
            (pb.buffer.shape[0] * jax.process_count(),),
        )
        return PackedBatch(buf, pb.layout)._with_lease(pb._lease)

    def step_many(self, stacked):
        """K-batch group over the multi-host mesh: the app pre-aligns and
        harmonizes each LOCAL batch (``prepare``), the SuperBatcher stacks
        K of them, and this assembles ONE global stacked batch ([K, ...]
        leaves, rows sharded on axis 1) for the mesh scan — one dispatch
        and one pooled stats fetch per K batches, multi-host included. A
        PackedBatch from ``pack_group_for_wire`` is already the assembled
        global coalesced wire — straight to the mesh scan."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..features.batch import PackedBatch
        from .sharding import _pspecs_for, _stacked

        if isinstance(stacked, PackedBatch):
            return self.inner.step_many(stacked)

        data_axis = self.mesh.axis_names[0]

        def to_global(host_arr, spec):
            host_arr = np.asarray(host_arr)
            global_shape = (
                host_arr.shape[0],
                host_arr.shape[1] * jax.process_count(),
            ) + host_arr.shape[2:]
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec), host_arr, global_shape
            )

        if isinstance(stacked, RaggedUnitBatch):
            local_shards = self.num_data // jax.process_count()
            if stacked.num_shards != local_shards:
                raise ValueError(
                    "stack prepare()-aligned batches (per-host local "
                    "shard segments)"
                )
            spec = P(None, data_axis)
            stacked = RaggedUnitBatch(
                *(to_global(a, spec) for a in (
                    stacked.units, stacked.offsets, stacked.numeric,
                    stacked.label, stacked.mask,
                )),
                row_len=stacked.row_len,
                num_shards=self.num_data,
            )
            return self.inner.step_many(stacked)
        specs = _stacked(_pspecs_for(type(stacked), data_axis))
        return self.inner.step_many(
            type(stacked)(*(
                to_global(a, s) for a, s in zip(stacked, specs)
            ))
        )

    def fetch_output_many(self, outs):
        """The group form of ``fetch_output``: [K]-vector global stats for
        every host; the lead localizes its own rows' predictions for each
        of the K batches ([K, B_local], shards sorted by their ROW offset —
        the row axis is axis 1 of a stacked output)."""
        from ..models.base import StepOutput

        # the quality leaf (None when --modelWatch off — an empty pytree)
        # rides the same ONE pooled transfer as the scalar stats
        count, mse, real_stdev, pred_stdev, quality = jax.device_get(  # lawcheck: disable=TW002 -- fetch_output_many IS the counted seam: FetchPipeline installs it as _fetch_many, one pooled get per K-group tick
            (outs.count, outs.mse, outs.real_stdev, outs.pred_stdev,
             outs.quality)
        )
        preds = None
        if self._lead:
            shards = sorted(
                outs.predictions.addressable_shards,
                key=lambda s: s.index[1].start or 0,
            )
            for s in shards:
                s.data.copy_to_host_async()
            preds = np.concatenate(
                [np.asarray(s.data) for s in shards], axis=1
            )
        return StepOutput(
            predictions=preds,
            count=count,
            mse=mse,
            real_stdev=real_stdev,
            pred_stdev=pred_stdev,
            quality=quality,
        )

    def fetch_output(self, out):
        """StepOutput → host numpy, the model-aware form of
        ``jax.device_get`` the fetch paths use (FetchPipeline workers and
        the wall-clock per-batch fetch): global scalars for every host,
        predictions localized to THIS host's contributed rows on the lead
        only (telemetry is lead-owned; followers skip the row fetch —
        each is a full transport round trip, BENCHMARKS.md)."""
        from ..models.base import StepOutput

        count, mse, real_stdev, pred_stdev, quality = jax.device_get(  # lawcheck: disable=TW002 -- fetch_output IS the counted seam: FetchPipeline installs it as _fetch, one pooled get per tick (counted in tests/test_distributed_multiprocess.py)
            (out.count, out.mse, out.real_stdev, out.pred_stdev, out.quality)
        )
        return StepOutput(
            predictions=(
                self._local_rows(out.predictions) if self._lead else None
            ),
            count=count,
            mse=mse,
            real_stdev=real_stdev,
            pred_stdev=pred_stdev,
            quality=quality,
        )


def host_local_batch_to_global(
    batch: FeatureBatch | UnitBatch | RaggedUnitBatch, mesh
) -> FeatureBatch | UnitBatch | RaggedUnitBatch:
    """Assemble each host's locally-featurized rows into one global
    row-sharded batch (multi-host stream sharding), for any wire format
    (host-hashed tokens, raw code units, or the ragged wire). Single
    process: no-op beyond device placement.

    Ragged wire: each host re-lays its rows into its LOCAL data shards'
    segments (``align_ragged_shards``), with the per-shard sub-buffer
    capacity AGREED across processes by one tiny allgather-max of each
    host's requirement — the lockstep scheduler guarantees every host
    assembles on every tick, so the collective always pairs, and the
    agreed bucket keeps every host's compiled program shapes identical
    (the lockstep contract). The r3 narrow-wire harmonization applies to
    the ragged units too.

    Topology requirement: per-host intake sharding assumes the mesh's data
    axis is PROCESS-ALIGNED (each data shard's devices belong to one
    process) — the default `make_mesh` over process-major `jax.devices()`
    satisfies this. A mesh whose model axis crosses processes makes every
    host's devices hold rows of every data shard; such layouts must ship
    the full batch from each host via `shard_batch` instead (see
    tests/distributed_worker.py's 2d mode)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .sharding import _pspecs_for

    if jax.process_count() == 1:
        from .sharding import shard_batch

        return shard_batch(batch, mesh)

    def to_global(host_arr, spec):
        sharding = NamedSharding(mesh, spec)
        global_shape = (
            host_arr.shape[0] * jax.process_count(),
        ) + host_arr.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(host_arr), global_shape
        )

    if isinstance(batch, RaggedUnitBatch):
        data_axis = mesh.axis_names[0]
        num_data = mesh.shape[data_axis]
        batch = _ragged_local_aligned(batch, mesh)
        spec = P(data_axis)
        return RaggedUnitBatch(
            *(to_global(a, spec) for a in (
                batch.units, batch.offsets, batch.numeric, batch.label,
                batch.mask,
            )),
            row_len=batch.row_len,
            num_shards=num_data,
        )

    if isinstance(batch, UnitBatch) and batch.units.dtype != np.uint16:
        # the units wire dtype is per-batch metadata (uint8 iff every row
        # is ASCII, featurizer._pad_ragged_units); cross-process assembly
        # needs ONE dtype on every host, and hosts see different shards —
        # harmonize to the full uint16 schema here (multi-host intake rides
        # DCN, not the single-host transport the narrow wire optimizes)
        batch = batch._replace(units=batch.units.astype(np.uint16))
    specs = _pspecs_for(type(batch), mesh.axis_names[0])
    return type(batch)(*(
        to_global(host_arr, spec) for host_arr, spec in zip(batch, specs)
    ))
