"""Multi-host process-group formation.

The reference's multi-node story is Spark cluster managers + Akka RPC
(README.md:40-55 `--master spark://...`; SURVEY.md §2.4). The TPU-native
equivalent is ``jax.distributed``: one Python controller per host joins a
process group over DCN, after which ``jax.devices()`` spans the pod and the
same Mesh/shard_map programs from sharding.py scale out — gradient psums ride
ICI within a slice and DCN across slices, with zero application-code change.

Stream intake is sharded by host (SURVEY.md §7 stage 5): each process runs
its own source/featurizer and contributes its rows of the global batch via
``host_local_batch_to_global``.
"""

from __future__ import annotations

import jax
import numpy as np

from ..features.batch import FeatureBatch, UnitBatch
from ..utils import get_logger

log = get_logger("parallel.distributed")


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the jax.distributed process group (idempotent). With no args,
    reads the cluster env (TPU pod metadata / JAX_COORDINATOR_ADDRESS...).

    Must run before anything initializes the XLA backend (jax.distributed's
    own contract) — do NOT probe jax.process_count() first, that probe itself
    initializes the backend and forecloses pod formation."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        log.info(
            "joined process group: process %d/%d, %d global devices",
            jax.process_index(), jax.process_count(), jax.device_count(),
        )
    except RuntimeError as exc:
        # "already initialized" (re-entry) is fine; anything else on an
        # explicitly-requested pod is a real failure the caller must see.
        if "already" in str(exc).lower():
            log.debug("jax.distributed already initialized")
        elif coordinator_address is not None:
            raise
        else:
            log.debug("jax.distributed not initialized (%s); single-process", exc)
    except Exception as exc:  # auto-detection found no cluster env
        if coordinator_address is not None:
            raise
        log.debug("jax.distributed not initialized (%s); single-process mode", exc)


def host_local_batch_to_global(
    batch: FeatureBatch | UnitBatch, mesh
) -> FeatureBatch | UnitBatch:
    """Assemble each host's locally-featurized rows into one global
    row-sharded batch (multi-host stream sharding), for either wire format
    (host-hashed tokens or raw code units). Single-process: no-op beyond
    device placement.

    Topology requirement: per-host intake sharding assumes the mesh's data
    axis is PROCESS-ALIGNED (each data shard's devices belong to one
    process) — the default `make_mesh` over process-major `jax.devices()`
    satisfies this. A mesh whose model axis crosses processes makes every
    host's devices hold rows of every data shard; such layouts must ship
    the full batch from each host via `shard_batch` instead (see
    tests/distributed_worker.py's 2d mode)."""
    from jax.sharding import NamedSharding

    from .sharding import _pspecs_for

    if jax.process_count() == 1:
        from .sharding import shard_batch

        return shard_batch(batch, mesh)
    if isinstance(batch, UnitBatch) and batch.units.dtype != np.uint16:
        # the units wire dtype is per-batch metadata (uint8 iff every row
        # is ASCII, featurizer._pad_ragged_units); cross-process assembly
        # needs ONE dtype on every host, and hosts see different shards —
        # harmonize to the full uint16 schema here (multi-host intake rides
        # DCN, not the single-host transport the narrow wire optimizes)
        batch = batch._replace(units=batch.units.astype(np.uint16))
    specs = _pspecs_for(type(batch), mesh.axis_names[0])
    arrays = []
    for host_arr, spec in zip(batch, specs):
        sharding = NamedSharding(mesh, spec)
        global_shape = (host_arr.shape[0] * jax.process_count(),) + host_arr.shape[1:]
        arrays.append(
            jax.make_array_from_process_local_data(sharding, np.asarray(host_arr),
                                                   global_shape)
        )
    return type(batch)(*arrays)
