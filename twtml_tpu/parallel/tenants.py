"""Multi-tenant model plane: M models, ONE jit program, ONE fetch (ISSUE 7).

The reference trains one global retweet model; the scenario axis (per-topic /
per-language / per-A/B-arm) would naively cost M full pipelines — M wires, M
dispatches, and above all M host fetches at ~70–100 ms RTT each (the r2 law:
fetches, not arrays, are what cost). This module stacks M models along a
leading tenant axis so the marginal tenant costs device FLOPs (µs, nowhere
near binding on the measured ladder) instead of tunnel round trips:

- **weights** are one ``[M, F+4]`` array (one optimizer-state pytree; one
  donated buffer), per-tenant hyperparams (step size, L2) ride as mapped
  scalar leaves of a separate ``hyper`` pytree;
- **the step** maps the EXISTING fused SGD step over the tenant axis.
  Default mapping is ``lax.map`` — a scan of the single-tenant step program
  with no carry, which keeps every tenant's math BIT-IDENTICAL to the
  reference single-model path (the parity law; ``step_many`` uses the same
  trick over K batches). ``mapping="vmap"`` batches the tenants across the
  device instead — mathematically equivalent, but XLA's batched-matmul
  accumulation order differs on the dense path, so it is an opt-in for
  deployments that trade bit-parity for device parallelism (device compute
  is µs either way; the win of this plane is fetch amortization, not FLOPs);
- **the wire** is shared: rows route to tenants on the host by a cheap
  deterministic key (``features/batch.tenant_route_keys``), split into M
  same-signature batches (dry tenants = all-padding, the lockstep
  invariant), and ship as the K-batch superbatch wire reused as the
  K-tenant wire — ``stack_batches`` (``--wirePack stacked``) or the
  coalesced one-buffer ``pack_ragged_group`` (``--wirePack group``);
- **the fetch** is one ``jax.device_get`` of the ``[M, ...]`` StepOutput
  through the existing FetchPipeline — fetch count per tick is ONE
  regardless of M (asserted by the counting tests).

Mesh composition: a 1D ('data',) mesh shards every tenant batch's rows over
``data`` (tenant axis unsharded — weights replicated) with the per-shard
body's psums riding the existing collectives; a 2D ('data','model') mesh
maps the TENANT axis onto ``model`` (the cross-process model axis proven in
parallel/distributed.py + tests/test_distributed_multiprocess.py): each
model shard holds M/num_model tenants' weights and maps only those — tenant
independence means NO collective ever crosses the model axis.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..features.batch import (
    NUM_NUMBER_FEATURES,
    PackedBatch,
    RaggedUnitBatch,
    pack_ragged_group,
    split_batch_tenants,
    stack_batches,
    tenant_route_keys,
    unpack_batch,
)
from ..models.base import StepOutput
from ..models.sgd import make_sgd_train_step
from ..utils import get_logger

log = get_logger("parallel.tenants")


def aggregate_tenant_output(out, batch, model) -> StepOutput:
    """The delivered ``[M, ...]`` StepOutput → ONE batch-level StepOutput in
    the ORIGINAL batch's row order, for the app handler / sentinel /
    session-stats chain that predates tenants. Pure host numpy on the
    already-fetched arrays — zero added device work or fetches.

    M = 1 passes tenant 0's output through untouched (bit-exact — the M=1
    parity law). M > 1 aggregates: ``count`` sums; ``mse`` is the
    row-weighted mean of per-tenant mses (exact — mse is a per-row mean);
    the stdevs are row-weighted POOLED within-tenant stdevs (each tenant is
    an independent model, so a cross-tenant stdev is not a reference
    quantity; the pooled form is documented in PARITY.md). ``predictions``
    re-order to original rows via the deterministic routing key — the same
    route the wire used, recomputed instead of carried through the fetch
    pipeline. A non-finite stat in ANY tenant propagates into the
    aggregate, so the divergence sentinel still sees every poisoning.

    ``quality`` (ISSUE 8): M = 1 passes tenant 0's vector through like
    every other leaf; M > 1 leaves the aggregate's quality None — norms of
    M independent models don't pool into one meaningful vector, and the
    model-watch adapter consumes the per-tenant [M, Q] leaf BEFORE this
    aggregation (apps/common.attach_super_batcher wrapping order)."""
    from ..features.batch import tenant_rows

    m = model.num_tenants
    if m == 1:
        return StepOutput(*(
            None if f is None else f[0] for f in out
        ))
    counts = np.asarray(out.count, np.float64)
    total = float(counts.sum())
    denom = max(total, 1.0)
    mse = float((counts * np.asarray(out.mse, np.float64)).sum() / denom)
    real_sd = float(np.sqrt(
        (counts * np.square(np.asarray(out.real_stdev, np.float64))).sum()
        / denom
    ))
    pred_sd = float(np.sqrt(
        (counts * np.square(np.asarray(out.pred_stdev, np.float64))).sum()
        / denom
    ))
    preds = None
    if out.predictions is not None:
        tenant_preds = np.asarray(out.predictions)
        preds = np.zeros(tenant_preds.shape[1:], tenant_preds.dtype)
        rows_per = tenant_rows(batch, model.route_ids(batch), m)
        for i, rows in enumerate(rows_per):
            preds[rows] = tenant_preds[i][: rows.shape[0]]
    return StepOutput(
        predictions=preds,
        count=np.float32(total),
        mse=np.float32(mse),
        real_stdev=np.float32(real_sd),
        pred_stdev=np.float32(pred_sd),
    )


def split_tenant_output(out: StepOutput, num_tenants: int):
    """Host-side split of the ONE fetched ``[M, ...]`` StepOutput into M
    per-tenant StepOutputs (plain numpy views — no further host fetch)."""
    return [
        StepOutput(*(
            None if f is None else f[m] for f in out
        ))
        for m in range(num_tenants)
    ]


class TenantStackModel:
    """M stacked streaming-SGD learners with the single-model step surface
    the pipelines consume (``step``/``latest_weights``/``set_initial_weights``
    /``prepare``/``pack_for_wire``), so FetchPipeline, checkpoints, the
    divergence sentinel and the lockstep scheduler all work unchanged.

    ``step(batch)`` accepts an ORDINARY featurized host batch: it routes the
    rows (``tenant_route_keys`` → ``split_batch_tenants``), builds the
    stacked/coalesced tenant wire, and runs the one mapped jit program;
    the returned StepOutput carries ``[M]``-leading leaves (``[M, B]``
    predictions in per-tenant row order — ``route_ids`` re-derives the
    original-row permutation on the host). A pre-routed wire (a stacked
    batch from ``prepare_wire`` or a PackedBatch from ``pack_for_wire``)
    passes straight through — the pack happens once, at the model boundary,
    exactly like the single-tenant packed wire."""

    accepts_packed = True

    def __init__(
        self,
        num_tenants: int,
        num_text_features: int = 1000,
        num_iterations: int = 50,
        step_size: float = 0.1,
        mini_batch_fraction: float = 1.0,
        l2_reg: float = 0.0,
        convergence_tol: float = 0.001,
        dtype=jnp.float32,
        residual_fn: Callable | None = None,
        prediction_fn: Callable | None = None,
        round_predictions: bool = True,
        use_sparse: bool | None = None,
        use_gram: bool | None = None,
        gram_int8: bool | None = None,
        tenant_key: str = "hash",
        wire_pack: str = "stacked",
        wire_codec: str = "",
        mesh=None,
        step_sizes=None,
        l2_regs=None,
        mapping: str = "scan",
        quality: bool = False,
    ) -> None:
        if num_tenants < 1:
            raise ValueError(f"num_tenants must be >= 1, got {num_tenants}")
        if mapping not in ("scan", "vmap"):
            raise ValueError(f"mapping must be 'scan' or 'vmap', got {mapping!r}")
        if wire_pack not in ("stacked", "group"):
            raise ValueError(
                f"wire_pack must be 'stacked' or 'group', got {wire_pack!r}"
            )
        self.num_tenants = num_tenants
        self.num_text_features = num_text_features
        self.dtype = dtype
        self.tenant_key = tenant_key
        self.wire_pack = wire_pack
        # compressed units wire on the coalesced tenant wire (r15,
        # --wireCodec): the group pack digram-compresses each tenant
        # segment; "" / "off" = raw. Stacked wire ships raw by design
        # (the codec rides the packed one-buffer forms only).
        self.wire_codec = wire_codec
        self.mapping = mapping
        self.mesh = mesh
        # --modelWatch: the mapped step computes each tenant's quality
        # vector inside the one jit program — the stacked [M, Q] leaf rides
        # the existing ONE stacked fetch, so per-tenant quality is free
        self.quality = quality
        f_total = num_text_features + NUM_NUMBER_FEATURES

        # per-tenant hyperparams as MAPPED scalar leaves: they are consumed
        # only inside jnp arithmetic (eta = step/√i, the L2 pre-scale), so a
        # traced per-tenant scalar flows through the existing step builder
        # unchanged. Structural knobs (num_iterations, miniBatchFraction,
        # convergenceTol) stay shared — they shape the compiled program.
        def _vec(v, default):
            if v is None:
                return jnp.full((num_tenants,), default, dtype)
            v = jnp.asarray(v, dtype)
            if v.shape != (num_tenants,):
                raise ValueError(
                    f"per-tenant hyperparam needs shape ({num_tenants},), "
                    f"got {v.shape}"
                )
            return v

        self._hyper = {
            "step_size": _vec(step_sizes, step_size),
            "l2_reg": _vec(l2_regs, l2_reg),
        }

        def one(weights, hyper, batch):
            # build the EXISTING fused step with this tenant's (traced)
            # hyperparams closed over — the parity-critical semantics live
            # in models/sgd.py exactly once
            step = make_sgd_train_step(
                num_text_features=num_text_features,
                num_iterations=num_iterations,
                step_size=hyper["step_size"],
                mini_batch_fraction=mini_batch_fraction,
                l2_reg=hyper["l2_reg"],
                convergence_tol=convergence_tol,
                residual_fn=residual_fn,
                prediction_fn=prediction_fn,
                round_predictions=round_predictions,
                axis_name=self._data_axis,
                use_sparse=use_sparse,
                use_gram=use_gram,
                gram_int8=gram_int8,
                quality=quality,
            )
            return step(weights, batch)

        self._one = one
        self._weights = jnp.zeros((num_tenants, f_total), dtype)
        self._progs: dict = {}
        if mesh is not None:
            self._init_mesh(mesh)

    # -- mesh plumbing ------------------------------------------------------
    @property
    def _data_axis(self):
        return self.mesh.axis_names[0] if self.mesh is not None else None

    @property
    def _tenant_axis(self):
        """The mesh axis the TENANT dim shards over: the 'model' axis of a
        2D mesh (cross-process tenants), None on 1D (replicated)."""
        if self.mesh is not None and len(self.mesh.axis_names) > 1:
            return self.mesh.axis_names[1]
        return None

    def _init_mesh(self, mesh) -> None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        t_axis = self._tenant_axis
        self.num_data = mesh.shape[self._data_axis]
        if t_axis is not None:
            n_t = mesh.shape[t_axis]
            if self.num_tenants % n_t:
                raise ValueError(
                    f"{self.num_tenants} tenants not divisible by the "
                    f"mesh's {t_axis} axis ({n_t})"
                )
            w_spec = P(t_axis, None)
            self._weights = jax.device_put(
                np.asarray(self._weights), NamedSharding(mesh, w_spec)
            )
            self._hyper = jax.device_put(
                self._hyper,
                NamedSharding(mesh, P(t_axis)),
            )
            self._w_spec, self._h_spec = w_spec, P(t_axis)
            self._out_specs = StepOutput(
                predictions=P(t_axis, self._data_axis),
                count=P(t_axis), mse=P(t_axis),
                real_stdev=P(t_axis), pred_stdev=P(t_axis),
                # [M, Q]: tenant axis sharded like the other stacked leaves
                quality=P(t_axis) if self.quality else None,
            )
        else:
            self._w_spec, self._h_spec = P(), P()
            self._out_specs = StepOutput(
                predictions=P(None, self._data_axis),
                count=P(), mse=P(), real_stdev=P(), pred_stdev=P(),
                # [M, Q] psum-global over data, replicated like the scalars
                quality=P() if self.quality else None,
            )

    def _batch_spec(self, batch_cls):
        from jax.sharding import PartitionSpec as P

        from .sharding import _pspecs_for, _stacked

        t_axis = self._tenant_axis
        if batch_cls is PackedBatch:
            # the coalesced tenant wire is shard-major ([S, M, seg] flat):
            # P(data) hands each device its own M segments (1D mesh only —
            # the 2D tenant layout ships the stacked wire)
            return P(self._data_axis)
        spec = _stacked(_pspecs_for(batch_cls, self._data_axis))
        if t_axis is not None:
            # tenants over the model axis: replace the leading None
            spec = jax.tree_util.tree_map(
                lambda s: P(*((t_axis,) + tuple(s)[1:])),
                spec, is_leaf=lambda x: isinstance(x, P),
            )
        return spec

    # -- the one mapped program ---------------------------------------------
    def _mapped(self, weights, hyper, batch):
        if isinstance(batch, PackedBatch):
            # coalesced tenant wire (pack_ragged_group): rebuild the
            # stacked [M, ...] leaves in-program — zero-copy bitcasts
            batch = unpack_batch(batch.buffer, batch.layout)
        if self.mapping == "vmap":
            return jax.vmap(self._one)(weights, hyper, batch)
        # lax.map = scan of the single-tenant step with no carry: the SAME
        # program per tenant, hence bit-identical math (the parity law)
        return lax.map(lambda args: self._one(*args), (weights, hyper, batch))

    def _prog_for(self, batch_cls) -> Callable:
        fn = self._progs.get(batch_cls)
        if fn is None:
            if self.mesh is None:
                fn = jax.jit(self._mapped, donate_argnums=0)
            else:
                from ..utils import shard_map

                sharded = shard_map()(
                    self._mapped,
                    mesh=self.mesh,
                    in_specs=(
                        self._w_spec, self._h_spec,
                        self._batch_spec(batch_cls),
                    ),
                    out_specs=(self._w_spec, self._out_specs),
                )
                fn = jax.jit(sharded, donate_argnums=0)
            self._progs[batch_cls] = fn
        return fn

    # -- routing + wire ------------------------------------------------------
    def route_ids(self, batch) -> np.ndarray:
        """Per-row tenant ids for a host batch — deterministic, so delivery-
        side consumers (per-tenant stats, prediction re-ordering) recompute
        it instead of threading a permutation through the fetch pipeline."""
        return tenant_route_keys(batch, self.num_tenants, self.tenant_key)

    def split(self, batch):
        """Route + split into the M same-signature tenant batches."""
        return split_batch_tenants(
            batch, self.route_ids(batch), self.num_tenants
        )

    def _is_tenant_wire(self, batch) -> bool:
        if isinstance(batch, PackedBatch):
            return True
        mask = getattr(batch, "mask", None)
        return mask is not None and getattr(mask, "ndim", 1) == 2

    def prepare_wire(self, batch):
        """Host batch → the stacked/coalesced M-tenant wire (the K-batch
        group wire reused with K = M tenants). ``--wirePack group``
        coalesces the M ragged batches into ONE contiguous buffer (one
        main-thread put, uint16-delta offsets); ``stacked`` ships M
        per-field arrays. Bit-identical math either way (the superbatch
        wire law, tests/test_superwire.py)."""
        return self.prepare_wire_from_parts(self.split(batch))

    def prepare_wire_from_parts(self, parts):
        """The wire-layout half of ``prepare_wire`` for callers that route
        themselves (tests, custom routers): M same-signature per-tenant
        batches → the stacked/coalesced tenant wire."""
        if self.mesh is not None:
            # ragged parts shard-align to the data axis BEFORE stacking
            # (alignment is a flat-batch operation — the superbatch rule)
            parts = [self._prepare_part(p) for p in parts]
        if (
            self.wire_pack == "group"
            and isinstance(parts[0], RaggedUnitBatch)
            # the coalesced shard-major buffer has no tenant-axis layout;
            # the 2D (tenants-on-model-axis) plane ships the stacked wire
            and self._tenant_axis is None
        ):
            codec = self.wire_codec or None
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                pb = pack_ragged_group(parts, codec=codec)
                # the host buffer's arena lease rides to the dispatch
                # pipeline (retired on fetch delivery — apps/common.py)
                return PackedBatch(
                    jax.device_put(
                        pb.buffer,
                        NamedSharding(self.mesh, P(self._data_axis)),
                    ),
                    pb.layout,
                )._with_lease(pb._lease)
            return pack_ragged_group(parts, codec=codec)
        return stack_batches(parts)

    def _prepare_part(self, part):
        from ..features.batch import align_ragged_shards

        if (
            isinstance(part, RaggedUnitBatch)
            and part.num_shards != self.num_data
        ):
            return align_ragged_shards(part, self.num_data)
        return part

    # FetchPipeline's pack hook: the tenant wire IS the pack (one routed
    # wire per batch, built once at the model boundary)
    def pack_for_wire(self, batch):
        return self.prepare_wire(batch)

    # -- model surface -------------------------------------------------------
    def step(self, batch) -> StepOutput:
        wire = batch if self._is_tenant_wire(batch) else self.prepare_wire(batch)
        if self.mesh is not None and not isinstance(
            jax.tree_util.tree_leaves(wire)[0], jax.Array
        ):
            wire = self._place(wire)
        self._weights, out = self._prog_for(type(wire))(
            self._weights, self._hyper, wire
        )
        return out

    def _place(self, wire):
        from jax.sharding import NamedSharding, PartitionSpec as P

        if isinstance(wire, PackedBatch):
            return PackedBatch(
                jax.device_put(
                    wire.buffer, NamedSharding(self.mesh, P(self._data_axis))
                ),
                wire.layout,
            )
        if self._tenant_axis is None:
            # 1D mesh: tenants unsharded, rows over data — exactly the
            # stacked-superbatch placement shard_batch already implements
            from .sharding import shard_batch

            return shard_batch(wire, self.mesh)
        spec = self._batch_spec(type(wire))
        if isinstance(wire, RaggedUnitBatch):
            sharding = NamedSharding(self.mesh, spec)  # one prefix spec
            return RaggedUnitBatch(
                *(jax.device_put(a, sharding) for a in (
                    wire.units, wire.offsets, wire.numeric, wire.label,
                    wire.mask,
                )),
                row_len=wire.row_len, num_shards=wire.num_shards,
            )
        return type(wire)(*(
            jax.device_put(a, NamedSharding(self.mesh, s))
            for a, s in zip(
                wire,
                jax.tree_util.tree_leaves(
                    spec, is_leaf=lambda x: isinstance(x, P)
                ),
            )
        ))

    @staticmethod
    def _to_host(arr) -> np.ndarray:
        if (
            isinstance(arr, jax.Array)
            and not arr.is_fully_addressable
            and not arr.is_fully_replicated
        ):
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(arr, tiled=True)
            )
        return np.asarray(arr)

    @property
    def latest_weights(self) -> np.ndarray:
        """[M, F+4] — one checkpointable array for all tenants."""
        return self._to_host(self._weights)

    def tenant_weights(self, m: int) -> np.ndarray:
        return self.latest_weights[m]

    def set_initial_weights(self, weights) -> "TenantStackModel":
        """Accepts the stacked [M, F+4] state (checkpoint restore) or one
        flat [F+4] vector broadcast to every tenant (the sentinel's
        zeros-reset, and MLlib-style shared initial weights)."""
        weights = np.asarray(weights, dtype=self.dtype)
        if weights.ndim == 1:
            weights = np.broadcast_to(
                weights, (self.num_tenants,) + weights.shape
            ).copy()
        if weights.shape[0] != self.num_tenants:
            raise ValueError(
                f"stacked weights lead with {weights.shape[0]} tenants; "
                f"this plane has {self.num_tenants}"
            )
        if self.mesh is not None and self._tenant_axis is not None:
            from jax.sharding import NamedSharding

            sharding = NamedSharding(self.mesh, self._w_spec)
            self._weights = jax.make_array_from_callback(
                weights.shape, sharding, lambda idx: weights[idx]
            )
        else:
            self._weights = jnp.asarray(weights)
        return self

    def reset(self) -> "TenantStackModel":
        return self.set_initial_weights(
            np.zeros(
                (self.num_text_features + NUM_NUMBER_FEATURES,), np.float32
            )
        )

    @classmethod
    def from_conf(cls, conf, mesh=None, **overrides):
        kwargs = dict(
            num_tenants=int(getattr(conf, "tenants", 1) or 1),
            num_text_features=conf.numTextFeatures,
            num_iterations=conf.numIterations,
            step_size=conf.stepSize,
            mini_batch_fraction=conf.miniBatchFraction,
            l2_reg=conf.l2Reg,
            convergence_tol=conf.convergenceTol,
            dtype=jnp.dtype(conf.dtype),
            tenant_key=getattr(conf, "tenantKey", "hash"),
            wire_pack=(
                "group"
                if getattr(conf, "effective_wire_pack", lambda: "stacked")()
                == "group" and conf.effective_wire() == "ragged"
                else "stacked"
            ),
            wire_codec=(
                getattr(conf, "effective_wire_codec", lambda: "off")()
            ),
            mesh=mesh,
            quality=getattr(conf, "modelWatch", "off") == "on",
        )
        kwargs.update(overrides)
        return cls(**kwargs)

    def train_on(self, stream) -> None:
        stream.foreach_batch(lambda batch, _time: self.step(batch))


class MultiHostTenantModel:
    """App-level tenant fleet (r16, ISSUE 13 / PR 7 REMAINING b): the
    multi-tenant plane behind per-host sharded intake on a REAL process
    group — ``--tenants M`` + ``--coordinator`` was rejected before this.

    Topology: the 1D process-aligned ('data',) mesh the app-level
    multi-host flow already builds (tenant axis unsharded — every host
    holds the whole [M, F+4] stack, replicated like the single-model
    weights). Each host routes ITS OWN rows into the M-tenant split
    (deterministic key — identical routing on every host), stacks them
    locally, and assembles the global [M, B_global, ...] tenant wire with
    ``make_array_from_process_local_data`` on the row axis — the
    ``step_many`` stacked-wire assembly reused with K = M tenants, so no
    new wire form and no new collective. Stats come back [M]-stacked and
    psum-global; ONE pooled fetch per tick, exactly like single-host.

    The stacked wire is the only multi-host tenant wire (the coalesced
    group buffer has no tenant-axis layout across processes). The RAGGED
    tenant split (r20, lifting the padded-only rejection) needs every
    tenant part on every host to share ONE per-shard unit capacity before
    stacking — agreed by a single allgather-max of this host's max
    per-part need (the ``[need]`` widening template: the agree collective
    rides the same once-per-batch cadence the single-model ragged wire
    already pays, zero new collectives). The stacked assembly then mirrors
    ``MultiHostSGDModel.step_many``'s ragged branch: rows shard on axis 1
    under ``P(None, data)``, per-shard segments land on their devices, and
    the stacked wire ships raw (the codec rides the packed one-buffer
    forms only — same rule as the single-host stacked wire). Elastic
    membership (``--elastic on``) rebuilds this wrapper in place across
    epochs via ``rebuild``, the same contract as MultiHostSGDModel."""

    accepts_packed = False  # stacked tenant wire only across processes

    def __init__(self, inner: TenantStackModel, mesh, rebuilder=None):
        self.inner = inner
        self.mesh = mesh
        self.num_data = getattr(inner, "num_data", 1)
        self._lead = jax.process_index() == 0
        self._rebuilder = rebuilder

    # tenant-plane surface the delivery chain reads (apps/common)
    @property
    def num_tenants(self) -> int:
        return self.inner.num_tenants

    @property
    def tenant_key(self) -> str:
        return self.inner.tenant_key

    @property
    def wire_pack(self) -> str:
        return "stacked"

    def route_ids(self, batch) -> np.ndarray:
        return self.inner.route_ids(batch)

    def rebuild(self, mesh) -> "MultiHostTenantModel":
        """Elastic epoch change: fresh inner stack on the new mesh, in
        place (weights restored by the caller from the lead's broadcast
        checkpoint — the PR 4 path)."""
        if self._rebuilder is None:
            raise RuntimeError(
                "MultiHostTenantModel.rebuild needs the rebuilder closure "
                "(set by apps/common.build_model)"
            )
        self.inner = self._rebuilder(mesh)
        self.mesh = self.inner.mesh  # may be None on a 1-device epoch
        self.num_data = getattr(self.inner, "num_data", 1)
        self._lead = jax.process_index() == 0
        return self

    def _to_global_stacked(self, stacked):
        from jax.sharding import NamedSharding

        from .sharding import _pspecs_for, _stacked

        data_axis = self.mesh.axis_names[0]
        specs = _stacked(_pspecs_for(type(stacked), data_axis))

        def to_global(host_arr, spec):
            host_arr = np.asarray(host_arr)
            global_shape = (
                host_arr.shape[0],
                host_arr.shape[1] * jax.process_count(),
            ) + host_arr.shape[2:]
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec), host_arr, global_shape
            )

        return type(stacked)(*(
            to_global(a, s) for a, s in zip(stacked, specs)
        ))

    def _stack_ragged_parts(self, parts):
        """M ragged tenant parts → ONE [M]-stacked, LOCAL-shard-aligned
        ragged batch. Stacking needs every part to share one per-shard
        unit capacity, and the fleet needs every HOST to share it too:
        one allgather-max of this host's max per-part need agrees it
        (the ``[need]`` widening template — the same once-per-batch
        collective cadence as the single-model ragged wire). Units
        harmonize to uint16 first, the pre-codec multi-host schema rule
        (a uint8 host next to a uint16 host must not fork signatures)."""
        local_shards = max(1, self.num_data // jax.process_count())
        from ..features.batch import align_ragged_shards, ragged_shard_bucket

        parts = [
            p if p.units.dtype == np.uint16 else RaggedUnitBatch(
                np.asarray(p.units, np.uint16), p.offsets, p.numeric,
                p.label, p.mask, row_len=p.row_len, num_shards=p.num_shards,
            )
            for p in parts
        ]
        need = max(ragged_shard_bucket(p, local_shards) for p in parts)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            need = int(
                multihost_utils.process_allgather(
                    np.array([need], np.int64)
                ).max()
            )
        return stack_batches([
            align_ragged_shards(p, local_shards, unit_bucket=need)
            for p in parts
        ])

    def _to_global_ragged(self, stacked):
        """[M]-stacked local-shard ragged wire → the global tenant wire:
        every leaf assembles on the ROW axis (axis 1) under ``P(None,
        data)``, exactly ``MultiHostSGDModel.step_many``'s ragged branch
        with K = M tenants — each process contributes its local shards'
        segments and the data axis hands every device its own."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(
            self.mesh, P(None, self.mesh.axis_names[0])
        )

        def to_global(host_arr):
            host_arr = np.asarray(host_arr)
            global_shape = (
                host_arr.shape[0],
                host_arr.shape[1] * jax.process_count(),
            ) + host_arr.shape[2:]
            return jax.make_array_from_process_local_data(
                sharding, host_arr, global_shape
            )

        return RaggedUnitBatch(
            *(to_global(a) for a in (
                stacked.units, stacked.offsets, stacked.numeric,
                stacked.label, stacked.mask,
            )),
            row_len=stacked.row_len, num_shards=self.num_data,
        )

    def step(self, local_batch) -> StepOutput:
        """Route + split THIS host's rows, stack, assemble the global
        tenant wire on the row axis, and run the stacked program. Dispatch
        only — the host transfer lives in ``fetch_output`` (the r3 law:
        the main thread never blocks a transport round trip)."""
        parts = self.inner.split(local_batch)
        if isinstance(parts[0], RaggedUnitBatch):
            # ragged tenant wire (r20): shared-bucket aligned stack; the
            # 1-process degenerate epoch skips only the row-axis assembly
            # (the aligned stack IS the single-host placement input)
            stacked = self._stack_ragged_parts(parts)
            if jax.process_count() == 1:
                return self.inner.step(stacked)
            return self.inner.step(self._to_global_ragged(stacked))
        stacked = stack_batches(parts)
        if jax.process_count() == 1:
            # degenerate epoch (an elastic fleet shrunk to one host): the
            # inner plane's own placement path is the single-host truth
            return self.inner.step(stacked)
        return self.inner.step(self._to_global_stacked(stacked))

    def fetch_output(self, out) -> StepOutput:
        """[M]-stacked global stats for every host; the lead additionally
        localizes its own rows' [M, B_local] predictions (shards sorted by
        their ROW offset — axis 1 of the stacked output), so per-row
        telemetry stays host-local exactly like the single-model plane."""
        count, mse, real_stdev, pred_stdev, quality = jax.device_get(  # lawcheck: disable=TW002 -- fetch_output IS the counted seam: FetchPipeline installs it as _fetch, one pooled get per tick (the tenant-fleet form of MultiHostSGDModel.fetch_output)
            (out.count, out.mse, out.real_stdev, out.pred_stdev, out.quality)
        )
        preds = None
        if self._lead:
            p = out.predictions
            if p.is_fully_addressable:
                preds = np.asarray(p)
            else:
                shards = sorted(
                    p.addressable_shards,
                    key=lambda s: s.index[1].start or 0,
                )
                for s in shards:
                    s.data.copy_to_host_async()
                preds = np.concatenate(
                    [np.asarray(s.data) for s in shards], axis=1
                )
        return StepOutput(
            predictions=preds, count=count, mse=mse,
            real_stdev=real_stdev, pred_stdev=pred_stdev, quality=quality,
        )

    @property
    def latest_weights(self) -> np.ndarray:
        return self.inner.latest_weights

    def set_initial_weights(self, weights) -> "MultiHostTenantModel":
        self.inner.set_initial_weights(weights)
        return self
