"""Fate-isolated coordination-service host (r20, ISSUE 17).

The epoch coordination service used to live INSIDE the pid-0 member's
process. That made whoever hosted it the fleet's last single point of
failure at the TRANSPORT level: when that process hard-died, its service
socket closed and every surviving member's client error-poll thread
(``PollForError``) reacted with the ``client.h:80`` LOG(FATAL) — SIGABRT
within milliseconds, long before the app-level lockstep watchdog could
run the election (measured; doc/elastic_probe_notes.md probe 5). With
heartbeat detection disabled, the service's SOCKET is the only thing a
live client's poll thread can trip on — so the fix is fate isolation:
the service runs in this tiny standalone process, spawned by the epoch's
pid-0 member (``ElasticRuntime.form``), and survives any member's death.

Lifetime: the fleet's members cannot reap this process (its whole point
is outliving them), so it watches the membership BEACON port instead —
the one address that stays bound across elections (the winner re-binds
it within seconds of a lead death). Once the beacon has been unreachable
for ``linger_s`` straight (default ``TWTML_ELASTIC_SERVICE_LINGER_S``,
45 s — well past a worst-case election + rescue), the run is over and
this process exits. It must NOT exit sooner: abandoned epochs' clients
keep leaked poll threads pointed here (probe 4), and closing the socket
under them would FATAL every still-running member.

Only ``jaxlib`` is imported (no ``jax``, no backend init): the service
is pure coordination, it owns no devices.

Usage: python -m twtml_tpu.parallel.service_host <port> <nprocs> \
           <beacon_host> <beacon_port> [linger_s]
"""

from __future__ import annotations

import os
import socket
import sys
import time

# mirrors parallel/elastic.py: detection stays OFF — the app-level
# lockstep watchdog is the one death detector
_HEARTBEAT_INTERVAL_S = 10
_HEARTBEAT_DISABLED = 1_000_000

LINGER_ENV = "TWTML_ELASTIC_SERVICE_LINGER_S"
LINGER_DEFAULT_S = 45.0


def _beacon_up(host: str, port: int) -> bool:
    try:
        with socket.create_connection((host, port), timeout=0.5):
            return True
    except OSError:
        return False


def main(argv: "list[str] | None" = None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    port, nprocs = int(args[0]), int(args[1])
    beacon_host, beacon_port = args[2], int(args[3])
    linger_s = float(args[4]) if len(args) > 4 else float(
        os.environ.get(LINGER_ENV, "") or LINGER_DEFAULT_S
    )
    import jaxlib.xla_extension as _xe  # jaxlib only: no jax, no backend

    service = _xe.get_distributed_runtime_service(
        f"[::]:{port}", nprocs,
        heartbeat_interval=_HEARTBEAT_INTERVAL_S,
        max_missing_heartbeats=_HEARTBEAT_DISABLED,
    )
    last_ok = time.monotonic()
    while True:
        time.sleep(2.0)
        if _beacon_up(beacon_host, beacon_port):
            last_ok = time.monotonic()
        elif time.monotonic() - last_ok > linger_s:
            break
    del service  # nothing polls a finished fleet; plain teardown is safe
    os._exit(0)


if __name__ == "__main__":
    main()
