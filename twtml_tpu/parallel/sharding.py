"""Sharded training — shard_map + psum over the device mesh.

This is the TPU-native replacement for the reference's distributed runtime:
MLlib's per-iteration ``treeAggregate`` of gradients to the driver
(SURVEY.md §3.3) becomes an in-program ``psum`` over the mesh's ``data`` axis,
and the driver→executor weight broadcast disappears entirely — weights are
device-resident (replicated over ``data``, optionally sharded over ``model``).

Two layouts:

- **data-parallel** (model_axis=None): weights replicated, batch rows sharded;
  reuses the single-device fused step (models/sgd.py) with ``axis_name`` so
  gradient/stat reductions turn into ICI collectives. This is BASELINE
  config #5 (4-way sharded stream + gradient allreduce).
- **feature-sharded** (2D mesh): the hashed text-feature axis of the weights
  is sharded over ``model`` for numTextFeatures=2^18 (BASELINE config #4):
  each shard gathers/scatter-adds only tokens hashing into its slice, with a
  ``psum`` over ``model`` reassembling predictions — a sharded-embedding
  pattern, not a translation of any reference code (the reference caps at
  1000 dims in one JVM).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.backend import axis_size as _axis_size
from jax.sharding import NamedSharding, PartitionSpec as P

from ..features.batch import (
    NUM_NUMBER_FEATURES,
    FeatureBatch,
    PackedBatch,
    RaggedUnitBatch,
    UnitBatch,
    align_ragged_shards,
    pack_ragged_sharded,
    unpack_batch,
)
from ..models.base import StepOutput
from ..models.sgd import (
    dual_scale_and_alpha,
    make_sgd_train_step,
    run_dual_loop,
    sampling_key,
    sgd_inner_loop,
)
from ..ops.gram import add_numeric_block, fits_gram, text_gram
from ..ops.ragged import ragged_repad
from ..ops.sparse import sparse_grad_text, sparse_text_dot
from ..ops.stats import batch_stats
from ..ops.text_hash import hash_bigrams_device
from ..utils.rounding import jnp_round_half_up


def batch_pspecs(data_axis: str = "data") -> FeatureBatch:
    """PartitionSpecs sharding a FeatureBatch's rows across ``data``."""
    return FeatureBatch(
        token_idx=P(data_axis, None),
        token_val=P(data_axis, None),
        numeric=P(data_axis, None),
        label=P(data_axis),
        mask=P(data_axis),
    )


def unit_batch_pspecs(data_axis: str = "data") -> UnitBatch:
    """PartitionSpecs sharding a UnitBatch's rows across ``data`` (the
    on-device-featurization wire format, ops/text_hash.py)."""
    return UnitBatch(
        units=P(data_axis, None),
        length=P(data_axis),
        numeric=P(data_axis, None),
        label=P(data_axis),
        mask=P(data_axis),
    )


def _pspecs_for(batch_cls, data_axis: str):
    if batch_cls is RaggedUnitBatch:
        # one P(data) prefix-spec: every ragged leaf (units sub-buffers,
        # segment-relative offsets, rows) shards its leading dim — the
        # shard-aligned layout makes them all divisible by the data axis
        return P(data_axis)
    if batch_cls is PackedBatch:
        # the per-shard packed buffer (pack_ragged_sharded): S equal shard
        # segments, so P(data) hands each device exactly its rows' bytes
        return P(data_axis)
    return (
        unit_batch_pspecs(data_axis)
        if batch_cls is UnitBatch
        else batch_pspecs(data_axis)
    )


def _stacked(spec_tree):
    """Prepend an unsharded leading axis to every PartitionSpec — the specs
    for a superbatch ([K, ...] leaves, K scanned on-device)."""
    return jax.tree_util.tree_map(
        lambda s: P(*((None,) + tuple(s))),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_batch(batch: FeatureBatch | UnitBatch | RaggedUnitBatch, mesh):
    """Place a host batch onto the mesh with row sharding (explicit
    device_put so repeated steps don't re-infer layouts). Stacked
    superbatches ([K, ...] leaves — detected by the mask rank) shard their
    row axis the same way with K unsharded. A RaggedUnitBatch is
    shard-ALIGNED first (``align_ragged_shards`` — a host memcpy unless the
    featurizer already aligned it), after which every leaf row-shards over
    ``data`` like the padded wire; a STACKED ragged batch must already be
    aligned per batch (alignment is a flat-batch operation — the grouping
    path aligns before stacking, apps/common.py)."""
    data_axis = mesh.axis_names[0]
    if isinstance(batch, RaggedUnitBatch):
        num_data = mesh.shape[data_axis]
        stacked = batch.mask.ndim == 2
        if batch.num_shards != num_data:
            if stacked:
                raise ValueError(
                    "stacked ragged batches must be shard-aligned per "
                    "batch before stacking (model.prepare)"
                )
            batch = align_ragged_shards(batch, num_data)
        spec = P(None, data_axis) if stacked else P(data_axis)
        sharding = NamedSharding(mesh, spec)
        return RaggedUnitBatch(
            *(jax.device_put(a, sharding) for a in (
                batch.units, batch.offsets, batch.numeric, batch.label,
                batch.mask,
            )),
            row_len=batch.row_len,
            num_shards=batch.num_shards,
        )
    specs = _pspecs_for(type(batch), data_axis)
    if batch.mask.ndim == 2:  # stacked: [K, B] mask
        specs = _stacked(specs)
    return type(batch)(*(
        jax.device_put(arr, NamedSharding(mesh, spec))
        for arr, spec in zip(batch, specs)
    ))


def _make_feature_sharded_step(
    *,
    f_text: int,
    f_text_local: int,
    num_iterations: int,
    step_size: float,
    mini_batch_fraction: float,
    l2_reg: float,
    convergence_tol: float,
    residual_fn: Callable | None,
    prediction_fn: Callable | None,
    round_predictions: bool,
    data_axis: str,
    model_axis: str,
    use_gram: bool | None = None,
    gram_int8: bool | None = None,
):
    """Per-shard body for the 2D (data × model) mesh. Weights arrive as a
    {'text': [f_text_local], 'num': [4]} pytree; token indices are global and
    each shard contributes only the tokens landing in its slice.

    The inner loop runs in the Gram (dual) basis whenever it applies (f32
    weights, per-shard dense counts within HBM budget — ops/gram.py): one
    all-gather of the batch over ``data``, each shard's feature slice
    contributes its partial G row panel (psum over ``model``), one
    all-gather over ``data`` replicates G, and the [B]-sized dual loop runs
    replicated with ZERO per-iteration collectives — versus one predict
    psum over ``model`` plus one gradient psum over ``data`` per iteration
    (2·numIterations collectives/batch) in the scatter formulation. The
    write-back stays slice-local (this shard's rows × its feature slice)
    with one psum over ``data``."""
    residual_fn = residual_fn or (lambda raw, label: raw - label)
    prediction_fn = prediction_fn or (lambda raw: raw)

    def step(weights, batch: FeatureBatch | UnitBatch | RaggedUnitBatch):
        w_text, w_num = weights["text"], weights["num"]
        dtype = w_text.dtype
        if isinstance(batch, RaggedUnitBatch):
            # ragged wire, shard-local arrays: re-pad + fold on device
            # (ops/ragged.py), then hash like the padded units wire below
            buf, lens = ragged_repad(
                batch.units, batch.offsets, batch.row_len,
                batch.mask.shape[0],
            )
            batch = UnitBatch(buf, lens, batch.numeric, batch.label, batch.mask)
        mask = batch.mask.astype(dtype)
        labels = batch.label.astype(dtype)
        if isinstance(batch, UnitBatch):
            # on-device featurization: each data shard hashes its own rows'
            # code units to GLOBAL indices, then slices per model shard below
            g_idx, token_val = hash_bigrams_device(
                batch.units, batch.length, f_text, dtype
            )
        else:
            # compact wire dtype (batch.compact_tokens) → int32 index math
            g_idx = batch.token_idx.astype(jnp.int32)
            token_val = batch.token_val.astype(dtype)
        numeric = batch.numeric.astype(dtype)
        lo = lax.axis_index(model_axis) * f_text_local
        rel = g_idx - lo
        in_slice = ((rel >= 0) & (rel < f_text_local)).astype(dtype)
        rel = jnp.clip(rel, 0, f_text_local - 1)
        local_val = token_val * in_slice  # zero out tokens outside this slice

        def predict(w):
            part = sparse_text_dot(w["text"], rel, local_val)
            return lax.psum(part, model_axis) + numeric @ w["num"]

        # ---- predict + stats with pre-update weights --------------------
        raw = predict(weights)
        preds = prediction_fn(raw)
        if round_predictions:
            preds = jnp_round_half_up(preds)
        stats = batch_stats(labels, preds, mask, data_axis)

        # ---- Gram (dual) basis when it applies (see docstring) ----------
        b_local = mask.shape[0]
        b_global = b_local * _axis_size(data_axis)
        gram = (
            dtype == jnp.float32
            and fits_gram(b_global, f_text_local, num_iterations)
            if use_gram is None
            else use_gram
        )
        if gram:
            gather = lambda a: lax.all_gather(a, data_axis, axis=0, tiled=True)
            idx_g, val_g, num_g, lab_g, mask_g, u = map(
                gather, (g_idx, token_val, numeric, labels, mask, raw)
            )
            rel_g = idx_g - lo
            in_g = ((rel_g >= 0) & (rel_g < f_text_local)).astype(dtype)
            panel = text_gram(
                jnp.clip(rel_g, 0, f_text_local - 1),
                val_g * in_g,
                f_text_local,
                row_start=lax.axis_index(data_axis) * b_local,
                rows=b_local,
                int8_plane=gram_int8,
            )  # [B_local, B_global] partial over this feature slice
            g_mat = lax.all_gather(
                lax.psum(panel, model_axis), data_axis, axis=0, tiled=True
            )
            g_mat = add_numeric_block(g_mat, num_g, dtype)

            dual = run_dual_loop(
                u=u,
                g=g_mat,
                labels=lab_g,
                mask=mask_g,
                dtype=dtype,
                residual_fn=residual_fn,
                num_iterations=num_iterations,
                step_size=step_size,
                mini_batch_fraction=mini_batch_fraction,
                l2_reg=l2_reg,
                convergence_tol=convergence_tol,
                p_prev=lax.psum(jnp.sum(w_text * w_text), model_axis)
                + jnp.sum(w_num * w_num),
                vary_axis=data_axis,
            )
            # psum-mean of the (identical-everywhere) scale + psum of the
            # slice-local write-back: statically invariant over ``data``
            c, alpha_local = dual_scale_and_alpha(dual, data_axis, b_local)
            delta_text = lax.psum(
                sparse_grad_text(rel, local_val, alpha_local, f_text_local),
                data_axis,
            )
            w_final = {
                "text": w_text * c + delta_text,
                "num": w_num * c + lax.psum(numeric.T @ alpha_local, data_axis),
            }
            return w_final, StepOutput(predictions=preds, **stats)

        # ---- the shared MLlib iteration loop over the sharded pytree ----
        def grad_and_count(w, sel):
            residual = residual_fn(predict(w), labels) * sel
            g_text = lax.psum(
                sparse_grad_text(rel, local_val, residual, f_text_local), data_axis
            )
            g_num = lax.psum(residual @ numeric, data_axis)
            count = lax.psum(jnp.sum(sel), data_axis)
            return {"text": g_text, "num": g_num}, count

        def norm_sq(a, b):
            # text slices live on the model axis; num is replicated there
            return lax.psum(jnp.sum((a["text"] - b["text"]) ** 2), model_axis) + (
                jnp.sum((a["num"] - b["num"]) ** 2)
            )

        w_final = sgd_inner_loop(
            {"text": w_text, "num": w_num},
            num_iterations=num_iterations,
            step_size=step_size,
            mini_batch_fraction=mini_batch_fraction,
            l2_reg=l2_reg,
            convergence_tol=convergence_tol,
            mask=mask,
            sample_key=sampling_key(data_axis, mini_batch_fraction),
            grad_and_count=grad_and_count,
            norm_sq=norm_sq,
        )
        return w_final, StepOutput(predictions=preds, **stats)

    return step


class ParallelSGDModel:
    """Mesh-sharded streaming SGD learner with the same step surface as the
    single-device models (models/sgd.py StreamingSGDModel)."""

    def __init__(
        self,
        mesh,
        num_text_features: int = 1000,
        num_iterations: int = 50,
        step_size: float = 0.005,
        mini_batch_fraction: float = 1.0,
        l2_reg: float = 0.0,
        convergence_tol: float = 0.001,
        dtype=jnp.float32,
        residual_fn: Callable | None = None,
        prediction_fn: Callable | None = None,
        round_predictions: bool = True,
        use_sparse: bool | None = None,
        use_gram: bool | None = None,
        gram_int8: bool | None = None,
        quality: bool = False,
    ) -> None:
        self.mesh = mesh
        self.num_text_features = num_text_features
        self.dtype = dtype
        axes = mesh.axis_names
        self.data_axis = axes[0]
        self.model_axis = axes[1] if len(axes) > 1 else None
        self.num_data = mesh.shape[self.data_axis]
        out_pred_spec = P(self.data_axis)
        scalar = P()
        if quality and self.model_axis is not None:
            # the feature-sharded (2D) step has its own body below; its
            # weight norms would need model-axis psums the quality plane
            # doesn't wire yet — degrade loudly rather than mis-report
            from ..utils import get_logger

            get_logger("parallel.sharding").warning(
                "--modelWatch quality vector is not wired for the "
                "feature-sharded (2D model-axis) layout; disabling the "
                "in-step quality leaf for this model"
            )
            quality = False
        self.quality = quality

        if self.model_axis is None:
            step = make_sgd_train_step(
                num_text_features=num_text_features,
                num_iterations=num_iterations,
                step_size=step_size,
                mini_batch_fraction=mini_batch_fraction,
                l2_reg=l2_reg,
                convergence_tol=convergence_tol,
                residual_fn=residual_fn,
                prediction_fn=prediction_fn,
                round_predictions=round_predictions,
                axis_name=self.data_axis,
                use_sparse=use_sparse,
                use_gram=use_gram,
                gram_int8=gram_int8,
                quality=quality,
            )
            self._weights = jnp.zeros(
                (num_text_features + NUM_NUMBER_FEATURES,), dtype
            )
            w_spec = P()
        else:
            num_model = mesh.shape[self.model_axis]
            if num_text_features % num_model:
                raise ValueError(
                    f"numTextFeatures={num_text_features} not divisible by "
                    f"model-axis size {num_model}"
                )
            step = _make_feature_sharded_step(
                f_text=num_text_features,
                f_text_local=num_text_features // num_model,
                num_iterations=num_iterations,
                step_size=step_size,
                mini_batch_fraction=mini_batch_fraction,
                l2_reg=l2_reg,
                convergence_tol=convergence_tol,
                residual_fn=residual_fn,
                prediction_fn=prediction_fn,
                round_predictions=round_predictions,
                data_axis=self.data_axis,
                model_axis=self.model_axis,
                use_gram=use_gram,
                gram_int8=gram_int8,
            )
            self._weights = {
                "text": jax.device_put(
                    jnp.zeros((num_text_features,), dtype),
                    NamedSharding(mesh, P(self.model_axis)),
                ),
                "num": jnp.zeros((NUM_NUMBER_FEATURES,), dtype),
            }
            w_spec = {"text": P(self.model_axis), "num": P()}

        # the shard_map is built lazily per wire format (FeatureBatch and
        # UnitBatch differ in pytree structure, hence in in_specs); a stream
        # uses one format throughout, so this stays one compiled program
        self._step_body = step
        self._w_spec = w_spec
        self._out_specs = (
            w_spec,
            StepOutput(
                predictions=out_pred_spec,
                count=scalar,
                mse=scalar,
                real_stdev=scalar,
                pred_stdev=scalar,
                # the quality vector is psum-global (axis-invariant), hence
                # replicated like the scalar stats; None when the plane is
                # off keeps the spec tree structurally the HEAD tree
                quality=scalar if quality else None,
            ),
        )
        # compiled programs: keyed by batch class, plus (cls, 'scan')
        # for the superbatch variants
        self._sharded: dict[object, Callable] = {}

    def _step_for(self, batch_cls) -> Callable:
        fn = self._sharded.get(batch_cls)
        if fn is None:
            body = self._step_body
            if batch_cls is PackedBatch:
                # per-shard packed ragged wire: each device's local slice is
                # ONE shard segment; rebuild the shard-local batch in-program
                # (zero-copy bitcasts) and run the ordinary per-shard body
                def body(weights, pb, _inner=self._step_body):
                    return _inner(
                        weights, unpack_batch(pb.buffer, pb.layout)
                    )

            from ..utils import shard_map

            sharded = shard_map()(
                body,
                mesh=self.mesh,
                in_specs=(self._w_spec, _pspecs_for(batch_cls, self.data_axis)),
                out_specs=self._out_specs,
            )
            fn = jax.jit(sharded, donate_argnums=0)
            self._sharded[batch_cls] = fn
        return fn

    def _scan_for(self, batch_cls) -> Callable:
        """The superbatch program: lax.scan of the per-shard step body over a
        stacked batch ([K, ...] leaves; K unsharded, rows sharded as usual).
        Same math as K sequential steps — the scan carries the weights
        through the identical body (mirrors StreamingSGDModel.step_many).

        A PackedBatch here is the COALESCED group wire
        (``pack_ragged_group``: one shard-major buffer whose local slice
        holds this shard's K segments): the body unpacks the slice into the
        stacked shard-local batch in-program — zero-copy bitcasts plus the
        narrow-offset cumsum — and runs the identical scan."""
        key = (batch_cls, "scan")
        fn = self._sharded.get(key)
        if fn is None:
            body = self._step_body
            if batch_cls is PackedBatch:
                def scanned(weights, pb, _inner=body):
                    return lax.scan(
                        _inner, weights, unpack_batch(pb.buffer, pb.layout)
                    )

                in_spec = _pspecs_for(PackedBatch, self.data_axis)
            else:
                def scanned(weights, stacked_batch):
                    return lax.scan(body, weights, stacked_batch)

                in_spec = _stacked(_pspecs_for(batch_cls, self.data_axis))

            from ..utils import shard_map

            sharded = shard_map()(
                scanned,
                mesh=self.mesh,
                in_specs=(self._w_spec, in_spec),
                out_specs=(self._out_specs[0], _stacked(self._out_specs[1])),
            )
            fn = jax.jit(sharded, donate_argnums=0)
            self._sharded[key] = fn
        return fn

    @classmethod
    def from_conf(cls, conf, mesh, **overrides):
        kwargs = dict(
            num_text_features=conf.numTextFeatures,
            num_iterations=conf.numIterations,
            step_size=conf.stepSize,
            mini_batch_fraction=conf.miniBatchFraction,
            l2_reg=conf.l2Reg,
            convergence_tol=conf.convergenceTol,
            dtype=jnp.dtype(conf.dtype),
            quality=getattr(conf, "modelWatch", "off") == "on",
        )
        kwargs.update(overrides)
        return cls(mesh, **kwargs)

    @staticmethod
    def _to_host(arr) -> np.ndarray:
        """Global array → host numpy, gathering across processes when this
        process doesn't address every shard (a multi-host mesh whose model
        axis crosses process boundaries) — required for checkpointing and
        telemetry of feature-sharded weights on pods."""
        if (
            isinstance(arr, jax.Array)
            and not arr.is_fully_addressable
            and not arr.is_fully_replicated  # replicated: local copy suffices
        ):
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
        return np.asarray(arr)

    @property
    def latest_weights(self) -> np.ndarray:
        if isinstance(self._weights, dict):
            return np.concatenate(
                [self._to_host(self._weights["text"]),
                 self._to_host(self._weights["num"])]
            )
        return self._to_host(self._weights)

    def set_initial_weights(self, weights) -> "ParallelSGDModel":
        weights = np.asarray(weights, dtype=self.dtype)
        if isinstance(self._weights, dict):
            ft = self.num_text_features
            text = weights[:ft]
            sharding = NamedSharding(self.mesh, P(self.model_axis))
            # make_array_from_callback, not device_put: checkpoint restore
            # must also work when the model axis spans processes and this
            # process does not address every shard (the allgather mirror of
            # _to_host) — each process materializes only its local slices
            self._weights = {
                "text": jax.make_array_from_callback(
                    text.shape, sharding, lambda idx: text[idx]
                ),
                "num": jnp.asarray(weights[ft:]),
            }
        else:
            self._weights = jnp.asarray(weights)
        return self

    def _check_rows(self, rows: int) -> None:
        if rows % self.num_data:
            raise ValueError(
                f"batch rows {rows} not divisible by data shards "
                f"{self.num_data}; set --batchBucket to a multiple of the "
                f"mesh's data axis"
            )

    # the shard-aligned ragged wire also ships PACKED — one buffer laid out
    # per shard (pack_ragged_sharded); the app-side pack opt-in keys off
    # this capability (apps/common.py)
    accepts_packed = True
    # compressed units wire (r15, --wireCodec): set by the app driver when
    # the codec is effective — the mesh packs below compress each shard
    # segment into a shared bucket (single-process mesh: this process
    # picks the bucket freely; the MULTI-HOST model keeps the raw wire —
    # a cross-host agreed compressed bucket would need a new collective)
    wire_codec = ""

    def prepare(self, batch):
        """Host-side shard alignment WITHOUT device placement — the
        grouping paths (SuperBatcher) call this per batch so shape
        signatures and stacking see the final shard-aligned layout (a
        stacked batch cannot be re-aligned)."""
        if (
            isinstance(batch, RaggedUnitBatch)
            and batch.num_shards != self.num_data
        ):
            return align_ragged_shards(batch, self.num_data)
        return batch

    def pack_for_wire(self, batch) -> PackedBatch:
        """The mesh form of the one-buffer ragged wire: shard-align, then
        pack per shard and place with row sharding (each device receives
        exactly its shard segment's bytes)."""
        if not isinstance(batch, RaggedUnitBatch):
            raise TypeError(
                "pack_for_wire is the ragged wire's mesh pack; padded "
                "batches shard as plain arrays"
            )
        pb = pack_ragged_sharded(
            self.prepare(batch), codec=self.wire_codec or None
        )
        # the host buffer's arena lease rides to the dispatch pipeline,
        # which retires it once the step's fetch delivers (apps/common.py)
        return PackedBatch(
            jax.device_put(
                pb.buffer, NamedSharding(self.mesh, P(self.data_axis))
            ),
            pb.layout,
        )._with_lease(pb._lease)

    def pack_group_for_wire(self, batches) -> PackedBatch:
        """The mesh form of the COALESCED superbatch wire (Lean wire v2):
        shard-align each of the K batches, pack them into ONE shard-major
        buffer (``pack_ragged_group``) and place it with row sharding —
        one main-thread put whose P(data) slice hands every device its own
        K segments; ``step_many`` consumes it via the scanned unpack."""
        from ..features.batch import pack_ragged_group

        pb = pack_ragged_group(
            [self.prepare(b) for b in batches], codec=self.wire_codec or None
        )
        return PackedBatch(
            jax.device_put(
                pb.buffer, NamedSharding(self.mesh, P(self.data_axis))
            ),
            pb.layout,
        )._with_lease(pb._lease)

    def _packed_rows(self, pb: PackedBatch, group: bool = False) -> int:
        """Global row count recorded in a RaggedShardSegments (or, for the
        coalesced superbatch wire, RaggedGroupSegments) layout."""
        want = "RaggedGroupSegments" if group else "RaggedShardSegments"
        if pb.layout[0] != want:
            raise ValueError(
                "mesh models take the per-shard packed layout "
                f"({'pack_group_for_wire' if group else 'pack_for_wire'}), "
                "not the flat pack_batch buffer"
            )
        s = pb.layout[2][1]
        if s != self.num_data:
            raise ValueError(
                f"packed buffer is laid out for {s} shards; this mesh's "
                f"data axis is {self.num_data}"
            )
        return pb.layout[1][4][0][0] * s  # per-shard mask rows × shards

    def step(
        self, batch: FeatureBatch | UnitBatch | RaggedUnitBatch | PackedBatch
    ) -> StepOutput:
        if isinstance(batch, PackedBatch):
            self._check_rows(self._packed_rows(batch))
            if not isinstance(batch.buffer, jax.Array):
                batch = PackedBatch(
                    jax.device_put(
                        batch.buffer,
                        NamedSharding(self.mesh, P(self.data_axis)),
                    ),
                    batch.layout,
                )
        else:
            self._check_rows(batch.mask.shape[0])
            if (
                isinstance(batch, RaggedUnitBatch)
                and batch.num_shards != self.num_data
            ):
                # host ragged batch straight from a featurizer: re-lay into
                # per-shard segments + place (a no-op for pre-aligned
                # batches, e.g. the multi-host global assembly)
                batch = shard_batch(batch, self.mesh)
        self._weights, out = self._step_for(type(batch))(self._weights, batch)
        return out

    def step_many(
        self, stacked: FeatureBatch | UnitBatch | RaggedUnitBatch | PackedBatch
    ) -> StepOutput:
        """K micro-batch steps as one dispatch over the mesh (superbatch:
        ``features.batch.stack_batches``); per-batch stats return along
        axis 0. Stacked ragged batches must be shard-aligned per batch
        (``prepare`` before stacking) and are placed explicitly; already-
        global arrays (multi-host assembly) pass through. A PackedBatch is
        the coalesced group wire (``pack_group_for_wire``) — one buffer,
        unpacked inside the scanned program. See ``_scan_for``."""
        if isinstance(stacked, PackedBatch):
            self._check_rows(self._packed_rows(stacked, group=True))
            if not isinstance(stacked.buffer, jax.Array):
                stacked = PackedBatch(
                    jax.device_put(
                        stacked.buffer,
                        NamedSharding(self.mesh, P(self.data_axis)),
                    ),
                    stacked.layout,
                )
            self._weights, outs = self._scan_for(PackedBatch)(
                self._weights, stacked
            )
            return outs
        self._check_rows(stacked.mask.shape[1])
        if isinstance(stacked, RaggedUnitBatch) and not isinstance(
            stacked.units, jax.Array
        ):
            stacked = shard_batch(stacked, self.mesh)
        self._weights, outs = self._scan_for(type(stacked))(
            self._weights, stacked
        )
        return outs

    def train_on(self, stream) -> None:
        stream.foreach_batch(lambda batch, _time: self.step(batch))
