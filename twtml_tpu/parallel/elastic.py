"""Elastic process-group runtime: form, shrink, and re-grow a
jax.distributed gloo group IN-PROCESS (``--elastic on``).

The reference inherited Spark 1.6.1's executor-loss recovery for free
(SURVEY §1): a lost executor degraded capacity, it never killed the job.
Our lockstep fleet had the opposite failure mode — every peer loss funneled
into ``ssc.request_abort`` — because ``jax.distributed`` has no membership
concept: the group is its launch topology forever, and its coordination
service's default reaction to a dead task is to TERMINATE every survivor
(``client.h:80`` LOG(FATAL) on the error broadcast, measured in this PR's
probe runs — doc/elastic_probe_notes.md records every observed failure
mode these deviations dodge).

This module makes the group a sequence of EPOCHS instead. Per epoch it owns
the jaxlib distributed client/service directly (not
``jax.distributed.initialize``) with four deliberate deviations, each
forced by a measured failure mode of the stock lifecycle:

- **dead-task detection is disabled at the transport** (service
  ``max_missing_heartbeats`` effectively infinite): the stock service
  broadcasts an error when a task misses heartbeats, and the pybind caster
  for a Python ``missed_heartbeat_callback`` in this jaxlib throws
  ``std::bad_cast`` (process abort) — so the APP-level lockstep watchdog
  (``TWTML_LOCKSTEP_TIMEOUT_S``) is the one death detector, exactly the
  seam the repo already trusts;
- **no shutdown barrier, ever, mid-run** (``shutdown_on_destruction=False``
  + ``abandon()`` instead of ``client.shutdown()``): the stock shutdown
  barrier with a dead peer LOG(FATAL)s the survivor. Abandoned epoch
  objects go to a process-lifetime graveyard — a few leaked threads and one
  bound port per epoch, bounded by churn count;
- **hard exit once any epoch was abandoned** (``finalize_exit``): an
  abandoned client's leaked error-poll thread LOG(FATAL)s the process the
  moment its service's socket closes during interpreter teardown (probe 4),
  so an elastic process must leave via ``os._exit`` after flushing — the
  same discipline tests/distributed_worker.py's peer_kill mode already
  uses for exactly this reason;
- **the coordination service never shares a process with a member**
  (r20, ``parallel/service_host.py``): a member-hosted service socket
  closes with its host, and every LIVE client's error-poll thread answers
  ``Socket closed`` with the same ``client.h:80`` LOG(FATAL) within
  milliseconds — faster than any watchdog, which made the service owner
  the fleet's last single point of failure (probe 5). Each epoch's pid-0
  member SPAWNS the service as a detached jaxlib-only subprocess that
  outlives every member and self-reaps once the membership beacon has
  been gone past the linger window (``TWTML_ELASTIC_SERVICE_LINGER_S``).

Epoch e's coordinator listens on ``base_port + 2 + e`` (base_port is the
``--master twtml://host:port`` port; +1 is the membership beacon); every
member derives it locally, so re-formation needs no negotiation beyond the
agreed epoch number and member set. Backend re-creation clears the
xla_bridge backend table AND its lru-cached topology readers
(``process_count``/``local_devices``) — stale caches were the first probe's
silent wrong-world bug.

The **beacon** is the lead's out-of-band membership channel: a tiny
host-side JSON-over-TCP listener (NOT a collective — the per-tick law is
untouched) used only when the in-band flag row cannot work: wedge reports
after a peer death (the dead peer can never ack in-band), join requests
from parked/restarted hosts, and plan polling while a host is outside the
group. Healthy ticks never touch it.

**Lead election (r20, ISSUE 17)**: the lead is no longer special. The
beacon PORT is the election lock — exactly one process can bind
``base + 1``, and the OS arbitrates the race atomically. A dead lead's
socket closes with it (``os._exit`` releases the fd), so survivors whose
wedge reports hit connection-refused know the beacon is ORPHANED (a
merely-paused lead's beacon thread still answers — pause never triggers
an election) and run the successor rule: candidates rank by uid in the
committed view, each waits rank × stagger while probing, then tries the
bind — so the lowest LIVE uid wins deterministically and every loser
observes the winner's beacon instead. The winner adopts ``lead_uid``,
publishes the rescue plan, and restores fleet state from its own
verified checkpoint (every elastic host shadow-saves — the
any-host-can-restore discipline, apps/common.AppCheckpoint). Because
the successor is the lowest live uid, it is also pid 0 of the epoch it
forms — service spawner, broadcast authority, and beacon owner stay one
host by construction (the service itself runs fate-isolated in its own
subprocess, so no lead's death ever closes a live epoch's socket). Leadership is STICKY thereafter: a rejoining
ex-lead is admitted as a follower (demotion is just "your uid is no
longer the elected lead's"), so ``lead_uid`` only moves at elections.

Reachability note: election assumes the beacon/coordinator ``host:port``
space stays bindable wherever a lead lands — true for the virtual
(single-machine) fleets the proof harness runs, or for real fleets
fronted by a shared address (VIP/DNS). A lead pinned to one machine's
address keeps the PR 13 behavior: its death is unrecoverable.
"""

from __future__ import annotations

import gc
import json
import os
import socket
import threading
import time

from ..utils import get_logger

log = get_logger("parallel.elastic")

# transport-level heartbeat detection is DISABLED (the app watchdog owns
# death detection); the interval still paces the agent's liveness RPCs
_HEARTBEAT_INTERVAL_S = 10
_HEARTBEAT_DISABLED = 1_000_000

# beacon request cap: one JSON line per connection, bounded
_BEACON_MAX_BYTES = 65536

BEACON_OFFSET = 1      # beacon port = base + 1
EPOCH_PORT_OFFSET = 2  # epoch e coordinator port = base + 2 + e

INIT_TIMEOUT_ENV = "TWTML_ELASTIC_INIT_TIMEOUT_S"
INIT_TIMEOUT_DEFAULT_S = 60.0


def _init_timeout_s() -> int:
    return int(float(
        os.environ.get(INIT_TIMEOUT_ENV, "") or INIT_TIMEOUT_DEFAULT_S
    ))


def uids_from_mask(mask: int) -> "list[int]":
    """Member uids encoded in a view bitmask, ascending (uid = bit index).
    Uids are the ORIGINAL launch process ids — stable across epochs, which
    is what makes the mask meaningful on every host."""
    out = []
    bit = 0
    m = int(mask)
    while m:
        if m & 1:
            out.append(bit)
        m >>= 1
        bit += 1
    return out


def mask_from_uids(uids) -> int:
    mask = 0
    for u in uids:
        if not 0 <= int(u) < 52:
            # the mask rides a float64 flag column; int-exactness ends at
            # 2^53, so 52 hosts is the hard fleet ceiling of this encoding
            raise ValueError(f"elastic member uid {u} out of range [0, 52)")
        mask |= 1 << int(u)
    return mask


class BeaconServer:
    """The lead's membership side-channel: JSON-over-TCP, one request per
    connection, answered from a lock-protected state dict the membership
    plane updates. Runs on a daemon thread; never touches jax."""

    def __init__(self, port: int, lead_uid: int = 0):
        self.port = port
        self._lock = threading.Lock()
        self._state: dict = {
            "state": "forming", "epoch": 0, "members": [], "plan": None,
            "lead_uid": int(lead_uid),
        }
        self._joins: "dict[int, float]" = {}     # uid -> monotonic seen
        self._wedged: "dict[int, int]" = {}      # uid -> epoch reported
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(16)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="twtml-elastic-beacon", daemon=True
        )
        self._thread.start()
        log.info("elastic membership beacon listening on :%d", port)

    # -- state the membership plane publishes --------------------------------

    def publish(self, state: str, epoch: int, members: "list[int]") -> None:
        with self._lock:
            self._state["state"] = state
            self._state["epoch"] = int(epoch)
            self._state["members"] = [int(u) for u in members]

    def publish_plan(self, plan: "dict | None") -> None:
        """The committed next-epoch plan ({epoch, members}) parked/wedged
        hosts poll for; None clears it once the epoch is live. Plans carry
        the owner's ``lead_uid`` so followers that resolve a plan through a
        HANDED-OFF beacon adopt the elected lead in the same poll."""
        with self._lock:
            if plan is not None:
                plan = dict(plan)
                plan.setdefault("lead_uid", self._state["lead_uid"])
            self._state["plan"] = plan

    def fresh_joins(self, max_age_s: float) -> "list[int]":
        """Uids with a join request newer than ``max_age_s`` — admission
        only proposes FRESH joiners (they re-send per poll), because the
        new epoch's formation blocks until every admitted member connects
        and a no-show joiner would wedge it."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                u for u, t in self._joins.items() if now - t <= max_age_s
            )

    def wedge_reports(self, epoch: int) -> "list[int]":
        """Uids that reported a wedged collective at ``epoch``."""
        with self._lock:
            return sorted(
                u for u, e in self._wedged.items() if e == int(epoch)
            )

    def clear_wedges(self) -> None:
        with self._lock:
            self._wedged.clear()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # -- wire ----------------------------------------------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # closed
            try:
                conn.settimeout(2.0)
                data = b""
                while b"\n" not in data and len(data) < _BEACON_MAX_BYTES:
                    chunk = conn.recv(4096)
                    if not chunk:
                        break
                    data += chunk
                req = json.loads(data.decode("utf-8").strip() or "{}")
                resp = self._handle(req)
                conn.sendall((json.dumps(resp) + "\n").encode("utf-8"))
            except Exception:
                log.debug("beacon request failed", exc_info=True)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _handle(self, req: dict) -> dict:
        op = req.get("op", "")
        uid = int(req.get("uid", -1))
        with self._lock:
            st = dict(self._state)
            if op == "hello":
                return {
                    "state": st["state"], "epoch": st["epoch"],
                    "members": st["members"],
                    "member": uid in st["members"],
                    "plan": st["plan"], "lead_uid": st["lead_uid"],
                }
            if op == "join":
                self._joins[uid] = time.monotonic()
                return {
                    "queued": True, "epoch": st["epoch"],
                    "lead_uid": st["lead_uid"],
                }
            if op == "wedged":
                self._wedged[uid] = int(req.get("epoch", -1))
                return {"ok": True, "plan": st["plan"],
                        "lead_uid": st["lead_uid"]}
            if op == "plan":
                return {"plan": st["plan"], "epoch": st["epoch"],
                        "lead_uid": st["lead_uid"]}
        return {"error": f"unknown op {op!r}"}


class BeaconClient:
    """Follower/joiner side of the beacon: short-lived connections, every
    failure surfaced as None (the caller owns retry/abort policy)."""

    def __init__(self, host: str, port: int, timeout_s: float = 3.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s

    def request(self, op: str, uid: int, **kw) -> "dict | None":
        try:
            with socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s
            ) as conn:
                payload = dict(op=op, uid=uid, **kw)
                conn.sendall((json.dumps(payload) + "\n").encode("utf-8"))
                conn.settimeout(self.timeout_s)
                data = b""
                while b"\n" not in data and len(data) < _BEACON_MAX_BYTES:
                    chunk = conn.recv(4096)
                    if not chunk:
                        break
                    data += chunk
                return json.loads(data.decode("utf-8").strip() or "null")
        except (OSError, ValueError) as exc:
            log.debug("beacon %s failed: %s", op, exc)
            return None


def probe_port(host: str, port: int, timeout_s: float = 0.5) -> bool:
    """Plain TCP reachability probe. A joiner MUST probe before
    ``client.connect()``: a connect whose coordinator never comes up dies
    by LOG(FATAL) (DEADLINE_EXCEEDED on RegisterTask), not by exception —
    measured in this PR's probes."""
    try:
        with socket.create_connection((host, port), timeout=timeout_s):
            return True
    except OSError:
        return False


class ElasticRuntime:
    """Owns the per-epoch jax.distributed lifecycle for one process.

    ``uid`` is this host's ORIGINAL process id (stable across epochs).
    ``lead_uid`` is the CURRENT lead's uid — uid 0 at launch, then sticky
    across epochs until an election moves it (module docstring). A
    restarted ex-lead finds the beacon port taken by its successor, keeps
    ``beacon=None``, and rejoins through the follower parking path —
    demotion is just losing the bind."""

    def __init__(self, host: str, base_port: int, uid: int):
        self.host = host
        self.base_port = int(base_port)
        self.uid = int(uid)
        self.epoch = -1
        self.members: "list[int]" = []
        self.reformed_ever = False
        # True when this process joined a LIVE run (a restarted host
        # admitted mid-flight): replay-index intake shards must then park
        # as standby (apps/common._rebalance_intake's rejoin rule applies
        # from the first batch, not only at later reforms)
        self.joined_late = False
        # abandoned epochs' client/service objects: destructing them risks
        # the error-poll LOG(FATAL) (see module docstring) — they leak for
        # the process lifetime, and finalize_exit skips teardown entirely
        self._graveyard: list = []
        # fate-isolated coordination-service subprocesses this host
        # spawned (parallel/service_host.py) — kept only for diagnostics;
        # they self-reap off the beacon's liveness, never via this list
        self._service_hosts: list = []
        self.lead_uid = 0
        self.beacon: "BeaconServer | None" = None
        if self.uid == 0:
            # launch-lead bind is a TRY: a restarted ex-lead races the
            # elected successor for this port and must lose gracefully
            # (beacon stays None → _init_elastic routes it through the
            # follower hello/park path and it adopts the winner's lead_uid)
            try:
                self.beacon = BeaconServer(self.beacon_port, lead_uid=0)
            except OSError:
                log.warning(
                    "beacon port :%d already owned — uid 0 restarting into "
                    "a fleet led by an elected successor; joining as a "
                    "follower", self.beacon_port,
                )

    @property
    def is_lead(self) -> bool:
        return self.uid == self.lead_uid

    def set_lead(self, uid: int) -> None:
        """Adopt ``uid`` as the current lead (from a beacon hello/plan, or
        self after winning an election)."""
        self.lead_uid = int(uid)

    def take_over_beacon(self) -> bool:
        """Attempt the election bind race: bind the beacon port and become
        the lead. EXACTLY ONE caller can win (the OS arbitrates the bind);
        a loser returns False and must re-resolve through the winner's
        beacon. Winner adopts its own uid as ``lead_uid``."""
        if self.beacon is not None:
            return True
        try:
            self.beacon = BeaconServer(self.beacon_port, lead_uid=self.uid)
        except OSError as exc:
            log.info(
                "beacon takeover lost (:%d already bound: %s) — another "
                "survivor won the election", self.beacon_port, exc,
            )
            return False
        self.lead_uid = self.uid
        return True

    # -- address arithmetic --------------------------------------------------

    @property
    def beacon_port(self) -> int:
        return self.base_port + BEACON_OFFSET

    def port_for(self, epoch: int) -> int:
        return self.base_port + EPOCH_PORT_OFFSET + int(epoch)

    def beacon_client(self) -> BeaconClient:
        return BeaconClient(self.host, self.beacon_port)

    @property
    def pid(self) -> int:
        """This host's dense jax process id in the CURRENT epoch (index of
        its uid in the sorted member list)."""
        return self.members.index(self.uid)

    # -- epoch lifecycle -----------------------------------------------------

    def _spawn_service_host(self, port: int, nprocs: int) -> None:
        """Launch epoch ``port``'s coordination service in a FATE-ISOLATED
        subprocess (parallel/service_host.py): the service socket must
        survive any member's death — including this spawner's — or every
        survivor's client error-poll thread LOG(FATAL)s the instant it
        closes (probe 5, doc/elastic_probe_notes.md). Detached session,
        all stdio on /dev/null: the host must not hold a pipe a test
        harness waits on. It self-reaps once the beacon has been gone for
        the linger window (the run is over)."""
        import subprocess
        import sys

        proc = subprocess.Popen(
            [sys.executable, "-m", "twtml_tpu.parallel.service_host",
             str(port), str(nprocs), self.host, str(self.beacon_port)],
            stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, start_new_session=True,
        )
        self._service_hosts.append(proc)
        log.info(
            "elastic coordination service for :%d hosted fate-isolated "
            "(pid %d, %d task(s))", port, proc.pid, nprocs,
        )

    def form(self, epoch: int, members: "list[int]") -> None:
        """Join epoch ``epoch`` with the given member uids (sorted; this
        host must be one of them). Spawns the epoch's fate-isolated
        coordination service from the pid-0 slot, creates a
        detection-disabled client everywhere, and leaves the xla_bridge
        caches cleared so the next jax call builds the new world's
        backend."""
        from jax._src import distributed as _dist
        from jax._src.lib import xla_extension as _xe

        members = sorted(int(u) for u in members)
        if self.uid not in members:
            raise ValueError(
                f"host uid {self.uid} is not in epoch {epoch}'s member set "
                f"{members}"
            )
        pid = members.index(self.uid)
        nprocs = len(members)
        port = self.port_for(epoch)
        coordinator = f"{self.host}:{port}"
        state = _dist.global_state
        if pid == 0:
            self._spawn_service_host(port, nprocs)
        client = _xe.get_distributed_runtime_client(
            coordinator, pid,
            init_timeout=_init_timeout_s(),
            shutdown_timeout=5,
            heartbeat_interval=_HEARTBEAT_INTERVAL_S,
            max_missing_heartbeats=_HEARTBEAT_DISABLED,
            shutdown_on_destruction=False,
            use_compression=True,
        )
        client.connect()
        state.client = client
        state.process_id = pid
        state.num_processes = nprocs
        state.coordinator_address = coordinator
        self.epoch = int(epoch)
        self.members = members
        if self.beacon is not None:
            self.beacon.publish("live", self.epoch, members)
        log.info(
            "elastic epoch %d formed: %d host(s) %s, this host uid=%d "
            "pid=%d, coordinator %s",
            epoch, nprocs, members, self.uid, pid, coordinator,
        )

    def abandon(self) -> None:
        """Leave the current epoch WITHOUT the shutdown barrier (the
        barrier with a dead/absent peer LOG(FATAL)s — module docstring).
        The epoch's client/service objects are kept alive in the graveyard
        forever; jax's backend table and cached topology readers are
        cleared so the next epoch builds a fresh gloo world."""
        import jax
        from jax._src import distributed as _dist
        from jax._src import xla_bridge

        state = _dist.global_state
        if state.client is not None or state.service is not None:
            self._graveyard.append((state.client, state.service))
        state.client = None
        state.service = None
        state.process_id = 0
        state.num_processes = 1
        state.coordinator_address = None
        self.reformed_ever = True
        jax.clear_caches()
        xla_bridge.process_count.cache_clear()
        xla_bridge.local_devices.cache_clear()
        xla_bridge._clear_backends()
        gc.collect()
        log.info(
            "elastic epoch %d abandoned (graveyard now %d epoch(s))",
            self.epoch, len(self._graveyard),
        )

    def finalize_exit(self, code: int) -> None:
        """Leave the process via ``os._exit`` after flushing std streams —
        MANDATORY after any ``abandon()``: interpreter teardown would
        destruct graveyard services under live leaked poll threads, which
        LOG(FATAL)s (observed SIGABRT in probe 4). No-op-ish when nothing
        was ever abandoned is still fine: elastic runs always exit here so
        the exit path does not depend on churn history."""
        import sys

        for stream in (sys.stdout, sys.stderr):
            try:
                stream.flush()
            except Exception:
                log.debug("stream flush failed at elastic exit")
        os._exit(int(code))


# process-wide runtime: formation happens before the app builds anything,
# and the lockstep loop + app teardown both need the same instance
_RUNTIME: "ElasticRuntime | None" = None


def install_runtime(host: str, base_port: int, uid: int) -> ElasticRuntime:
    global _RUNTIME
    if _RUNTIME is not None:
        raise RuntimeError("elastic runtime already installed")
    _RUNTIME = ElasticRuntime(host, base_port, uid)
    return _RUNTIME


def get_runtime() -> "ElasticRuntime | None":
    return _RUNTIME


def reset_for_tests() -> None:
    global _RUNTIME
    if _RUNTIME is not None and _RUNTIME.beacon is not None:
        _RUNTIME.beacon.close()
    _RUNTIME = None
