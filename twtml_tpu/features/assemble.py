"""One-pass wire assembly (r17) — the fused native fast path of the three
packed-wire builders.

The numpy pack pipeline in ``features/batch.py`` stays the byte-identical
ground truth (the parity law, PARITY.md): it touches the wire bytes 3-5
times between featurize and ``device_put`` (per-field stack/contiguous
copies, the offsets→deltas pass, the digram-encode pass, the final
concatenate). On the one-core host that is pure CPU churn right under the
tunnel-upload rung of the measured ladder, so this module routes every
eligible pack through ONE C sweep (native/wireassemble.cpp) that emits
the final ``PackedBatch`` buffer — units digram-encoded in place during
the copy (same LUT, same greedy encode, same all-or-nothing per-segment
fallback as ``_encode_units_segments``), offsets as uint16 deltas under
the same static ``row_len`` gate, sideband laid down behind them — into a
buffer LEASED from the pooled arena (features/arena.py).

Dispatch contract: each ``try_assemble_*`` returns a PackedBatch
byte-identical to its numpy twin, or None — wrong mode, stale/absent
native library (the ``native.assemble_degraded`` seam), an ineligible
dtype/layout, or an input the C pass refuses (delta overflow, forced
codec bucket under-coverage) — and the caller falls through to the numpy
pipeline, which raises the canonical errors. Differential-tested on every
layout × codec × fallback in tests/test_wireassemble.py; sanitized by
tools/native_sanity.py.

``--wireAssemble <auto|on|off>`` (config.py) drives ``configure``; auto
means "whenever the native assembler is loadable" — unlike the wire
codec there is no transport-regime risk to gate on: the assembler moves
host work only and the wire bytes are identical by law.
"""

from __future__ import annotations

import contextlib
import os

import numpy as np

NUM_NUMBER_FEATURES = 4  # features/batch.py (MllibHelper.scala:13)

_MODES = ("auto", "on", "off")
_mode = os.environ.get("TWTML_WIRE_ASSEMBLE", "auto")
if _mode not in _MODES:
    _mode = "auto"


def configure(mode: str) -> None:
    """Set the process-wide assembler mode (the ``--wireAssemble`` seam)."""
    global _mode
    if mode not in _MODES:
        raise ValueError(
            f"wireAssemble must be one of {_MODES}, got {mode!r}"
        )
    _mode = mode


def mode() -> str:
    return _mode


def available() -> bool:
    """Whether packs will actually ride the fused C pass right now."""
    from . import native

    return _mode != "off" and native.assemble_available()


@contextlib.contextmanager
def forced(mode_: str):
    """Scoped mode override — the differential tests and the paired bench
    flip between the numpy ground truth and the fused path with it."""
    prev = _mode
    configure(mode_)
    try:
        yield
    finally:
        configure(prev)


# int64 per-segment encode-length scratch, cached per (thread, size):
# tiny (8 bytes per segment), but the pack hot path allocates nothing per
# tick (TW008); thread-local because a prefetch worker may pack while the
# main thread packs a different stream (utils/benchloop prefetch)
_len_scratch = __import__("threading").local()


def _enc_lens_scratch(n: int) -> np.ndarray:
    cache = getattr(_len_scratch, "bufs", None)
    if cache is None:
        cache = _len_scratch.bufs = {}
    buf = cache.get(n)
    if buf is None:
        buf = cache[n] = np.empty((n,), np.int64)
    return buf


def _field_arrays(rb) -> "tuple | None":
    """(units, offsets, numeric, label, mask) as contiguous numpy arrays
    in the exact wire dtypes the C pass assumes, or None when any field
    is off-schema (the numpy pipeline handles exotic inputs)."""
    units = np.ascontiguousarray(np.asarray(rb.units))
    offsets = np.ascontiguousarray(np.asarray(rb.offsets))
    numeric = np.ascontiguousarray(np.asarray(rb.numeric))
    label = np.ascontiguousarray(np.asarray(rb.label))
    mask = np.ascontiguousarray(np.asarray(rb.mask))
    if units.dtype not in (np.uint8, np.uint16) or units.ndim != 1:
        return None
    if offsets.dtype != np.int32 or offsets.ndim != 1:
        return None
    b = mask.shape[0] if mask.ndim == 1 else -1
    if (
        numeric.dtype != np.float32
        or numeric.shape != (b, NUM_NUMBER_FEATURES)
        or label.dtype != np.float32
        or label.shape != (b,)
        or mask.dtype != np.float32
    ):
        return None
    return units, offsets, numeric, label, mask


def _codec_lut(codec: "str | None", units_dtype) -> "np.ndarray | None":
    """The pair LUT when the codec applies, None for the raw wire. An
    unknown codec returns the sentinel ``()`` so callers fall back to the
    numpy path, which raises the canonical error."""
    if codec is None or codec in ("", "off"):
        return None
    if codec != "dict":
        return ()  # type: ignore[return-value]
    if np.dtype(units_dtype) != np.uint8:
        return None  # non-ASCII-widened wire ships raw, like numpy
    from .wirecodec import pair_lut

    return pair_lut()


def _run(
    fields_per_batch: "list[tuple]",
    s: int,
    bl: int,
    n_sb: int,
    narrow: bool,
    lut: "np.ndarray | None",
    forced_bucket: int,
):
    """Lease destination (+ scratch), run the C pass, return
    (buffer view, enc_bucket, lease) or None."""
    from . import native
    from .arena import lease_wire

    k = len(fields_per_batch)
    unit_size = fields_per_batch[0][0].dtype.itemsize
    per_units_raw = n_sb * unit_size
    per_offs = bl * 2 if narrow else (bl + 1) * 4
    per_side = bl * NUM_NUMBER_FEATURES * 4 + bl * 4 + bl * 4
    raw_total = s * k * (per_units_raw + per_offs + per_side)
    scratch_lease = None
    scratch = enc_lens = None
    if lut is not None:
        scratch_lease = lease_wire(s * k * n_sb)
        scratch = scratch_lease.buf
        enc_lens = _enc_lens_scratch(s * k)
    lease = lease_wire(raw_total)
    try:
        got = native.wire_assemble(
            [f[0] for f in fields_per_batch],
            [f[1] for f in fields_per_batch],
            [f[2] for f in fields_per_batch],
            [f[3] for f in fields_per_batch],
            [f[4] for f in fields_per_batch],
            s, n_sb, bl, narrow, lut, forced_bucket,
            scratch, enc_lens, lease.buf,
        )
    finally:
        if scratch_lease is not None:
            # encode scratch is transient: nothing references it past the
            # call, so it goes straight back to the pool
            scratch_lease.retire()
    if got is None:
        lease.retire()
        return None
    total, enc_bucket = got
    buffer = lease.buf if total == raw_total else lease.buf[:total]
    from ..telemetry import metrics as _metrics

    _metrics.get_registry().counter("wire.assembled_native").inc()
    return buffer, enc_bucket, lease


def _attach(pb, lease):
    # the lease rides the PackedBatch to the dispatch pipelines, which
    # retire it when the corresponding fetch delivers (apps/common.py)
    pb._lease = lease
    return pb


def try_assemble_group(
    batches, s: int, bl: int, n_sb: int, narrow: bool,
    codec: "str | None", codec_bucket: "int | None",
    num_shards_out: int,
):
    """Fused twin of ``pack_ragged_group``'s body (validation already done
    by the caller). None → numpy pipeline. ``codec_bucket`` forces the
    cross-host agreed group bucket (multi-host codec groups), mirroring
    ``try_assemble_sharded``."""
    if not available():
        return None
    first = batches[0]
    lut = _codec_lut(codec, np.asarray(first.units).dtype)
    if isinstance(lut, tuple):  # unknown codec: numpy raises
        return None
    fields = []
    for rb in batches:
        fa = _field_arrays(rb)
        if fa is None:
            return None
        fields.append(fa)
    got = _run(fields, s, bl, n_sb, narrow, lut, int(codec_bucket or 0))
    if got is None:
        return None
    buffer, enc_bucket, lease = got
    k = len(batches)
    units_meta = (
        ((enc_bucket,), np.dtype(np.uint8).str)
        if enc_bucket
        else ((n_sb,), fields[0][0].dtype.str)
    )
    offs_meta = (
        ((bl,), np.dtype(np.uint16).str)
        if narrow
        else ((bl + 1,), np.dtype(np.int32).str)
    )
    f4 = np.dtype(np.float32).str
    layout = (
        "RaggedGroupSegments",
        (
            units_meta, offs_meta,
            ((bl, NUM_NUMBER_FEATURES), f4), ((bl,), f4), ((bl,), f4),
        ),
        (
            first.row_len, num_shards_out or s, k,
            "u16delta" if narrow else "i32",
        ) + (() if not enc_bucket else (("dict", n_sb),)),
    )
    from .batch import PackedBatch

    return _attach(PackedBatch(buffer, layout), lease)


def try_assemble_sharded(
    rb, s: int, bl: int, n_sb: int, narrow: bool,
    codec: "str | None", codec_bucket: "int | None",
    num_shards_out: int,
):
    """Fused twin of ``pack_ragged_sharded``'s body. None → numpy."""
    if not available():
        return None
    lut = _codec_lut(codec, np.asarray(rb.units).dtype)
    if isinstance(lut, tuple):
        return None
    fa = _field_arrays(rb)
    if fa is None:
        return None
    got = _run([fa], s, bl, n_sb, narrow, lut, int(codec_bucket or 0))
    if got is None:
        return None
    buffer, enc_bucket, lease = got
    units_meta = (
        ((enc_bucket,), np.dtype(np.uint8).str)
        if enc_bucket
        else ((n_sb,), fa[0].dtype.str)
    )
    offs_meta = (
        ((bl,), np.dtype(np.uint16).str)
        if narrow
        else ((bl + 1,), np.dtype(np.int32).str)
    )
    f4 = np.dtype(np.float32).str
    layout = (
        "RaggedShardSegments",
        (
            units_meta, offs_meta,
            ((bl, NUM_NUMBER_FEATURES), f4), ((bl,), f4), ((bl,), f4),
        ),
        (rb.row_len, num_shards_out or s, "u16delta" if narrow else "i32")
        + (() if not enc_bucket else (("dict", n_sb),)),
    )
    from .batch import PackedBatch

    return _attach(PackedBatch(buffer, layout), lease)


def try_assemble_flat(rb, narrow: bool, codec: "str | None"):
    """Fused twin of ``pack_batch``'s ragged branch — the k=1, s=1
    degenerate of the same C entry (one segment holding the whole batch,
    fields back to back = the field-major flat wire). Shard-aligned flat
    packs (num_shards > 1) keep the numpy path: their delta segments
    differ from their units segmentation, a layout only the ground truth
    carries. None → numpy."""
    if not available() or rb.num_shards != 1:
        return None
    lut = _codec_lut(codec, np.asarray(rb.units).dtype)
    if isinstance(lut, tuple):
        return None
    fa = _field_arrays(rb)
    if fa is None:
        return None
    units, offsets = fa[0], fa[1]
    b = fa[4].shape[0]
    if offsets.shape[0] != b + 1:
        return None
    n = units.shape[0]
    got = _run([fa], 1, b, n, narrow, lut, 0)
    if got is None:
        return None
    buffer, enc_bucket, lease = got
    units_meta = (
        ((enc_bucket,), np.dtype(np.uint8).str)
        if enc_bucket
        else ((n,), units.dtype.str)
    )
    offs_meta = (
        ((b,), np.dtype(np.uint16).str)
        if narrow
        else ((b + 1,), np.dtype(np.int32).str)
    )
    f4 = np.dtype(np.float32).str
    layout = (
        "RaggedUnitBatch",
        (
            units_meta, offs_meta,
            ((b, NUM_NUMBER_FEATURES), f4), ((b,), f4), ((b,), f4),
        ),
        (rb.row_len, 1, "u16delta" if narrow else "i32")
        + (() if not enc_bucket else (("dict", (n,)),)),
    )
    from .batch import PackedBatch

    return _attach(PackedBatch(buffer, layout), lease)
