"""Tweet filter + feature assembly (reference: MllibHelper.scala:11-96).

Semantics preserved exactly:
- filter: only retweets whose original's retweetCount lies in
  [numRetweetBegin, numRetweetEnd] pass (MllibHelper.scala:89-95);
- text features: lowercase the *original* tweet's text, split into character
  bigrams, hash with HashingTF into numTextFeatures dims
  (MllibHelper.scala:42-56);
- numeric features: followers/favourites/friends counts scaled by 1e-12 and
  tweet age in milliseconds scaled by 1e-14 (MllibHelper.scala:58-71);
- label: the original tweet's retweetCount (MllibHelper.scala:81).

Deliberate divergences from reference quirks (SURVEY.md §2.5), both fixed
here because they are plain bugs there:
- ``reset`` actually applies numTextFeatures (the reference shadows its own
  fields with local vars, MllibHelper.scala:27-29, so the hasher stays at
  1000 dims no matter the flag);
- accent normalization is still OFF by default for hash parity with the
  reference (which computes ``noAccentText`` and then ignores it,
  MllibHelper.scala:49-54), but can be enabled via ``normalize_accents=True``.
"""

from __future__ import annotations

import datetime
import functools
import inspect
import itertools
import operator
import time
import unicodedata
from dataclasses import dataclass, field
from email.utils import parsedate_to_datetime
from typing import Any, Callable

import numpy as np

from .batch import (
    NUM_NUMBER_FEATURES,
    FeatureBatch,
    UnitBatch,
    compact_tokens,
    pad_feature_batch,
)
from .hashing import char_bigrams, hashing_tf_counts

# One C-level pass over the originals for every numeric column + the label
# (lambda-per-column fromiter costs ~25% more in the hot path).
_NUMERIC_COLS = operator.attrgetter(
    "followers_count", "favourites_count", "friends_count",
    "created_at_ms", "retweet_count",
)
# single-attribute getters for the r18 one-traversal gather: list(map(...))
# runs the extraction at C speed, so the only Python-bytecode loop left on
# the object featurize path is the filter itself
_RS_GET = operator.attrgetter("retweeted_status")
_TEXT_GET = operator.attrgetter("text")

# hand-scaling constants of the reference (MllibHelper.scala:64-67)
COUNT_SCALE = 1e-12  # followers / favourites / friends
AGE_SCALE = 1e-14  # tweet age in milliseconds


@functools.lru_cache(maxsize=32)
def _accepts_encoded(fn) -> bool:
    """Whether a batched labeler declares an ``encoded=`` keyword (the
    opt-in contract for reusing the featurizer's UTF-16 encode pass)."""
    try:
        return "encoded" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def _pad_ragged_units(
    units: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    n: int,
    b: int,
    lu: int,
    narrow: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Ragged UTF-16 units → ([b, lu] buffer, [b] int32 lengths) with ASCII
    case folded — C row-copy fast path, numpy gather fallback. Shared by
    both UnitBatch builders (Status lists and columnar blocks).

    ``narrow=True`` ships the buffer as uint8 — the half-width wire format
    for batches every caller-known-ASCII row fits (the overwhelmingly common
    case). Host→device transfer is the measured bottleneck of the streaming
    hot loop and the units buffer is its largest tensor, so this halves the
    dominant wire cost with ZERO extra data passes: the flag comes from
    metadata both builders already have (parser ascii flags / isascii), the
    narrow write happens inside the same C pad copy, and the device hash
    upcasts to int32 either way (ops/text_hash.py) — identical features. A
    stream mixing both dtypes compiles at most one extra program
    (apps/common.warmup_compile warms both)."""
    from . import native

    if units.dtype == np.uint8:
        # narrow-wire block units (zero-copy parser) on the PADDED wire:
        # the C pad copy reads uint16 — widen once (the padded wire is not
        # the wire parser's target; apps gate it to the ragged wire)
        units = units.astype(np.uint16)

    padded = (
        native.pad_units((units, offsets), n, b, lu, ascii_lower=True,
                         narrow=narrow)
        if n
        else None
    )
    if padded is not None:
        return padded
    buf = np.zeros((b, lu), dtype=np.uint16)
    length = np.zeros((b,), dtype=np.int32)
    if n:
        cols = np.arange(lu, dtype=np.int64)[None, :]
        valid = cols < lengths[:, None]
        pos = offsets[:-1, None] + cols
        buf[:n][valid] = units[pos[valid]]
        length[:n] = lengths
        upper = (buf >= 65) & (buf <= 90)
        buf[upper] += 32
    if narrow:
        buf = buf.astype(np.uint8)
    return buf, length


def _parse_created_at_ms(value: Any) -> int:
    """Twitter timestamps: epoch ms int, ``timestamp_ms`` string, or the
    classic ``Wed Aug 27 13:08:45 +0000 2008`` format."""
    if value is None:
        return 0
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value)
    if s.isdigit():
        return int(s)
    try:
        # Twitter's format is close enough to RFC 2822 for this parser once
        # the weekday/month tokens are in the expected order (datetime is a
        # module-scope import: this fallback sits on the hot created_at
        # path of object ingest, where a per-call import taxed every tweet)
        dt = datetime.datetime.strptime(s, "%a %b %d %H:%M:%S %z %Y")
        return int(dt.timestamp() * 1000)
    except ValueError:
        try:
            return int(parsedate_to_datetime(s).timestamp() * 1000)
        except Exception:  # lawcheck: disable=TW005 -- reference parse semantics: an unparsable created_at is 0, the Status-path ground truth (parity law: don't fix reference quirks)
            return 0


def _strip_accents(text: str) -> str:
    """NFD-decompose and drop combining marks (the reference computes this
    and then ignores it — MllibHelper.scala:49-54; opt-in here)."""
    return "".join(
        ch
        for ch in unicodedata.normalize("NFD", text)
        if unicodedata.category(ch) != "Mn"
    )


@dataclass(slots=True)
class Status:
    """Minimal tweet model covering the Twitter4j Status surface the
    reference reads (getRetweetedStatus/getText/getUser/getCreatedAt/
    getRetweetCount — MllibHelper.scala:42-95)."""

    text: str = ""
    retweet_count: int = 0
    followers_count: int = 0
    favourites_count: int = 0
    friends_count: int = 0
    created_at_ms: int = 0
    retweeted_status: "Status | None" = None
    lang: str = ""
    # the tweet's snowflake id (getId) — the live multi-host intake shard
    # key (streaming/sources.IdShardedSource); 0 when absent (synthetic/
    # replay fixtures without ids)
    id: int = 0

    @property
    def is_retweet(self) -> bool:
        return self.retweeted_status is not None

    @classmethod
    def from_json(cls, obj: dict[str, Any]) -> "Status":
        """Parse a (standard-API) tweet JSON object, including the nested
        ``retweeted_status``."""
        user = obj.get("user") or {}
        rs = obj.get("retweeted_status")
        return cls(
            text=obj.get("text") or obj.get("full_text") or "",
            retweet_count=int(obj.get("retweet_count") or 0),
            followers_count=int(user.get("followers_count") or 0),
            favourites_count=int(user.get("favourites_count") or 0),
            friends_count=int(user.get("friends_count") or 0),
            created_at_ms=_parse_created_at_ms(
                obj.get("timestamp_ms") or obj.get("created_at")
            ),
            retweeted_status=cls.from_json(rs) if rs else None,
            lang=obj.get("lang") or "",
            id=int(obj.get("id") or 0),
        )


@dataclass
class Featurizer:
    """Configured featurizer. Unlike the reference's mutable singleton
    (``MllibHelper`` object), this is an explicit value you construct from
    config — no global mutable state, safe to use from multiple streams."""

    num_text_features: int = 1000  # MllibHelper.scala:17
    num_retweet_begin: int = 100  # MllibHelper.scala:15
    num_retweet_end: int = 1000  # MllibHelper.scala:16
    normalize_accents: bool = False  # reference computes-and-drops, §2.5
    now_ms: int | None = None  # fixed clock for deterministic replay; None=wall
    label_fn: "Callable[[Status], float] | None" = None  # default: retweetCount
    # optional batched form of label_fn (same semantics, one call per batch)
    # for hot paths — e.g. features/sentiment.py sentiment_labels
    batch_label_fn: "Callable[[list[Status]], np.ndarray] | None" = None
    # optional labeler over ragged UTF-16 units for the block-ingest path,
    # where no Status objects exist — e.g. sentiment_labels_from_units.
    # NOTE: narrow-wire blocks (zero-copy parser) carry uint8 units —
    # labelers must accept either dtype (values are code units either way;
    # sentiment_labels_from_units upcasts internally)
    unit_label_fn: "Callable[[np.ndarray, np.ndarray], np.ndarray] | None" = None
    num_number_features: int = field(default=NUM_NUMBER_FEATURES, init=False)
    # per-call featurize sub-stage clock [(name, t0, seconds)] — read by
    # FeatureStream._featurize after each call (telemetry side-channel:
    # ``featurize.{encode,numeric,wire_build}_ms`` gauges + nested trace
    # spans, so the straggler ladder can name WHICH half of featurize
    # gates a host). Three perf_counter reads per BATCH, never per tweet.
    last_substages: list = field(default_factory=list, init=False, repr=False)

    @classmethod
    def from_conf(cls, conf) -> "Featurizer":
        """Equivalent of MllibHelper.reset(conf) (MllibHelper.scala:22-32),
        except the knobs actually take effect (see module docstring).

        ``TWTML_NOW_MS`` (env) pins the age-feature clock — the
        deterministic-replay hook app-level differential tests use to
        compare a real app run against a library-built ground truth (the
        age feature otherwise reads the wall clock, as the reference's
        ``new Date()`` does — MllibHelper.scala:73)."""
        import os as _os

        now_env = _os.environ.get("TWTML_NOW_MS", "")
        return cls(
            num_text_features=conf.numTextFeatures,
            num_retweet_begin=conf.numRetweetBegin,
            num_retweet_end=conf.numRetweetEnd,
            now_ms=int(now_env) if now_env else None,
        )

    @property
    def num_features(self) -> int:
        return self.num_text_features + self.num_number_features

    # -- filter (MllibHelper.scala:84-95) -----------------------------------
    def retweet_interval(self, status: Status) -> bool:
        n = status.retweeted_status.retweet_count
        return self.num_retweet_begin <= n <= self.num_retweet_end

    def filtrate(self, status: Status) -> bool:
        return status.is_retweet and self.retweet_interval(status)

    # -- featurize (MllibHelper.scala:42-82) ---------------------------------
    def featurize_text(self, status: Status) -> dict[int, float]:
        text = status.retweeted_status.text.lower()
        if self.normalize_accents:
            text = _strip_accents(text)
        return hashing_tf_counts(char_bigrams(text), self.num_text_features)

    def unit_len(self, status: Status) -> int:
        """UTF-16 unit count the wire formats will carry for this status's
        text — the same original-tweet/lower/accent handling as
        ``featurize_batch_units``/``featurize_text``, kept HERE so the
        over-long-row probe (multi-host lockstep overflow handling,
        streaming/context.py) can never drift from the canonical encoding.
        Unmeasurable rows count as over-long."""
        try:
            text = status.retweeted_status.text.lower()
            if self.normalize_accents:
                text = _strip_accents(text)
            return len(text.encode("utf-16-le", "surrogatepass")) // 2
        except Exception:  # lawcheck: disable=TW005 -- documented degrade (docstring above): an unmeasurable row counts as over-long so lockstep overflow handling drops it instead of desyncing
            return 1 << 30

    def featurize_numbers(self, status: Status) -> np.ndarray:
        original = status.retweeted_status
        now = self.now_ms if self.now_ms is not None else int(time.time() * 1000)
        time_left = now - original.created_at_ms
        return np.array(
            [
                original.followers_count * COUNT_SCALE,
                original.favourites_count * COUNT_SCALE,
                original.friends_count * COUNT_SCALE,
                time_left * AGE_SCALE,
            ],
            dtype=np.float32,
        )

    def featurize(self, status: Status) -> tuple[dict[int, float], np.ndarray, float]:
        """Sparse text counts + dense numerics + label, the host-side half of
        the LabeledPoint assembly; the device half (scatter into a dense or
        sharded vector) lives in ops/sparse.py."""
        label = (
            float(status.retweeted_status.retweet_count)
            if self.label_fn is None
            else float(self.label_fn(status))
        )
        return (self.featurize_text(status), self.featurize_numbers(status), label)

    def featurize_batch(
        self,
        statuses: list[Status],
        row_bucket: int = 0,
        token_bucket: int = 0,
        pre_filtered: bool = False,
        row_multiple: int = 1,
    ) -> FeatureBatch:
        """Filter + featurize + pad a micro-batch of tweets.

        Hot path: text hashing runs in the C++ extension (native/fasthash.cpp)
        writing straight into the padded buffers, and the numeric/label
        columns are assembled vectorized — the Python per-tweet path remains
        as semantic ground truth and fallback."""
        keep = statuses if pre_filtered else [s for s in statuses if self.filtrate(s)]
        fast = self._featurize_batch_native(keep, row_bucket, token_bucket, row_multiple)
        if fast is not None:
            return fast
        if self.batch_label_fn is not None:
            # featurize() consults label_fn only; the batched labeler must
            # apply on this fallback path too (else labels silently revert).
            # Features first with whatever label featurize produces cheaply,
            # then one batched labeling pass (never both per-status AND
            # batched — that would double the labeling cost here)
            rows = [
                (self.featurize_text(s), self.featurize_numbers(s), 0.0)
                for s in keep
            ]
            labels = self.batch_label_fn(keep)
            rows = [
                (text, nums, float(lab))
                for (text, nums, _), lab in zip(rows, labels)
            ]
        else:
            rows = [self.featurize(s) for s in keep]
        # token_val here is always hashing_tf_counts output — counts by
        # construction (label_fn customizes labels, never token values)
        return pad_feature_batch(
            rows, row_bucket=row_bucket, token_bucket=token_bucket,
            row_multiple=row_multiple, num_features=self.num_text_features,
            counts=True,
        )

    def _featurize_batch_native(
        self, keep: list[Status], row_bucket: int, token_bucket: int,
        row_multiple: int = 1,
    ) -> FeatureBatch | None:
        from . import native
        from .batch import _bucket, pad_row_count

        if self.normalize_accents:
            return None  # python path handles the uncommon configuration
            # (accent stripping changes the hashed units themselves)
        if not native.available():
            return None
        n = len(keep)
        originals = [s.retweeted_status for s in keep]
        texts = [o.text.lower() for o in originals]
        encoded = native.encode_texts(texts)
        # distinct bigrams per tweet can't exceed its UTF-16 unit count − 1
        # (bigrams window over code units, like the JVM — astral chars count
        # twice), so this token bucket only needs a retry in the pathological
        # >1024-distinct-terms case where the C side signals fallback
        lengths = np.diff(encoded[1])
        max_tok = int(np.maximum(lengths - 1, 1).max()) if n else 1
        b = pad_row_count(n, row_bucket, row_multiple)
        lt = (
            token_bucket
            if token_bucket >= max_tok and token_bucket > 0
            else _bucket(max_tok)
        )
        token_idx = np.zeros((b, lt), dtype=np.int32)
        token_val = np.zeros((b, lt), dtype=np.float32)
        ntok = native.hash_texts(
            texts, self.num_text_features, token_idx, token_val, encoded=encoded
        )
        if ntok is None:
            return None

        numeric, label, mask = self._numeric_label_mask(
            keep, originals, b, encoded=encoded
        )
        token_idx, token_val = compact_tokens(
            token_idx, token_val, self.num_text_features, counts=True,
            validate=False,  # C hasher output is in-range by construction
        )
        return FeatureBatch(token_idx, token_val, numeric, label, mask)

    def _sub(self, name: str, t0: float) -> float:
        """Record one featurize sub-stage span; returns the stage end
        time (the next stage's t0)."""
        t1 = time.perf_counter()
        self.last_substages.append((name, t0, t1 - t0))
        return t1

    def _apply_label_fns(self, label: np.ndarray, keep, encoded) -> bool:
        """Apply a configured custom labeler over ``label[:n]`` — the ONE
        definition of the label_fn/batch_label_fn precedence both the
        numpy ground truth and the fused native path share. Returns False
        when no custom labeler is set (the default label is the numeric
        columns' retweet count, filled by whichever path ran)."""
        n = len(keep)
        if self.batch_label_fn is not None:
            if encoded is not None and _accepts_encoded(self.batch_label_fn):
                label[:n] = self.batch_label_fn(keep, encoded=encoded)
            else:
                label[:n] = self.batch_label_fn(keep)
            return True
        if self.label_fn is not None:
            label[:n] = [self.label_fn(s) for s in keep]
            return True
        return False

    def _numeric_label_mask(
        self, keep, originals, b: int, encoded=None, cols=None
    ):
        """Padded numeric/label/mask columns. ``cols``: the float64 [n, 5]
        numeric columns already gathered by ``_gather_rows`` (one Python
        traversal, r18); None falls back to the attrgetter pass over
        ``originals``. ``encoded``: the batch's already-computed (units,
        offsets) of the originals' (lowercased) texts, offered to a
        batched labeler that accepts it — avoids a second encode pass on
        the hot path."""
        n = len(keep)
        numeric = np.zeros((b, NUM_NUMBER_FEATURES), dtype=np.float32)
        label = np.zeros((b,), dtype=np.float32)
        mask = np.zeros((b,), dtype=np.float32)
        if not n:
            return numeric, label, mask
        now = self.now_ms if self.now_ms is not None else int(time.time() * 1000)
        if cols is None:
            cols = np.fromiter(
                itertools.chain.from_iterable(map(_NUMERIC_COLS, originals)),
                np.float64, n * 5,
            ).reshape(n, 5)
        numeric[:n, :3] = cols[:, :3] * COUNT_SCALE
        numeric[:n, 3] = (now - cols[:, 3]) * AGE_SCALE
        if not self._apply_label_fns(label, keep, encoded):
            label[:n] = cols[:, 4]
        mask[:n] = 1.0
        return numeric, label, mask

    def _gather_rows(self, statuses: list[Status], pre_filtered: bool):
        """ONE Python-level traversal of the Status objects (r18): the
        filter is the only remaining Python-bytecode loop; texts and the
        five numeric columns then extract from the kept originals at C
        speed (``list(map(attrgetter))`` / ``np.fromiter``). The object
        ingest path previously paid four separate per-tweet Python
        traversals (the filtrate comprehension with two method calls per
        row, the originals comprehension, the isascii/lower loop, the
        attrgetter fromiter) — on the one-core host that WAS the
        featurize stage (BENCHMARKS r17 → r18).

        Returns (keep, texts, cols float64 [n, 5] in _NUMERIC_COLS
        order). ``keep`` is the kept Status objects when a custom
        labeler will need them; with no labeler configured it is the
        kept ORIGINALS — only its length is read downstream, and
        skipping the second per-row append is measurable. Texts are the
        originals' RAW texts — per-text lower()/accent handling stays in
        ``_encode_batch_texts``. ``filtrate``/``retweet_interval`` are
        inlined only when not overridden (a subclassed filter keeps its
        exact semantics at one method call per row); the inlined compare
        is the same Python-int comparison the ground truth makes."""
        inline = (
            type(self).filtrate is Featurizer.filtrate
            and type(self).retweet_interval is Featurizer.retweet_interval
        )
        need_statuses = (
            self.label_fn is not None or self.batch_label_fn is not None
        )
        if pre_filtered:
            keep: list = statuses
            rts = list(map(_RS_GET, statuses))
        elif inline and not need_statuses:
            nb, ne = self.num_retweet_begin, self.num_retweet_end
            rts = []
            ra = rts.append
            for s in statuses:
                rs = s.retweeted_status
                if rs is not None and nb <= rs.retweet_count <= ne:
                    ra(rs)
            keep = rts  # length-only sentinel (no labeler reads it)
        else:
            nb, ne = self.num_retweet_begin, self.num_retweet_end
            keep = []
            rts = []
            ka, ra = keep.append, rts.append
            if inline:
                for s in statuses:
                    rs = s.retweeted_status
                    if rs is not None and nb <= rs.retweet_count <= ne:
                        ka(s)
                        ra(rs)
            else:
                for s in statuses:
                    if self.filtrate(s):
                        ka(s)
                        ra(s.retweeted_status)
        n = len(rts)
        texts = list(map(_TEXT_GET, rts))
        # float64 conversion from the Python ints in one C pass — the
        # exact conversion the pre-r18 fromiter ground truth performed
        # (the parity law's numeric columns)
        cols = np.fromiter(
            itertools.chain.from_iterable(map(_NUMERIC_COLS, rts)),
            np.float64, n * 5,
        ).reshape(n, 5)
        return keep, texts, cols

    def _encode_batch_texts(self, statuses: list[Status], pre_filtered: bool):
        """Shared filter + UTF-16 encode for the unit-wire builders
        (padded ``featurize_batch_units`` and ragged
        ``featurize_batch_ragged``): returns
        (keep, cols, units, offsets, all_ascii) — ``cols`` the float64
        [n, 5] numeric columns from the same single Status traversal
        (``_gather_rows``)."""
        from . import native

        keep, texts, cols = self._gather_rows(statuses, pre_filtered)
        if self.normalize_accents:
            texts = [_strip_accents(t.lower()) for t in texts]
            all_ascii = all(t.isascii() for t in texts)
            units, offsets = native.encode_texts(texts)
            return keep, cols, units, offsets, all_ascii
        # case-folding strategy: texts with non-ASCII chars need Python's
        # Unicode lower(); pure-ASCII texts (the common case) are folded
        # for free later — during the pad copy (padded wire) or on device
        # (ragged wire); re-folding the pre-lowered rows' ASCII range is
        # idempotent. The ascii probe is ONE C scan of the joined batch
        # text, and on the all-ASCII batch the probe's join IS the encode
        # join (one unit per char — the same split encode_texts computes)
        joined = "".join(texts)
        if not joined.isascii():
            texts = [t if t.isascii() else t.lower() for t in texts]
            units, offsets = native.encode_texts(texts)
            return keep, cols, units, offsets, False
        units = np.frombuffer(
            joined.encode("utf-16-le", "surrogatepass"), dtype=np.uint16
        )
        n = len(texts)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(
            np.fromiter(map(len, texts), np.int64, n), out=offsets[1:]
        )
        if units.size == 0:
            units = np.zeros(1, dtype=np.uint16)
        return keep, cols, units, offsets, True

    @staticmethod
    def _row_len_bucket(max_len: int, unit_bucket: int) -> int:
        """The padded row length L for a given max row length — the ONE
        bucket policy both unit wires and the fused native path share.
        L ≥ 2 so the device's [:, :-1]/[:, 1:] bigram windows are
        non-empty."""
        from .batch import _bucket

        return (
            unit_bucket
            if unit_bucket >= max(max_len, 2) and unit_bucket > 0
            else _bucket(max(max_len, 2))
        )

    @staticmethod
    def _unit_batch_shape(
        n: int, lengths, row_bucket: int, unit_bucket: int, row_multiple: int
    ) -> tuple[int, int]:
        """The ONE (padded rows, padded row length) policy for both unit
        wires — padded and ragged MUST agree on compile shapes or the
        bit-identical-features contract drifts."""
        from .batch import pad_row_count

        max_len = int(lengths.max()) if n else 0
        b = pad_row_count(n, row_bucket, row_multiple)
        return b, Featurizer._row_len_bucket(max_len, unit_bucket)

    def featurize_batch_ragged(
        self,
        statuses: list[Status],
        row_bucket: int = 0,
        unit_bucket: int = 0,
        pre_filtered: bool = False,
        row_multiple: int = 1,
        pack: bool = False,
    ):
        """Filter + encode a micro-batch for the RAGGED device wire
        (features/batch.RaggedUnitBatch): the units ship concatenated
        (Σlengths, rounded to RAGGED_UNIT_MULTIPLE) instead of padded
        (B·L_bucket) — the learner re-pads with one gather and case-folds
        ASCII inside the jit step, producing features bit-identical to the
        padded paths (differential tests in tests/test_ragged_wire.py).
        ``unit_bucket`` still pins the REBUILT row length L (compile-shape
        discipline); only the wire stops paying for padding."""
        from .batch import RaggedUnitBatch, pad_row_count, ragged_wire_arrays

        self.last_substages = []
        t0 = time.perf_counter()
        keep, cols, units, offsets, all_ascii = (
            self._encode_batch_texts(statuses, pre_filtered)
        )
        t0 = self._sub("encode", t0)
        n = len(keep)
        b = pad_row_count(n, row_bucket, row_multiple)
        enc = (units, offsets) if not self.normalize_accents else None
        # one-pass native fast path (r18, --featurizeNative): ONE C sweep
        # emits the final ragged-wire arrays — flat units (narrow uint8
        # iff every row is ASCII, the same metadata gate as the padded
        # wire), padded int32 offsets, scaled f32 numeric/label/mask —
        # into one arena lease; None falls through to the ground truth
        from . import featurize_native as _ffz

        fast = _ffz.try_fill(
            units, offsets, cols, _ffz.object_col_order(), n, b,
            narrow=all_ascii,
            now_ms=(
                self.now_ms if self.now_ms is not None
                else int(time.time() * 1000)
            ),
        )
        if fast is not None:
            flat, offs, numeric, label, mask, max_len, lease = fast
            t0 = self._sub("wire_build", t0)
            if n:
                self._apply_label_fns(label, keep, enc)
            self._sub("numeric", t0)
            batch = RaggedUnitBatch(
                flat, offs, numeric, label, mask,
                row_len=self._row_len_bucket(max_len, unit_bucket),
            )
            _ffz.attach_lease(batch, lease)
        else:
            lengths = np.diff(offsets).astype(np.int32)
            lu = self._row_len_bucket(
                int(lengths.max()) if n else 0, unit_bucket
            )
            flat, offs = ragged_wire_arrays(
                units, offsets, n, b, narrow=all_ascii
            )
            t0 = self._sub("wire_build", t0)
            numeric, label, mask = self._numeric_label_mask(
                keep, None, b, encoded=enc, cols=cols
            )
            self._sub("numeric", t0)
            batch = RaggedUnitBatch(
                flat, offs, numeric, label, mask, row_len=lu
            )
        if pack:
            # one-buffer wire (+11.4% paired through the tunnel) for callers
            # that feed the model directly; apps keep the unpacked batch for
            # their handlers and pack at the model boundary (FetchPipeline)
            from .batch import pack_batch

            return pack_batch(batch)
        return batch

    def featurize_batch_units(
        self,
        statuses: list[Status],
        row_bucket: int = 0,
        unit_bucket: int = 0,
        pre_filtered: bool = False,
        row_multiple: int = 1,
    ) -> UnitBatch:
        """Filter + encode + pad a micro-batch for ON-DEVICE featurization.

        The text half is shipped as raw UTF-16 code units (lowercased — case
        folding is genuinely host work; hashing is not) and the learner
        hashes bigrams inside its jit step (ops/text_hash.py), producing
        features bit-identical to `featurize_batch`'s. Host cost per batch
        drops to one encode + one vectorized pad — no per-bigram work at all.
        """
        self.last_substages = []
        t0 = time.perf_counter()
        keep, cols, units, offsets, all_ascii = (
            self._encode_batch_texts(statuses, pre_filtered)
        )
        t0 = self._sub("encode", t0)
        n = len(keep)
        lengths = np.diff(offsets).astype(np.int32)
        b, lu = self._unit_batch_shape(
            n, lengths, row_bucket, unit_bucket, row_multiple
        )
        buf, length = _pad_ragged_units(
            units, offsets, lengths, n, b, lu, narrow=all_ascii
        )
        t0 = self._sub("wire_build", t0)
        # the encode is reusable by a batched labeler only when it reflects
        # the plain lowercased text (accent stripping changes the tokens)
        enc = (units, offsets) if not self.normalize_accents else None
        numeric, label, mask = self._numeric_label_mask(
            keep, None, b, encoded=enc, cols=cols
        )
        self._sub("numeric", t0)
        return UnitBatch(buf, length, numeric, label, mask)

    def featurize_parsed_block(
        self,
        block,
        row_bucket: int = 0,
        unit_bucket: int = 0,
        row_multiple: int = 1,
        ragged: bool = False,
        pack: bool = False,
    ):
        """Columnar block (features/blocks.py, rows already filtered by the
        native parser) → UnitBatch, with zero per-tweet Python work in the
        common case: numeric scaling is vectorized and text goes straight to
        the C pad (ASCII case folded there). Only rows containing non-ASCII
        units — or every row under ``normalize_accents`` — pay a Python
        lower()/normalize round-trip. Custom labels: set ``unit_label_fn``
        (labels from the ORIGINAL raw units, e.g. the lexicon sentiment
        scorer); the Status-based ``label_fn``/``batch_label_fn`` need the
        object ingest path and are rejected here."""
        from . import native
        from .blocks import (
            COL_CREATED_MS,
            COL_FAVOURITES,
            COL_FOLLOWERS,
            COL_FRIENDS,
            COL_LABEL,
        )

        if self.unit_label_fn is None and (
            self.label_fn is not None or self.batch_label_fn is not None
        ):
            raise ValueError(
                "featurize_parsed_block labels come from unit_label_fn "
                "(Status-based label_fn/batch_label_fn need the object "
                "ingest path)"
            )
        self.last_substages = []
        t0 = time.perf_counter()
        n = block.rows
        # one-pass native fast path (r18, --featurizeNative): in the
        # common case — ragged wire, every row parser-ASCII-flagged (so
        # no Unicode redo round-trip exists), no accent stripping — ONE C
        # sweep emits the final wire arrays from the parser's columns
        # (int64 → float64 scale, bit-matching the astype ground truth)
        # into one arena lease, and the stage runs no numpy passes at all
        if (
            ragged
            and not self.normalize_accents
            and (n == 0 or not bool((np.asarray(block.ascii) == 0).any()))
        ):
            from . import featurize_native as _ffz
            from .batch import (
                RaggedUnitBatch as _RB,
                pack_batch as _pack_batch,
                pad_row_count as _pad_row_count,
            )

            t0 = self._sub("encode", t0)  # the ascii probe IS the text prep
            b = _pad_row_count(n, row_bucket, row_multiple)
            fast = _ffz.try_fill(
                block.units, block.offsets, block.numeric,
                _ffz.block_col_order(), n, b, narrow=True,
                now_ms=(
                    self.now_ms if self.now_ms is not None
                    else int(time.time() * 1000)
                ),
            )
            if fast is not None:
                flat, offs, numeric, label, mask, max_len, lease = fast
                t0 = self._sub("wire_build", t0)
                if n and self.unit_label_fn is not None:
                    # labels from the ORIGINAL raw units, like the ground
                    # truth below
                    label[:n] = self.unit_label_fn(
                        block.units, block.offsets
                    )
                self._sub("numeric", t0)
                batch = _RB(
                    flat, offs, numeric, label, mask,
                    row_len=self._row_len_bucket(max_len, unit_bucket),
                )
                _ffz.attach_lease(batch, lease)
                return _pack_batch(batch) if pack else batch
        units, offsets = block.units, block.offsets.copy()
        redo = (
            np.arange(n)
            if self.normalize_accents
            else np.nonzero(block.ascii == 0)[0]
        )
        if n and redo.size and units.dtype == np.uint8:
            # narrow-wire block (the zero-copy parser emits uint8 units
            # when every row is ASCII, so redo is normally empty here) that
            # still needs the per-row Unicode round-trip — only under
            # normalize_accents: widen once for the utf-16 decode below
            units = units.astype(np.uint16)
        if n and redo.size:
            # per-row Unicode round-trip for the rows that need it. The
            # common case (lower() preserves length) writes in place —
            # O(redo rows), not O(all rows); only a length-CHANGING mapping
            # (e.g. İ → i̇) forces a ragged reassembly, and then only the
            # changed rows pay Python-level work
            new_units = units.copy()
            new_lens = np.diff(block.offsets)
            resized: dict[int, np.ndarray] = {}
            for i in redo:
                raw = units[block.offsets[i] : block.offsets[i + 1]]
                text = raw.tobytes().decode("utf-16-le", "surrogatepass").lower()
                if self.normalize_accents:
                    text = _strip_accents(text)
                enc = np.frombuffer(
                    text.encode("utf-16-le", "surrogatepass"), dtype=np.uint16
                )
                if enc.size == raw.size:
                    new_units[block.offsets[i] : block.offsets[i + 1]] = enc
                else:
                    resized[int(i)] = enc
                    new_lens[i] = enc.size
            if resized:
                pieces = [
                    resized.get(
                        i, new_units[block.offsets[i] : block.offsets[i + 1]]
                    )
                    for i in range(n)
                ]
                units = np.concatenate(pieces) if pieces else np.zeros(1, np.uint16)
                np.cumsum(new_lens, out=offsets[1:])
            else:
                units = new_units
        t0 = self._sub("encode", t0)
        lengths = np.diff(offsets).astype(np.int32)
        b, lu = self._unit_batch_shape(
            n, lengths, row_bucket, unit_bucket, row_multiple
        )
        # narrow wire iff every row is parser-ASCII-flagged: redo rows are
        # exactly the non-ASCII ones (normalize_accents marks all rows redo,
        # so it conservatively keeps the wide wire) — metadata, never sniffed
        narrow = n == 0 or redo.size == 0

        now = self.now_ms if self.now_ms is not None else int(time.time() * 1000)
        numeric = np.zeros((b, NUM_NUMBER_FEATURES), dtype=np.float32)
        label = np.zeros((b,), dtype=np.float32)
        mask = np.zeros((b,), dtype=np.float32)
        if n:
            cols64 = block.numeric.astype(np.float64)
            numeric[:n, 0] = cols64[:, COL_FOLLOWERS] * COUNT_SCALE
            numeric[:n, 1] = cols64[:, COL_FAVOURITES] * COUNT_SCALE
            numeric[:n, 2] = cols64[:, COL_FRIENDS] * COUNT_SCALE
            numeric[:n, 3] = (now - cols64[:, COL_CREATED_MS]) * AGE_SCALE
            if self.unit_label_fn is not None:
                # labels from the ORIGINAL raw units (pre-lower/normalize:
                # the object path labels over the original text too, and
                # normalize_accents must never leak into labels — stripping
                # 'bàd'→'bad' would change a lexicon hit)
                label[:n] = self.unit_label_fn(block.units, block.offsets)
            else:
                label[:n] = cols64[:, COL_LABEL]
            mask[:n] = 1.0
        t0 = self._sub("numeric", t0)
        if ragged:
            # the block ALREADY holds concatenated units + offsets — the
            # ragged wire ships them as-is (no pad copy at all); the jit
            # step re-pads with one gather + device ASCII fold, features
            # bit-identical to the padded path (tests/test_ragged_wire.py)
            from .batch import RaggedUnitBatch, pack_batch, ragged_wire_arrays

            flat, offs = ragged_wire_arrays(units, offsets, n, b, narrow=narrow)
            batch = RaggedUnitBatch(
                flat, offs, numeric, label, mask, row_len=lu
            )
            self._sub("wire_build", t0)
            return pack_batch(batch) if pack else batch
        buf, length = _pad_ragged_units(
            units, offsets, lengths, n, b, lu, narrow=narrow
        )
        self._sub("wire_build", t0)
        return UnitBatch(buf, length, numeric, label, mask)
