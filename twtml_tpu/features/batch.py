"""Fixed-shape padded micro-batches — the XLA-facing data contract.

The reference hands MLlib a per-tweet ``LabeledPoint`` with a 1004-dim sparse
vector (MllibHelper.scala:73-82). XLA wants static shapes, so a micro-batch
here is a struct of padded arrays: hashed token indices/counts per tweet
(sparse text features), the 4 dense numeric features, labels, and a validity
mask. Batch row counts and token counts are padded up to bucket sizes so a
stream of varying batch sizes reuses a small set of compiled programs instead
of recompiling per batch (SURVEY.md §7 "hard parts" (a)).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

NUM_NUMBER_FEATURES = 4  # MllibHelper.scala:13


class FeatureBatch(NamedTuple):
    """One padded micro-batch. All arrays are host numpy until the learner
    moves them to device; as a NamedTuple it is automatically a JAX pytree.

    Shapes (B = padded rows, L = padded tokens/tweet):
      token_idx: int  [B, L] — hashed bigram indices into [0, numTextFeatures)
      token_val: num  [B, L] — term-frequency counts (0 where padded)
      numeric:   float32[B, 4] — scaled followers/favourites/friends/age feats
      label:     float32[B]    — retweet count of the retweeted status
      mask:      float32[B]    — 1.0 for real rows, 0.0 for padding

    ``token_idx``/``token_val`` travel in the narrowest lossless dtype
    (int16/uint16 when the feature space and counts fit — see
    ``compact_tokens``): host→device transfer is the measured bottleneck of
    the streaming hot loop, and the learner steps upcast on device.
    """

    token_idx: np.ndarray
    token_val: np.ndarray
    numeric: np.ndarray
    label: np.ndarray
    mask: np.ndarray

    @property
    def num_valid(self) -> int:
        return int(self.mask.sum())


class UnitBatch(NamedTuple):
    """A padded micro-batch carrying raw UTF-16 code units instead of
    host-hashed tokens — the wire format of the on-device featurization path
    (ops/text_hash.py). The learner hashes bigrams inside the jit step, so
    host work per tweet drops to encode + pad and the transfer shrinks to
    2 bytes/unit. Learner steps accept either batch type; both produce
    bit-identical features (same Java-hashCode bigram hash).

    Shapes (B = padded rows, L = padded units/tweet, L ≥ 2):
      units:   uint8|uint16 [B, L] — lowercased text as UTF-16-LE code
               units; ships uint8 when every row is ASCII (metadata-gated,
               the common case — halves the dominant wire tensor; the
               device hash upcasts to int32 either way)
      length:  int32  [B]      — real unit count per row (0 for padding)
      numeric: float32[B, 4], label: float32[B], mask: float32[B] — as in
      FeatureBatch.
    """

    units: np.ndarray
    length: np.ndarray
    numeric: np.ndarray
    label: np.ndarray
    mask: np.ndarray

    @property
    def num_valid(self) -> int:
        return int(self.mask.sum())


def compact_tokens(
    token_idx: np.ndarray,
    token_val: np.ndarray,
    num_features: int,
    counts: bool = False,
    validate: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Downcast the token arrays to the narrowest lossless wire dtype.

    ``num_features`` is the text-index space: indices lie in
    [0, num_features), so they fit int16 whenever num_features ≤ 2^15 (the
    1000-dim default does; the 2^18-dim config keeps int32). Values go to
    uint16 only when the caller declares them term-frequency counts
    (``counts=True``) — a schema property, NOT sniffed from the data, so
    every batch of a stream shares one dtype (one compiled program, and
    multi-host global-batch assembly sees matching per-process dtypes). The
    learner steps upcast on device, so this only changes wire bytes.

    A misdeclared schema raises rather than silently wrapping or switching
    dtype mid-stream: indices outside [0, num_features), and ``counts=True``
    values that don't survive the uint16 round-trip (fractional, negative,
    or ≥ 2^16 — true term-frequency counts are bounded by a tweet's bigram
    count, ≪ 2^16). ``validate=False`` skips those data passes for callers
    whose arrays are in-range by construction (the native featurizer path:
    the C hasher emits ``hash % num_features`` indices and per-tweet counts
    ≤ the token bucket).
    """
    if 0 < num_features <= np.iinfo(np.int16).max + 1:
        if validate and token_idx.size and (
            token_idx.min() < 0 or token_idx.max() >= num_features
        ):
            raise ValueError(
                "token indices outside the declared feature space "
                f"[0, {num_features})"
            )
        token_idx = token_idx.astype(np.int16)
    if counts:
        compacted = token_val.astype(np.uint16)
        if validate and not np.array_equal(compacted, token_val):
            raise ValueError(
                "counts=True but token values are not uint16-exact "
                "(fractional, negative, or >= 2**16)"
            )
        token_val = compacted
    return token_idx, token_val


class PackedBatch:
    """A FeatureBatch or UnitBatch flattened into ONE contiguous uint8
    buffer for the wire, plus static layout metadata.

    Why it exists: transports that expose a per-transfer cost make five
    small arrays ~1.6× the price of one 190 KB buffer (measured through
    this build's TPU tunnel under fully-serialized upload→step→fetch).
    Status by regime (both measured): on the 188 KB PADDED wire the
    per-array overhead hides behind overlapped transfers (r2: end-to-end
    delta zero → opt-in), but on the lean RAGGED wire it no longer hides —
    packing is the SHIPPED default there (+11.4% paired, r3; BENCHMARKS.md
    "Packing stacks on ragged"). The learner steps accept a
    PackedBatch and unpack INSIDE the jit program with offset slices +
    ``lax.bitcast_convert_type`` — zero-copy reinterpretation, bit-identical
    arrays — so packing changes wire shape only, never semantics.

    Registered as a pytree whose only leaf is the buffer; the layout (field
    shapes/dtypes and the batch class) is static aux data, so each distinct
    layout compiles once, exactly like the unpacked batch types.
    """

    def __init__(self, buffer, layout: tuple):
        self.buffer = buffer
        self.layout = layout  # (cls_name, ((shape, dtype_str), ...))
        # arena lease backing the buffer (features/arena.py), when the
        # pack leased its destination: the dispatch pipelines retire it
        # once the corresponding fetch delivers (apps/common.py). Not
        # pytree state — a re-built PackedBatch simply carries no lease.
        self._lease = None

    def _with_lease(self, lease) -> "PackedBatch":
        self._lease = lease
        return self

    @property
    def num_valid(self) -> int:
        return int(unpack_batch(self.buffer, self.layout).mask.sum())


def _register_packed():
    import jax

    jax.tree_util.register_pytree_node(
        PackedBatch,
        lambda pb: ((pb.buffer,), pb.layout),
        lambda layout, leaves: PackedBatch(leaves[0], layout),
    )


_register_packed()


class RaggedUnitBatch:
    """A micro-batch whose text ships as CONCATENATED code units + row
    offsets — no per-row padding on the wire.

    Why: the padded ``UnitBatch`` units buffer is the dominant wire tensor
    of the streaming hot loop, and every unit beyond a row's length is pure
    waste on the upload-bound transport (the padded [B, L] carries
    B·L units where only Σlengths are real — the padding fraction is
    measured in BENCHMARKS.md). The ragged wire carries Σlengths units
    (rounded up to ``RAGGED_UNIT_MULTIPLE`` so program count stays finite)
    plus a [B+1] int32 offsets vector; the learner re-pads INSIDE the jit
    step with one [B, L] gather (ops-side cost ~nothing; TPU gathers are
    cheap — it is scatters that serialize) and case-folds ASCII on device,
    producing bit-identical features (tests/test_ragged_wire.py).

    ``row_len`` (the padded L the device gather rebuilds) is STATIC aux
    data, like PackedBatch's layout: each distinct (shapes, row_len)
    compiles once.

    Fields: units [N] uint8|uint16 (narrow iff every row ASCII, as in
    UnitBatch), offsets [B+1] int32, numeric/label/mask as in UnitBatch.

    ``num_shards`` > 1 marks a SHARD-ALIGNED buffer (``align_ragged_shards``):
    the units are S equal sub-buffers of N/S units (shard s's rows
    concatenated, zero-padded per sub-buffer) and the offsets are S
    segment-RELATIVE [B/S + 1] blocks ([B + S] total) — every leaf's
    leading dim is divisible by S, so the mesh data axis shards the ragged
    wire like any padded batch and each device receives exactly its rows'
    units with no cross-shard bytes. ``ops/ragged.ragged_repad`` rebuilds
    identically in every layout. Static aux, like ``row_len``.
    """

    def __init__(
        self, units, offsets, numeric, label, mask, row_len: int,
        num_shards: int = 1,
    ):
        self.units = units
        self.offsets = offsets
        self.numeric = numeric
        self.label = label
        self.mask = mask
        self.row_len = int(row_len)
        self.num_shards = int(num_shards)

    @property
    def num_valid(self) -> int:
        return int(np.asarray(self.mask).sum())


def _register_ragged():
    import jax

    jax.tree_util.register_pytree_node(
        RaggedUnitBatch,
        lambda rb: (
            (rb.units, rb.offsets, rb.numeric, rb.label, rb.mask),
            (rb.row_len, rb.num_shards),
        ),
        lambda aux, leaves: RaggedUnitBatch(
            *leaves, row_len=aux[0], num_shards=aux[1]
        ),
    )


_register_ragged()


_WIRE_FIELDS = (
    "token_idx", "token_val", "units", "offsets", "length",
    "numeric", "label", "mask", "buffer",
)


def wire_nbytes(batch) -> int:
    """Bytes this batch puts on the host→device wire (the sum of its array
    fields' nbytes, whatever the batch type) — the per-batch cost the
    upload-bound transport actually pays, recorded by the telemetry layer
    (telemetry/trace.py spans, ``wire.bytes`` counter)."""
    total = 0
    for name in _WIRE_FIELDS:
        arr = getattr(batch, name, None)
        nbytes = getattr(arr, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def wire_composition(batch) -> "dict[str, int]":
    """The per-batch wire split {units, offsets, sideband} in bytes — what
    the Lean-wire-v2 offset shrink moves, surfaced as gauges in the metrics
    registry (streaming/context.py) so /api/metrics and trace reports show
    the wire composition without a bench run. ``units`` is the text
    payload (code units, or hashed token idx/val on the host-hash wire),
    ``offsets`` the row-boundary sideband (offsets/length deltas), and
    ``sideband`` the numeric/label/mask tail. A PackedBatch reports its
    layout's recorded fields (× segment count), so the packed and unpacked
    views of one batch agree byte-for-byte. A codec layout
    (``--wireCodec dict``) keeps ``units`` as the RAW units bytes (still
    agreeing with the unpacked view) and adds ``units_compressed`` — the
    bytes the transport actually carries; their quotient is the live
    ``wire.codec_ratio`` gauge (apps/common.py)."""
    if isinstance(batch, PackedBatch):
        tag = batch.layout[0]
        if tag in ("RaggedShardSegments", "RaggedGroupSegments"):
            segs = 1
            per_seg = sum(
                int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
                for shape, dt in batch.layout[1]
            )
            if per_seg:
                segs = int(batch.buffer.shape[0]) // per_seg
            names = ("units", "offsets", "sideband", "sideband", "sideband")
        else:
            names = {
                "FeatureBatch": (
                    "units", "units", "sideband", "sideband", "sideband"
                ),
                "UnitBatch": (
                    "units", "offsets", "sideband", "sideband", "sideband"
                ),
                "RaggedUnitBatch": (
                    "units", "offsets", "sideband", "sideband", "sideband"
                ),
            }[tag]
            segs = 1
        out = {"units": 0, "offsets": 0, "sideband": 0}
        for name, (shape, dt) in zip(names, batch.layout[1]):
            out[name] += segs * int(
                np.prod(shape, dtype=np.int64)
            ) * np.dtype(dt).itemsize
        codec_tag = _layout_codec(batch.layout)
        if codec_tag is not None:
            # compressed wire: "units" stays the raw bytes (the unpacked
            # view), "units_compressed" is what the transport carries
            out["units_compressed"] = out["units"]
            out["units"] = (
                int(np.prod(codec_tag[1], dtype=np.int64))
                if tag == "RaggedUnitBatch"
                else segs * int(codec_tag[1])
            )
        return out
    groups = {
        "units": ("units", "token_idx", "token_val"),
        "offsets": ("offsets", "length"),
        "sideband": ("numeric", "label", "mask"),
    }
    out = {}
    for name, attrs in groups.items():
        total = 0
        for attr in attrs:
            arr = getattr(batch, attr, None)
            nbytes = getattr(arr, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
        out[name] = total
    return out


def _shard_segment_need(rb: "RaggedUnitBatch", num_shards: int) -> int:
    """Raw units each shard segment must hold (the longest shard's real
    units) — the ONE shard-boundary computation align/bucket share."""
    b = rb.mask.shape[0]
    if b % num_shards:
        raise ValueError(f"batch rows {b} not divisible by {num_shards} shards")
    offs = np.asarray(rb.offsets, np.int64)
    starts = offs[0 : b + 1 : b // num_shards]
    return int((starts[1:] - starts[:-1]).max())


def ragged_shard_bucket(rb: "RaggedUnitBatch", num_shards: int) -> int:
    """The per-shard sub-buffer capacity ``align_ragged_shards`` would pick
    for this batch — exposed so multi-host assembly can allgather-max it
    across processes and pass the agreed value back as ``unit_bucket``
    (every host must compile the same program shapes)."""
    if rb.num_shards == num_shards:
        return rb.units.shape[0] // num_shards
    if rb.num_shards != 1:
        # a batch aligned to a DIFFERENT shard count would fall through to
        # _shard_segment_need, which reads the segment-relative offsets as
        # one flat [B+1] vector and returns garbage — and in multi-host
        # assembly that garbage is allgathered before align_ragged_shards
        # finally raises, surfacing as a confusing cross-host bucket
        # mismatch (r4 advisor). Mirror align's "re-align from flat" check.
        raise ValueError(
            f"batch is aligned to {rb.num_shards} shards; re-align from "
            f"flat before bucketing for {num_shards}"
        )
    need = _shard_segment_need(rb, num_shards)
    return max(
        RAGGED_UNIT_MULTIPLE,
        -(-need // RAGGED_UNIT_MULTIPLE) * RAGGED_UNIT_MULTIPLE,
    )


def align_ragged_shards(
    rb: "RaggedUnitBatch", num_shards: int, unit_bucket: int = 0
) -> "RaggedUnitBatch":
    """Re-lay a ragged batch into ``num_shards`` equal shard segments so a
    mesh data axis can shard it (see RaggedUnitBatch docstring). Host-side,
    two memcpys of the units. ``unit_bucket`` pins the per-shard sub-buffer
    capacity (multi-host runs agree it via the lockstep tick so every
    process compiles the same program); 0 sizes it from this batch's
    longest shard, rounded to RAGGED_UNIT_MULTIPLE."""
    if rb.num_shards == num_shards:
        cur = rb.units.shape[0] // num_shards
        if not unit_bucket or unit_bucket == cur:
            return rb
        if unit_bucket < cur:
            raise ValueError(
                f"batch is aligned to sub-buffers of {cur} units; cannot "
                f"shrink to the pinned bucket {unit_bucket}"
            )
        # grow each sub-buffer to the pinned bucket (a multi-host agreed
        # bucket can exceed this host's local need — e.g. every process
        # owning ONE data shard, where a flat batch is trivially aligned);
        # segment-relative offsets are untouched by tail padding
        grown = np.zeros((num_shards, unit_bucket), rb.units.dtype)
        grown[:, :cur] = np.asarray(rb.units).reshape(num_shards, cur)
        return RaggedUnitBatch(
            grown.reshape(-1), rb.offsets, rb.numeric, rb.label, rb.mask,
            row_len=rb.row_len, num_shards=num_shards,
        )
    if rb.num_shards != 1:
        raise ValueError("batch is already shard-aligned; re-align from flat")
    b = rb.mask.shape[0]
    b_local = b // num_shards
    need = _shard_segment_need(rb, num_shards)
    n_sb = ragged_shard_bucket(rb, num_shards)
    offs = np.asarray(rb.offsets, np.int64)
    starts = offs[0 : b + 1 : b_local]  # shard boundaries, [S+1]
    if unit_bucket:
        if need > unit_bucket:
            raise ValueError(
                f"shard units {need} exceed the pinned bucket {unit_bucket}"
            )
        n_sb = unit_bucket
    units = np.asarray(rb.units)
    flat = np.zeros((num_shards * n_sb,), units.dtype)
    new_offs = np.empty((b + num_shards,), np.int32)
    for s in range(num_shards):
        lo, hi = int(starts[s]), int(starts[s + 1])
        flat[s * n_sb : s * n_sb + (hi - lo)] = units[lo:hi]
        blk = offs[s * b_local : (s + 1) * b_local + 1] - lo
        new_offs[s * (b_local + 1) : (s + 1) * (b_local + 1)] = blk
    return RaggedUnitBatch(
        flat, new_offs, rb.numeric, rb.label, rb.mask,
        row_len=rb.row_len, num_shards=num_shards,
    )

# the ragged units buffer rounds its total up to this multiple: waste is
# bounded by RAGGED_UNIT_MULTIPLE units (≤8 KB uint16) per batch while the
# program count stays small (total unit counts concentrate tightly around
# B·mean_len, so real streams hit one or two buckets)
RAGGED_UNIT_MULTIPLE = 4096

# ---- narrow offset wire (Lean wire v2) ------------------------------------
# The ragged wire's [B+1] int32 offsets are pure sideband: every row length
# is bounded by the STATIC rebuilt row length L (``row_len`` — the
# featurizer's bucket policy guarantees lengths ≤ L), so whenever L fits
# uint16 the offsets can ship as per-row LENGTH DELTAS in half the bytes
# minus four per segment (b16384: 65,540 → 32,768 bytes). The device
# cumsums them back to segment-relative offsets in-program
# (ops/ragged.offsets_from_deltas) — a pure re-encoding, bit-identical
# features. The gate is static per program, exactly like the uint8/uint16
# units switch: a schema property of the layout, never sniffed per batch,
# with the int32 path as the metadata-gated fallback for row_len > 65,535.
OFFSET_DELTA_MAX = 2**16 - 1


def offsets_narrow(row_len: int) -> bool:
    """Whether this batch's offsets may ship as uint16 length deltas —
    static in ``row_len`` (see OFFSET_DELTA_MAX note)."""
    return 0 < int(row_len) <= OFFSET_DELTA_MAX


def _offsets_to_deltas(offsets, num_segments: int) -> np.ndarray:
    """Segment-relative int32 offsets [S·(B_s+1)] → uint16 per-row length
    deltas [S·B_s] (the narrow offset wire). Each segment's offsets start
    at 0 by construction (ragged_wire_arrays / align_ragged_shards), so the
    deltas are lossless; a delta that overflows uint16 means the caller's
    ``row_len`` gate was misdeclared — raise, never wrap."""
    offs = np.asarray(offsets, np.int64).reshape(num_segments, -1)
    d = offs[:, 1:] - offs[:, :-1]
    if d.size and (d.min() < 0 or d.max() > OFFSET_DELTA_MAX):
        raise ValueError(
            "offsets are not uint16-delta encodable (negative or "
            f"> {OFFSET_DELTA_MAX} length); keep the int32 offset wire"
        )
    return d.astype(np.uint16).reshape(-1)


def _deltas_to_offsets_np(deltas, num_segments: int) -> np.ndarray:
    """Host twin of ``ops/ragged.offsets_from_deltas``."""
    d = np.asarray(deltas, np.int64).reshape(num_segments, -1)
    out = np.zeros((num_segments, d.shape[1] + 1), np.int64)
    np.cumsum(d, axis=1, out=out[:, 1:])
    return out.reshape(-1).astype(np.int32)


def _decode_offsets(arr, num_segments: int):
    """Delta-wire decode for ``unpack_batch``: host numpy cumsums here; a
    traced device array cumsums in-program (ops/ragged.offsets_from_deltas)
    — either way the rebuilt offsets are bit-identical to the int32 wire."""
    if isinstance(arr, np.ndarray):
        return _deltas_to_offsets_np(arr, num_segments)
    from ..ops.ragged import offsets_from_deltas

    return offsets_from_deltas(arr, num_segments)


# ---- compressed units wire (r15, --wireCodec dict) -------------------------
# The digram codec (features/wirecodec.py: static-dictionary byte-pair
# coding, C-side encode, in-jit gather-expand decode) shrinks the dominant
# wire tensor another ~1.4-2x on ASCII tweet text. It applies ONLY to the
# PACKED wire forms (pack_batch / pack_ragged_sharded / pack_ragged_group):
# compression compounds the per-array-overhead trap that already made
# packing the lean-wire default (+11.4% paired, r3), and every host-side
# consumer between featurize and pack (tenant routing, shard alignment,
# stacking) indexes RAW units by offset. Two gates, both loud and lossless:
# uint16 (non-ASCII-widened) units ship uncompressed — a metadata gate,
# like the int32 offset fallback — and a batch whose bucketed encoding is
# not strictly smaller than its raw buffer ships raw, recorded in the
# layout and counted by the app seam (wire.codec_fallbacks).


def _encode_units_codec(units: np.ndarray, codec: "str | None"):
    """Bucketed digram codes for an eligible raw units buffer, or None →
    the raw wire (codec off, uint16 units, or incompressible batch)."""
    if codec is None or codec in ("", "off"):
        return None
    if codec != "dict":
        raise ValueError(f"unknown wire codec {codec!r} (know: dict)")
    units = np.asarray(units)
    if units.dtype != np.uint8:
        return None  # non-ASCII-widened wire: uncompressed, like int32 offsets
    from .wirecodec import encode_bucketed

    return encode_bucketed(units.reshape(-1))


def _encode_units_segments(
    units: np.ndarray, num_segments: int, codec: "str | None",
    bucket: "int | None" = None,
):
    """Per-segment digram codes [num_segments, shared bucket] for a
    SEGMENTED raw units buffer (shard sub-buffers / group segments —
    each must decode independently under its device's slice), or None →
    raw wire. The bucket is joint (max segment, rounded) so every segment
    is the same static shape; all-or-nothing per pack.

    ``bucket`` (r16, multi-host codec) FORCES the shared bucket to a
    cross-host AGREED value (parallel/distributed.py
    ``_ragged_local_aligned_codec``): every process must emit identical
    codec segment shapes for the global wire assembly, so the local-max
    bucket (and the local incompressibility fallback) must not decide. A
    segment encoding past the agreed bucket is a codec-bound bug and
    raises — silent truncation would corrupt the wire."""
    if codec is None or codec in ("", "off"):
        return None
    if codec != "dict":
        raise ValueError(f"unknown wire codec {codec!r} (know: dict)")
    u = np.asarray(units)
    if u.dtype != np.uint8:
        return None  # non-ASCII-widened wire ships uncompressed
    from .wirecodec import encode, encoded_bucket

    rows = u.reshape(num_segments, -1)
    enc = [encode(r) for r in rows]
    if bucket is None:
        bucket = encoded_bucket(max(e.shape[0] for e in enc))
        if bucket >= rows.shape[1]:
            return None  # incompressible: the raw wire is the smaller wire
    else:
        over = max(e.shape[0] for e in enc)
        if over > bucket:
            raise ValueError(
                f"agreed codec bucket {bucket} under-covers a segment "
                f"encoding of {over} units — the cross-host zero-pad bound "
                "is violated (codec bug)"
            )
    out = np.zeros((num_segments, bucket), np.uint8)
    for i, e in enumerate(enc):
        out[i, : e.shape[0]] = e
    return out


def _decode_units(arr, out_len: int):
    """Codec-wire decode for the unpack paths: host numpy decodes via the
    wirecodec twin; a traced device array decodes in-program
    (ops/ragged.units_from_codes) — either way the rebuilt units are
    bit-identical to the uncompressed wire. ``arr`` holds per-stream codes
    along the LAST axis ([..., M] → [..., out_len]; leading axes pass
    through, so stacked/segmented wires decode in one call)."""
    if isinstance(arr, np.ndarray):
        from .wirecodec import decode_np

        return decode_np(arr, out_len)
    from ..ops.ragged import units_from_codes

    return units_from_codes(arr, out_len)


def _layout_codec(layout: tuple) -> "tuple | None":
    """The codec entry ``("dict", raw_units_per_stream)`` of a packed
    layout, or None for the raw wire. One reader for all three packed
    tags, so the position of the appended entry cannot drift."""
    extra = layout[2] if len(layout) > 2 else None
    if not extra:
        return None
    at = {
        "RaggedUnitBatch": 3, "RaggedShardSegments": 3,
        "RaggedGroupSegments": 4,
    }.get(layout[0])
    if at is None or len(extra) <= at:
        return None
    return extra[at]


def ragged_wire_arrays(
    units: np.ndarray, offsets: np.ndarray, n: int, b: int, narrow: bool
) -> tuple[np.ndarray, np.ndarray]:
    """(flat units buffer, padded [b+1] int32 offsets) for the ragged wire —
    the ONE bucket/narrowing policy shared by both featurizer builders
    (Status lists and columnar blocks), so the formats cannot drift.
    ``narrow`` ships uint8 (lossless iff every row is ASCII — the callers'
    metadata gate); pad rows get ``offsets[i] = total`` (length 0)."""
    total = int(offsets[-1]) if n else 0
    n_bucket = max(
        RAGGED_UNIT_MULTIPLE,
        -(-total // RAGGED_UNIT_MULTIPLE) * RAGGED_UNIT_MULTIPLE,
    )
    flat = np.zeros((n_bucket,), np.uint8 if narrow else np.uint16)
    flat[:total] = units[:total]
    offs = np.full((b + 1,), total, np.int32)
    offs[: n + 1] = offsets[: n + 1].astype(np.int32)
    return flat, offs


def _finish_pack(chunks, axis: int, layout: tuple) -> PackedBatch:
    """The one place the numpy packers materialize their final wire
    buffer: ``np.concatenate`` into an ARENA-LEASED destination
    (features/arena.py — fresh per-tick wire buffers are the TW008
    finding class: one-core CPU churn plus fuel for the measured
    axon-client RSS retention). The lease rides the PackedBatch to the
    dispatch pipelines, which retire it on fetch delivery."""
    from .arena import lease_wire

    lease = lease_wire(sum(c.nbytes for c in chunks))
    shape = list(chunks[0].shape)
    shape[axis] = sum(c.shape[axis] for c in chunks)
    out = lease.buf.reshape(shape)
    np.concatenate(chunks, axis=axis, out=out)
    return PackedBatch(out.reshape(-1), layout)._with_lease(lease)


def pack_ragged_sharded(
    rb: "RaggedUnitBatch", num_shards_out: int = 0,
    narrow_offsets: "bool | None" = None,
    codec: "str | None" = None,
    codec_bucket: "int | None" = None,
) -> PackedBatch:
    """A SHARD-ALIGNED ragged batch → one wire buffer laid out PER SHARD, so
    a mesh data axis can shard the single buffer (r5: the +11.4% packing
    win was single-device-only because ``pack_batch``'s field-major layout
    has no row sharding).

    Layout: the buffer is S equal segments; segment s holds shard s's five
    fields back to back (units sub-buffer, segment-relative offsets,
    numeric, label, mask). ``P(data)`` on the buffer then gives each device
    exactly its own rows' bytes, and the shard_map body rebuilds its local
    RaggedUnitBatch with the same zero-copy bitcasts as ``unpack_batch``.
    The static layout records PER-SHARD field shapes under the
    ``RaggedShardSegments`` tag plus (row_len, total shards).

    ``num_shards_out`` overrides the recorded shard count — multi-host
    callers pack their LOCAL shards and assemble the global buffer from
    every process, so the layout must carry the GLOBAL count. ``s = 1`` is
    legal (a 1-device mesh, or the one-data-shard-per-process topology):
    the "per-shard" layout is then simply the whole local batch as one
    segment.

    ``narrow_offsets`` (default: auto from the static ``row_len`` gate,
    ``offsets_narrow``) ships the per-shard offsets as uint16 LENGTH DELTAS
    instead of [B_s+1] int32 — the Lean-wire-v2 sideband shrink; the unpack
    cumsums them back in-program, bit-identically.

    ``codec="dict"`` (r15, ``--wireCodec``) digram-compresses each shard's
    units sub-buffer into a shared static bucket; the unpack gather-expands
    them back in-program ahead of the re-pad — byte-identical units
    (tests/test_wirecodec.py). Ineligible/incompressible batches keep the
    raw layout (see ``_encode_units_segments``)."""
    s = rb.num_shards
    b = rb.mask.shape[0]
    bl = b // s
    n_sb = rb.units.shape[0] // s
    narrow = (
        offsets_narrow(rb.row_len) if narrow_offsets is None
        else narrow_offsets
    )
    # fused native fast path (r17): one C sweep emits the identical final
    # buffer into an arena lease; None falls through to the ground truth
    from .assemble import try_assemble_sharded

    fast = try_assemble_sharded(
        rb, s, bl, n_sb, narrow, codec, codec_bucket, num_shards_out
    )
    if fast is not None:
        return fast
    offs_wire = (
        (_offsets_to_deltas(rb.offsets, s), (bl,))
        if narrow
        else (rb.offsets, (bl + 1,))
    )
    codes = _encode_units_segments(rb.units, s, codec, bucket=codec_bucket)
    units_wire = (
        (rb.units, (n_sb,)) if codes is None else (codes, (codes.shape[1],))
    )
    fields = tuple(
        np.ascontiguousarray(np.asarray(a).reshape((s,) + shape))
        for a, shape in (
            units_wire,
            offs_wire,
            (rb.numeric, (bl, NUM_NUMBER_FEATURES)),
            (rb.label, (bl,)),
            (rb.mask, (bl,)),
        )
    )
    layout = (
        "RaggedShardSegments",
        tuple((f.shape[1:], f.dtype.str) for f in fields),
        (rb.row_len, num_shards_out or s, "u16delta" if narrow else "i32")
        + (() if codes is None else (("dict", n_sb),)),
    )
    return _finish_pack(
        [f.view(np.uint8).reshape(s, -1) for f in fields], 1, layout
    )


def _unpack_ragged_shards(buffer, layout: tuple) -> "RaggedUnitBatch":
    """Rebuild from a ``RaggedShardSegments`` buffer. Host numpy gets the
    full S-segment buffer back as the shard-aligned batch; inside a
    shard_map body the local slice holds ONE segment and rebuilds the
    shard-local batch (num_shards=1 — the body is per-shard by
    construction). A ``u16delta`` layout (narrow offset wire) cumsums the
    per-row length deltas back to segment-relative offsets here —
    in-program on device, numpy on host — before the batch is rebuilt; a
    codec layout (``--wireCodec dict``) likewise gather-expands each
    shard's digram codes back to its raw units sub-buffer first."""
    fields_meta = layout[1]
    row_len, s_total = layout[2][0], layout[2][1]
    offs_mode = layout[2][2] if len(layout[2]) > 2 else "i32"
    codec_tag = _layout_codec(layout)
    per_shard = sum(
        int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in fields_meta
    )
    s_here = buffer.shape[0] // per_shard
    if buffer.shape[0] != s_here * per_shard:
        raise ValueError(
            f"buffer of {buffer.shape[0]} bytes is not a whole number of "
            f"{per_shard}-byte shard segments"
        )
    fields = []
    off = 0
    for shape, dtype_str in fields_meta:
        dt = np.dtype(dtype_str)
        count = int(np.prod(shape, dtype=np.int64))
        nbytes = count * dt.itemsize
        if isinstance(buffer, np.ndarray):
            chunk = np.ascontiguousarray(
                buffer.reshape(s_here, per_shard)[:, off : off + nbytes]
            )
            arr = chunk.view(dt).reshape((s_here,) + shape)
        else:
            from jax import lax

            if s_here != 1:
                raise ValueError(
                    "device-side unpack sees exactly one shard segment "
                    "(the shard_map-local slice)"
                )
            chunk = buffer[off : off + nbytes]
            if dt.itemsize > 1:
                chunk = chunk.reshape(count, dt.itemsize)
            arr = lax.bitcast_convert_type(chunk, dt).reshape((1,) + shape)
        off += nbytes
        # flatten the segment axis back into the leading dim
        fields.append(arr.reshape((arr.shape[0] * shape[0],) + shape[1:]))
    if codec_tag is not None:
        n_sb_raw = int(codec_tag[1])
        fields[0] = _decode_units(
            fields[0].reshape(s_here, -1), n_sb_raw
        ).reshape(s_here * n_sb_raw)
    if offs_mode == "u16delta":
        fields[1] = _decode_offsets(fields[1], s_here)
    return RaggedUnitBatch(
        *fields, row_len=row_len, num_shards=s_here if s_here > 1 else 1
    )


def pack_ragged_group(
    batches, num_shards_out: int = 0,
    narrow_offsets: "bool | None" = None,
    codec: "str | None" = None,
    codec_bucket: "int | None" = None,
) -> PackedBatch:
    """K same-signature ragged batches → ONE contiguous uint8 wire buffer
    (the coalesced superbatch wire, Lean wire v2).

    Why: upload bandwidth through the tunnel IMPROVES with transfer size
    and packing the lean ragged wire paid +11.4% (BENCHMARKS.md), yet the
    stacked superbatch wire still shipped K separate per-field arrays —
    K small puts where one large coalesced put rides the bandwidth curve.
    This pack composes the two measured facts: the K batches' five fields
    flatten into one buffer with a STATIC per-group layout, uploaded by
    ONE main-thread ``device_put`` (rides the step_many dispatch), and the
    in-jit unpack (``_unpack_ragged_group``) slices the K segments back
    into the stacked [K, ...] leaves the existing scanned K-step program
    consumes — bit-identical features, differential-tested against the
    K-separate-wires path (tests/test_superwire.py).

    Layout: the buffer is laid out SHARD-MAJOR, [S, K, per-segment bytes]
    flattened — ``P(data)`` on the one buffer then hands each device its
    own K segments (the shard-aligned variant of the one-buffer wire,
    parallel/sharding.py), with S = 1 collapsing to the single-device
    [K, per-batch] layout. Offsets ride the narrow uint16-delta wire under
    the same static ``row_len`` gate as ``pack_ragged_sharded``.

    All batches must share one wire signature (shapes, dtypes, row_len,
    shard alignment) — the SuperBatcher's signature grouping guarantees
    this, so each distinct (signature, K) compiles exactly one program.
    ``num_shards_out`` mirrors ``pack_ragged_sharded`` (multi-host callers
    pack local shards, the layout carries the global count); ``codec``
    mirrors it too (per-segment digram compression, shared bucket,
    all-or-nothing raw fallback — see ``_encode_units_segments``), as does
    ``codec_bucket`` (the cross-host AGREED group bucket: every process
    must emit identical codec segment shapes for the global wire)."""
    if not batches:
        raise ValueError("cannot pack an empty group")
    first = batches[0]
    if not isinstance(first, RaggedUnitBatch):
        raise TypeError("pack_ragged_group is the ragged wire's group pack")
    k = len(batches)
    for rb in batches[1:]:
        if (
            not isinstance(rb, RaggedUnitBatch)
            or (rb.row_len, rb.num_shards) != (first.row_len, first.num_shards)
            or rb.units.shape != first.units.shape
            or rb.units.dtype != first.units.dtype
            or rb.mask.shape != first.mask.shape
        ):
            raise ValueError(
                "group batches must share one wire signature (shapes, "
                "dtypes, row_len, shard alignment)"
            )
    s = first.num_shards
    b = first.mask.shape[0]
    bl = b // s
    n_sb = first.units.shape[0] // s
    narrow = (
        offsets_narrow(first.row_len) if narrow_offsets is None
        else narrow_offsets
    )
    # fused native fast path (r17): one C sweep over the K batches emits
    # the identical shard-major buffer; None falls through to the truth
    from .assemble import try_assemble_group

    fast = try_assemble_group(
        batches, s, bl, n_sb, narrow, codec, codec_bucket, num_shards_out
    )
    if fast is not None:
        return fast
    specs = (
        ((lambda rb: rb.units), (n_sb,)),
        (
            (lambda rb: _offsets_to_deltas(rb.offsets, s))
            if narrow else (lambda rb: rb.offsets),
            (bl,) if narrow else (bl + 1,),
        ),
        ((lambda rb: rb.numeric), (bl, NUM_NUMBER_FEATURES)),
        ((lambda rb: rb.label), (bl,)),
        ((lambda rb: rb.mask), (bl,)),
    )
    # [S, K, ...] per field: shard-major so P(data) on the flattened buffer
    # hands each device exactly its own K segments
    fields = list(
        np.ascontiguousarray(np.stack(
            [np.asarray(get(rb)).reshape((s,) + shape) for rb in batches],
            axis=1,
        ))
        for get, shape in specs
    )
    # compressed units wire (``--wireCodec dict``): every (shard, k)
    # segment's sub-buffer encodes independently into one shared bucket —
    # each device slice / scan step decodes exactly its own segments
    codes = _encode_units_segments(fields[0], s * k, codec, bucket=codec_bucket)
    if codes is not None:
        fields[0] = np.ascontiguousarray(
            codes.reshape(s, k, codes.shape[1])
        )
    layout = (
        "RaggedGroupSegments",
        tuple((f.shape[2:], f.dtype.str) for f in fields),
        (
            first.row_len, num_shards_out or s, k,
            "u16delta" if narrow else "i32",
        ) + (() if codes is None else (("dict", n_sb),)),
    )
    return _finish_pack(
        [f.view(np.uint8).reshape(s, k, -1) for f in fields], 2, layout
    )


def _decode_offsets_stacked(arr, s_here: int):
    """Stacked [K, S·B_s] delta wire → [K, S·(B_s+1)] int32 offsets."""
    if isinstance(arr, np.ndarray):
        k = arr.shape[0]
        return _deltas_to_offsets_np(
            arr.reshape(k * s_here, -1), k * s_here
        ).reshape(k, -1)
    from ..ops.ragged import offsets_from_deltas

    return offsets_from_deltas(arr, s_here)


def _unpack_ragged_group(buffer, layout: tuple) -> "RaggedUnitBatch":
    """Rebuild the STACKED ragged batch ([K, ...] leaves — what
    ``stack_batches`` would have produced) from a ``RaggedGroupSegments``
    buffer. Host numpy gets the full group back shard-aligned; inside a
    jit program (single device, or a shard_map body's local slice) the
    buffer holds ONE shard's K segments and the zero-copy bitcasts rebuild
    the shard-local stacked batch the scanned step consumes."""
    fields_meta = layout[1]
    row_len, _s_total, k, offs_mode = layout[2][:4]
    codec_tag = _layout_codec(layout)
    per_seg = sum(
        int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in fields_meta
    )
    s_here = buffer.shape[0] // (k * per_seg)
    if buffer.shape[0] != s_here * k * per_seg:
        raise ValueError(
            f"buffer of {buffer.shape[0]} bytes is not a whole number of "
            f"{k}x{per_seg}-byte group segments"
        )
    fields = []
    off = 0
    for shape, dtype_str in fields_meta:
        dt = np.dtype(dtype_str)
        count = int(np.prod(shape, dtype=np.int64))
        nbytes = count * dt.itemsize
        if isinstance(buffer, np.ndarray):
            chunk = np.ascontiguousarray(
                buffer.reshape(s_here, k, per_seg)[:, :, off : off + nbytes]
            )
            arr = chunk.view(dt).reshape((s_here, k) + shape)
            # [S, K, d0, ...] → [K, S·d0, ...]: K leads (the scan axis),
            # the segment axis folds back into each leaf's leading dim
            arr = np.ascontiguousarray(
                arr.transpose((1, 0) + tuple(range(2, arr.ndim)))
            ).reshape((k, s_here * shape[0]) + shape[1:])
        else:
            from jax import lax

            if s_here != 1:
                raise ValueError(
                    "device-side group unpack sees exactly one shard "
                    "segment (the shard_map-local slice)"
                )
            chunk = buffer.reshape(k, per_seg)[:, off : off + nbytes]
            if dt.itemsize > 1:
                chunk = chunk.reshape(k, count, dt.itemsize)
            arr = lax.bitcast_convert_type(chunk, dt).reshape((k,) + shape)
        off += nbytes
        fields.append(arr)
    if codec_tag is not None:
        n_sb_raw = int(codec_tag[1])
        fields[0] = _decode_units(
            fields[0].reshape(k, s_here, -1), n_sb_raw
        ).reshape(k, s_here * n_sb_raw)
    if offs_mode == "u16delta":
        fields[1] = _decode_offsets_stacked(fields[1], s_here)
    return RaggedUnitBatch(
        *fields, row_len=row_len, num_shards=s_here if s_here > 1 else 1
    )


def pack_batch(
    batch: "FeatureBatch | UnitBatch | RaggedUnitBatch",
    narrow_offsets: "bool | None" = None,
    codec: "str | None" = None,
) -> PackedBatch:
    """Flatten a host batch into one uint8 wire buffer (cheap memcpy).
    RaggedUnitBatch packs its five arrays too, with ``row_len`` carried in
    the static layout (third element) — and its offsets ship as uint16
    length deltas whenever the static ``row_len`` gate allows
    (``offsets_narrow``; the in-jit unpack cumsums them back,
    bit-identically — the Lean-wire-v2 sideband shrink). ``codec="dict"``
    additionally digram-compresses the ragged units buffer (one stream —
    this flat layout is never device-sliced), decoded in-jit by the
    unpack; ineligible/incompressible batches keep the raw layout."""
    if isinstance(batch, RaggedUnitBatch):
        narrow = (
            offsets_narrow(batch.row_len) if narrow_offsets is None
            else narrow_offsets
        )
        # fused native fast path (r17): the k=1, s=1 degenerate of the
        # same C entry; None falls through to the ground truth
        from .assemble import try_assemble_flat

        fast = try_assemble_flat(batch, narrow, codec)
        if fast is not None:
            return fast
        offs = (
            _offsets_to_deltas(batch.offsets, batch.num_shards)
            if narrow
            else batch.offsets
        )
        units = np.asarray(batch.units)
        codes = _encode_units_codec(units, codec)
        arrays: tuple = (
            units if codes is None else codes, offs, batch.numeric,
            batch.label, batch.mask,
        )
        extra: "tuple | None" = (
            batch.row_len, batch.num_shards,
            "u16delta" if narrow else "i32",
        ) + (() if codes is None else (("dict", tuple(units.shape)),))
    else:
        arrays = tuple(batch)
        extra = None
    fields = tuple(np.ascontiguousarray(a) for a in arrays)
    layout = (
        type(batch).__name__,
        tuple((a.shape, a.dtype.str) for a in fields),
    ) + ((extra,) if extra is not None else ())
    return _finish_pack(
        [a.view(np.uint8).reshape(-1) for a in fields], 0, layout
    )


def unpack_batch(buffer, layout: tuple):
    """Rebuild the batch from the wire buffer — works on device inside jit
    (bitcast + reshape; no data movement) and on host numpy alike."""
    if layout[0] == "RaggedShardSegments":
        return _unpack_ragged_shards(buffer, layout)
    if layout[0] == "RaggedGroupSegments":
        return _unpack_ragged_group(buffer, layout)
    cls = {
        "FeatureBatch": FeatureBatch,
        "UnitBatch": UnitBatch,
        "RaggedUnitBatch": RaggedUnitBatch,
    }[layout[0]]
    fields = []
    off = 0
    for shape, dtype_str in layout[1]:
        dt = np.dtype(dtype_str)
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = count * dt.itemsize
        chunk = buffer[off : off + nbytes]
        off += nbytes
        if isinstance(chunk, np.ndarray):
            arr = chunk.view(dt).reshape(shape)
        else:
            from jax import lax

            if dt.itemsize > 1:
                chunk = chunk.reshape(count, dt.itemsize)
            arr = lax.bitcast_convert_type(chunk, dt).reshape(shape)
        fields.append(arr)
    if cls is RaggedUnitBatch:
        extra = layout[2]
        num_shards = extra[1] if len(extra) > 1 else 1
        codec_tag = _layout_codec(layout)
        if codec_tag is not None:
            raw_shape = tuple(codec_tag[1])
            n_raw = int(np.prod(raw_shape, dtype=np.int64))
            fields[0] = _decode_units(
                fields[0].reshape(-1), n_raw
            ).reshape(raw_shape)
        if len(extra) > 2 and extra[2] == "u16delta":
            fields[1] = _decode_offsets(fields[1], num_shards)
        return RaggedUnitBatch(
            *fields,
            row_len=extra[0],
            num_shards=num_shards,
        )
    return cls(*fields)


# ---- multi-tenant routing (ISSUE 7) ---------------------------------------
# The tenant plane splits one featurized batch's VALID rows into M per-tenant
# batches of the SAME padded shape (one wire signature — the lockstep
# invariant extended to tenants: dry tenants ship all-padding batches so the
# collective/jit program is identical every tick), then reuses the K-batch
# superbatch wire (stack_batches / pack_ragged_group) as the K-tenant wire.
# Routing is a pure deterministic function of the batch, so the delivery-side
# split (per-tenant stats, prediction re-ordering) recomputes it instead of
# carrying a permutation through the fetch pipeline.

def _splitmix(x: np.ndarray) -> np.ndarray:
    """SplitMix64 finalizer over uint64 lanes — a routing mixer, not a
    cryptographic hash (uniform-ish A/B-arm splits from weak row sums)."""
    x = x ^ (x >> np.uint64(33))
    x = x * np.uint64(0xFF51AFD7ED558CCD)
    return x ^ (x >> np.uint64(33))


def _ragged_row_sums(units: np.ndarray, offsets: np.ndarray):
    """(per-row unit sums, per-row lengths) of a FLAT ragged buffer —
    cumsum-based so the host pass stays vectorized."""
    offs = np.asarray(offsets, np.int64)
    u = np.asarray(units, np.uint64)
    c = np.zeros((u.shape[0] + 1,), np.uint64)
    np.cumsum(u, out=c[1:])
    return c[offs[1:]] - c[offs[:-1]], (offs[1:] - offs[:-1])


def tenant_route_keys(
    batch, num_tenants: int, mode: str = "hash"
) -> np.ndarray:
    """Per-row tenant id [B] for a host batch — the cheap host-side routing
    key of the multi-tenant plane (``--tenantKey``).

    ``hash``: SplitMix64 over (unit-sum, length) per row — a uniform
    A/B-arm style split, content-deterministic on every wire (FeatureBatch
    rows key off their hashed-token sums instead of raw units).
    ``lang``: a script-class heuristic from the row's max code unit (0 for
    pure-ASCII rows, else keyed by the max unit's high byte) — the
    per-language/per-script scenario axis; requires a raw-units wire
    (device hashing), because host-hashed tokens carry no script signal.

    Padding rows get tenant 0 (they are masked out of every tenant batch
    anyway). Keys are heuristic ROUTING, not semantics: each tenant's model
    math on its routed rows stays byte-identical to the reference
    single-model path (PARITY.md)."""
    m = np.uint64(num_tenants)
    if isinstance(batch, RaggedUnitBatch):
        if batch.num_shards != 1:
            raise ValueError(
                "route before shard alignment (tenant batches are "
                "shard-aligned per tenant afterwards)"
            )
        sums, lengths = _ragged_row_sums(batch.units, batch.offsets)
        if mode == "lang":
            units = np.asarray(batch.units, np.uint64)
            offs = np.asarray(batch.offsets, np.int64)
            if units.shape[0] == 0:
                maxs = np.zeros(lengths.shape, np.uint64)
            else:
                safe = np.minimum(offs[:-1], units.shape[0] - 1)
                maxs = np.maximum.reduceat(units, safe)
            maxs = np.where(lengths > 0, maxs, np.uint64(0))
            cls = np.where(
                maxs < 128, np.uint64(0), np.uint64(1) + (maxs >> np.uint64(8))
            )
            return (cls % m).astype(np.int32)
    elif isinstance(batch, UnitBatch):
        units = np.asarray(batch.units, np.uint64)
        sums = units.sum(axis=1)
        lengths = np.asarray(batch.length, np.uint64)
        if mode == "lang":
            maxs = units.max(axis=1) if units.shape[1] else np.zeros_like(sums)
            cls = np.where(
                maxs < 128, np.uint64(0), np.uint64(1) + (maxs >> np.uint64(8))
            )
            return (cls % m).astype(np.int32)
    elif isinstance(batch, FeatureBatch):
        if mode == "lang":
            raise ValueError(
                "--tenantKey lang needs a raw-units wire (--hashOn device); "
                "host-hashed tokens carry no script signal"
            )
        sums = np.asarray(batch.token_idx, np.int64).astype(np.uint64).sum(axis=1)
        lengths = (np.asarray(batch.token_val) != 0).sum(axis=1).astype(np.uint64)
    else:
        raise TypeError(f"cannot route a {type(batch).__name__}")
    if mode != "hash":
        raise ValueError(f"tenant key mode must be 'hash' or 'lang', got {mode!r}")
    x = (
        sums.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        + lengths.astype(np.uint64) * np.uint64(0xBF58476D1CE4E5B9)
    )
    return (_splitmix(x) % m).astype(np.int32)


def tenant_rows(batch, tenant_ids: np.ndarray, num_tenants: int):
    """Per-tenant original-row indices [list of M int arrays], valid rows
    only, ascending (original relative order preserved within each tenant —
    the parity law's ordering holds on each tenant's sub-stream)."""
    valid = np.asarray(batch.mask) > 0
    ids = np.where(valid, np.asarray(tenant_ids), -1)
    return [np.nonzero(ids == m)[0] for m in range(num_tenants)]


def split_batch_tenants(batch, tenant_ids: np.ndarray, num_tenants: int):
    """One featurized batch → M per-tenant batches of the SAME padded shape
    (same row bucket, same units buffer / token shape, same row_len), valid
    rows routed by ``tenant_ids`` and packed to the front in original
    relative order; dry tenants come back all-padding. The M batches share
    one wire signature by construction, so ``stack_batches`` /
    ``pack_ragged_group`` turn them into the one-tenant-wire upload."""
    rows_per = tenant_rows(batch, tenant_ids, num_tenants)
    if isinstance(batch, RaggedUnitBatch):
        units = np.asarray(batch.units)
        offs = np.asarray(batch.offsets, np.int64)
        lengths = offs[1:] - offs[:-1]
        b = batch.mask.shape[0]
        out = []
        for rows in rows_per:
            lens_m = lengths[rows]
            total = int(lens_m.sum())
            units_m = np.zeros_like(units)
            cml = np.zeros((rows.shape[0] + 1,), np.int64)
            np.cumsum(lens_m, out=cml[1:])
            if total:
                idx = (
                    np.arange(total, dtype=np.int64)
                    - np.repeat(cml[:-1], lens_m)
                    + np.repeat(offs[rows], lens_m)
                )
                units_m[:total] = units[idx]
            offs_m = np.full((b + 1,), total, np.int32)
            offs_m[: rows.shape[0] + 1] = cml.astype(np.int32)
            numeric = np.zeros_like(np.asarray(batch.numeric))
            label = np.zeros_like(np.asarray(batch.label))
            mask = np.zeros_like(np.asarray(batch.mask))
            n = rows.shape[0]
            numeric[:n] = np.asarray(batch.numeric)[rows]
            label[:n] = np.asarray(batch.label)[rows]
            mask[:n] = 1.0
            out.append(RaggedUnitBatch(
                units_m, offs_m, numeric, label, mask,
                row_len=batch.row_len, num_shards=1,
            ))
        return out
    out = []
    for rows in rows_per:
        n = rows.shape[0]
        fields = []
        for arr in batch:
            arr = np.asarray(arr)
            dest = np.zeros_like(arr)
            dest[:n] = arr[rows]
            fields.append(dest)
        out.append(type(batch)(*fields))
    return out


def stack_batches(batches):
    """K same-shape batches → one batch whose arrays carry a leading [K]
    axis — the superbatch wire format for ``StreamingSGDModel.step_many``
    (one transfer + one dispatch per K micro-batches). All batches must
    share type, shapes, and dtypes (the padded-bucket contract guarantees
    this within a stream; ragged batches additionally share their
    data-dependent units bucket — the SuperBatcher's shape signature
    groups only batches that do)."""
    first = batches[0]
    for b in batches[1:]:
        if type(b) is not type(first):
            raise TypeError("cannot stack mixed batch types")
    if isinstance(first, RaggedUnitBatch):
        for b in batches[1:]:
            if (b.row_len, b.num_shards) != (first.row_len, first.num_shards):
                raise ValueError(
                    "cannot stack ragged batches with different row_len or "
                    "shard alignment"
                )
        return RaggedUnitBatch(
            *(
                np.stack([getattr(b, f) for b in batches])
                for f in ("units", "offsets", "numeric", "label", "mask")
            ),
            row_len=first.row_len,
            num_shards=first.num_shards,
        )
    return type(first)(*(np.stack(arrs) for arrs in zip(*batches)))


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket ≥ n (≥ minimum), to bound compile count."""
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_row_count(n: int, row_bucket: int, row_multiple: int = 1) -> int:
    """Padded row count: the requested bucket when it fits, else the
    power-of-two bucket — then rounded up to ``row_multiple`` (mesh data-axis
    divisibility for shard_map training)."""
    b = row_bucket if row_bucket >= n and row_bucket > 0 else _bucket(max(n, 1))
    if row_multiple > 1:
        b += (-b) % row_multiple
    return b


def pad_feature_batch(
    rows: list[tuple[dict[int, float], np.ndarray, float]],
    row_bucket: int = 0,
    token_bucket: int = 0,
    row_multiple: int = 1,
    num_features: int = 0,
    counts: bool = False,
) -> FeatureBatch:
    """Assemble per-tweet sparse features into one padded FeatureBatch.

    ``rows`` holds (text_counts: {hashed_idx: count}, numeric[4], label) per
    tweet, i.e. the output of ``Featurizer.featurize``. Padding rows carry
    mask 0 and are excluded from every statistic and gradient on device.
    """
    n = len(rows)
    max_tok = max((len(r[0]) for r in rows), default=1)
    b = pad_row_count(n, row_bucket, row_multiple)
    lt = token_bucket if token_bucket >= max_tok and token_bucket > 0 else _bucket(
        max(max_tok, 1)
    )

    token_idx = np.zeros((b, lt), dtype=np.int32)
    token_val = np.zeros((b, lt), dtype=np.float32)
    numeric = np.zeros((b, NUM_NUMBER_FEATURES), dtype=np.float32)
    label = np.zeros((b,), dtype=np.float32)
    mask = np.zeros((b,), dtype=np.float32)

    for i, (text_counts, nums, lab) in enumerate(rows):
        for j, (idx, val) in enumerate(text_counts.items()):
            token_idx[i, j] = idx
            token_val[i, j] = val
        numeric[i] = nums
        label[i] = lab
        mask[i] = 1.0
    token_idx, token_val = compact_tokens(
        token_idx, token_val, num_features, counts=counts
    )
    return FeatureBatch(token_idx, token_val, numeric, label, mask)
