"""Fixed-shape padded micro-batches — the XLA-facing data contract.

The reference hands MLlib a per-tweet ``LabeledPoint`` with a 1004-dim sparse
vector (MllibHelper.scala:73-82). XLA wants static shapes, so a micro-batch
here is a struct of padded arrays: hashed token indices/counts per tweet
(sparse text features), the 4 dense numeric features, labels, and a validity
mask. Batch row counts and token counts are padded up to bucket sizes so a
stream of varying batch sizes reuses a small set of compiled programs instead
of recompiling per batch (SURVEY.md §7 "hard parts" (a)).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

NUM_NUMBER_FEATURES = 4  # MllibHelper.scala:13


class FeatureBatch(NamedTuple):
    """One padded micro-batch. All arrays are host numpy until the learner
    moves them to device; as a NamedTuple it is automatically a JAX pytree.

    Shapes (B = padded rows, L = padded tokens/tweet):
      token_idx: int32  [B, L] — hashed bigram indices into [0, numTextFeatures)
      token_val: float32[B, L] — term-frequency counts (0 where padded)
      numeric:   float32[B, 4] — scaled followers/favourites/friends/age feats
      label:     float32[B]    — retweet count of the retweeted status
      mask:      float32[B]    — 1.0 for real rows, 0.0 for padding
    """

    token_idx: np.ndarray
    token_val: np.ndarray
    numeric: np.ndarray
    label: np.ndarray
    mask: np.ndarray

    @property
    def num_valid(self) -> int:
        return int(self.mask.sum())


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket ≥ n (≥ minimum), to bound compile count."""
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_row_count(n: int, row_bucket: int, row_multiple: int = 1) -> int:
    """Padded row count: the requested bucket when it fits, else the
    power-of-two bucket — then rounded up to ``row_multiple`` (mesh data-axis
    divisibility for shard_map training)."""
    b = row_bucket if row_bucket >= n and row_bucket > 0 else _bucket(max(n, 1))
    if row_multiple > 1:
        b += (-b) % row_multiple
    return b


def pad_feature_batch(
    rows: list[tuple[dict[int, float], np.ndarray, float]],
    row_bucket: int = 0,
    token_bucket: int = 0,
    row_multiple: int = 1,
) -> FeatureBatch:
    """Assemble per-tweet sparse features into one padded FeatureBatch.

    ``rows`` holds (text_counts: {hashed_idx: count}, numeric[4], label) per
    tweet, i.e. the output of ``Featurizer.featurize``. Padding rows carry
    mask 0 and are excluded from every statistic and gradient on device.
    """
    n = len(rows)
    max_tok = max((len(r[0]) for r in rows), default=1)
    b = pad_row_count(n, row_bucket, row_multiple)
    lt = token_bucket if token_bucket >= max_tok and token_bucket > 0 else _bucket(
        max(max_tok, 1)
    )

    token_idx = np.zeros((b, lt), dtype=np.int32)
    token_val = np.zeros((b, lt), dtype=np.float32)
    numeric = np.zeros((b, NUM_NUMBER_FEATURES), dtype=np.float32)
    label = np.zeros((b,), dtype=np.float32)
    mask = np.zeros((b,), dtype=np.float32)

    for i, (counts, nums, lab) in enumerate(rows):
        for j, (idx, val) in enumerate(counts.items()):
            token_idx[i, j] = idx
            token_val[i, j] = val
        numeric[i] = nums
        label[i] = lab
        mask[i] = 1.0
    return FeatureBatch(token_idx, token_val, numeric, label, mask)
