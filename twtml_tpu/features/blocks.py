"""Columnar tweet blocks — the native data-loader's output format.

A ParsedBlock is a filtered batch of tweets in columnar form, straight from
the C parser (native/tweetjson.cpp): the featurizer-relevant numeric fields,
plus the original tweets' text as concatenated UTF-16 code units. It skips
per-tweet Python objects entirely — the ~11 µs/tweet of json.loads +
Status assembly that caps the object ingest path near 90k tweets/s on one
core. ``Featurizer.featurize_parsed_block`` turns one (or several merged)
blocks directly into the UnitBatch wire format.

The Python object path (sources.ReplayFileSource → Status → featurize_*)
remains the semantic ground truth; differential tests assert the two paths
produce identical batches.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

# columns of ParsedBlock.numeric (int64), in parser output order
COL_LABEL = 0  # retweeted status' retweet_count (the label)
COL_FOLLOWERS = 1
COL_FAVOURITES = 2
COL_FRIENDS = 3
COL_CREATED_MS = 4


class ParsedBlock(NamedTuple):
    """Filtered, columnar tweets. ``numeric`` is int64 [rows, 5] (see COL_*),
    ``units`` the concatenated UTF-16 code units of the original texts (NOT
    lowercased), ``offsets`` int64 [rows+1] into units, ``ascii`` uint8
    [rows] (1 = every unit < 128, so ASCII pad-time folding suffices).

    ``units`` is uint16, or **uint8** straight from the zero-copy wire
    parser (``native.parse_tweet_block_wire``) when every row is ASCII —
    the ragged wire's narrow dtype, carried from the parser so no
    downstream downcast pass exists. The values are the same code units
    either way; ``merge_blocks`` of mixed-dtype blocks promotes to uint16
    (numpy concatenate), which is exactly the non-ASCII wire dtype."""

    numeric: np.ndarray
    units: np.ndarray
    offsets: np.ndarray
    ascii: np.ndarray

    @property
    def rows(self) -> int:
        return int(self.numeric.shape[0])


def slice_block(block: ParsedBlock, start: int, stop: int) -> ParsedBlock:
    """Rows [start, stop) as a standalone block (offsets re-based)."""
    return ParsedBlock(
        block.numeric[start:stop],
        block.units[block.offsets[start] : block.offsets[stop]],
        block.offsets[start : stop + 1] - block.offsets[start],
        block.ascii[start:stop],
    )


def iter_row_chunks(blocks, rows: int):
    """Regroup a stream of ParsedBlocks into blocks of exactly ``rows`` rows
    (the final chunk may be short) — the micro-batch slicer between the
    native parser's IO-sized blocks and the learner's fixed batch shape.
    Consumes ``blocks`` lazily, so it composes with a parser running on
    another thread (the parse/featurize/train pipeline)."""
    pending: list[ParsedBlock] = []
    have = 0
    for b in blocks:
        if b.rows == 0:
            continue
        pending.append(b)
        have += b.rows
        while have >= rows:
            take, acc = rows, []
            while take:
                head = pending[0]
                if head.rows <= take:
                    acc.append(pending.pop(0))
                    take -= head.rows
                else:
                    acc.append(slice_block(head, 0, take))
                    pending[0] = slice_block(head, take, head.rows)
                    take = 0
            have -= rows
            yield merge_blocks(acc)
    if have:
        yield merge_blocks(pending)


def empty_block() -> ParsedBlock:
    """A zero-row block (a replay file where no line passed the filter)."""
    return ParsedBlock(
        np.zeros((0, 5), np.int64),
        np.zeros((0,), np.uint16),
        np.zeros((1,), np.int64),
        np.zeros((0,), np.uint8),
    )


def merge_blocks(blocks: "list[ParsedBlock]") -> ParsedBlock:
    """Concatenate blocks drained from one micro-batch interval; an empty
    list merges to a zero-row block."""
    if not blocks:
        return empty_block()
    if len(blocks) == 1:
        return blocks[0]
    numeric = np.concatenate([b.numeric for b in blocks], axis=0)
    units = np.concatenate([b.units for b in blocks])
    sizes = [b.offsets[-1] for b in blocks]
    offsets = [blocks[0].offsets]
    base = sizes[0]
    for b, size in zip(blocks[1:], sizes[1:]):
        offsets.append(b.offsets[1:] + base)
        base += size
    return ParsedBlock(
        numeric,
        units,
        np.concatenate(offsets),
        np.concatenate([b.ascii for b in blocks]),
    )
