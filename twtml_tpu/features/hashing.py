"""Hashing-trick text features, bit-compatible with MLlib's HashingTF.

The reference featurizes tweets with ``new HashingTF(numTextFeatures)`` over
character bigrams (MllibHelper.scala:18,42-56). MLlib 1.6's HashingTF maps a
term to ``nonNegativeMod(term.##, numFeatures)`` where ``.##`` on a String is
Java ``String.hashCode`` — a 31-ary polynomial over UTF-16 code units in
32-bit signed arithmetic. Reproducing that hash exactly makes our feature
vectors (and therefore RMSE curves) directly comparable with the reference.

A C++ fast path for whole-tweet hashing lives in ``native/`` (optional); this
module is the always-available pure-Python implementation and the semantic
ground truth.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable


def java_string_hashcode(s: str) -> int:
    """Java ``String.hashCode``: h = 31*h + c over UTF-16 code units,
    wrapping in 32-bit signed arithmetic.

    Characters outside the BMP (emoji — common in tweets) contribute their
    two surrogate code units, exactly as on the JVM.
    """
    h = 0
    for unit_lo, unit_hi in zip(
        *[iter(s.encode("utf-16-le"))] * 2
    ):  # little-endian 16-bit code units
        cu = unit_lo | (unit_hi << 8)
        h = (31 * h + cu) & 0xFFFFFFFF
    if h >= 0x80000000:
        h -= 0x100000000
    return h


def non_negative_mod(x: int, mod: int) -> int:
    """MLlib Utils.nonNegativeMod; equals Python's ``%`` for positive mod."""
    return x % mod


def char_bigrams(text: str) -> list[str]:
    """Scala ``text.sliding(2)``: consecutive 2-char windows; a string shorter
    than 2 yields itself as the single (short) window, empty yields nothing."""
    if len(text) == 0:
        return []
    if len(text) < 2:
        return [text]
    return [text[i : i + 2] for i in range(len(text) - 1)]


def hashing_tf_counts(terms: Iterable[str], num_features: int) -> dict[int, float]:
    """HashingTF.transform: term-frequency counts keyed by hashed index.
    Distinct terms colliding on an index accumulate, like MLlib."""
    counts: Counter[int] = Counter()
    for term in terms:
        counts[non_negative_mod(java_string_hashcode(term), num_features)] += 1
    return {idx: float(c) for idx, c in counts.items()}
