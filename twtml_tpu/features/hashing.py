"""Hashing-trick text features, bit-compatible with MLlib's HashingTF.

The reference featurizes tweets with ``new HashingTF(numTextFeatures)`` over
character bigrams (MllibHelper.scala:18,42-56). MLlib 1.6's HashingTF maps a
term to ``nonNegativeMod(term.##, numFeatures)`` where ``.##`` on a String is
Java ``String.hashCode`` — a 31-ary polynomial over UTF-16 code units in
32-bit signed arithmetic. Reproducing that hash exactly makes our feature
vectors (and therefore RMSE curves) directly comparable with the reference.

A C++ fast path for whole-tweet hashing lives in ``native/`` (optional); this
module is the always-available pure-Python implementation and the semantic
ground truth.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable


def utf16_units(s: str) -> list[int]:
    """The string as JVM chars: UTF-16 code units (surrogates split)."""
    b = s.encode("utf-16-le", errors="surrogatepass")
    return [b[i] | (b[i + 1] << 8) for i in range(0, len(b), 2)]


def java_string_hashcode(s: str) -> int:
    """Java ``String.hashCode``: h = 31*h + c over UTF-16 code units,
    wrapping in 32-bit signed arithmetic.

    Characters outside the BMP (emoji — common in tweets) contribute their
    two surrogate code units, exactly as on the JVM; lone surrogates (which
    arise from unit-level bigram windows, see ``char_bigrams``) are accepted.
    """
    h = 0
    for cu in utf16_units(s):
        h = (31 * h + cu) & 0xFFFFFFFF
    if h >= 0x80000000:
        h -= 0x100000000
    return h


def non_negative_mod(x: int, mod: int) -> int:
    """MLlib Utils.nonNegativeMod; equals Python's ``%`` for positive mod."""
    return x % mod


def char_bigrams(text: str) -> list[str]:
    """Scala ``text.sliding(2)``: consecutive 2-char windows over the JVM's
    chars, i.e. UTF-16 CODE UNITS — an astral character (emoji) is two chars
    on the JVM, so its surrogate halves land in separate windows. A string
    shorter than 2 units yields itself as the single window, empty yields
    nothing. Returned strings may contain lone surrogates (valid Python str;
    hashing handles them via surrogatepass)."""
    units = utf16_units(text)
    if not units:
        return []
    if len(units) < 2:
        return [text]
    return [
        chr(units[i]) + chr(units[i + 1]) for i in range(len(units) - 1)
    ]


def hashing_tf_counts(terms: Iterable[str], num_features: int) -> dict[int, float]:
    """HashingTF.transform: term-frequency counts keyed by hashed index.
    Distinct terms colliding on an index accumulate, like MLlib."""
    counts: Counter[int] = Counter()
    for term in terms:
        counts[non_negative_mod(java_string_hashcode(term), num_features)] += 1
    return {idx: float(c) for idx, c in counts.items()}
