from .hashing import java_string_hashcode, hashing_tf_counts, char_bigrams
from .featurizer import Status, Featurizer
from .batch import FeatureBatch, UnitBatch, pad_feature_batch

__all__ = [
    "java_string_hashcode",
    "hashing_tf_counts",
    "char_bigrams",
    "Status",
    "Featurizer",
    "FeatureBatch",
    "UnitBatch",
    "pad_feature_batch",
]
