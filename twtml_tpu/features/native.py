"""ctypes bridge to the C++ fast featurizer (native/fasthash.cpp).

Builds the shared library on first use (g++ is in the image; no network or
pybind11 required), loads it via ctypes, and exposes ``fasthash_batch``
filling padded numpy buffers in place. Falls back silently when a compiler
isn't available — features/hashing.py stays the semantic ground truth and
the parity test asserts the two implementations agree bigram-for-bigram.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..utils import get_logger

log = get_logger("features.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRCS = [
    os.path.join(_REPO_ROOT, "native", "fasthash.cpp"),
    os.path.join(_REPO_ROOT, "native", "tweetjson.cpp"),
    os.path.join(_REPO_ROOT, "native", "wirecodec.cpp"),
    os.path.join(_REPO_ROOT, "native", "wireassemble.cpp"),
    os.path.join(_REPO_ROOT, "native", "featurize.cpp"),
]
# TWTML_NATIVE_LIB: alternate build/load path for the shared library. The
# sanitizer harness (tools/native_sanity.py) builds an ASan/UBSan-
# instrumented copy WITHOUT clobbering the production .so next to the
# sources (a sanitized library needs its runtime preloaded — loading it
# from a normal run would fail).
_LIB = os.environ.get("TWTML_NATIVE_LIB", "") or os.path.join(
    _REPO_ROOT, "native", "libfasthash.so"
)


def _build_flags() -> list[str]:
    """Compile flags: full warnings always (the C parity fast paths get
    the same scrutiny as the Python side); TWTML_NATIVE_SANITIZE adds
    instrumented-build flags — comma-separated subset of {asan, ubsan},
    e.g. ``TWTML_NATIVE_SANITIZE=asan,ubsan`` — at -O1 with frame
    pointers so reports carry usable stacks."""
    flags = ["-O3", "-march=native", "-shared", "-fPIC", "-pthread",
             "-Wall", "-Wextra"]
    san = os.environ.get("TWTML_NATIVE_SANITIZE", "")
    if san:
        modes = {m.strip() for m in san.split(",") if m.strip()}
        unknown = modes - {"asan", "ubsan"}
        if unknown:
            log.warning(
                "TWTML_NATIVE_SANITIZE=%s: unknown mode(s) %s ignored "
                "(known: asan, ubsan)", san, ",".join(sorted(unknown)),
            )
            modes -= unknown
        sanitizers = [s for m, s in (("asan", "address"),
                                     ("ubsan", "undefined")) if m in modes]
        if sanitizers:
            flags = ["-O1", "-g", "-fno-omit-frame-pointer",
                     f"-fsanitize={','.join(sanitizers)}",
                     "-march=native", "-shared", "-fPIC", "-pthread",
                     "-Wall", "-Wextra", "-Werror"]
    return flags

# the C data-loader's per-row text bound (kMaxTextUnits, native/tweetjson.cpp):
# a retweeted status whose text/full_text exceeds this many UTF-16 units makes
# the line a counted bad line in BOTH block paths (C and Python fallback)
MAX_TEXT_UNITS = 4096


def _sources_ok() -> bool:
    return all(os.path.exists(s) for s in _SRCS)


def _sources_newer_than_lib() -> bool:
    lib_mtime = os.path.getmtime(_LIB)
    return any(
        os.path.exists(s) and os.path.getmtime(s) > lib_mtime for s in _SRCS
    )

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
# set when the loaded library predates the wire emitter (stale .so whose
# OLD symbol set still works): parse_tweet_block_wire() then returns None
# and block sources degrade LOUDLY to the ParsedBlock path — one warning +
# a registry counter, never a ctypes AttributeError mid-stream
_wire_missing = False
# same degrade seam for the digram wire-codec encoder (r15): a stale
# library missing ``digram_encode`` only flags this, and the codec falls
# back to the byte-identical numpy encoder (features/wirecodec.encode_np)
_codec_missing = False
# and for the fused wire assembler (r17): a stale library missing
# ``wire_assemble`` only flags this — one warning + the
# ``native.assemble_degraded`` counter — and every pack falls back to the
# byte-identical numpy pipeline (features/batch.py, the ground truth)
_assemble_missing = False
# and for the one-pass featurize emitter (r18): a stale library missing
# ``featurize_wire`` only flags this — one warning + the
# ``native.featurize_degraded`` counter — and the featurizer keeps
# running on the byte-identical Python/numpy path (the ground truth)
_featurize_missing = False


def _build() -> bool:
    # build to a temp path and os.replace: dlopen caches by inode, so a
    # rebuild in place would hand a retrying loader the same stale image —
    # the replace gives the retry a fresh inode (and never destroys a
    # still-loadable old library when the compile itself fails)
    tmp = _LIB + ".tmp"
    try:
        subprocess.run(
            ["g++", *_build_flags(), "-o", tmp, *_SRCS],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB)
        return True
    except Exception as exc:
        log.warning("native featurizer build failed (%s); using python path", exc)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def get_lib() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or _sources_newer_than_lib():
            if not _sources_ok() or not _build():
                return None
        try:
            lib = _load(_LIB)
        except AttributeError:
            # stale .so from before a symbol was added (mtime-equal artifact
            # copy defeats the rebuild check): rebuild once (to a fresh
            # inode — see _build) and retry
            if _sources_ok() and _build():
                try:
                    lib = _load(_LIB)
                except AttributeError:
                    lib = _try_degraded_load()
                except OSError as exc:
                    log.warning("native featurizer load failed (%s)", exc)
                    return None
            else:
                # cannot rebuild: keep the stale library usable for the
                # symbols it HAS — only the wire entry degrades (loudly)
                lib = _try_degraded_load()
            if lib is None:
                return None
        except OSError as exc:
            log.warning("native featurizer load failed (%s)", exc)
            return None
        _lib = lib
        return _lib


def _try_degraded_load() -> ctypes.CDLL | None:
    """Last-resort load of a stale library: every pre-wire symbol must
    bind (those AttributeErrors stay fatal — the lib is unusably old), but
    a missing wire emitter / codec encoder only flags ``_wire_missing`` /
    ``_codec_missing`` so block sources fall back to the ParsedBlock path
    (and the codec to its numpy encoder) instead of dying mid-stream."""
    try:
        return _load(_LIB, strict=False)
    except (OSError, AttributeError) as exc:
        log.warning("native featurizer is stale and could not be rebuilt "
                    "or loaded (%s); using python path", exc)
        return None


def _load(path: str, strict: bool = True) -> ctypes.CDLL:
    """dlopen + bind every exported symbol; AttributeError = stale library.
    ``strict=False`` tolerates the post-r6 additions — the wire emitter
    and the codec encoder — by flagging ``_wire_missing`` /
    ``_codec_missing`` instead of raising (see get_lib)."""
    lib = ctypes.CDLL(path)
    lib.fasthash_batch.restype = ctypes.c_int32
    lib.fasthash_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint16),  # units
        ctypes.POINTER(ctypes.c_int64),  # offsets
        ctypes.c_int32,  # batch
        ctypes.c_int32,  # num_features
        ctypes.c_int32,  # l_max
        ctypes.POINTER(ctypes.c_int32),  # out_idx
        ctypes.POINTER(ctypes.c_float),  # out_val
        ctypes.POINTER(ctypes.c_int32),  # out_ntok
        ctypes.c_int32,  # n_threads (<=0 = auto)
    ]
    lib.pad_units_batch.restype = ctypes.c_int32
    lib.pad_units_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint16),  # units
        ctypes.POINTER(ctypes.c_int64),  # offsets
        ctypes.c_int32,  # batch
        ctypes.c_int32,  # padded_rows
        ctypes.c_int32,  # l_max
        ctypes.c_int32,  # ascii_lower
        ctypes.POINTER(ctypes.c_uint16),  # out_units
        ctypes.POINTER(ctypes.c_int32),  # out_len
    ]
    lib.pad_units_batch_u8.restype = ctypes.c_int32
    lib.pad_units_batch_u8.argtypes = [
        ctypes.POINTER(ctypes.c_uint16),  # units
        ctypes.POINTER(ctypes.c_int64),  # offsets
        ctypes.c_int32,  # batch
        ctypes.c_int32,  # padded_rows
        ctypes.c_int32,  # l_max
        ctypes.c_int32,  # ascii_lower
        ctypes.POINTER(ctypes.c_uint8),  # out_units
        ctypes.POINTER(ctypes.c_int32),  # out_len
    ]
    lib.lexicon_score_batch.restype = None
    lib.lexicon_score_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint16),  # units
        ctypes.POINTER(ctypes.c_int64),  # offsets
        ctypes.c_int32,  # batch
        ctypes.POINTER(ctypes.c_uint16),  # pos_words
        ctypes.POINTER(ctypes.c_int64),  # pos_off
        ctypes.POINTER(ctypes.c_int32),  # pos_hash
        ctypes.c_int32,  # n_pos
        ctypes.POINTER(ctypes.c_uint16),  # neg_words
        ctypes.POINTER(ctypes.c_int64),  # neg_off
        ctypes.POINTER(ctypes.c_int32),  # neg_hash
        ctypes.c_int32,  # n_neg
        ctypes.POINTER(ctypes.c_int32),  # out_score
        ctypes.POINTER(ctypes.c_uint8),  # out_ok
    ]
    lib.parse_tweet_block.restype = ctypes.c_int64
    lib.parse_tweet_block.argtypes = [
        ctypes.c_char_p,  # buf
        ctypes.c_int64,  # len
        ctypes.c_int64,  # begin
        ctypes.c_int64,  # end
        ctypes.c_int64,  # cap_rows
        ctypes.c_int64,  # cap_units
        ctypes.POINTER(ctypes.c_int64),  # out_numeric [rows,5]
        ctypes.POINTER(ctypes.c_uint16),  # out_units
        ctypes.POINTER(ctypes.c_int64),  # out_offsets [rows+1]
        ctypes.POINTER(ctypes.c_uint8),  # out_ascii [rows]
        ctypes.POINTER(ctypes.c_int64),  # consumed
        ctypes.POINTER(ctypes.c_int64),  # bad_lines
    ]
    _bind_wire(lib, strict)
    _bind_codec(lib, strict)
    _bind_assemble(lib, strict)
    _bind_featurize(lib, strict)
    return lib


def _bind_wire(lib: ctypes.CDLL, strict: bool) -> None:
    """Bind the zero-copy wire emitter. A library missing it is stale;
    strict loads raise (so get_lib's rebuild kicks in), degraded loads flag
    ``_wire_missing`` ONCE — warning + ``native.wire_degraded`` counter —
    and the block sources keep running on the ParsedBlock path."""
    global _wire_missing
    try:
        fn = lib.parse_tweet_block_wire
    except AttributeError:
        if strict:
            raise
        _wire_missing = True
        log.warning(
            "native library is stale: parse_tweet_block_wire missing — "
            "block sources degrade to the ParsedBlock parser (delete "
            "native/libfasthash.so to force a rebuild of the zero-copy "
            "wire path)"
        )
        from ..telemetry import metrics as _metrics

        _metrics.get_registry().counter("native.wire_degraded").inc()
        return
    fn.restype = ctypes.c_int64
    fn.argtypes = [
        ctypes.c_char_p,  # buf
        ctypes.c_int64,  # len
        ctypes.c_int64,  # begin
        ctypes.c_int64,  # end
        ctypes.c_int64,  # cap_rows
        ctypes.c_int64,  # cap_units
        ctypes.POINTER(ctypes.c_int64),  # out_numeric [rows,5]
        ctypes.POINTER(ctypes.c_uint8),  # out_units_u8
        ctypes.POINTER(ctypes.c_uint16),  # out_units_u16
        ctypes.POINTER(ctypes.c_int64),  # out_offsets [rows+1]
        ctypes.POINTER(ctypes.c_uint8),  # out_ascii [rows]
        ctypes.POINTER(ctypes.c_int64),  # consumed
        ctypes.POINTER(ctypes.c_int64),  # bad_lines
        ctypes.POINTER(ctypes.c_int64),  # narrow (out)
        ctypes.POINTER(ctypes.c_int64),  # needs_wide (out)
    ]
    _wire_missing = False


def _bind_codec(lib: ctypes.CDLL, strict: bool) -> None:
    """Bind the digram wire-codec encoder (native/wirecodec.cpp). Same
    degrade contract as ``_bind_wire``: strict loads raise (get_lib
    rebuilds), degraded loads flag ``_codec_missing`` ONCE and the codec
    keeps running on the byte-identical numpy encoder."""
    global _codec_missing
    try:
        fn = lib.digram_encode
    except AttributeError:
        if strict:
            raise
        _codec_missing = True
        log.warning(
            "native library is stale: digram_encode missing — the wire "
            "codec uses the numpy encoder (delete native/libfasthash.so "
            "to force a rebuild of the C fast path)"
        )
        from ..telemetry import metrics as _metrics

        _metrics.get_registry().counter("native.codec_degraded").inc()
        return
    fn.restype = ctypes.c_int64
    fn.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),  # in
        ctypes.c_int64,  # n
        ctypes.POINTER(ctypes.c_uint8),  # lut[65536]
        ctypes.POINTER(ctypes.c_uint8),  # out
        ctypes.c_int64,  # cap
    ]
    _codec_missing = False


def _bind_assemble(lib: ctypes.CDLL, strict: bool) -> None:
    """Bind the fused one-pass wire assembler (native/wireassemble.cpp).
    Same degrade contract as ``_bind_wire``/``_bind_codec``: strict loads
    raise (get_lib rebuilds), degraded loads flag ``_assemble_missing``
    ONCE — warning + ``native.assemble_degraded`` counter — and every
    pack keeps running on the byte-identical numpy pipeline."""
    global _assemble_missing
    try:
        fn = lib.wire_assemble
    except AttributeError:
        if strict:
            raise
        _assemble_missing = True
        log.warning(
            "native library is stale: wire_assemble missing — packs use "
            "the numpy pipeline (delete native/libfasthash.so to force a "
            "rebuild of the fused one-pass assembler)"
        )
        from ..telemetry import metrics as _metrics

        _metrics.get_registry().counter("native.assemble_degraded").inc()
        return
    fn.restype = ctypes.c_int64
    fn.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),  # units ptrs [k]
        ctypes.POINTER(ctypes.c_void_p),  # offsets ptrs [k]
        ctypes.POINTER(ctypes.c_void_p),  # numeric ptrs [k]
        ctypes.POINTER(ctypes.c_void_p),  # label ptrs [k]
        ctypes.POINTER(ctypes.c_void_p),  # mask ptrs [k]
        ctypes.c_int64,  # k
        ctypes.c_int64,  # s
        ctypes.c_int64,  # n_sb
        ctypes.c_int64,  # bl
        ctypes.c_int64,  # unit_size
        ctypes.c_int64,  # narrow_offsets
        ctypes.POINTER(ctypes.c_uint8),  # lut (None = codec off)
        ctypes.c_int64,  # forced codec bucket
        ctypes.POINTER(ctypes.c_uint8),  # scratch
        ctypes.POINTER(ctypes.c_int64),  # enc_lens
        ctypes.POINTER(ctypes.c_uint8),  # out
        ctypes.c_int64,  # cap
        ctypes.POINTER(ctypes.c_int64),  # out enc_bucket
    ]
    _assemble_missing = False


def _bind_featurize(lib: ctypes.CDLL, strict: bool) -> None:
    """Bind the one-pass featurize emitter (native/featurize.cpp). Same
    degrade contract as its siblings: strict loads raise (get_lib
    rebuilds), degraded loads flag ``_featurize_missing`` ONCE — warning
    + ``native.featurize_degraded`` counter — and the featurizer keeps
    running on the byte-identical Python/numpy ground truth."""
    global _featurize_missing
    try:
        fn = lib.featurize_wire
    except AttributeError:
        if strict:
            raise
        _featurize_missing = True
        log.warning(
            "native library is stale: featurize_wire missing — featurize "
            "uses the Python/numpy path (delete native/libfasthash.so to "
            "force a rebuild of the one-pass featurize emitter)"
        )
        from ..telemetry import metrics as _metrics

        _metrics.get_registry().counter("native.featurize_degraded").inc()
        return
    fn.restype = ctypes.c_int64
    # every pointer is c_void_p on purpose: the wrapper passes raw
    # ``arr.ctypes.data`` integers — ``data_as`` casts measured ~7 µs
    # EACH and this entry runs per batch on the featurize hot path
    fn.argtypes = [
        ctypes.c_void_p,  # units
        ctypes.c_int64,  # unit_size
        ctypes.c_void_p,  # offsets [n+1] int64
        ctypes.c_void_p,  # cols_f64 [n,5] or None
        ctypes.c_void_p,  # cols_i64 [n,5] or None
        ctypes.c_void_p,  # col_order [5] int64
        ctypes.c_int64,  # n
        ctypes.c_int64,  # b
        ctypes.c_int64,  # n_bucket
        ctypes.c_int64,  # now_ms
        ctypes.c_int64,  # narrow
        ctypes.c_void_p,  # out_units
        ctypes.c_void_p,  # out_offsets [b+1] int32
        ctypes.c_void_p,  # out_numeric [b,4] f32
        ctypes.c_void_p,  # out_label [b] f32
        ctypes.c_void_p,  # out_mask [b] f32
    ]
    _featurize_missing = False


def featurize_available() -> bool:
    """Whether the one-pass featurize emitter is loadable (library up and
    the symbol present — see _bind_featurize's degrade seam)."""
    return get_lib() is not None and not _featurize_missing


def featurize_wire_raw(*args) -> "int | None":
    """Raw-pointer form of the one-pass featurize entry: ``args`` are
    exactly the C signature's 16 values with every pointer as a plain
    int (or None). The hot caller (features/featurize_native.try_fill)
    computes its five output pointers from the ONE lease base address —
    each numpy ``.ctypes`` access builds an interface object (~2-3 µs)
    and this entry runs per batch. Returns the max row length, or None
    when the library is unavailable, predates the emitter, or refuses
    the input — callers fall back to the Python/numpy ground truth."""
    lib = get_lib()
    if lib is None or _featurize_missing:
        return None
    max_len = lib.featurize_wire(*args)
    if max_len < 0:  # caller sized n_bucket from these offsets; never expected
        return None
    return int(max_len)


def featurize_wire(
    units: np.ndarray,
    offsets: np.ndarray,
    cols: np.ndarray,
    col_order: np.ndarray,
    n: int,
    b: int,
    n_bucket: int,
    now_ms: int,
    narrow: bool,
    out_units: np.ndarray,
    out_offsets: np.ndarray,
    out_numeric: np.ndarray,
    out_label: np.ndarray,
    out_mask: np.ndarray,
) -> "int | None":
    """One C pass from encoded units + numeric columns to the final
    ragged-wire arrays (native/featurize.cpp): flat units (narrow uint8
    under the caller's metadata gate), padded int32 offsets, scaled f32
    numeric/label/mask — all written into the caller's (arena-leased)
    destinations. ``cols`` is float64 [n, 5] (object path) or int64
    [n, 5] (block parser columns); ``col_order`` maps its layout onto
    followers/favourites/friends/created_ms/label. Array-argument
    convenience form of ``featurize_wire_raw`` (same contract)."""
    if cols.dtype == np.float64:
        cols_f64, cols_i64 = cols.ctypes.data, None
    elif cols.dtype == np.int64:
        cols_f64, cols_i64 = None, cols.ctypes.data
    elif n:
        return None
    else:
        cols_f64 = cols_i64 = None
    return featurize_wire_raw(
        units.ctypes.data,
        int(units.dtype.itemsize),
        offsets.ctypes.data,
        cols_f64,
        cols_i64,
        col_order.ctypes.data,
        n,
        b,
        n_bucket,
        int(now_ms),
        1 if narrow else 0,
        out_units.ctypes.data,
        out_offsets.ctypes.data,
        out_numeric.ctypes.data,
        out_label.ctypes.data,
        out_mask.ctypes.data,
    )


def assemble_available() -> bool:
    """Whether the fused wire assembler is loadable (library up and the
    symbol present — see _bind_assemble's degrade seam)."""
    return get_lib() is not None and not _assemble_missing


def _ptr_array(arrays: "list[np.ndarray]"):
    return (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data for a in arrays]
    )


def wire_assemble(
    units: "list[np.ndarray]",
    offsets: "list[np.ndarray]",
    numeric: "list[np.ndarray]",
    label: "list[np.ndarray]",
    mask: "list[np.ndarray]",
    s: int,
    n_sb: int,
    bl: int,
    narrow: bool,
    lut: "np.ndarray | None",
    forced_bucket: int,
    scratch: "np.ndarray | None",
    enc_lens: "np.ndarray | None",
    out: np.ndarray,
) -> "tuple[int, int] | None":
    """One C pass from K batches' field arrays to the final packed wire
    buffer (native/wireassemble.cpp). Returns (written bytes,
    enc_bucket — 0 = raw units wire), or None when the library is
    unavailable, predates the assembler, or reports an input the caller
    must route through the numpy ground truth (delta overflow, forced
    codec bucket under-coverage — the numpy path raises the canonical
    errors). The caller (features/assemble.py) owns eligibility gating,
    layout construction, and the arena leases for ``scratch``/``out``."""
    lib = get_lib()
    if lib is None or _assemble_missing:
        return None
    k = len(units)
    u8 = ctypes.POINTER(ctypes.c_uint8)
    i64 = ctypes.POINTER(ctypes.c_int64)
    enc_bucket = ctypes.c_int64(0)
    total = lib.wire_assemble(
        _ptr_array(units),
        _ptr_array(offsets),
        _ptr_array(numeric),
        _ptr_array(label),
        _ptr_array(mask),
        k,
        s,
        n_sb,
        bl,
        int(units[0].dtype.itemsize),
        1 if narrow else 0,
        lut.ctypes.data_as(u8) if lut is not None else None,
        int(forced_bucket),
        scratch.ctypes.data_as(u8) if scratch is not None else None,
        enc_lens.ctypes.data_as(i64) if enc_lens is not None else None,
        out.ctypes.data_as(u8),
        int(out.shape[0]),
        ctypes.byref(enc_bucket),
    )
    if total < 0:
        # -2 delta overflow / -3 forced-bucket under-coverage: the numpy
        # path raises the canonical ValueError; -1 capacity means the
        # caller mis-sized the lease — same route, the ground truth can
        # never hit it
        return None
    return int(total), int(enc_bucket.value)


def digram_encode(buf: np.ndarray, lut: np.ndarray) -> "np.ndarray | None":
    """C greedy digram encode of a uint8 buffer (features/wirecodec.py owns
    the dictionary and the numpy ground truth; the two are byte-identical
    by construction and differential-tested). None when the native library
    is unavailable or predates the encoder — callers fall back to
    ``wirecodec.encode_np``. The output can never exceed the input length
    (a pair shrinks, a literal copies), so ``n`` capacity always fits."""
    lib = get_lib()
    if lib is None or _codec_missing:
        return None
    n = int(buf.shape[0])
    out = np.empty((n,), np.uint8)
    m = lib.digram_encode(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n,
        lut.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n,
    )
    if m < 0:  # cannot happen with cap = n; be loud if it ever does
        raise RuntimeError("digram_encode overflowed its full-size buffer")
    return out[:m].copy()


def rebind_flags() -> None:
    """Re-evaluate EVERY degrade flag against the real loaded library.
    Test support for the stale-library seam tests: ``_load(path,
    strict=False)`` on an old .so flags every symbol that .so lacks —
    the module-global flags are shared with the production library, so
    a seam test restoring only ITS OWN flag leaves the younger fast
    paths silently degraded for the rest of the process (found by r18's
    lease-accounting tests: the r9 stale test left the r15/r17/r18
    paths off for the remainder of tier-1)."""
    lib = get_lib()
    if lib is not None:
        _bind_wire(lib, strict=False)
        _bind_codec(lib, strict=False)
        _bind_assemble(lib, strict=False)
        _bind_featurize(lib, strict=False)


def available() -> bool:
    return get_lib() is not None


def _thread_count_from_env() -> int:
    """TWTML_NATIVE_THREADS: <=0 or unset/non-integer = auto (the C side
    picks hardware concurrency, capped, scaled down for small batches)."""
    try:
        return int(os.environ.get("TWTML_NATIVE_THREADS", "0"))
    except ValueError:
        log.warning("TWTML_NATIVE_THREADS is not an integer; using auto")
        return 0


def encode_texts(texts: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """One-pass UTF-16-LE encode of a batch: (units, offsets). Callers reuse
    the offsets for token-bucket sizing so texts are encoded exactly once.

    One join + one encode instead of per-text encodes (2048 small encodes
    were ~40% of the whole featurize hot path). UTF-16-LE is BOM-free and
    concatenation-safe, so per-text unit counts are all that's needed to
    split the joined buffer: len(t) when every char is BMP (1 unit each),
    with a per-text re-encode only in the rare astral-emoji case."""
    joined = "".join(texts)
    # surrogatepass: json.loads produces lone surrogates (escaped \uD800 or
    # raw surrogate UTF-8 bytes, which it decodes permissively); the JVM
    # ground truth treats them as ordinary units (features/hashing.py)
    units = np.frombuffer(
        joined.encode("utf-16-le", "surrogatepass"), dtype=np.uint16
    )
    offsets = np.zeros(len(texts) + 1, dtype=np.int64)
    if units.size == len(joined):  # no astral chars: 1 unit per char
        counts = [len(t) for t in texts]
    else:
        counts = [
            len(t) if t.isascii()
            else len(t.encode("utf-16-le", "surrogatepass")) >> 1
            for t in texts
        ]
    np.cumsum(counts, out=offsets[1:])
    if units.size == 0:
        units = np.zeros(1, dtype=np.uint16)
    return units, offsets


def pad_units(
    encoded: tuple[np.ndarray, np.ndarray],
    n: int,
    padded_rows: int,
    l_max: int,
    ascii_lower: bool = False,
    narrow: bool = False,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Ragged (units, offsets) → ([padded_rows, l_max] units, [padded_rows]
    int32 lengths) via the C row-memcpy loop; None if the library is
    unavailable (caller falls back to the numpy gather). ``ascii_lower``
    folds 'A'-'Z' during the copy. ``narrow`` writes a uint8 buffer — the
    half-width wire format for batches the caller KNOWS are byte-ranged
    (ascii-flagged rows); it is metadata-driven, never sniffed from data."""
    lib = get_lib()
    if lib is None:
        return None
    units, offsets = encoded
    if narrow:
        buf: np.ndarray = np.empty((padded_rows, l_max), dtype=np.uint8)
        fn, ptr_t = lib.pad_units_batch_u8, ctypes.c_uint8
    else:
        buf = np.empty((padded_rows, l_max), dtype=np.uint16)
        fn, ptr_t = lib.pad_units_batch, ctypes.c_uint16
    length = np.empty((padded_rows,), dtype=np.int32)
    max_len = fn(
        units.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        padded_rows,
        l_max,
        1 if ascii_lower else 0,
        buf.ctypes.data_as(ctypes.POINTER(ptr_t)),
        length.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if max_len > l_max:  # caller sized l_max from these offsets; never expected
        return None
    return buf, length


def hash_texts(
    texts: list[str],
    num_features: int,
    out_idx: np.ndarray,
    out_val: np.ndarray,
    encoded: tuple[np.ndarray, np.ndarray] | None = None,
) -> np.ndarray | None:
    """Hash lowercased texts into the caller's padded [B, L] buffers.
    Returns per-row distinct-term counts, or None if the native path is
    unavailable or L was too small (caller should re-bucket or fall back).
    ``encoded``: optional pre-computed (units, offsets) from encode_texts."""
    lib = get_lib()
    if lib is None:
        return None
    b, l_max = out_idx.shape
    assert len(texts) <= b
    units, offsets = encoded if encoded is not None else encode_texts(texts)
    assert offsets.size == len(texts) + 1, "encoded does not match texts"
    ntok = np.zeros(b, dtype=np.int32)

    max_terms = lib.fasthash_batch(
        units.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(texts),
        num_features,
        l_max,
        out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ntok.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _thread_count_from_env(),
    )
    if max_terms > l_max or (ntok[: len(texts)] < 0).any():
        # token bucket too small, or a row overflowed the C scratch table
        return None
    return ntok


def parse_tweet_block(
    data: bytes,
    begin: int,
    end: int,
    cap_rows: int = 0,
    copy: bool = True,
) -> tuple | None:
    """Parse newline-delimited tweet JSON with the C data-loader, applying
    the isRetweet + [begin, end] retweet-count filter in-line.

    Returns (numeric int64 [rows, 5] = {label, followers, favourites,
    friends, created_ms}, units uint16 (concatenated), offsets int64
    [rows+1], ascii uint8 [rows], consumed_bytes, bad_lines) — or None when
    the C library is unavailable (callers fall back to the Python
    json.loads + Status path, the semantic ground truth).

    ``copy=False`` returns views into the freshly allocated backing buffers
    (each call allocates its own, so views never alias across calls) —
    skips ~n bytes of memcpy per call on the streaming hot path, at the
    price of pinning the worst-case-sized buffers for the block's life;
    right for blocks consumed promptly, wrong for long-lived accumulation."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(data)
    if cap_rows <= 0:
        # upper-bound rows without scanning for newlines: real tweet lines
        # are hundreds of bytes, so n/64 over-provisions; pathological
        # shorter lines just trip the parser's clean early-stop and the
        # caller continues from *consumed (same contract as cap_units)
        cap_rows = max(16, n >> 6)
    # total text units from n input bytes is < n; the parser additionally
    # reserves one full row (kMaxTextUnits) of headroom before each line,
    # so size past that to never trip the early-stop mid-block
    cap_units = n + MAX_TEXT_UNITS + 1
    numeric = np.empty((cap_rows, 5), dtype=np.int64)
    units = np.empty((cap_units,), dtype=np.uint16)
    offsets = np.empty((cap_rows + 1,), dtype=np.int64)
    ascii_flags = np.empty((cap_rows,), dtype=np.uint8)
    consumed = ctypes.c_int64(0)
    bad = ctypes.c_int64(0)
    rows = lib.parse_tweet_block(
        data,
        n,
        begin,
        end,
        cap_rows,
        cap_units,
        numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        units.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ascii_flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.byref(consumed),
        ctypes.byref(bad),
    )
    # default: copies, not views — the backing buffers are sized for the
    # worst case (~3 bytes per input byte) and callers accumulate blocks
    if copy:
        return (
            numeric[:rows].copy(),
            units[: offsets[rows]].copy(),
            offsets[: rows + 1].copy(),
            ascii_flags[:rows].copy(),
            int(consumed.value),
            int(bad.value),
        )
    return (
        numeric[:rows],
        units[: offsets[rows]],
        offsets[: rows + 1],
        ascii_flags[:rows],
        int(consumed.value),
        int(bad.value),
    )


def wire_available() -> bool:
    """Whether the zero-copy wire emitter is loadable (the library is up
    and carries the symbol — see _bind_wire's degrade seam)."""
    return get_lib() is not None and not _wire_missing


def parse_tweet_block_wire(
    data: bytes,
    begin: int,
    end: int,
    cap_rows: int = 0,
    copy: bool = True,
) -> tuple | None:
    """One C pass from raw block bytes to the ragged wire's unit
    representation (native/tweetjson.cpp parse_tweet_block_wire): same
    kept rows / numeric / offsets / ascii as ``parse_tweet_block``, but the
    units come back **uint8** whenever every kept row is ASCII (the narrow
    wire — no separate downcast pass) and uint16 otherwise (the parser
    widens its committed prefix ONCE, in C, when the first non-ASCII row
    commits). Returns the same tuple shape as ``parse_tweet_block``
    (numeric, units, offsets, ascii, consumed, bad) — callers can treat
    the two interchangeably — or None when the C library is unavailable
    OR predates the wire emitter (``_wire_missing``; callers fall back to
    the ParsedBlock path, which keeps working on old symbol sets).

    Both unit buffers are allocated with ``np.empty`` up front; the wide
    one stays untouched (no page faults) unless a row actually widens, so
    the common ASCII stream never pays for it."""
    lib = get_lib()
    if lib is None or _wire_missing:
        return None
    n = len(data)
    if cap_rows <= 0:
        cap_rows = max(16, n >> 6)  # same over-provision rule as above
    cap_units = n + MAX_TEXT_UNITS + 1
    numeric = np.empty((cap_rows, 5), dtype=np.int64)
    units_u8 = np.empty((cap_units,), dtype=np.uint8)
    units_u16 = np.empty((cap_units,), dtype=np.uint16)
    offsets = np.empty((cap_rows + 1,), dtype=np.int64)
    ascii_flags = np.empty((cap_rows,), dtype=np.uint8)
    consumed = ctypes.c_int64(0)
    bad = ctypes.c_int64(0)
    narrow = ctypes.c_int64(0)
    needs_wide = ctypes.c_int64(0)
    rows = lib.parse_tweet_block_wire(
        data,
        n,
        begin,
        end,
        cap_rows,
        cap_units,
        numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        units_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        units_u16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ascii_flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.byref(consumed),
        ctypes.byref(bad),
        ctypes.byref(narrow),
        ctypes.byref(needs_wide),
    )
    if needs_wide.value:  # can't happen: a wide buffer is always passed
        raise RuntimeError("wire parser requested a wide buffer it was given")
    units = units_u8 if narrow.value else units_u16
    total = int(offsets[rows]) if rows else 0
    if copy:
        return (
            numeric[:rows].copy(),
            units[:total].copy(),
            offsets[: rows + 1].copy(),
            ascii_flags[:rows].copy(),
            int(consumed.value),
            int(bad.value),
        )
    return (
        numeric[:rows],
        units[:total],
        offsets[: rows + 1],
        ascii_flags[:rows],
        int(consumed.value),
        int(bad.value),
    )


def lexicon_scores(
    encoded: tuple[np.ndarray, np.ndarray],
    n: int,
    pos_lex: tuple[np.ndarray, np.ndarray, np.ndarray],
    neg_lex: tuple[np.ndarray, np.ndarray, np.ndarray],
) -> tuple[np.ndarray, np.ndarray] | None:
    """Batch lexicon sentiment scores over ragged UTF-16 units.

    ``pos_lex``/``neg_lex`` are (words_units, word_offsets, word_hashes)
    from features/sentiment.py's packed lexicons. Returns (scores int32 [n],
    ok uint8 [n]) — ok=0 rows contain non-ASCII units and must be scored in
    Python for exact tokenization parity. None when the C library is
    unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    units, offsets = encoded
    assert offsets.size == n + 1, "encoded does not match the batch"
    score = np.empty((n,), dtype=np.int32)
    ok = np.empty((n,), dtype=np.uint8)
    pw, po, ph = pos_lex
    nw, no, nh = neg_lex
    lib.lexicon_score_batch(
        units.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        pw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        po.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ph.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(ph),
        nw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        no.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        nh.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(nh),
        score.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return score, ok
