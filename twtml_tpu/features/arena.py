"""Pooled destination buffers for the packed wire (r17).

Why this exists: the r2/r3 bottleneck ladder puts host work right under
tunnel uploads, and every pack built its destination buffer FRESH each
tick — pure allocator churn on the one usable core, and the fuel for the
measured production blocker: host RSS grows ∝ uploaded bytes (~4-6 MB per
65k-tweet pass; axon transfer-buffer retention, BENCHMARKS.md r3 soak —
ever-new upload buffers mean the tunnel client's retained references pin
ever-new pages, while recycled buffers bound them). The arena is a
size-bucketed free list of uint8 buffers: the wire assembler (or the
numpy fallback's ``np.concatenate(..., out=)``) writes into a LEASED
buffer, ``device_put`` uploads it, and the lease retires back to the pool
when the FetchPipeline/SuperBatcher delivers (or refunds) the
corresponding dispatch — by which point the step has executed and nothing
can alias the bytes (a ``device_get`` completing is the proof the
dispatch consumed its inputs; retiring at pack/dispatch time would race
the backend's zero-copy aliasing of host numpy buffers).

Ownership only, never layout: the arena changes WHO owns the bytes, not
what they are — decoded features stay bit-identical and model
trajectories bitwise-equal (tests/test_wireassemble.py). Packed-wire
sizes repeat per (signature, K) exactly like compiled programs, so the
free list is keyed by exact byte size and stays small; a bounded
``max_pool_bytes`` cap drops the oldest buffers rather than growing
without bound.

Leases are resilient by construction: a caller that never retires (a
test packing one batch, a bench) simply gets a fresh buffer that the GC
reclaims — indistinguishable from the pre-arena world. ``discard()`` is
the abort path: a wedged-tunnel dispatch whose execution state is
unknown must never donate its buffer back for reuse.

Telemetry: ``wire.arena_in_use`` (gauge — outstanding leases),
``wire.arena_recycled`` / ``wire.arena_misses`` (counters — pool hits vs
fresh allocations) and ``wire.arena_pool_mb`` (gauge) ride /api/metrics
and the dashboard's arena tile. TW008 (tools/lawcheck) makes the arena a
paid-for law: fresh wire-sized allocations in the pack hot path outside
this module are findings.
"""

from __future__ import annotations

import threading

import numpy as np


class Lease:
    """One leased destination buffer. ``buf`` is the uint8 array to write
    into; call ``retire()`` when the dispatch that uploaded it has
    provably executed (the pipeline's fetch delivery), or ``discard()``
    on abort paths. Both are idempotent."""

    __slots__ = ("_arena", "buf", "_done")

    def __init__(self, arena: "WireArena", buf: np.ndarray):
        self._arena = arena
        self.buf = buf
        self._done = False

    def retire(self) -> None:
        if not self._done:
            self._done = True
            self._arena._retire(self.buf, recycle=True)

    def discard(self) -> None:
        """Abort path: count the lease closed but never reuse the buffer
        (the dispatch that uploaded it may still execute on a wedged
        backend — donating the pages back would risk aliasing)."""
        if not self._done:
            self._done = True
            self._arena._retire(self.buf, recycle=False)


class LeaseChain:
    """Several leases retiring/discarding as ONE — the dispatch-site
    handle for a batch whose wire buffer AND featurize-stage arrays
    (the one-pass native featurizer, r18) are both arena-leased. The
    pipelines hold one lease object per in-flight dispatch; chaining
    keeps that contract while both buffers ride to the same fetch
    delivery. ``buf`` exposes the primary (wire) buffer so accounting
    probes keep working."""

    __slots__ = ("leases", "buf")

    def __init__(self, *leases):
        self.leases = [le for le in leases if le is not None]
        self.buf = self.leases[0].buf if self.leases else None

    def retire(self) -> None:
        for le in self.leases:
            le.retire()

    def discard(self) -> None:
        for le in self.leases:
            le.discard()


def chain_leases(*leases):
    """None-safe, identity-deduplicating combinator: the single lease
    when only one distinct lease is present (the common case — an
    unpacked dispatch sees the same object through both the wire and the
    batch), a ``LeaseChain`` otherwise, None for none."""
    seen: list = []
    for le in leases:
        if le is not None and not any(le is s for s in seen):
            seen.append(le)
    if not seen:
        return None
    if len(seen) == 1:
        return seen[0]
    return LeaseChain(*seen)


class WireArena:
    """Size-bucketed pool of wire destination buffers (module docstring)."""

    def __init__(self, max_pool_bytes: int = 256 << 20):
        self.max_pool_bytes = int(max_pool_bytes)
        self._lock = threading.Lock()
        self._free: "dict[int, list[np.ndarray]]" = {}
        self._free_bytes = 0
        self._in_use = 0
        self.enabled = True

    # gauges/counters resolved lazily so importing this module never pulls
    # the telemetry registry (or anything heavier) at import time; looked
    # up per call, NOT cached — reset_for_tests clears the registry in
    # place, and its contract is exactly that the hot path holds no metric
    # references across calls
    def _metrics(self):
        from ..telemetry import metrics as _metrics

        reg = _metrics.get_registry()
        return (
            reg.gauge("wire.arena_in_use"),
            reg.counter("wire.arena_recycled"),
            reg.counter("wire.arena_misses"),
            reg.gauge("wire.arena_pool_mb"),
        )

    def lease(self, nbytes: int) -> Lease:
        """A uint8 buffer of exactly ``nbytes``, recycled when the pool
        has one, freshly allocated (a counted miss) otherwise."""
        nbytes = int(nbytes)
        in_use, recycled, misses, pool_mb = self._metrics()
        with self._lock:
            bucket = self._free.get(nbytes)
            if self.enabled and bucket:
                buf = bucket.pop()
                self._free_bytes -= nbytes
                recycled.inc()
            else:
                buf = np.empty((nbytes,), np.uint8)
                misses.inc()
            self._in_use += 1
            in_use.set(self._in_use)
            pool_mb.set(round(self._free_bytes / 1e6, 3))
        return Lease(self, buf)

    def _retire(self, buf: np.ndarray, recycle: bool) -> None:
        in_use, _recycled, _misses, pool_mb = self._metrics()
        with self._lock:
            self._in_use -= 1
            in_use.set(self._in_use)
            if (
                recycle
                and self.enabled
                and self._free_bytes + buf.nbytes <= self.max_pool_bytes
            ):
                self._free.setdefault(int(buf.nbytes), []).append(buf)
                self._free_bytes += int(buf.nbytes)
            pool_mb.set(round(self._free_bytes / 1e6, 3))

    def stats(self) -> dict:
        with self._lock:
            return {
                "in_use": self._in_use,
                "free_buffers": sum(len(v) for v in self._free.values()),
                "free_bytes": self._free_bytes,
            }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._free.clear()
            self._free_bytes = 0
            self._in_use = 0
            self.enabled = True


_arena: "WireArena | None" = None
_arena_lock = threading.Lock()


def get_arena() -> WireArena:
    """The process-wide arena every pack destination leases from."""
    global _arena
    with _arena_lock:
        if _arena is None:
            _arena = WireArena()
        return _arena


def set_enabled(on: bool) -> None:
    """Soak/bench control (``tools/soak.py --arena off``): a disabled
    arena hands out fresh buffers and recycles nothing — the pre-arena
    allocation behavior, kept reachable so RSS-slope comparisons have a
    true control arm."""
    get_arena().enabled = bool(on)


def lease_wire(nbytes: int) -> Lease:
    """Module-level convenience: ``get_arena().lease(nbytes)``."""
    return get_arena().lease(nbytes)
