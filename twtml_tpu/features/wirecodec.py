"""Digram dictionary codec for the ragged units wire (``--wireCodec dict``).

The r2/r3 bottleneck ladder puts tunnel upload on top and ASCII tweet text
is entropy-rich (ROADMAP item 3): this module is the host half of the
compressed wire — a byte-pair (digram) substitution code over the uint8
ragged units buffer, with a STATIC 128-entry dictionary so the device-side
decode table is a compile-time constant (no table bytes on the wire, no
data-dependent decode program).

Code space: input bytes are ASCII (< 0x80 — the narrow-wire metadata gate,
features/batch.py), so output bytes ``0x00..0x7F`` are literals (one unit
each) and ``0x80..0xFF`` are dictionary codes (two units each, from
``decode_table()``). Encoding is GREEDY left-to-right maximal munch — the
natural sequential C loop (native/wirecodec.cpp ``digram_encode``) and the
vectorized numpy run-parity form below provably emit the SAME stream, and
the differential tests + tools/native_sanity.py pin that byte-for-byte.

Decode is a bounded gather-expand (every code expands to ≤ 2 units) +
cumsum — the ``offsets_from_deltas`` family: in-jit as
``ops/ragged.units_from_codes`` (searchsorted + two gathers, no scatters —
the TW004 law), host twin ``decode_np`` here. Decoded units are
BYTE-identical to the uncompressed wire, including the zero tail (the
dictionary's entry 0 is ``"\\x00\\x00"`` so bucket padding halves too).

Parity law: this module is the pure-numpy ground truth; the C encoder is a
fast path that must match it exactly (tests/test_wirecodec.py,
tools/native_sanity.py). Compression changes wire REPRESENTATION only,
never features, ordering, or rounding (PARITY.md).
"""

from __future__ import annotations

import numpy as np

# first byte value of the two-unit dictionary codes; 0x00..0x7F stay literal
CODE_BASE = 128

# the compressed buffer rounds up to this multiple: same program-count
# argument as features/batch.RAGGED_UNIT_MULTIPLE (compressed totals
# concentrate like raw totals), finer-grained because the codec also runs
# per shard/group segment where buffers are smaller
CODEC_UNIT_MULTIPLE = 1024

# The static dictionary: 128 digrams of ASCII tweet text. Entry 0 is the
# zero-pair (bucket-padding tail); then English letter digrams by corpus
# frequency, and the t.co link fragments every retweet body carries
# ("https://t.co/…" under greedy left-to-right pairing hits "ht tp s: //
# t. co"). Quality here moves the RATIO only — parity never depends on the
# dictionary, and changing it is wire-compatible per run (both ends read
# this one table) but NOT across a mixed-version fleet; treat the list as
# frozen like a wire format.
_DIGRAMS: "tuple[bytes, ...]" = (
    b"\x00\x00",
    b"e ", b" t", b"th", b"he", b"s ", b" a", b"t ", b"in", b"d ", b"er",
    b"an", b" s", b"on", b"re", b" w", b"at", b"en", b" o", b"or", b"es",
    b" i", b"is", b"te", b"it", b" b", b"ar", b"nd", b" m", b"ou", b" h",
    b"ed", b"to", b"nt", b" f", b"as", b"st", b" c", b"io", b"ng", b"le",
    b"al", b"me", b"ve", b"y ", b" p", b"co", b"ro", b"ll", b"ea", b"se",
    b"of", b"no", b"f ", b" d", b"ha", b"ne", b"ur", b"ni", b"ti", b"ri",
    b"hi", b"o ", b"r ", b"n ", b"a ", b"g ", b"ho", b"ma", b"li", b"om",
    b"ce", b"ow", b"us", b"ut", b"ac", b"el", b"la", b"ta", b"wh", b"be",
    b"wa", b"un", b"wi", b"et", b"ad", b"ch", b"fo", b"de", b"pe", b"ee",
    b"ld", b"ca", b"ra", b"so", b"do", b"yo", b"sh", b"we", b"ai", b"lo",
    b"im", b"oo", b"pr", b"mo", b"su", b"id", b"ge", b"em", b"tt", b"ay",
    b"ke", b"am", b"ic", b"il", b"gh", b"ig", b"ot",
    b"ht", b"tp", b"s:", b"//", b"t.", b".c", b"o/",
    b", ", b". ", b"'s",
)

_lut: "np.ndarray | None" = None
_table: "np.ndarray | None" = None


def _build_tables() -> "tuple[np.ndarray, np.ndarray]":
    """(pair LUT uint8[65536], decode table uint8[128, 2]) from the static
    dictionary. LUT[(b0 << 8) | b1] is the dictionary index, 0xFF = no
    code (literal). Built once; validates the frozen-list invariants."""
    global _lut, _table
    if _lut is not None and _table is not None:
        return _lut, _table
    assert len(_DIGRAMS) == CODE_BASE, len(_DIGRAMS)
    assert len(set(_DIGRAMS)) == CODE_BASE, "duplicate dictionary digram"
    table = np.zeros((CODE_BASE, 2), np.uint8)
    lut = np.full((65536,), 0xFF, np.uint8)
    for i, pair in enumerate(_DIGRAMS):
        assert len(pair) == 2 and max(pair) < CODE_BASE, pair
        table[i, 0], table[i, 1] = pair[0], pair[1]
        lut[(pair[0] << 8) | pair[1]] = i
    _lut, _table = lut, table
    return lut, table


def pair_lut() -> np.ndarray:
    """uint8[65536] digram → code index (0xFF = literal) — the one table
    both encoders (numpy below, C ``digram_encode``) read."""
    return _build_tables()[0]


def decode_table() -> np.ndarray:
    """uint8[128, 2] code → its two units — the decode-side constant
    (baked into the jit program by ``ops/ragged.units_from_codes``)."""
    return _build_tables()[1]


def encode_np(buf: np.ndarray) -> np.ndarray:
    """Greedy digram encode, vectorized numpy — the ground truth.

    Greedy maximal munch has a sequential look ("pair here consumes the
    next byte"), but with a STATIC dictionary it reduces to run parity:
    within each maximal run of consecutive hit positions (positions whose
    byte pair is in the dictionary), greedy takes exactly the pairs at
    EVEN offsets from the run start — every run start is provably arrived
    at (the preceding position either emitted a literal and stepped 1, or
    closed a pair of the previous run and stepped past it), so the whole
    decision is position arithmetic over runs. That makes the encode three
    vectorized passes over the buffer — it must ride the one-core host
    budget (CLAUDE.md), never a per-byte Python loop.
    """
    b = np.ascontiguousarray(buf).reshape(-1)
    if b.dtype != np.uint8:
        raise TypeError("digram codec encodes the uint8 (ASCII) units wire")
    n = b.shape[0]
    if n < 2:
        return b.copy()
    lut = pair_lut()
    cand = lut[(b[:-1].astype(np.uint16) << 8) | b[1:]]  # [n-1]
    hit = cand != 0xFF
    idx = np.arange(n - 1, dtype=np.int64)
    run_start = hit & ~np.concatenate(([False], hit[:-1]))
    start_of = np.maximum.accumulate(np.where(run_start, idx, -1))
    taken = hit & (((idx - start_of) & 1) == 0)
    # emit = every position not consumed as a pair's second byte
    second = np.concatenate(([False], taken))  # [n]
    taken_full = np.concatenate((taken, [False]))  # [n]
    emit = ~second
    cand_full = np.concatenate((cand, [0]))
    out = np.where(
        taken_full[emit],
        cand_full[emit].astype(np.int16) + CODE_BASE,
        b[emit],
    )
    return out.astype(np.uint8)


def encode(buf: np.ndarray) -> np.ndarray:
    """Greedy digram encode — the C fast path when the native library
    carries ``digram_encode`` (native/wirecodec.cpp; byte-identical to
    ``encode_np`` — same algorithm, same LUT, differential-tested), the
    numpy ground truth otherwise. One pass over the units at memcpy-class
    speed, riding the native ingest machinery like every fast path."""
    b = np.ascontiguousarray(buf).reshape(-1)
    if b.dtype != np.uint8:
        raise TypeError("digram codec encodes the uint8 (ASCII) units wire")
    if b.shape[0] >= 2:
        from . import native

        out = native.digram_encode(b, pair_lut())
        if out is not None:
            return out
    return encode_np(b)


def decode_np(codes: np.ndarray, out_len: int) -> np.ndarray:
    """Host twin of ``ops/ragged.units_from_codes``: code stream(s) →
    the first ``out_len`` expanded units, uint8. Accepts a leading batch
    axis ([..., M] → [..., out_len]) like the in-jit decode. Trailing
    padding codes past ``out_len`` are never read — the encoder zero-pads
    the bucketed stream with literal codes, exactly like the raw wire's
    zero tail."""
    c = np.asarray(codes)
    lead = c.shape[:-1]
    if out_len == 0 or c.shape[-1] == 0:
        if out_len:
            raise ValueError(f"empty code stream; {out_len} units requested")
        return np.zeros(lead + (0,), np.uint8)
    c2 = c.reshape(-1, c.shape[-1]).astype(np.int64)
    table = decode_table()
    out = np.empty((c2.shape[0], out_len), np.uint8)
    t = np.arange(out_len, dtype=np.int64)
    for r in range(c2.shape[0]):
        row = c2[r]
        lens = 1 + (row >= CODE_BASE).astype(np.int64)
        ends = np.cumsum(lens)
        if out_len and (ends.size == 0 or ends[-1] < out_len):
            raise ValueError(
                f"code stream expands to {int(ends[-1]) if ends.size else 0}"
                f" units; {out_len} requested"
            )
        j = np.searchsorted(ends, t, side="right")
        k = t - (ends[j] - lens[j])
        cj = row[j]
        exp = table[np.clip(cj - CODE_BASE, 0, CODE_BASE - 1), k]
        out[r] = np.where(cj < CODE_BASE, cj, exp).astype(np.uint8)
    return out.reshape(lead + (out_len,))


def encoded_bucket(m: int) -> int:
    """Compressed-buffer bucket: round up to CODEC_UNIT_MULTIPLE (program
    count stays finite, like the raw wire's RAGGED_UNIT_MULTIPLE)."""
    return max(
        CODEC_UNIT_MULTIPLE,
        -(-int(m) // CODEC_UNIT_MULTIPLE) * CODEC_UNIT_MULTIPLE,
    )


def encode_bucketed(buf: np.ndarray) -> "np.ndarray | None":
    """Encode + zero-pad to the codec bucket, or None when the bucketed
    encoding is not strictly smaller than the raw buffer — the
    incompressible fallback (caller ships the raw wire and counts it,
    like the int32 offset fallback)."""
    raw = np.ascontiguousarray(buf).reshape(-1)
    codes = encode(raw)
    bucket = encoded_bucket(codes.shape[0])
    if bucket >= raw.shape[0]:
        return None
    out = np.zeros((bucket,), np.uint8)
    out[: codes.shape[0]] = codes
    return out
