"""Tiny lexicon sentiment labeler for the streaming logistic model.

BASELINE config #3 is "StreamingLogisticRegressionWithSGD (binary sentiment)
on the same stream" — the reference repo has no sentiment code, so the label
definition is ours: 1.0 when the original tweet's text contains at least as
many positive-lexicon words as negative ones, else 0.0. Deterministic,
dependency-free, and cheap enough for the hot path; swap ``label`` for a real
classifier's output if one is available.
"""

from __future__ import annotations

import re

from .featurizer import Status

POSITIVE = frozenset(
    """good great awesome amazing love happy excellent fantastic wonderful best
    beautiful fun win winning cool nice brilliant perfect thanks thank glad
    excited super sweet favorite favourite enjoy enjoyed impressive stunning
    delightful positive success successful""".split()
)

NEGATIVE = frozenset(
    """bad terrible awful hate sad horrible worst ugly fail failing broken
    angry annoying disappointing disappointed poor boring gross nasty sucks
    suck wrong problem problems negative disaster painful worse useless""".split()
)

_WORD = re.compile(r"[a-z']+")


def sentiment_score(text: str) -> int:
    """#positive − #negative lexicon hits over lowercased word tokens."""
    words = _WORD.findall(text.lower())
    return sum(w in POSITIVE for w in words) - sum(w in NEGATIVE for w in words)


def sentiment_label(status: Status) -> float:
    """Binary label from the ORIGINAL tweet's text (featurization also reads
    the original, MllibHelper.scala:42-44)."""
    return 1.0 if sentiment_score(status.retweeted_status.text) >= 0 else 0.0


def _pack_lexicon(words: frozenset) -> tuple:
    """Lexicon as (concatenated UTF-16 units, offsets, Java hashCodes) for
    the C scorer (native/fasthash.cpp lexicon_score_batch)."""
    import numpy as np

    from .hashing import java_string_hashcode

    ws = sorted(words)
    units = np.concatenate([
        np.frombuffer(w.encode("utf-16-le"), np.uint16) for w in ws
    ])
    off = np.zeros(len(ws) + 1, np.int64)
    np.cumsum([len(w) for w in ws], out=off[1:])
    hashes = np.array([java_string_hashcode(w) for w in ws], np.int32)
    return units, off, hashes


_POS_PACKED = _pack_lexicon(POSITIVE)
_NEG_PACKED = _pack_lexicon(NEGATIVE)


def sentiment_labels(statuses: list, encoded=None) -> "np.ndarray":
    """Batched ``sentiment_label`` over the ORIGINAL texts — C hot path
    (one scan over UTF-16 units), exact per-row Python fallback for
    non-ASCII texts and when the library is unavailable.

    ``encoded``: optionally the featurizer's already-computed
    (units, offsets) of the originals' (lowercased) texts — skips a second
    encode pass; the C scorer's ASCII fold is idempotent on pre-lowered
    rows, and Python-scored fallback rows lowercase idempotently too."""
    import numpy as np

    from . import native

    n = len(statuses)
    out = None
    if n and native.available():
        if encoded is None:
            encoded = native.encode_texts(
                [s.retweeted_status.text for s in statuses]
            )
        out = native.lexicon_scores(encoded, n, _POS_PACKED, _NEG_PACKED)
    if out is None:
        return np.array([sentiment_label(s) for s in statuses], np.float32)
    score, ok = out
    labels = (score >= 0).astype(np.float32)
    for i in np.nonzero(ok == 0)[0]:
        labels[i] = sentiment_label(statuses[i])
    return labels


def sentiment_labels_from_units(units, offsets) -> "np.ndarray":
    """Batched labels straight from ragged UTF-16 units — the block-ingest
    path's labeler (no Status objects exist there). C scan for ASCII rows;
    non-ASCII rows decode and score in Python (pre-lowered units score
    identically: sentiment_score lowercases idempotently)."""
    import numpy as np

    from . import native

    n = offsets.size - 1
    if n <= 0:
        return np.zeros((0,), np.float32)
    if units.dtype == np.uint8:
        # narrow-wire block (zero-copy parser): the C lexicon scan reads
        # uint16 units — widen once; values are identical code units
        units = units.astype(np.uint16)
    out = native.lexicon_scores((units, offsets), n, _POS_PACKED, _NEG_PACKED)
    if out is None:  # no C library: every row takes the Python loop below
        score = np.zeros((n,), np.int32)
        ok = np.zeros((n,), np.uint8)
    else:
        score, ok = out
    labels = (score >= 0).astype(np.float32)
    for i in np.nonzero(ok == 0)[0]:
        text = (
            units[offsets[i] : offsets[i + 1]]
            .tobytes()
            .decode("utf-16-le", "surrogatepass")
        )
        labels[i] = 1.0 if sentiment_score(text) >= 0 else 0.0
    return labels
