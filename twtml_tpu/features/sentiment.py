"""Tiny lexicon sentiment labeler for the streaming logistic model.

BASELINE config #3 is "StreamingLogisticRegressionWithSGD (binary sentiment)
on the same stream" — the reference repo has no sentiment code, so the label
definition is ours: 1.0 when the original tweet's text contains at least as
many positive-lexicon words as negative ones, else 0.0. Deterministic,
dependency-free, and cheap enough for the hot path; swap ``label`` for a real
classifier's output if one is available.
"""

from __future__ import annotations

import re

from .featurizer import Status

POSITIVE = frozenset(
    """good great awesome amazing love happy excellent fantastic wonderful best
    beautiful fun win winning cool nice brilliant perfect thanks thank glad
    excited super sweet favorite favourite enjoy enjoyed impressive stunning
    delightful positive success successful""".split()
)

NEGATIVE = frozenset(
    """bad terrible awful hate sad horrible worst ugly fail failing broken
    angry annoying disappointing disappointed poor boring gross nasty sucks
    suck wrong problem problems negative disaster painful worse useless""".split()
)

_WORD = re.compile(r"[a-z']+")


def sentiment_score(text: str) -> int:
    """#positive − #negative lexicon hits over lowercased word tokens."""
    words = _WORD.findall(text.lower())
    return sum(w in POSITIVE for w in words) - sum(w in NEGATIVE for w in words)


def sentiment_label(status: Status) -> float:
    """Binary label from the ORIGINAL tweet's text (featurization also reads
    the original, MllibHelper.scala:42-44)."""
    return 1.0 if sentiment_score(status.retweeted_status.text) >= 0 else 0.0
