"""One-pass host featurize (r18) — the fused native fast path of the
ragged-wire featurize stage, behind ``--featurizeNative``.

BENCHMARKS r17 measured the host chain featurize-dominated (61-70 ms per
65k-tweet pass vs ~1.4 ms of pack): PR 6 made parse native and PR 14
made pack native, but the stage between them still ran several separate
numpy array passes (float64 scale + f32 cast, label/mask fills, the
ragged-wire zero+copy) on BOTH ingest paths. This module routes the
array half of featurize through ONE C sweep (native/featurize.cpp): the
batch's encoded units + numeric columns go straight to the final
ragged-wire arrays — flat units (narrow uint8 under the caller's
metadata gate), padded int32 offsets, scaled float32 numeric/label/mask
— carved as views out of ONE pooled arena lease (features/arena.py),
so the stage allocates nothing fresh per tick (the TW008 law extended
to the featurize rung).

Dispatch contract: each ``try_fill`` returns the five wire arrays (+
max row length + the lease) byte-identical to the Python/numpy ground
truth in ``features/featurizer.py``, or None — mode off, stale/absent
native library (the ``native.featurize_degraded`` seam), or an input
the C pass refuses — and the featurizer falls through to the ground
truth. Differential-tested in tests/test_featurize_native.py; sanitized
by tools/native_sanity.py.

Lease lifetime: the lease rides the RaggedUnitBatch (``batch._lease``)
to the dispatch sites in apps/common.py, which chain it with the packed
wire's own lease (``arena.chain_leases``) and retire both when the
batch's stats fetch delivers — after the delivery handler has run, so
nothing can still read the arrays. Batches that never reach a dispatch
site (tests, benches, warmup) carry a GC finalizer that ``discard``s
the lease instead: accounting stays exact and the buffer is simply
never reused — indistinguishable from a fresh allocation.

``--featurizeNative <auto|on|off>`` (config.py) drives ``configure``;
auto means "whenever the native emitter is loadable" — like
``--wireAssemble``, this moves host work only and the batches are
byte-identical by law, so there is no transport-regime gate.
"""

from __future__ import annotations

import contextlib
import os
import weakref

import numpy as np

NUM_NUMBER_FEATURES = 4  # features/batch.py (MllibHelper.scala:13)

# column order the C pass reads: followers, favourites, friends,
# created_ms, label — mapped per caller so the scaling code exists once.
# The pointer ints are cached alongside: the arrays are module-lifetime
# constants and a numpy ``.ctypes`` access costs ~2-3 µs per call
_OBJECT_COL_ORDER = np.arange(5, dtype=np.int64)  # the Status traversal
_BLOCK_COL_ORDER = np.array([1, 2, 3, 4, 0], np.int64)  # blocks.COL_*
_COL_ORDER_PTRS = {
    id(_OBJECT_COL_ORDER): _OBJECT_COL_ORDER.ctypes.data,
    id(_BLOCK_COL_ORDER): _BLOCK_COL_ORDER.ctypes.data,
}

_MODES = ("auto", "on", "off")
_mode = os.environ.get("TWTML_FEATURIZE_NATIVE", "auto")
if _mode not in _MODES:
    _mode = "auto"


def configure(mode: str) -> None:
    """Set the process-wide featurize mode (the ``--featurizeNative``
    seam)."""
    global _mode
    if mode not in _MODES:
        raise ValueError(
            f"featurizeNative must be one of {_MODES}, got {mode!r}"
        )
    _mode = mode


def mode() -> str:
    return _mode


def available() -> bool:
    """Whether featurize will actually ride the fused C pass right now."""
    from . import native

    return _mode != "off" and native.featurize_available()


@contextlib.contextmanager
def forced(mode_: str):
    """Scoped mode override — the differential tests and the paired
    bench flip between the Python ground truth and the fused path."""
    prev = _mode
    configure(mode_)
    try:
        yield
    finally:
        configure(prev)


def _lease_views(b: int, n_bucket: int, unit_dtype):
    """ONE arena lease carved into the five wire arrays. Layout keeps
    every 4-byte field at a 4-byte offset (numeric, label, mask, offsets
    first; units last): numeric [b,4] f32 | label [b] f32 | mask [b] f32
    | offsets [b+1] i32 | units [n_bucket] u8|u16. Also returns the five
    section pointers, derived from the ONE lease base address (one
    ``.ctypes`` access instead of five)."""
    from .arena import lease_wire

    unit_itemsize = np.dtype(unit_dtype).itemsize
    side = b * NUM_NUMBER_FEATURES * 4 + b * 4 + b * 4 + (b + 1) * 4
    lease = lease_wire(side + n_bucket * unit_itemsize)
    buf = lease.buf
    base = buf.ctypes.data
    o_label = b * 16
    o_mask = o_label + b * 4
    o_offsets = o_mask + b * 4
    o_units = o_offsets + (b + 1) * 4
    numeric = buf[0:o_label].view(np.float32).reshape(b, 4)
    label = buf[o_label:o_mask].view(np.float32)
    mask = buf[o_mask:o_offsets].view(np.float32)
    offsets = buf[o_offsets:o_units].view(np.int32)
    units = buf[o_units:].view(unit_dtype)
    ptrs = (base + o_units, base + o_offsets, base, base + o_label,
            base + o_mask)  # units, offsets, numeric, label, mask
    return lease, units, offsets, numeric, label, mask, ptrs


def _fused_counter():
    # looked up per call, not cached: reset_for_tests clears the registry
    # in place — its contract is that hot paths hold no metric references
    from ..telemetry import metrics as _metrics

    return _metrics.get_registry().counter("featurize.fused_native")


def try_fill(
    units: np.ndarray,
    offsets: np.ndarray,
    cols: np.ndarray,
    col_order: np.ndarray,
    n: int,
    b: int,
    narrow: bool,
    now_ms: int,
):
    """The shared fused fill: (flat units, padded offsets, numeric,
    label, mask, max_len, lease) or None → the Python ground truth.
    ``cols`` is float64 [n, 5] (object path) or int64 [n, 5] (block
    columns); the C pass applies the reference scaling bit-identically
    (float64 multiply, f32 cast on store)."""
    if not available():
        return None
    from . import native
    from .batch import RAGGED_UNIT_MULTIPLE

    units = np.ascontiguousarray(units)
    offsets = np.ascontiguousarray(offsets)
    cols = np.ascontiguousarray(cols)
    if offsets.dtype != np.int64 or units.dtype not in (np.uint8, np.uint16):
        return None
    total = int(offsets[n]) if n else 0
    n_bucket = max(
        RAGGED_UNIT_MULTIPLE,
        -(-total // RAGGED_UNIT_MULTIPLE) * RAGGED_UNIT_MULTIPLE,
    )
    out_dtype = np.uint8 if narrow else np.uint16
    lease, out_units, out_offsets, numeric, label, mask, ptrs = (
        _lease_views(b, n_bucket, out_dtype)
    )
    if cols.dtype == np.float64:
        cols_f64, cols_i64 = cols.ctypes.data, None
    elif cols.dtype == np.int64:
        cols_f64, cols_i64 = None, cols.ctypes.data
    elif n:
        lease.retire()
        return None
    else:
        cols_f64 = cols_i64 = None
    max_len = native.featurize_wire_raw(
        units.ctypes.data,
        int(units.dtype.itemsize),
        offsets.ctypes.data,
        cols_f64,
        cols_i64,
        _COL_ORDER_PTRS.get(id(col_order)) or col_order.ctypes.data,
        n,
        b,
        n_bucket,
        int(now_ms),
        1 if narrow else 0,
        *ptrs,
    )
    if max_len is None:
        lease.retire()  # untouched destination: straight back to the pool
        return None
    _fused_counter().inc()
    return out_units, out_offsets, numeric, label, mask, max_len, lease


def attach_lease(batch, lease) -> None:
    """Hang the featurize lease on the batch for the dispatch sites
    (apps/common.chain_leases → retire on fetch delivery), with a GC
    ``discard`` finalizer as the never-dispatched backstop (accounting
    stays exact; a discarded buffer is never reused, so views extracted
    from the batch can never alias a recycled buffer)."""
    batch._lease = lease
    weakref.finalize(batch, lease.discard)


def object_col_order() -> np.ndarray:
    return _OBJECT_COL_ORDER


def block_col_order() -> np.ndarray:
    return _BLOCK_COL_ORDER
