"""Gram-domain (dual) SGD — the sparse inner loop re-expressed as MXU matmuls.

The 2^18-dim sparse regime (BASELINE config #4) was device-bound in r1/r2:
every one of the ``numIterations`` (50) rounds of MLlib's GradientDescent
loop (SURVEY.md §3.3) did a [B·L]-wide gather plus a scatter-add into the
2^18-entry weight vector — XLA lowers those to serialized scatter updates,
~100 ms/step on a v5e chip.

The fix is algebra, not a kernel. Within one micro-batch the design matrix
``Z = [X_text | numeric]`` is FIXED across all iterations; the loop only ever
needs ``Z·W`` (predictions) and ``Zᵀ·r`` (gradient). Re-parameterize the
weight trajectory in the span the updates actually live in:

    W_i = c_i · W_prev + Zᵀ · α_i          (c_0 = 1, α_0 = 0)

Then with ``u = Z·W_prev`` ([B], the batch's pre-update predictions) and
``G = Z·Zᵀ`` ([B,B], the Gram matrix):

    Z·W_i     = c_i·u + G·α_i              — a [B,B]×[B] matvec
    update    : c ← c·(1−ηλ);  α ← α·(1−ηλ) − η·(sel·r)/denom
    ‖W_a−W_b‖² = Δc²·‖W_prev‖² + 2·Δc·(u·Δα) + Δαᵀ·G·Δα

so MLlib's exact update rule — √-decay step, SquaredL2Updater pre-scale,
Bernoulli sampling, zero-sample skip, convergence freeze — runs unchanged
through ``sgd_inner_loop`` on the tiny dual state {c, α}, and the 2^18
feature space is touched exactly twice per batch: once building the dense
count matrix for G (one scatter + ONE bf16×bf16→f32 matmul on the MXU) and
once scattering ``Zᵀα`` back at write-back. The residual function enters
only elementwise on ``Z·W``, so the same dual loop serves the logistic
learner. Nothing here is approximate: it is the same recursion in a
different basis (floating-point summation order differs; differential tests
in tests/test_gram_sgd.py pin both paths together).

Even the G build avoids scatters. XLA lowers a [B·L]-update scatter into
[B, 2^18] to ~220 ns/update on a v5e chip (~28 ms/batch — it would dominate
the whole step), so the dense count matrix is instead built as a batched
MXU matmul over a two-level split of the feature index, ``f = hi·K + lo``:

    C[b, hi, lo] = Σ_l val[b,l] · 1[hi_l = hi] · 1[lo_l = lo]
                 = (OHhiᵀ · diag(val) · OHlo)[hi, lo]       per row b

i.e. one ``[B, √F, L] × [B, L, √F]`` batched matmul (~0.07 TFLOP at
B=2048, F=2^18 — 3% of the G matmul itself), with 0/1 one-hot operands
that are exact in bf16 and f32 accumulation, so counts come out exact.

Exactness is cond-gated at runtime, never assumed: token values that don't
round-trip through bf16 fall back to the f32 scatter densify, and a count
matrix that doesn't round-trip through bf16 (a per-row-feature count above
256 — beyond any real tweet) promotes the G matmul to
``Precision.HIGHEST``. G is therefore (near-)exact for every input the
scatter path accepts, and fast for every input that can occur.

A third, faster plane rides the same gate ladder: when every row's total
absolute token mass is ≤ 127 (true for every real tweet — per-occurrence
1.0 values, ≤ ~70 bigrams), every count is an integer in [−127, 127] and
therefore EXACT in int8, so both matmuls run s8×s8→s32 on the MXU — ~2×
bf16 peak on v5e, and the [B, F] count matrix is half the bytes. Integer
accumulation makes this plane bit-exact (no rounding at all), strictly
stronger than the bf16 plane it tightens.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
from jax import lax

from .sparse import densify_text

# The int8 plane is on by default; the flag exists so benches can build the
# bf16-only program for paired A/B comparison (trace-time capture: set it
# before the model's first step). TWTML_GRAM_INT8=0 disables it process-wide.
GRAM_INT8_PLANE = os.environ.get("TWTML_GRAM_INT8", "1").lower() not in (
    "0",
    "false",
)

# Above this dense-counts footprint (B·F·4 bytes) the Gram build would not
# fit comfortably in HBM next to the program's other buffers; the learner
# falls back to the per-iteration gather/scatter loop. 4 GB leaves >10 GB
# headroom on a 16 GB v5e chip for G, the bf16 planes, and the weights.
GRAM_DENSE_BYTES_LIMIT = 4 << 30

# Below this iteration count the Gram build (one densify scatter + one
# matmul) costs about as much as just running the scatter loop.
GRAM_MIN_ITERATIONS = 4


def fits_gram(batch_rows: int, f_text: int, num_iterations: int) -> bool:
    """Static-shape gate: use the Gram path when the dense counts matrix
    fits the HBM budget and there are enough iterations to amortize it.
    All inputs are trace-time constants, so this never recompiles."""
    return (
        num_iterations >= GRAM_MIN_ITERATIONS
        and batch_rows * f_text * 4 <= GRAM_DENSE_BYTES_LIMIT
    )


def _split_feature_index(token_idx, f_text: int):
    """The two-level split ``f = hi·k_lo + lo`` both one-hot count builders
    share — ONE definition so the planes cannot drift on feature layout."""
    lo_bits = (max(f_text - 1, 1).bit_length() + 1) // 2
    k_lo = 1 << lo_bits
    k_hi = -(-f_text // k_lo)
    return token_idx // k_lo, token_idx % k_lo, k_hi, k_lo


_ONEHOT_DIMS = (((1,), (1,)), ((0,), (0,)))  # contract over l, batch over b


def onehot_counts(token_idx, token_val, f_text: int, dtype=jnp.bfloat16):
    """[B, L] (idx, val) pairs → dense [B, F] ``dtype`` counts with NO
    scatter: the two-level one-hot batched matmul of the module docstring.
    Accumulation is f32 regardless of ``dtype``; the output cast fuses into
    the matmul epilogue, so the bf16 default halves the write (and the
    downstream G matmul's read) vs an f32 count matrix."""
    b, l = token_idx.shape
    hi, lo, k_hi, k_lo = _split_feature_index(token_idx, f_text)
    oh_hi = (hi[:, :, None] == jnp.arange(k_hi, dtype=hi.dtype)).astype(
        jnp.bfloat16
    ) * token_val[:, :, None].astype(jnp.bfloat16)
    oh_lo = (lo[:, :, None] == jnp.arange(k_lo, dtype=lo.dtype)).astype(jnp.bfloat16)
    c = lax.dot_general(
        oh_hi,
        oh_lo,
        _ONEHOT_DIMS,
        preferred_element_type=jnp.float32,
    ).astype(dtype)  # [B, k_hi, k_lo]
    return c.reshape(b, k_hi * k_lo)[:, :f_text]


def onehot_counts_int8(token_idx, token_val, f_text: int):
    """The int8 twin of ``onehot_counts``: [B, L] (idx, val) pairs → dense
    [B, F] int8 counts via the same two-level one-hot batched matmul, with
    s8 operands and s32 accumulation — integer-exact whenever the caller's
    gate holds (integral values, per-row absolute mass ≤ 127, so every
    count and every partial sum is an integer within range)."""
    b, l = token_idx.shape
    hi, lo, k_hi, k_lo = _split_feature_index(token_idx, f_text)
    val_i8 = token_val.astype(jnp.int8)
    oh_hi = jnp.where(
        hi[:, :, None] == jnp.arange(k_hi, dtype=hi.dtype),
        val_i8[:, :, None],
        jnp.int8(0),
    )
    oh_lo = (lo[:, :, None] == jnp.arange(k_lo, dtype=lo.dtype)).astype(jnp.int8)
    c = lax.dot_general(
        oh_hi,
        oh_lo,
        _ONEHOT_DIMS,
        preferred_element_type=jnp.int32,
    ).astype(jnp.int8)  # counts ≤ row mass ≤ 127: the narrowing is exact
    return c.reshape(b, k_hi * k_lo)[:, :f_text]


def text_gram(
    token_idx,
    token_val,
    f_text: int,
    row_start=None,
    rows: int = 0,
    int8_plane: bool | None = None,
):
    """Text-feature Gram block: X·Xᵀ ([B,B] f32), or the row slice
    ``X[row_start:row_start+rows]·Xᵀ`` ([rows, B]) when ``rows`` > 0 — the
    building block sharded layouts use (each shard computes its row panel
    and/or its feature slice's partial G, then all-gathers/psums).

    Common path (every real tweet): token values are small integers and each
    row's total absolute mass is ≤ 127, which PROVES every count is an
    integer in [−127, 127] and therefore int8-exact — so the count matrix is
    built by the one-hot matmul straight into int8 and the product is one
    s8×s8→s32 MXU matmul (~2× bf16 peak on v5e, half the count-matrix
    bytes), bit-exact. Row mass in (127, 255] keeps the bf16 plane (counts
    ≤ 255 are bf16-exact). The predicates cost one pass over the [B, L]
    token values (not the [B, F] counts). Anything else — fractional values,
    a degenerate row with > 255 mass — takes the exact fallback: f32 scatter
    densify + full-f32 (``Precision.HIGHEST``) matmul.
    """
    if int8_plane is None:
        int8_plane = GRAM_INT8_PLANE
    val_f = token_val.astype(jnp.float32)
    # integral, bf16-representable values with row ABSOLUTE mass ≤ 255 ⇒
    # every count is an integer of magnitude ≤ 255 ⇒ counts and their bf16
    # products are exact (plain sum would be unsound for mixed-sign values:
    # cancellation can hide a per-feature count above the bf16 range)
    integral = jnp.all(val_f == jnp.round(val_f))
    row_mass = jnp.sum(jnp.abs(val_f), axis=1)
    vals_ok = (
        integral
        & jnp.all(val_f.astype(jnp.bfloat16).astype(jnp.float32) == val_f)
        & jnp.all(row_mass <= 255.0)
    )
    # row absolute mass ≤ 127 tightens every bound to the int8 range: each
    # |value| ≤ 127 (s8 operand) and each |count| ≤ 127 (s8 count matrix)
    vals_ok_i8 = integral & jnp.all(row_mass <= 127.0)

    def left(c):
        """The (possibly row-sliced) left operand. The slice makes the G
        MATMUL's FLOPs scale 1/shards in sharded builds; the count build
        itself is deliberately replicated per shard — the right operand
        needs all B_global rows anyway, and all-gathering shard-local
        count builds would move [B_global, F_local] bf16 (~0.5 GB at the
        2^18 operating point) to save a build worth ~3% of the G matmul."""
        if rows:
            return lax.dynamic_slice_in_dim(c, row_start, rows, axis=0)
        return c

    def fast_i8(i, v):
        c = onehot_counts_int8(i, v, f_text)  # [B, F] int8, exact
        g = jnp.matmul(left(c), c.T, preferred_element_type=jnp.int32)
        # |G| ≤ (Σ|c_a|)·max|c_b| ≤ 127² < 2²⁴: the f32 cast is exact
        return g.astype(jnp.float32)

    def fast(i, v):
        c = onehot_counts(i, v, f_text)  # [B, F] bf16, exact
        return jnp.matmul(left(c), c.T, preferred_element_type=jnp.float32)

    def exact(i, v):
        c = densify_text(i, v, f_text)  # [B, F] f32
        return jnp.matmul(left(c), c.T, precision=lax.Precision.HIGHEST)

    idx = vals_ok.astype(jnp.int32)
    branches = [exact, fast]
    if int8_plane:
        idx = idx + vals_ok_i8.astype(jnp.int32)  # i8-ok ⊆ bf16-ok: 0/1/2
        branches.append(fast_i8)
    return lax.switch(idx, branches, token_idx, val_f)


def add_numeric_block(g_text, numeric, dtype=jnp.float32):
    """G = g_text + N·Nᵀ, cast to the dual loop's dtype — the one place the
    numeric features enter G (shared by every layout so precision handling
    cannot drift between them)."""
    num = numeric.astype(jnp.float32)
    return (g_text + num @ num.T).astype(dtype)


def gram_matrix(
    token_idx,
    token_val,
    numeric,
    f_text: int,
    dtype=jnp.float32,
    int8_plane: bool | None = None,
):
    """G = Z·Zᵀ ([B,B] ``dtype``) for Z = [text counts | numeric features]."""
    return add_numeric_block(
        text_gram(token_idx, token_val, f_text, int8_plane=int8_plane),
        numeric,
        dtype,
    )


def dual_norm_sq(p_prev, u, g):
    """‖W_a − W_b‖² evaluated in the dual basis — the ``norm_sq`` hook for
    ``sgd_inner_loop`` (convergence tolerance), given ``p_prev = ‖W_prev‖²``,
    ``u = Z·W_prev`` and the Gram matrix ``g``."""

    def norm_sq(a, b):
        dc = a["c"] - b["c"]
        da = a["alpha"] - b["alpha"]
        return dc * dc * p_prev + 2.0 * dc * jnp.dot(u, da) + jnp.dot(da, g @ da)

    return norm_sq


def dual_writeback(w_text, w_num, c, alpha, token_idx, token_val, numeric):
    """W_new = c·W_prev + Zᵀ·α — the one feature-space scatter of the batch.

    Contributions for duplicate (row, feature) occurrences sum, exactly as
    the per-iteration ``sparse_grad_text`` scatter summed them."""
    contrib = token_val * alpha[:, None]  # [B, L]
    w_text_new = (w_text * c).at[token_idx.reshape(-1)].add(contrib.reshape(-1))  # lawcheck: disable=TW004 -- the ONE budgeted scatter per batch the Gram design ships (50 per-iteration scatters folded into a single writeback, ~21 ms/step measured)
    w_num_new = w_num * c + numeric.T @ alpha
    return w_text_new, w_num_new
