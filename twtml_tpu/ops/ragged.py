"""Device-side re-pad of the ragged units wire — ONE definition shared by
every step builder (single-device, data-parallel, feature-sharded), so the
wire semantics cannot drift between layouts.

The ragged wire (features/batch.py ``RaggedUnitBatch``) ships text as
concatenated code units + row offsets — no per-row pad bytes on the
upload-bound transport. The learner rebuilds the padded [B, L] layout
INSIDE the jit program with one gather (cheap on TPU — it is scatters that
serialize, not gathers) and case-folds ASCII there, which the padded wire's
C pad copy did on the host. Features are bit-identical either way
(tests/test_ragged_wire.py).

Under shard_map the arrays arrive SHARD-LOCAL (this shard's sub-buffer and
its shard-relative offsets — features/batch.py ``align_ragged_shards``),
and the same gather rebuilds this shard's [B_local, L] rows; ``row_len``
(L) is static and global, so every shard's re-pad agrees with the
single-device layout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def offsets_from_deltas(deltas, num_segments: int = 1):
    """uint16 per-row length deltas → segment-relative int32 offsets, in
    program — the decode half of the NARROW offset wire (Lean wire v2:
    features/batch.py ships offsets as length deltas in half the sideband
    bytes whenever the static ``row_len`` gate allows; this cumsum rebuilds
    the exact offsets, so every downstream consumer — ``ragged_repad``
    first — sees the int32 wire bit-identically).

    Shapes: [..., S·B_s] → [..., S·(B_s+1)] (leading axes pass through —
    a stacked [K, B] superbatch wire decodes to [K, B+1] in one call).
    Each segment's offsets start at 0 by construction
    (``ragged_wire_arrays`` / ``align_ragged_shards``), which is what makes
    the delta encoding lossless."""
    lead = deltas.shape[:-1]
    d = deltas.astype(jnp.int32).reshape(lead + (num_segments, -1))
    zero = jnp.zeros(lead + (num_segments, 1), jnp.int32)
    out = jnp.concatenate([zero, jnp.cumsum(d, axis=-1)], axis=-1)
    return out.reshape(lead + (-1,))


def units_from_codes(codes, out_len: int):
    """Digram-coded units wire → the raw uint8 units buffer, in program —
    the decode half of the COMPRESSED wire (``--wireCodec dict``:
    features/wirecodec.py encodes the all-ASCII uint8 units into literal
    bytes < 0x80 and two-unit dictionary codes >= 0x80 on the host; this
    rebuilds the exact buffer ahead of the ragged re-pad, so every
    downstream consumer — ``ragged_repad`` first — sees the uncompressed
    wire bit-identically).

    Decode is a bounded gather-expand + cumsum, the ``offsets_from_deltas``
    family: per-code expanded lengths (1 or 2) cumsum to output positions,
    one searchsorted maps each of the ``out_len`` output slots back to its
    code (a vectorized binary search — gathers only, never a scatter or a
    data-dependent loop: the TW004/XLA serialization trap), and a two-entry
    table gather materializes the unit. The 128×2 decode table is a static
    compile-time constant (the dictionary ships in the program, not on the
    wire). ``out_len`` is static (the raw units bucket recorded in the
    packed layout); trailing padding codes past it are never gathered.

    Shapes: [..., M] codes → [..., out_len] uint8 units (leading axes pass
    through — the stacked [K, M] group wire decodes in one call)."""
    from ..features.wirecodec import CODE_BASE, decode_table

    table = jnp.asarray(decode_table())  # [128, 2] uint8, baked constant

    def one(c1d):
        c = c1d.astype(jnp.int32)
        lens = 1 + (c >= CODE_BASE).astype(jnp.int32)
        ends = jnp.cumsum(lens)  # inclusive expansion ends, [M]
        t = jnp.arange(out_len, dtype=jnp.int32)
        j = jnp.clip(
            jnp.searchsorted(ends, t, side="right"), 0, c.shape[0] - 1
        ).astype(jnp.int32)
        k = jnp.clip(t - (ends[j] - lens[j]), 0, 1)
        cj = c[j]
        exp = table[jnp.clip(cj - CODE_BASE, 0, CODE_BASE - 1), k]
        return jnp.where(cj < CODE_BASE, cj, exp.astype(jnp.int32)).astype(
            jnp.uint8
        )

    if codes.ndim == 1:
        return one(codes)
    lead = codes.shape[:-1]
    out = jax.vmap(one)(codes.reshape((-1, codes.shape[-1])))
    return out.reshape(lead + (out_len,))


def ragged_repad(units, offsets, row_len: int, rows: int | None = None,
                 deltas: bool = False):
    """(flat units [N], offsets, static L) → (padded int32 [B, L]
    case-folded units, int32 [B] lengths) — the padded-wire layout, on
    device.

    ``rows`` (B, the row count the caller's mask carries) tells the shard
    count apart statically: a shard-ALIGNED buffer carries one
    [B_s + 1] offsets block per segment, so S = offsets.size − rows
    (S = 1 when offsets is the plain [B + 1] vector; None means plain).
    Segment s's sub-buffer starts at s·(N/S) and its offsets are
    segment-relative, so converting to absolute starts is one broadcast
    add — the gather itself is identical in every layout.

    ``deltas=True`` accepts the NARROW offset wire directly: ``offsets``
    then holds uint16 per-row length deltas ([B], one segment per
    ``rows``-worth of deltas is impossible to infer from size, so callers
    on the multi-segment layout decode via ``offsets_from_deltas`` first)
    and the cumsum happens here, in-program — the repad result is
    bit-identical to the int32 wire."""
    if deltas:
        offsets = offsets_from_deltas(offsets)
        rows = None
    offs = offsets.astype(jnp.int32)
    n_segments = 1 if rows is None else offsets.shape[0] - rows
    if n_segments > 1:
        ob = offs.reshape(n_segments, -1)  # [S, B_s + 1], segment-relative
        base = (
            jnp.arange(n_segments, dtype=jnp.int32)
            * (units.shape[0] // n_segments)
        )[:, None]
        starts = (ob[:, :-1] + base).reshape(-1)
        lens = (ob[:, 1:] - ob[:, :-1]).reshape(-1)
    else:
        starts, lens = offs[:-1], offs[1:] - offs[:-1]
    cols = jnp.arange(row_len, dtype=jnp.int32)[None, :]
    idx = jnp.clip(starts[:, None] + cols, 0, units.shape[0] - 1)
    buf = jnp.where(cols < lens[:, None], units[idx].astype(jnp.int32), 0)
    buf = buf + ((buf >= 65) & (buf <= 90)) * 32  # ASCII case fold
    return buf, lens
