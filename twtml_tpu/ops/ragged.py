"""Device-side re-pad of the ragged units wire — ONE definition shared by
every step builder (single-device, data-parallel, feature-sharded), so the
wire semantics cannot drift between layouts.

The ragged wire (features/batch.py ``RaggedUnitBatch``) ships text as
concatenated code units + row offsets — no per-row pad bytes on the
upload-bound transport. The learner rebuilds the padded [B, L] layout
INSIDE the jit program with one gather (cheap on TPU — it is scatters that
serialize, not gathers) and case-folds ASCII there, which the padded wire's
C pad copy did on the host. Features are bit-identical either way
(tests/test_ragged_wire.py).

Under shard_map the arrays arrive SHARD-LOCAL (this shard's sub-buffer and
its shard-relative offsets — features/batch.py ``align_ragged_shards``),
and the same gather rebuilds this shard's [B_local, L] rows; ``row_len``
(L) is static and global, so every shard's re-pad agrees with the
single-device layout.
"""

from __future__ import annotations

import jax.numpy as jnp


def ragged_repad(units, offsets, row_len: int, rows: int | None = None):
    """(flat units [N], offsets, static L) → (padded int32 [B, L]
    case-folded units, int32 [B] lengths) — the padded-wire layout, on
    device.

    ``rows`` (B, the row count the caller's mask carries) tells the shard
    count apart statically: a shard-ALIGNED buffer carries one
    [B_s + 1] offsets block per segment, so S = offsets.size − rows
    (S = 1 when offsets is the plain [B + 1] vector; None means plain).
    Segment s's sub-buffer starts at s·(N/S) and its offsets are
    segment-relative, so converting to absolute starts is one broadcast
    add — the gather itself is identical in every layout."""
    offs = offsets.astype(jnp.int32)
    n_segments = 1 if rows is None else offsets.shape[0] - rows
    if n_segments > 1:
        ob = offs.reshape(n_segments, -1)  # [S, B_s + 1], segment-relative
        base = (
            jnp.arange(n_segments, dtype=jnp.int32)
            * (units.shape[0] // n_segments)
        )[:, None]
        starts = (ob[:, :-1] + base).reshape(-1)
        lens = (ob[:, 1:] - ob[:, :-1]).reshape(-1)
    else:
        starts, lens = offs[:-1], offs[1:] - offs[:-1]
    cols = jnp.arange(row_len, dtype=jnp.int32)[None, :]
    idx = jnp.clip(starts[:, None] + cols, 0, units.shape[0] - 1)
    buf = jnp.where(cols < lens[:, None], units[idx].astype(jnp.int32), 0)
    buf = buf + ((buf >= 65) & (buf <= 90)) * 32  # ASCII case fold
    return buf, lens
