"""Per-batch standard scaling (reference: MLlib ``new StandardScaler(false,
true).fit(rdd).transform(rdd)`` in the k-means entry, KMeans.scala:103).

``withMean=false, withStd=true``: divide each column by its standard
deviation, leave centering alone. MLlib's summarizer uses the unbiased sample
variance (n−1); columns with zero std map to 0.0 (StandardScalerModel's
``if std != 0 value/std else 0``). Masked rows are excluded from the fit and
zeroed in the output.
"""

from __future__ import annotations

import jax.numpy as jnp


def standard_scale(points, mask):
    """points [B,D], mask [B] → scaled [B,D] (jit-safe, mask-aware)."""
    m = mask[:, None]
    n = jnp.maximum(jnp.sum(mask), 1.0)
    mean = jnp.sum(points * m, axis=0) / n
    var = jnp.sum(((points - mean) * m) ** 2, axis=0) / jnp.maximum(n - 1.0, 1.0)
    std = jnp.sqrt(var)
    factor = jnp.where(std > 0, 1.0 / jnp.maximum(std, 1e-30), 0.0)
    return points * factor[None, :] * m
