"""Pallas TPU kernel: the fused dense streaming-SGD hot loop (reference
implementation; see the honest status note at the bottom).

The per-batch compute core (SURVEY.md §3.3 — numIterations of
predict→gradient→update on a [B, F] design matrix) runs as ONE pallas program
with the design matrix resident in VMEM for the entire loop: X is loaded from
HBM once, then every iteration's MXU products and VPU vector updates hit
on-chip memory only, instead of re-streaming X from HBM per iteration.

Design (the parts that make it actually lower on a real v5e — the round-1
version OOM'd scoped VMEM at the flagship 2048×1024 shape because the
``X^T r`` contraction materialized a second f32 copy of X):

- **Both orientations ship as inputs.** The kernel receives ``X`` [B, F] and
  ``XT`` [F, B] so the forward (``X·w``) and gradient (``X^T·r``) products are
  both canonical ``(((1,), (0,)), ((), ()))`` matvecs — no in-kernel
  transpose, no relayout copy. The enclosing jit builds ``XT`` with XLA.
- **bf16 storage, f32 accumulation.** X/XT live in VMEM as bfloat16 (half the
  footprint; both fit in ~8 MB at 2048×1024), every dot accumulates in f32
  (``preferred_element_type``). For this workload the text half of X holds
  small integer bigram counts — exact in bf16 — so the only storage error is
  on the 4 scaled numeric features; ``w``/``r`` are cast to bf16 per product,
  giving ~1e-4 relative weight error vs the f32 XLA path (tests pin it).
- **No mask ref.** Padded batches zero their padding rows (features/batch.py
  zeroes X rows and labels), so ``r = X·w − y`` is already 0 there; the
  selected count arrives as one SMEM scalar. This trims ~1 MB of
  lane-padded [B, 1] vectors — the difference between fitting and OOM.
- The iteration loop is a ``lax.fori_loop`` inside the kernel (sequential on
  one core — exactly the dependency chain SGD imposes anyway) with the same
  MLlib semantics as models/sgd.py ``sgd_inner_loop``: 1-indexed stepSize/√i,
  L2 pre-scale, zero-count skip, convergence tolerance with converged-freeze.

STATUS / measurement honesty (BENCHMARKS.md has the full story): on this
build's TPU transport, dispatch costs milliseconds while the whole
50-iteration loop at 2048×1024 is micro-seconds of device time for BOTH the
XLA-compiled loop and this kernel — the difference is far below measurement
noise, and ``block_until_ready`` does not even sync through the tunnel
(tools/bench_pallas.py uses chained dispatches + one host fetch). The kernel
is therefore NOT wired into the model knobs (round 1's ``use_pallas`` flag is
gone); it stays as tested, hardware-lowerable reference code for the
VMEM-resident pattern, with semantics pinned against the XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _sgd_kernel(
    count_ref, x_ref, xt_ref, y_ref, w0_ref, wout_ref, preds_ref,
    *, num_iterations: int, step_size: float, l2_reg: float,
    convergence_tol: float,
):
    X = x_ref[:]    # [B, F] bf16 — stays in VMEM across the whole loop
    XT = xt_ref[:]  # [F, B] bf16
    y = y_ref[:]    # [B, 1] f32, already masked (padding rows are 0)
    w0 = w0_ref[:]  # [F, 1] f32
    count = count_ref[0]
    denom = jnp.maximum(count, 1.0)

    def matvec(w):  # [B, 1] f32
        return lax.dot_general(
            X, w.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def gradvec(r):  # [F, 1] f32
        return lax.dot_general(
            XT, r.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # predictions with pre-update weights (predict-then-train)
    preds_ref[:] = matvec(w0)

    def body(i, carry):
        w, converged = carry
        it = i + 1
        residual = matvec(w) - y  # padding rows: zero X row, zero y → 0
        grad = gradvec(residual) / denom
        eta = step_size / jnp.sqrt(jnp.float32(it))
        w_new = w * (1.0 - eta * l2_reg) - eta * grad
        w_new = jnp.where(count > 0, w_new, w)
        if convergence_tol > 0:
            delta = jnp.sqrt(jnp.sum((w_new - w) ** 2))
            norm_new = jnp.sqrt(jnp.sum(w_new * w_new))
            conv_now = (count > 0) & (
                delta < convergence_tol * jnp.maximum(norm_new, 1.0)
            )
        else:
            conv_now = False
        w_out = jnp.where(converged, w, w_new)
        return w_out, jnp.logical_or(converged, conv_now)

    w_final, _ = lax.fori_loop(
        0, num_iterations, body, (w0, jnp.array(False))
    )
    wout_ref[:] = w_final


# Scoped-VMEM model, calibrated against the Mosaic compiler's own accounting
# on v5e (hardware limit 16 MB): X+XT in bf16, the [·, 1] f32 vectors tiling
# to a full 128-lane stripe each (~512 B/row), plus the compiler's measured
# fixed overhead — at 2048×1024 Mosaic reports ~15.83 MB vs 14.7 MB for the
# first two terms, so the model carries that ~1.25 MB slack explicitly. The
# gate must track REAL usage: the round-1 kernel shipped a budget that
# approved shapes which then OOM'd at compile time on hardware.
VMEM_LIMIT_BYTES = 16 * 1024 * 1024
_MOSAIC_OVERHEAD_BYTES = 1_310_720  # ~1.25 MB measured at the flagship shape


def _vmem_estimate(batch_rows: int, f_padded: int) -> int:
    matrix = 2 * batch_rows * f_padded * 2  # X + XT, bf16
    # ~6 lane-padded [rows, 1] f32 stripes (y, w, preds, residual, grad, tmp)
    vectors = 6 * max(batch_rows, f_padded) * 512
    return matrix + vectors + _MOSAIC_OVERHEAD_BYTES


def padded_lanes(num_features: int) -> int:
    """The kernel's own padding rule — single source of truth for callers."""
    return -(-num_features // 128) * 128


def supports(
    *, batch_rows: int, num_features: int, mini_batch_fraction: float, dtype
) -> bool:
    f_padded = padded_lanes(num_features)
    backend = jax.default_backend()
    return (
        backend in ("tpu", "cpu")  # cpu runs the interpreter; others can't lower
        and mini_batch_fraction >= 1.0
        and dtype == jnp.float32
        and batch_rows % 8 == 0
        and _vmem_estimate(batch_rows, f_padded) <= VMEM_LIMIT_BYTES
    )


@functools.cache
def _build(batch_rows, f_padded, num_iterations, step_size, l2_reg,
           convergence_tol, interpret):
    kernel = functools.partial(
        _sgd_kernel,
        num_iterations=num_iterations,
        step_size=step_size,
        l2_reg=l2_reg,
        convergence_tol=convergence_tol,
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((f_padded, 1), jnp.float32),  # weights
            jax.ShapeDtypeStruct((batch_rows, 1), jnp.float32),  # raw preds
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # count
            pl.BlockSpec(memory_space=pltpu.VMEM),  # X (bf16)
            pl.BlockSpec(memory_space=pltpu.VMEM),  # XT (bf16)
            pl.BlockSpec(memory_space=pltpu.VMEM),  # y (masked)
            pl.BlockSpec(memory_space=pltpu.VMEM),  # w0
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )


def fused_dense_sgd(
    x_dense,
    labels,
    mask,
    weights,
    *,
    num_iterations: int,
    step_size: float,
    l2_reg: float = 0.0,
    convergence_tol: float = 0.001,
    interpret: bool | None = None,
):
    """Run the fused loop on a dense [B, F] batch. ``weights`` is the flat
    [F] vector; F is padded to a lane multiple internally. Rows with
    mask == 0 MUST have zeroed features and labels (features/batch.py
    guarantees this for real batches; the call masks labels defensively).
    Returns (new_weights [F], raw_predictions [B])."""
    b, f = x_dense.shape
    f_padded = padded_lanes(f)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if f_padded != f:
        x_dense = jnp.pad(x_dense, ((0, 0), (0, f_padded - f)))
        weights = jnp.pad(weights, (0, f_padded - f))
    mask = mask.astype(jnp.float32)
    # where, not multiply: garbage in masked rows may be NaN/Inf, and
    # NaN * 0 is NaN — it would poison every weight through the gradient
    x_dense = jnp.where(mask[:, None] > 0, x_dense, 0.0).astype(jnp.bfloat16)
    call = _build(
        b, f_padded, num_iterations, float(step_size), float(l2_reg),
        float(convergence_tol), bool(interpret),
    )
    w_out, preds = call(
        jnp.sum(mask).reshape(1),
        x_dense,
        x_dense.T,
        jnp.where(mask > 0, labels.astype(jnp.float32), 0.0)[:, None],
        weights.astype(jnp.float32)[:, None],
    )
    return w_out[:f, 0], preds[:, 0]
