"""Pallas TPU kernel: the fused dense streaming-SGD hot loop.

The per-batch compute core (SURVEY.md §3.3 — numIterations of
predict→gradient→update on a [B, F] design matrix) runs as ONE pallas program
with the design matrix resident in VMEM for the entire loop: X is loaded from
HBM once, then all ``num_iterations`` MXU matvecs (forward ``X·w`` and
gradient ``r·X``) and VPU vector updates hit on-chip memory only. The
XLA-built fallback re-streams X from HBM every iteration; this kernel removes
that traffic for models in the dense regime (the reference's 1004-dim model
padded to 1024 lanes: 2048×1024 f32 = 8 MB, comfortably inside ~16 MB VMEM).

Semantics match models/sgd.py's ``sgd_inner_loop`` for the configuration the
kernel supports (mini_batch_fraction == 1.0, least-squares residual): same
1-indexed stepSize/√i schedule, L2 pre-scale, zero-count skip, convergence
tolerance with converged-freeze. The builder gates itself on those knobs and
returns None otherwise, so callers fall back transparently.

Layout notes (guide: /opt/skills/guides/pallas_guide.md):
- all refs are ≥2D and VMEM-resident; B and F must be multiples of (8, 128);
- matvecs keep the MXU busy via dot_general with
  ``preferred_element_type=f32``; w lives as [F, 1];
- the iteration loop is a ``lax.fori_loop`` inside the kernel (sequential on
  one core — exactly the dependency chain SGD imposes anyway).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _sgd_kernel(
    x_ref, y_ref, mask_ref, w0_ref, wout_ref, preds_ref,
    *, num_iterations: int, step_size: float, l2_reg: float,
    convergence_tol: float,
):
    X = x_ref[:]  # [B, F] — stays in VMEM across the whole loop
    y = y_ref[:]  # [B, 1]
    m = mask_ref[:]  # [B, 1]
    w0 = w0_ref[:]  # [F, 1]

    def matvec(w):
        return jax.lax.dot_general(
            X, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [B, 1]

    def grad_sum(residual):
        return jax.lax.dot_general(
            X, residual, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [F, 1]

    # predictions with pre-update weights (predict-then-train)
    preds_ref[:] = matvec(w0)

    count = jnp.sum(m)
    denom = jnp.maximum(count, 1.0)

    def body(i, carry):
        w, converged = carry
        it = i + 1
        residual = (matvec(w) - y) * m
        grad = grad_sum(residual) / denom
        eta = step_size / jnp.sqrt(jnp.float32(it))
        w_new = w * (1.0 - eta * l2_reg) - eta * grad
        w_new = jnp.where(count > 0, w_new, w)
        if convergence_tol > 0:
            delta = jnp.sqrt(jnp.sum((w_new - w) ** 2))
            norm_new = jnp.sqrt(jnp.sum(w_new * w_new))
            conv_now = (count > 0) & (
                delta < convergence_tol * jnp.maximum(norm_new, 1.0)
            )
        else:
            conv_now = False
        w_out = jnp.where(converged, w, w_new)
        return w_out, jnp.logical_or(converged, conv_now)

    w_final, _ = lax.fori_loop(
        0, num_iterations, body, (w0, jnp.array(False))
    )
    wout_ref[:] = w_final


# VMEM budget: X + copies of w/preds must fit in ~16MB/core with headroom.
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def padded_lanes(num_features: int) -> int:
    """The kernel's own padding rule — single source of truth for callers."""
    return -(-num_features // 128) * 128


def supports(
    *, batch_rows: int, num_features: int, mini_batch_fraction: float, dtype
) -> bool:
    f_padded = padded_lanes(num_features)
    backend = jax.default_backend()
    return (
        backend in ("tpu", "cpu")  # cpu runs the interpreter; others can't lower
        and mini_batch_fraction >= 1.0
        and dtype == jnp.float32
        and batch_rows % 8 == 0
        and batch_rows * f_padded * 4 <= VMEM_BUDGET_BYTES
    )


@functools.cache
def _build(batch_rows, f_padded, num_iterations, step_size, l2_reg,
           convergence_tol, interpret):
    kernel = functools.partial(
        _sgd_kernel,
        num_iterations=num_iterations,
        step_size=step_size,
        l2_reg=l2_reg,
        convergence_tol=convergence_tol,
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((f_padded, 1), jnp.float32),  # weights
            jax.ShapeDtypeStruct((batch_rows, 1), jnp.float32),  # raw preds
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # X
            pl.BlockSpec(memory_space=pltpu.VMEM),  # y
            pl.BlockSpec(memory_space=pltpu.VMEM),  # mask
            pl.BlockSpec(memory_space=pltpu.VMEM),  # w0
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )


def fused_dense_sgd(
    x_dense,
    labels,
    mask,
    weights,
    *,
    num_iterations: int,
    step_size: float,
    l2_reg: float = 0.0,
    convergence_tol: float = 0.001,
    interpret: bool | None = None,
):
    """Run the fused loop on a dense [B, F] batch. ``weights`` is the flat
    [F] vector; F is padded to a lane multiple internally. Returns
    (new_weights [F], raw_predictions [B])."""
    b, f = x_dense.shape
    f_padded = padded_lanes(f)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if f_padded != f:
        x_dense = jnp.pad(x_dense, ((0, 0), (0, f_padded - f)))
        weights = jnp.pad(weights, (0, f_padded - f))
    call = _build(
        b, f_padded, num_iterations, float(step_size), float(l2_reg),
        float(convergence_tol), bool(interpret),
    )
    w_out, preds = call(
        x_dense.astype(jnp.float32),
        labels.astype(jnp.float32)[:, None],
        mask.astype(jnp.float32)[:, None],
        weights.astype(jnp.float32)[:, None],
    )
    return w_out[:f, 0], preds[:, 0]
