from .stats import masked_mean, masked_stdev, batch_stats
from .sparse import densify_text, sparse_predict, sparse_grad_text, sparse_text_dot
from .gram import gram_matrix, fits_gram

__all__ = [
    "masked_mean",
    "masked_stdev",
    "batch_stats",
    "densify_text",
    "sparse_predict",
    "sparse_grad_text",
    "sparse_text_dot",
    "gram_matrix",
    "fits_gram",
]
