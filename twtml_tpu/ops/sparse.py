"""Sparse↔dense feature assembly on device.

The reference's feature vector is an MLlib SparseVector: hashed-bigram text
dims followed by 4 dense numeric dims (MllibHelper.scala:73-82). On TPU there
are two regimes:

- **dense path** (small numTextFeatures, e.g. the default 1004 total): scatter
  the padded (idx, val) pairs into a dense [B, F] matrix once per batch, then
  every SGD iteration is a [B,F]×[F] matmul on the MXU — the whole
  numIterations loop stays compute-dense.
- **sparse path** (numTextFeatures = 2^18, BASELINE config #4): the dense
  matrix would be ~1GB of mostly zeros; instead predictions gather weight
  entries (w[token_idx]·token_val) and gradients scatter-add residuals with
  one ``segment_sum`` per iteration.

Token pairs arrive either host-hashed (features/hashing.py, native/) or are
computed in-program from raw code units (ops/text_hash.py — the default
wire format); both feed these same kernels.

Padded token slots carry (idx=0, val=0.0) so they contribute nothing to
either path.
"""

from __future__ import annotations

import jax.numpy as jnp


def densify_text(token_idx, token_val, num_text_features):
    """[B, L] (idx, val) pairs → dense [B, F_text] term-frequency matrix."""
    b = token_idx.shape[0]
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], token_idx.shape)
    dense = jnp.zeros((b, num_text_features), dtype=token_val.dtype)
    return dense.at[rows, token_idx].add(token_val)  # lawcheck: disable=TW004 -- dense-model densify for small F_text; the 2^18 config routes to ops/gram.py (the measured cliff is the [B,2^18] scatter)


def sparse_text_dot(w_text, token_idx, token_val):
    """Σ_j w_text[idx_j]·val_j per row — the text half of the sparse dot.
    Shared by the single-device sparse path and the feature-sharded path
    (which calls it on slice-local indices with out-of-slice values zeroed,
    then psums partial dots over the model axis)."""
    gathered = jnp.take(w_text, token_idx, axis=0)  # [B, L]
    return jnp.sum(gathered * token_val, axis=1)  # [B]


def sparse_predict(w_text, w_num, token_idx, token_val, numeric):
    """ŷ = Σ_j w_text[idx_j]·val_j + numeric·w_num, no dense materialization.
    Equivalent to SparseVector dot (MLlib predict, LinearRegression.scala:57)."""
    return sparse_text_dot(w_text, token_idx, token_val) + numeric @ w_num


def sparse_grad_text(token_idx, token_val, residual, num_text_features):
    """∇_w_text Σ_i r_i·x_i = scatter-add of r_i·val_ij at idx_ij — the
    sparse half of the least-squares gradient (sum, not yet averaged)."""
    contrib = token_val * residual[:, None]  # [B, L]
    flat_idx = token_idx.reshape(-1)
    flat_contrib = contrib.reshape(-1)
    return jnp.zeros((num_text_features,), dtype=token_val.dtype).at[flat_idx].add(  # lawcheck: disable=TW004 -- the pre-Gram reference scatter: ground truth for the gram differential tests; use_gram routes the 2^18 config around it
        flat_contrib
    )
