"""Masked on-device batch statistics.

The reference computes per-batch count / MSE / stdev(real) / stdev(pred) as
separate RDD jobs with driver-side collects (LinearRegression.scala:56-65,
61-62 — its scalability cliff per SURVEY.md §2.5). Here all statistics are
fused into the training step and come back as a handful of scalars in the
step output; padding rows are excluded by the mask. ``RDD.stdev`` is the
population stdev (divide by n), reproduced here.

Every reduction takes an optional ``axis_name`` so the same code runs
single-device (jit) and data-parallel (shard_map with a psum over ICI).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _maybe_psum(x, axis_name):
    return jax.lax.psum(x, axis_name) if axis_name else x


def masked_sum(x, mask, axis_name=None):
    return _maybe_psum(jnp.sum(x * mask), axis_name)


def masked_count(mask, axis_name=None):
    return _maybe_psum(jnp.sum(mask), axis_name)


def masked_mean(x, mask, axis_name=None):
    n = masked_count(mask, axis_name)
    return masked_sum(x, mask, axis_name) / jnp.maximum(n, 1.0)


def masked_stdev(x, mask, axis_name=None):
    """Population standard deviation over valid rows (Spark RDD.stdev)."""
    mean = masked_mean(x, mask, axis_name)
    var = masked_mean(x * x, mask, axis_name) - mean * mean
    return jnp.sqrt(jnp.maximum(var, 0.0))


def batch_stats(labels, rounded_preds, mask, axis_name=None):
    """count, mse(y, rounded ŷ), stdev(y), stdev(ŷ) — the five dashboard
    numbers minus the cumulative count (kept by the driver, reference
    accumulator at LinearRegression.scala:51,60)."""
    count = masked_count(mask, axis_name)
    err = (labels - rounded_preds) * mask
    mse = masked_sum(err * err, jnp.ones_like(mask), axis_name) / jnp.maximum(count, 1.0)
    return {
        "count": count,
        "mse": mse,
        "real_stdev": masked_stdev(labels, mask, axis_name),
        "pred_stdev": masked_stdev(rounded_preds, mask, axis_name),
    }
