"""On-device char-bigram HashingTF — featurization moved into the XLA program.

The host featurizer (features/hashing.py, native/fasthash.cpp) hashes bigram
strings on the CPU and ships (idx, count) pairs. On TPU the host is the
bottleneck of the streaming hot loop (one usable core), while the hash itself
is trivially vectorizable: MLlib's HashingTF index for a 2-char term is
``nonNegativeMod(javaStringHashCode(term), F)`` and Java ``String.hashCode``
of a 2-unit string is just ``31*c1 + c2`` over its UTF-16 code units
(max 31*65535 + 65535 < 2^31 — no wraparound, always non-negative). So the
wire format can be the padded code units themselves (uint16 — smaller than
the (idx, val) pairs) and the hash runs on device as two shifted loads, a
multiply-add, and a mod, fused by XLA into the same program as the SGD step.

Duplicate bigrams need no host-side aggregation: the learner's scatter-add
(`densify_text` / `sparse_grad_text`) turns per-occurrence 1.0 values into
exactly HashingTF's term-frequency counts, and the gather-dot predict path is
linear so occurrences sum identically.

Semantics matched to features/hashing.py (the ground truth, itself matched to
MllibHelper.scala:42-56 + Scala ``text.sliding(2)``):
- length ≥ 2: units [u0..u_{n-1}] → n−1 bigram terms, term j hashing to
  ``(31*u_j + u_{j+1}) % F``;
- length == 1: ``sliding(2)`` yields the whole 1-char string as the single
  window, hashing to ``u_0 % F``;
- length == 0: no terms (padding rows ride this case).
"""

from __future__ import annotations

import jax.numpy as jnp


def hash_bigrams_device(units, length, num_features: int, dtype=jnp.float32):
    """[B, L] uint16 code units + [B] lengths → ([B, L-1] idx, [B, L-1] val).

    Padded unit slots (beyond each row's length) produce val 0.0 and idx 0,
    so the output plugs straight into `densify_text`/`sparse_predict`/
    `sparse_grad_text` in place of host-hashed token pairs.
    """
    u = units.astype(jnp.int32)
    c1, c2 = u[:, :-1], u[:, 1:]
    h = 31 * c1 + c2
    # sliding(2) on a single-unit string yields that string itself: the
    # row's one term hashes to u0 (Java hashCode of a 1-char string).
    h = h.at[:, 0].set(jnp.where(length == 1, u[:, 0], h[:, 0]))  # lawcheck: disable=TW004 -- fixed single-column update (static index 0), not a data-indexed scatter
    n_terms = jnp.where(length == 1, 1, jnp.maximum(length - 1, 0))
    valid = jnp.arange(h.shape[1], dtype=length.dtype)[None, :] < n_terms[:, None]
    token_idx = jnp.where(valid, h % num_features, 0)
    token_val = valid.astype(dtype)
    return token_idx, token_val
