"""In-step model/data quality vector — the device half of the model
observability plane (ISSUE 8).

One fixed, small ``[QUALITY_WIDTH]`` f32 vector computed INSIDE the existing
fused predict-then-train step and appended as a new leaf of ``StepOutput``,
so it rides the ONE ``device_get`` per tick the pipeline already makes (the
r2/r3 measurement law: fetches cost ~70–100 ms RTT, device FLOPs are µs and
nowhere near binding). Everything here is observation-only: no value feeds
back into the weights, the predictions, or the reported stats — the parity
law stands, and with the quality leaf disabled the step program is
structurally the pre-ISSUE-8 program (the leaf is ``None``, an empty
pytree).

Signals (layout pinned by ``QUALITY_FIELDS``; telemetry/modelwatch.py keys
off the names, tests key off the indices):

- ``weight_norm`` / ``update_norm``: ‖w_new‖₂ and ‖w_new − w_prev‖₂ — the
  EWMA inputs for the host-side loss-trend/step-health detectors;
- ``grad_norm``: L2 norm of the masked pre-update residual — the gradient
  in the dual (Gram) basis (run_dual_loop's ∂/∂α at iteration 1), the one
  gradient quantity every layout (dense, scatter, Gram) exposes without an
  extra pass over the 2^18 feature space;
- prediction / label / residual first+second moments (masked, population
  variance like ops/stats);
- per-column moments of the 4 dense numeric features (the drift detector's
  feature-shift inputs);
- ``bucket_occupancy`` / ``bucket_top_share``: a folded
  ``QUALITY_NBINS``-bin histogram of the hashed token mass — occupancy is
  the fraction of folded bins touched, top_share the largest bin's mass
  share (a collision/skew proxy for the hash-bucket space; computed as
  ``QUALITY_NBINS`` fused masked reductions, never a scatter — the [B·L]
  scatter runs ~220 ns/update serialized, the r2 XLA trap).

Every reduction takes the optional ``axis_name`` so the same code runs
single-device and data-parallel (psum over the mesh — all outputs are then
axis-invariant, which is also what shard_map's replicated-output check
requires).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .stats import _maybe_psum

# folded-histogram width: small enough that the one-hot reductions stay
# trivially cheap at bench shapes (B·L ~ 10^5–10^6 tokens), wide enough
# that occupancy/top-share move when the token distribution does
QUALITY_NBINS = 32

# the 4 dense numeric features (features/batch.NUM_NUMBER_FEATURES) —
# asserted at trace time below so the field layout can never silently skew
NUM_NUMERIC = 4

QUALITY_FIELDS = (
    "weight_norm",
    "update_norm",
    "grad_norm",
    "pred_mean",
    "pred_var",
    "label_mean",
    "label_var",
    "resid_mean",
    "resid_var",
    "num_mean_0",
    "num_mean_1",
    "num_mean_2",
    "num_mean_3",
    "num_var_0",
    "num_var_1",
    "num_var_2",
    "num_var_3",
    "bucket_occupancy",
    "bucket_top_share",
)
QUALITY_WIDTH = len(QUALITY_FIELDS)
QUALITY_INDEX = {name: i for i, name in enumerate(QUALITY_FIELDS)}


def _tree_sq_sum(tree) -> jnp.ndarray:
    return sum(
        jnp.sum(leaf.astype(jnp.float32) ** 2)
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def quality_vector(
    w_prev,
    w_new,
    *,
    residual,
    preds,
    labels,
    mask,
    numeric,
    token_idx,
    token_val,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """The ``[QUALITY_WIDTH]`` f32 quality vector for one micro-batch.

    ``residual`` is the masked pre-update residual (``residual_fn(raw, y) ·
    mask``); ``preds`` the reported (post-rounding) predictions; ``mask``
    the valid-row mask; all row-dimensioned inputs are shard-LOCAL under a
    data axis — the psums here make every output global, exactly like
    ``ops/stats.batch_stats``. Weights are replicated over any data axis,
    so their norms need no collective."""
    f32 = jnp.float32
    m = mask.astype(f32)
    n = _maybe_psum(jnp.sum(m), axis_name)
    denom = jnp.maximum(n, 1.0)

    w_sq = _tree_sq_sum(w_new)
    upd_sq = sum(
        jnp.sum((a.astype(f32) - b.astype(f32)) ** 2)
        for a, b in zip(
            jax.tree_util.tree_leaves(w_new), jax.tree_util.tree_leaves(w_prev)
        )
    )
    grad_sq = _maybe_psum(jnp.sum(residual.astype(f32) ** 2), axis_name)

    def moments(x):
        x = x.astype(f32)
        mean = _maybe_psum(jnp.sum(x * m), axis_name) / denom
        var = _maybe_psum(jnp.sum(x * x * m), axis_name) / denom - mean * mean
        return mean, jnp.maximum(var, 0.0)

    pred_mean, pred_var = moments(preds)
    label_mean, label_var = moments(labels)
    resid_mean, resid_var = moments(labels.astype(f32) - preds.astype(f32))

    if numeric.shape[1] != NUM_NUMERIC:
        raise ValueError(
            f"quality_vector pins {NUM_NUMERIC} numeric columns "
            f"(QUALITY_FIELDS layout); got {numeric.shape[1]}"
        )
    num = numeric.astype(f32)
    num_mean = _maybe_psum(jnp.sum(num * m[:, None], axis=0), axis_name) / denom
    num_sq = (
        _maybe_psum(jnp.sum(num * num * m[:, None], axis=0), axis_name) / denom
    )
    num_var = jnp.maximum(num_sq - num_mean * num_mean, 0.0)

    # folded hash-bucket histogram: QUALITY_NBINS masked reductions (each a
    # fused pass over the token buffer) — no [N, NBINS] one-hot intermediate
    # and no scatter; padding tokens carry zero token_val and padded rows
    # are masked, so only real token mass lands in the bins
    folded = jnp.bitwise_and(
        token_idx.reshape(-1).astype(jnp.int32), QUALITY_NBINS - 1
    )
    tv = (token_val.astype(f32) * m[:, None]).reshape(-1)
    bins = jnp.stack(
        [
            jnp.sum(jnp.where(folded == b, tv, 0.0))
            for b in range(QUALITY_NBINS)
        ]
    )
    bins = _maybe_psum(bins, axis_name)
    total = jnp.sum(bins)
    occupancy = jnp.mean((bins > 0).astype(f32))
    top_share = jnp.max(bins) / jnp.maximum(total, 1.0)

    return jnp.stack(
        [
            jnp.sqrt(w_sq),
            jnp.sqrt(upd_sq),
            jnp.sqrt(grad_sq),
            pred_mean,
            pred_var,
            label_mean,
            label_var,
            resid_mean,
            resid_var,
        ]
        + [num_mean[i] for i in range(NUM_NUMERIC)]
        + [num_var[i] for i in range(NUM_NUMERIC)]
        + [occupancy, top_share]
    ).astype(f32)
