"""Layered configuration + CLI argument parsing.

Re-design of the reference's config layer (ConfArguments.scala:1-164 +
reference.conf:1-13): Typesafe-config layering becomes a small HOCON-subset
parser over packaged defaults plus an optional ``application.conf`` override,
and the hand-rolled recursive pattern-match CLI parser (ConfArguments.scala:91-158)
becomes an equivalent recursive parser with the same long/short flag surface.

Twitter OAuth credentials are routed into a process-wide property table under
``twitter4j.oauth.*`` keys, mirroring the JVM system properties the reference
sets (ConfArguments.scala:58-76,103-118) so downstream sources read creds from
one place.

Extensions over the reference (flagged in usage): ``--backend``, ``--source``,
``--replayFile``, ``--l2Reg``, ``--dtype``, ``--checkpointDir``, etc.
"""

from __future__ import annotations

import os
import sys
from importlib import resources as _importlib_resources

# Process-wide property table, the moral equivalent of JVM system properties
# (reference routes OAuth creds there, ConfArguments.scala:58-76).
_SYSTEM_PROPERTIES: dict[str, str] = {}


def set_property(key: str, value: str) -> None:
    _SYSTEM_PROPERTIES[key] = value


def get_property(key: str, default: str | None = None) -> str | None:
    return _SYSTEM_PROPERTIES.get(key, default)


def parse_conf_text(text: str) -> dict[str, str]:
    """Parse the HOCON subset used by the reference's .conf files
    (``key="value"`` / ``key=value`` lines, ``#``/``//`` comments)."""
    out: dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith("//"):
            continue
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        value = value.strip()
        if len(value) >= 2 and value[0] == '"':
            # Quoted value: take up to the closing quote (rest is comment/junk).
            end = value.find('"', 1)
            value = value[1:end] if end > 0 else value[1:]
        else:
            # Unquoted: strip trailing inline comments.
            for marker in ("#", "//"):
                pos = value.find(marker)
                if pos >= 0:
                    value = value[:pos].rstrip()
        out[key.strip()] = value
    return out


def _load_defaults() -> dict[str, str]:
    ref = _importlib_resources.files("twtml_tpu.resources").joinpath("reference.conf")
    return parse_conf_text(ref.read_text())


def _load_application_conf() -> dict[str, str]:
    """Optional override file, mirroring Typesafe-config's application.conf
    layering (README.md:85-105 of the reference documents this flow).

    Search order: $TWTML_CONFIG, then ./application.conf.
    """
    candidates = []
    env_path = os.environ.get("TWTML_CONFIG", "")
    if env_path:
        candidates.append(env_path)
    candidates.append(os.path.join(os.getcwd(), "application.conf"))
    for path in candidates:
        if os.path.isfile(path):
            with open(path, "r", encoding="utf-8") as fh:
                return parse_conf_text(fh.read())
    return {}


_OAUTH_KEYS = ("consumerKey", "consumerSecret", "accessToken", "accessTokenSecret")


class ConfArguments:
    """Config object with the same knob surface as the reference's
    ConfArguments (ConfArguments.scala:20-28 getters, :91-158 flags).

    Attribute names intentionally keep the reference's camelCase so the CLI
    flags, conf keys, and attributes line up one-to-one.
    """

    def __init__(self) -> None:
        conf = dict(_load_defaults())
        conf.update(_load_application_conf())
        self._conf = conf

        self.lightning: str = conf["lightning"]
        self.twtweb: str = conf["twtweb"]
        self.seconds: int = int(conf["seconds"])
        self.stepSize: float = float(conf["stepSize"])
        self.numIterations: int = int(conf["numIterations"])
        self.miniBatchFraction: float = float(conf["miniBatchFraction"])
        self.numRetweetBegin: int = int(conf["numRetweetBegin"])
        self.numRetweetEnd: int = int(conf["numRetweetEnd"])
        self.numTextFeatures: int = int(conf["numTextFeatures"])

        # Extensions (no reference equivalent).
        self.backend: str = conf.get("backend", "auto")
        self.source: str = conf.get("source", "replay")
        self.replayFile: str = conf.get("replayFile", "")
        self.replaySpeed: float = float(conf.get("replaySpeed", "0.0"))
        self.batchBucket: int = int(conf.get("batchBucket", "0"))
        self.tokenBucket: int = int(conf.get("tokenBucket", "0"))
        self.hashOn: str = conf.get("hashOn", "device")
        if self.hashOn not in ("device", "host"):
            raise ValueError(
                f"hashOn must be 'device' or 'host', got {self.hashOn!r}"
            )
        self.ingest: str = conf.get("ingest", "object")
        if self.ingest not in ("object", "block"):
            raise ValueError(
                f"ingest must be 'object' or 'block', got {self.ingest!r}"
            )
        self.wire: str = conf.get("wire", "auto")
        if self.wire not in ("auto", "padded", "ragged"):
            raise ValueError(
                f"wire must be 'auto', 'padded' or 'ragged', got {self.wire!r}"
            )
        self.blockWire: str = conf.get("blockWire", "auto")
        if self.blockWire not in ("auto", "on", "off"):
            raise ValueError(
                f"blockWire must be 'auto', 'on' or 'off', got "
                f"{self.blockWire!r}"
            )
        self.l2Reg: float = float(conf.get("l2Reg", "0.0"))
        self.convergenceTol: float = float(conf.get("convergenceTol", "0.001"))
        self.dtype: str = conf.get("dtype", "float32")
        self.checkpointDir: str = conf.get("checkpointDir", "")
        self.checkpointEvery: int = int(conf.get("checkpointEvery", "0"))
        self.journal: str = conf.get("journal", "auto")
        if self.journal not in ("auto", "on", "off"):
            raise ValueError(
                f"journal must be 'auto', 'on' or 'off', got {self.journal!r}"
            )
        self.journalMaxMb: int = int(conf.get("journalMaxMb", "512"))
        if self.journalMaxMb <= 0:
            raise ValueError(
                f"journalMaxMb must be positive, got {self.journalMaxMb}"
            )
        # telemetry historian (r22): durable long-horizon time series at
        # the stats-publish cadence + cross-run perf regression sentinel
        # (telemetry/historian.py)
        self.history: str = conf.get("history", "auto")
        if self.history not in ("auto", "on", "off"):
            raise ValueError(
                f"history must be 'auto', 'on' or 'off', got {self.history!r}"
            )
        self.historyMaxMb: int = int(conf.get("historyMaxMb", "256"))
        if self.historyMaxMb <= 0:
            raise ValueError(
                f"historyMaxMb must be positive, got {self.historyMaxMb}"
            )
        self.perfGuard: str = conf.get("perfGuard", "warn")
        if self.perfGuard not in ("warn", "off"):
            raise ValueError(
                f"perfGuard must be 'warn' or 'off', got {self.perfGuard!r}"
            )
        self.perfGuardRatio: float = float(conf.get("perfGuardRatio", "1.5"))
        if self.perfGuardRatio <= 1.0:
            raise ValueError(
                f"perfGuardRatio must be > 1.0, got {self.perfGuardRatio}"
            )
        self.profileDir: str = conf.get("profileDir", "")
        self.trace: str = conf.get("trace", "")
        self.traceMaxMb: int = int(conf.get("traceMaxMb", "256"))
        self.blackbox: str = conf.get("blackbox", "on")
        if self.blackbox not in ("on", "off"):
            raise ValueError(
                f"blackbox must be 'on' or 'off', got {self.blackbox!r}"
            )
        self.faultEvery: int = int(conf.get("faultEvery", "0"))
        self.chaos: str = conf.get("chaos", "")
        self.webTimeout: float = float(conf.get("webTimeout", "2.0"))
        self.superBatch: int = int(conf.get("superBatch", "1"))
        self.wirePack: str = conf.get("wirePack", "auto")
        if self.wirePack not in ("auto", "stacked", "group"):
            raise ValueError(
                "wirePack must be 'auto', 'stacked' or 'group', got "
                f"{self.wirePack!r}"
            )
        # compressed ragged units wire (r15): C-side digram encode,
        # in-jit gather-expand decode (features/wirecodec.py)
        self.wireCodec: str = conf.get("wireCodec", "auto")
        if self.wireCodec not in ("auto", "off", "dict"):
            raise ValueError(
                "wireCodec must be 'auto', 'off' or 'dict', got "
                f"{self.wireCodec!r}"
            )
        # fused one-pass wire assembly on a pooled buffer arena (r17):
        # the native emitter builds the final packed wire in one C sweep
        self.wireAssemble: str = conf.get("wireAssemble", "auto")
        if self.wireAssemble not in ("auto", "on", "off"):
            raise ValueError(
                "wireAssemble must be 'auto', 'on' or 'off', got "
                f"{self.wireAssemble!r}"
            )
        # one-pass native featurize (r18): the fused C emitter fills the
        # ragged-wire arrays straight from the batch's columns
        self.featurizeNative: str = conf.get("featurizeNative", "auto")
        if self.featurizeNative not in ("auto", "on", "off"):
            raise ValueError(
                "featurizeNative must be 'auto', 'on' or 'off', got "
                f"{self.featurizeNative!r}"
            )
        self.recycleAfterMb: int = int(conf.get("recycleAfterMb", "0"))
        # elastic lockstep membership (r16): host loss shrinks the fleet
        # instead of aborting it; recovered hosts rejoin at epoch
        # boundaries (parallel/elastic.py + streaming/membership.py)
        self.elastic: str = conf.get("elastic", "off")
        if self.elastic not in ("off", "on"):
            raise ValueError(
                f"elastic must be 'off' or 'on', got {self.elastic!r}"
            )
        self.elasticEvictTicks: int = int(conf.get("elasticEvictTicks", "0"))
        self.elasticEvictSkewMs: float = float(
            conf.get("elasticEvictSkewMs", "250")
        )
        self.elasticRejoin: str = conf.get("elasticRejoin", "on")
        if self.elasticRejoin not in ("off", "on"):
            raise ValueError(
                "elasticRejoin must be 'off' or 'on', got "
                f"{self.elasticRejoin!r}"
            )
        # multi-tenant model plane (r10): M models, one jit program, one fetch
        self.tenants: int = int(conf.get("tenants", "1"))
        if self.tenants < 1:
            raise ValueError(f"tenants must be >= 1, got {self.tenants}")
        self.tenantKey: str = conf.get("tenantKey", "hash")
        if self.tenantKey not in ("hash", "lang"):
            raise ValueError(
                f"tenantKey must be 'hash' or 'lang', got {self.tenantKey!r}"
            )
        # ingest/state robustness layer (r7)
        self.maxQueueRows: int = int(conf.get("maxQueueRows", "0"))
        self.shedPolicy: str = conf.get("shedPolicy", "block")
        if self.shedPolicy not in ("block", "shed-oldest"):
            raise ValueError(
                "shedPolicy must be 'block' or 'shed-oldest', got "
                f"{self.shedPolicy!r}"
            )
        self.sentinel: str = conf.get("sentinel", "on")
        if self.sentinel not in ("on", "off"):
            raise ValueError(
                f"sentinel must be 'on' or 'off', got {self.sentinel!r}"
            )
        self.sentinelRollbacks: int = int(conf.get("sentinelRollbacks", "3"))
        self.sentinelWindow: int = int(conf.get("sentinelWindow", "512"))
        # serving plane (r12): batched, pipelined low-latency inference
        # from verified snapshots (twtml_tpu/serving/, apps/serve.py)
        self.servePort: int = int(conf.get("servePort", "8888"))
        self.serveBatchRows: int = int(conf.get("serveBatchRows", "256"))
        if self.serveBatchRows < 1:
            raise ValueError(
                f"serveBatchRows must be >= 1, got {self.serveBatchRows}"
            )
        self.serveMaxWaitMs: float = float(conf.get("serveMaxWaitMs", "5.0"))
        self.serveDepth: int = int(conf.get("serveDepth", "8"))
        if self.serveDepth < 1:
            raise ValueError(f"serveDepth must be >= 1, got {self.serveDepth}")
        self.servePromoteEvery: float = float(
            conf.get("servePromoteEvery", "5.0")
        )
        # read fleet + champion/challenger (r14): N serve replicas behind a
        # router (twtml_tpu/serving/fleet.py, apps/router.py) and shadow-
        # scored A/B serving on the tenant stack (serving/abtest.py)
        self.routerPort: int = int(conf.get("routerPort", "8899"))
        self.replicas: str = conf.get("replicas", "")
        self.routePolicy: str = conf.get("routePolicy", "p99")
        if self.routePolicy not in ("p99", "hash"):
            raise ValueError(
                f"routePolicy must be 'p99' or 'hash', got "
                f"{self.routePolicy!r}"
            )
        self.abtest: str = conf.get("abtest", "off")
        if self.abtest not in ("on", "off"):
            raise ValueError(
                f"abtest must be 'on' or 'off', got {self.abtest!r}"
            )
        # model & data observability plane (r11): in-step quality telemetry
        self.modelWatch: str = conf.get("modelWatch", "on")
        if self.modelWatch not in ("on", "off"):
            raise ValueError(
                f"modelWatch must be 'on' or 'off', got {self.modelWatch!r}"
            )
        self.modelWatchWindow: int = int(conf.get("modelWatchWindow", "8"))
        # freshness plane (r16): event-time watermarks, per-batch critical
        # path, and staleness SLOs from lineage records on existing seams
        self.freshness: str = conf.get("freshness", "on")
        if self.freshness not in ("on", "off"):
            raise ValueError(
                f"freshness must be 'on' or 'off', got {self.freshness!r}"
            )
        self.freshnessSloMs: float = float(conf.get("freshnessSloMs", "0"))
        if self.freshnessSloMs < 0:
            raise ValueError(
                f"freshnessSloMs must be >= 0, got {self.freshnessSloMs}"
            )
        self.servingStaleSloS: float = float(
            conf.get("servingStaleSloS", "0")
        )
        if self.servingStaleSloS < 0:
            raise ValueError(
                f"servingStaleSloS must be >= 0, got {self.servingStaleSloS}"
            )

        # Multi-host process group (the reference's one-flag cluster story,
        # ConfArguments.scala:95-98 --master spark://host:port): here a
        # jax.distributed coordinator + process coordinates, settable either
        # via these flags or a twtml://host:port master URL.
        self.coordinator: str = conf.get("coordinator", "")
        self.numProcesses: int = int(conf.get("numProcesses", "0"))
        self.processId: int = int(conf.get("processId", "-1"))

        # Spark-compat knobs: --master/--name are accepted for CLI parity
        # (ConfArguments.scala:95-102); master is interpreted as a backend
        # hint ("local[N]" caps data-parallel shards on CPU) or a
        # twtml://host:port coordinator address. Unrecognized cluster
        # schemes (spark://, mesos://, yarn) are REJECTED at validation
        # (validate_master) — silently running single-host would be worse.
        self._appName: str = "twtml-tpu"
        self.master: str = "local[*]"

        # OAuth creds from conf files land in the property table exactly like
        # the reference's sysprops (ConfArguments.scala:58-76).
        for key in _OAUTH_KEYS:
            value = conf.get(key, "")
            if value != "":
                set_property("twitter4j.oauth." + key, value)

    # -- appName accessors (ConfArguments.scala:78-86) ----------------------
    def appName(self) -> str:
        return self._appName

    def setAppName(self, app_name: str) -> "ConfArguments":
        self._appName = app_name
        return self

    @property
    def usage(self) -> str:
        return f"""
Usage: twtml-train [options]
Usage: python -m twtml_tpu.apps.linear_regression [options]

  Options:
  -h, --help
  -m, --master <master_url>                    local[N] caps CPU shards; twtml://host:port joins
                                               a multi-host run (same as --coordinator). Other
                                               cluster schemes are rejected.
  -n, --name <name>                            A name of your application.
  -C, --consumerKey <consumerKey>              Twitter's consumer key
  -S, --consumerSecret <consumerSecret>        Twitter's consumer secret
  -A, --accessToken <accessToken>              Twitter's access token
  -T, --accessTokenSecret <accessTokenSecret>  Twitter's access token secret
  -l, --lightning <lightning_url>              Default: {self.lightning}
  -w, --twtweb <twtweb_url>                    Default: {self.twtweb}
  -s, --seconds <integer number>               Default: {self.seconds}
  -p, --stepSize <float number>                Default: {self.stepSize}
  -i, --numIterations <integer number>         Default: {self.numIterations}
  -b, --miniBatchFraction <float number>       Default: {self.miniBatchFraction}
  -B, --numRetweetBegin <integer number>       Default: {self.numRetweetBegin}
  -E, --numRetweetEnd <integer number>         Default: {self.numRetweetEnd}
  -f, --numTextFeatures <integer number>       Default: {self.numTextFeatures}

  TPU-framework extensions:
  --coordinator <host:port>                    Join a multi-host jax.distributed process group
                                               (with --numProcesses/--processId; the cluster
                                               analog of the reference's --master spark://...)
  --numProcesses <int>                         Total processes in the multi-host group
  --processId <int>                            This process's rank in the multi-host group
  --backend <auto|tpu|cpu>                     Default: {self.backend}
  --source <replay|twitter|synthetic>          Default: {self.source}
  --replayFile <path.jsonl>                    Tweet replay file (source=replay)
  --replaySpeed <float>                        0 = as-fast-as-possible, else x realtime
  --batchBucket <int>                          Pad batches up to this bucket size (0 = auto)
  --tokenBucket <int>                          Pad per-tweet tokens/units to this bucket
                                               (0 = auto per batch); pinning BOTH buckets
                                               fixes the XLA program shape, enabling the
                                               pre-stream compile warmup
  --hashOn <device|host>                       Bigram-hash featurization inside the XLA step
                                               (device, default) or on the host CPU (host);
                                               bit-identical features either way. Default: {self.hashOn}
  --ingest <object|block>                      Replay ingestion: per-tweet Status objects, or
                                               columnar blocks via the native C parser (~10x
                                               ingest throughput; replay source only). Default: {self.ingest}
  --wire <auto|padded|ragged>                  Units wire format: ragged ships concatenated
                                               units + offsets (no pad bytes; the measured-
                                               fastest wire on every layout — packed, sharded,
                                               superbatched), padded ships a [B, L] buffer.
                                               auto = ragged for hashOn=device back-to-back
                                               runs (--seconds 0); padded for wall-clock
                                               streaming (pre-compilable before the stream
                                               starts) and host hashing. Default: {self.wire}
  --l2Reg <float>                              L2 regularization. Default: {self.l2Reg}
  --convergenceTol <float>                     SGD convergence tolerance. Default: {self.convergenceTol}
  --dtype <float32|bfloat16|float64>           Device dtype. Default: {self.dtype}
  --checkpointDir <path>                       Enable model checkpoint/resume
  --checkpointEvery <int batches>              Checkpoint cadence. Default: {self.checkpointEvery}
  --journal <auto|on|off>                      Durable intake journal (streaming/journal.py):
                                               CRC-framed raw-row records at the intake seam
                                               make sentinel rollback, elastic resync and
                                               restart REPLAY rows instead of counting them
                                               lost; auto = on iff --checkpointDir is set
                                               (verified checkpoints carry the replay
                                               cursor). Default: {self.journal}
  --journalMaxMb <int MB>                      Journal disk ceiling; segments retire once a
                                               verified checkpoint covers them, and the
                                               oldest are dropped (loudly, counted) past
                                               this cap. Default: {self.journalMaxMb}
  --history <auto|on|off>                      Telemetry historian (telemetry/historian.py):
                                               durable CRC-framed time-series segments
                                               sampled at the EXISTING stats-publish cadence
                                               (zero added fetches/collectives) with
                                               health-phase intervals — long-horizon RSS
                                               slope, per-phase RTT/throughput trends, and
                                               the --perfGuard baseline survive the process
                                               (tools/history_report.py reads the leftovers).
                                               auto = on iff --checkpointDir is set; 'off'
                                               is bit-exact pre-historian behavior.
                                               Default: {self.history}
  --historyMaxMb <int MB>                      Historian disk ceiling; the oldest segments
                                               are dropped (loudly, counted) past this cap.
                                               Default: {self.historyMaxMb}
  --perfGuard <warn|off>                       Cross-run perf regression sentinel: healthy-
                                               phase per-stage publish-tick medians stamp a
                                               baseline.json at clean shutdown; the next run
                                               raises ONE warn-only blackbox event +
                                               perf.regressions counter per stage episode
                                               when a stage sustains above
                                               --perfGuardRatio x baseline for a full
                                               window. Never aborts. Default: {self.perfGuard}
  --perfGuardRatio <float>                     Sustained-regression threshold for
                                               --perfGuard. Default: {self.perfGuardRatio}
  --profileDir <path>                          Enable jax.profiler traces
  --trace <path.trace>                         Write a Chrome-trace-event pipeline trace
                                               (Perfetto-loadable): per-batch stage spans
                                               (source read/parse/featurize/dispatch/fetch/
                                               stats) with wire bytes + health-phase stamps;
                                               summarize with tools/trace_report.py
  --traceMaxMb <int MB>                        Size-rotate the --trace file: the active
                                               segment becomes PATH.1 when it crosses this
                                               size (events falling off the old PATH.1 are
                                               counted in trace.dropped_events);
                                               trace_report stitches both segments. 0 =
                                               unbounded. Default: {self.traceMaxMb}
  --blackbox <on|off>                          Crash flight recorder: a bounded in-memory
                                               ring of recent spans/guard events/chaos
                                               firings/sideband rows, dumped as ONE
                                               post-mortem JSON bundle next to the
                                               checkpoint dir on any abort or SIGTERM;
                                               render with tools/postmortem_report.py.
                                               Default: {self.blackbox}
  --faultEvery <int tweets>                    Inject a receiver crash every N tweets (chaos testing)
  --chaos <spec>                               Transport chaos injection BELOW the source layer
                                               (testing the runtime guards): comma-separated
                                               TARGET:ACTION[@TRIGGER] clauses over targets
                                               fetch|step|web. ACTION: delay=SECONDS (stall= is
                                               an alias) or error. TRIGGER: N (every Nth call),
                                               pP (probability P), fromN (every call from the
                                               Nth on); plus seed=N. Example:
                                               "fetch:delay=2@3,web:error@p0.5,seed=7"
  --webTimeout <float seconds>                 Dashboard/web-API request timeout (per publish;
                                               the publish circuit breaker stops a dead
                                               dashboard from costing this per batch).
                                               Default: {self.webTimeout}
  --recycleAfterMb <int MB>                    Bounded process lifetime: checkpoint at the next
                                               batch boundary and re-exec in place once process
                                               RSS crosses this ceiling (needs --checkpointDir;
                                               single-host; resume is exact). 0 = off. Made for
                                               the known tunnel-client RSS retention — see
                                               BENCHMARKS.md "Endurance soaks"
  --superBatch <int>                           Replay-mode superbatch: K micro-batches per device
                                               dispatch (one scan, one stats fetch; per-batch
                                               stats preserved; stops/checkpoints land on group
                                               boundaries). Default: {self.superBatch}
  --elastic <off|on>                           Elastic lockstep membership: a dead or evicted
                                               host SHRINKS the multi-host group (survivors
                                               re-form at an epoch boundary, restore the lead's
                                               verified checkpoint, and adopt the departed
                                               intake shards) instead of aborting the run; a
                                               recovered host REJOINS at the next boundary.
                                               SGD entry points, explicit --processId/
                                               --numProcesses. Default: {self.elastic}
  --elasticEvictTicks <int>                    Elastic straggler eviction: propose shrinking
                                               out a host the sideband attributor names gating
                                               for this many CONSECUTIVE ticks (with skew over
                                               --elasticEvictSkewMs). 0 = never auto-evict
                                               (watchdog-detected death still shrinks).
                                               Default: {self.elasticEvictTicks}
  --elasticEvictSkewMs <float>                 Minimum tick skew (ms) before a gating host
                                               counts toward --elasticEvictTicks.
                                               Default: {self.elasticEvictSkewMs}
  --elasticRejoin <off|on>                     Whether the lead admits parked/restarted hosts
                                               back at epoch boundaries (rejoiners restore the
                                               broadcast checkpoint before their first tick).
                                               Default: {self.elasticRejoin}
  --tenants <int M>                            Multi-tenant model plane: train M models
                                               (per-topic/per-language/per-A/B-arm) in ONE
                                               jit program — rows route to tenants on the
                                               host, the M per-tenant batches ship as one
                                               shared wire (the K-batch superbatch wire
                                               reused as the K-tenant wire; dry tenants ride
                                               all-padding batches), and all M tenants'
                                               stats come back in ONE stacked fetch.
                                               Per-tenant semantics stay byte-identical to
                                               the single-model path. Default: {self.tenants}
  --tenantKey <hash|lang>                      Tenant routing key: 'hash' = deterministic
                                               content hash (A/B-arm style uniform split);
                                               'lang' = script-class heuristic from the
                                               text's code units (per-language scenarios;
                                               needs --hashOn device). Default: {self.tenantKey}
  --maxQueueRows <int rows>                    Bounded intake backpressure: cap the source→
                                               batcher queue at this many ROWS. 0 = auto
                                               (8 x --batchBucket when pinned, else unbounded);
                                               -1 = explicitly unbounded. Default: {self.maxQueueRows}
  --shedPolicy <block|shed-oldest>             Policy when the intake queue is full: 'block'
                                               makes the producer wait (replay/backfill — no
                                               rows lost); 'shed-oldest' drops the OLDEST
                                               queued rows, counted in ingest.rows_shed (live
                                               streams — freshest rows win). Default: {self.shedPolicy}
  --sentinel <on|off>                          Divergence sentinel: checks the already-fetched
                                               per-batch stats for NaN/Inf (zero extra host
                                               fetches); on non-finite state rolls the model
                                               back to the last verified-finite checkpoint
                                               (or initial zeros without --checkpointDir),
                                               skips the poisoning batch, and counts
                                               model.rollbacks. Default: {self.sentinel}
  --sentinelRollbacks <int>                    Abort the run (clean checkpointed non-zero
                                               exit) after this many rollbacks within
                                               --sentinelWindow batches; 0 = never abort.
                                               Default: {self.sentinelRollbacks}
  --sentinelWindow <int batches>               The rollback-rate window above.
                                               Default: {self.sentinelWindow}
  --modelWatch <on|off>                        Model & data observability plane: a small
                                               quality vector (weight/update/gradient norms,
                                               prediction/label/residual and dense-feature
                                               moments, hash-bucket occupancy) computed INSIDE
                                               the fused step and fetched with the stats it
                                               already ships (zero extra fetches); the host
                                               derives drift z-scores, a loss-trend slope, and
                                               ok/warn/alert health levels (/api/model +
                                               dashboard "model · drift" tiles; verified
                                               checkpoints are stamped with the quality
                                               snapshot — tools/model_report.py). 'off' makes
                                               the step program bit-identical to the
                                               pre-observability program. Default: {self.modelWatch}
  --modelWatchWindow <int batches>             Sentinel early warning: after the model watch
                                               holds 'alert' this many delivered batches, emit
                                               a blackbox event + counter and force ONE
                                               verified-checkpoint save per episode (warn-only;
                                               no rollback behavior change).
                                               Default: {self.modelWatchWindow}
  --freshness <on|off>                         End-to-end freshness plane: per-batch lineage
                                               records stamped at the existing pipeline seams
                                               (source read → featurize → wire pack → dispatch
                                               → fetch delivery → publish) derive event-time
                                               watermarks (freshness.event_lag_ms p50/p95/p99
                                               from tweet created_at_ms to delivery), a
                                               per-batch critical-path edge, and a low
                                               watermark that rides the lockstep sideband —
                                               zero added host fetches, zero added
                                               collectives (/api/freshness + dashboard
                                               "freshness · e2e lag" tiles). 'off' is the
                                               pre-plane program bit-exactly.
                                               Default: {self.freshness}
  --freshnessSloMs <float ms>                  Freshness SLO: when > 0 and the event→delivery
                                               lag stays above this for a sustained run of
                                               batches, emit a blackbox event + counter and
                                               force ONE verified-checkpoint save per episode
                                               (warn-only, sentinel untouched; the
                                               --modelWatchWindow early-warning shape).
                                               0 = no gate. Default: {self.freshnessSloMs}
  --servingStaleSloS <float s>                 Serving staleness SLO: when > 0 and the served
                                               snapshot's age (serving.snapshot_age_s)
                                               exceeds this, emit a blackbox event + counter
                                               once per breach episode (warn-only). 0 = no
                                               gate. Default: {self.servingStaleSloS}
  --blockWire <auto|on|off>                    Zero-copy native ingest for --ingest block:
                                               'on' parses raw block bytes straight into the
                                               ragged wire's unit representation (one C pass,
                                               uint8 units when every row is ASCII — no
                                               intermediate repack); byte-identical batches
                                               (tests/test_blockwire.py). auto = on whenever
                                               the effective wire is ragged; off = the legacy
                                               ParsedBlock parser. Default: {self.blockWire}
  --servePort <int>                            Serving entry point (apps/serve.py): port the
                                               in-process web server (dashboard + POST
                                               /api/predict front door) listens on.
                                               Default: {self.servePort}
  --serveBatchRows <int rows>                  Serving coalescer: dispatch a predict batch
                                               once this many rows are admitted (the padded
                                               row bucket of the predict program; requests
                                               larger than this are rejected).
                                               Default: {self.serveBatchRows}
  --serveMaxWaitMs <float ms>                  Serving coalescer: bounded admission latency —
                                               dispatch a partial batch once the OLDEST
                                               admitted request has waited this long.
                                               Default: {self.serveMaxWaitMs}
  --serveDepth <int>                           Concurrent in-flight predict-result fetches
                                               (the measured 6.2x-at-depth-8 transport
                                               pipelining, BENCHMARKS r3).
                                               Default: {self.serveDepth}
  --servePromoteEvery <float seconds>          Snapshot promoter poll cadence over
                                               --checkpointDir (new verified checkpoints
                                               hot-swap in if their quality stamp is
                                               ok/warn; alert refuses — the
                                               tools/model_report.py --gate predicate).
                                               Default: {self.servePromoteEvery}
  --abtest <on|off>                            Champion/challenger serving
                                               (apps/serve.py over a --tenants M >= 2
                                               tenant-stack checkpoint): live predict
                                               traffic is answered by the CHAMPION tenant
                                               and mirrored shadow-mode to every
                                               challenger inside the same one-dispatch
                                               predict program (zero added fetches);
                                               challengers are scored by the per-tenant
                                               quality stamps the trainer writes, and a
                                               strictly better challenger auto-promotes
                                               the champion pointer through the same
                                               is_promotable gate snapshots use (an
                                               alert-stamped challenger is refused and
                                               counted). Default: {self.abtest}
  --routerPort <int>                           Fleet router entry point (apps/router.py):
                                               port the front-door web server (POST
                                               /api/predict proxy + GET /api/fleet)
                                               listens on. Default: {self.routerPort}
  --replicas <url,url,...>                     Fleet router: comma-separated base URLs of
                                               the serve replicas to route over (e.g.
                                               http://host:8888,http://host:8889). Each
                                               replica is health-checked via its GET
                                               /api/serving; a failing replica is ejected
                                               behind a jittered backoff and its traffic
                                               retried on the others.
  --routePolicy <p99|hash>                     Fleet routing policy: 'p99' sends each
                                               request to the healthy replica with the
                                               lowest rolling forward p99 (ties: fewest
                                               in-flight); 'hash' consistent-hashes the
                                               request body onto a vnode ring so a given
                                               key sticks to one replica and only ~1/N of
                                               keys move on membership change.
                                               Default: {self.routePolicy}
  --wirePack <auto|stacked|group>              Superbatch wire layout on the ragged wire:
                                               'group' coalesces the K batches into ONE
                                               contiguous buffer (one put; uint16-delta offsets)
                                               unpacked inside the scanned program; 'stacked'
                                               ships K per-field arrays. auto = the measured
                                               winner recorded in BENCHMARKS.md "Lean wire v2"
                                               (currently stacked pending a tunnel-regime
                                               verdict; bit-identical features either way).
                                               Default: {self.wirePack}
  --wireCodec <auto|off|dict>                  Compressed ragged units wire: 'dict' digram-
                                               compresses the uint8 (all-ASCII) units buffer
                                               in the one C ingest pass (static dictionary,
                                               ~1.3-2x on tweet text) and decodes it INSIDE
                                               the jit program ahead of the ragged re-pad —
                                               byte-identical units (tests/test_wirecodec.py).
                                               Applies to the packed wire forms; non-ASCII
                                               (uint16) units and incompressible batches ship
                                               raw, counted in wire.codec_fallbacks. With
                                               --superBatch, 'dict' + --wirePack auto resolves
                                               the group (coalesced) wire. auto = the measured
                                               default recorded in BENCHMARKS.md "Compressed
                                               wire" (currently off pending a tunnel-regime
                                               verdict). Default: {self.wireCodec}
  --wireAssemble <auto|on|off>                 Fused one-pass wire assembly (r17): 'on' builds
                                               every packed wire (flat / per-shard / coalesced
                                               group) in ONE native C sweep — units digram-
                                               encoded during the copy, uint16-delta offsets,
                                               sideband laid behind — into a pooled buffer
                                               arena (features/arena.py; leases retire when the
                                               batch's stats fetch delivers). Byte-identical
                                               wires and bitwise-equal trajectories vs the
                                               numpy pack pipeline (tests/test_wireassemble.py).
                                               auto = on whenever the native assembler is
                                               loadable (host-only work, no transport-regime
                                               gate); off = the numpy ground truth.
                                               Default: {self.wireAssemble}
  --featurizeNative <auto|on|off>              One-pass native featurize (r18): 'on' fills the
                                               ragged wire's arrays — flat units, padded
                                               offsets, scaled f32 numeric/label/mask — in ONE
                                               C sweep (native/featurize.cpp) into a pooled
                                               arena lease, on both ingest paths (object
                                               Status batches and parsed blocks). Bit-identical
                                               batches and trajectories vs the Python ground
                                               truth (tests/test_featurize_native.py). auto =
                                               on whenever the native emitter is loadable
                                               (host-only work, no transport-regime gate);
                                               off = the Python/numpy ground truth.
                                               Default: {self.featurizeNative}
"""

    def parse(self, args: list[str]) -> "ConfArguments":
        """Recursive flag parser, same shape as ConfArguments.scala:91-158."""
        if not args:
            return self
        flag, rest = args[0], args[1:]

        def take() -> str:
            if not rest:
                self.printUsage(1)
            return rest[0]

        if flag in ("--master", "-m"):
            self.master = take()
        elif flag in ("--name", "-n"):
            self.setAppName(take())
        elif flag in ("--consumerKey", "-C"):
            set_property("twitter4j.oauth.consumerKey", take())
        elif flag in ("--consumerSecret", "-S"):
            set_property("twitter4j.oauth.consumerSecret", take())
        elif flag in ("--accessToken", "-A"):
            set_property("twitter4j.oauth.accessToken", take())
        elif flag in ("--accessTokenSecret", "-T"):
            set_property("twitter4j.oauth.accessTokenSecret", take())
        elif flag in ("--lightning", "-l"):
            self.lightning = take()
        elif flag in ("--twtweb", "-w"):
            self.twtweb = take()
        elif flag in ("--seconds", "-s"):
            self.seconds = int(take())
        elif flag in ("--stepSize", "-p"):
            self.stepSize = float(take())
        elif flag in ("--numIterations", "-i"):
            self.numIterations = int(take())
        elif flag in ("--miniBatchFraction", "-b"):
            self.miniBatchFraction = float(take())
        elif flag in ("--numRetweetBegin", "-B"):
            self.numRetweetBegin = int(take())
        elif flag in ("--numRetweetEnd", "-E"):
            self.numRetweetEnd = int(take())
        elif flag in ("--numTextFeatures", "-f"):
            self.numTextFeatures = int(take())
        elif flag == "--coordinator":
            self.coordinator = take()
        elif flag == "--numProcesses":
            self.numProcesses = int(take())
        elif flag == "--processId":
            self.processId = int(take())
        elif flag == "--backend":
            self.backend = take()
        elif flag == "--source":
            self.source = take()
        elif flag == "--replayFile":
            self.replayFile = take()
        elif flag == "--replaySpeed":
            self.replaySpeed = float(take())
        elif flag == "--batchBucket":
            self.batchBucket = int(take())
        elif flag == "--tokenBucket":
            self.tokenBucket = int(take())
        elif flag == "--hashOn":
            self.hashOn = take()
            if self.hashOn not in ("device", "host"):
                self.printUsage(1)
        elif flag == "--ingest":
            self.ingest = take()
            if self.ingest not in ("object", "block"):
                self.printUsage(1)
        elif flag == "--wire":
            self.wire = take()
            if self.wire not in ("auto", "padded", "ragged"):
                self.printUsage(1)
        elif flag == "--blockWire":
            self.blockWire = take()
            if self.blockWire not in ("auto", "on", "off"):
                self.printUsage(1)
        elif flag == "--l2Reg":
            self.l2Reg = float(take())
        elif flag == "--convergenceTol":
            self.convergenceTol = float(take())
        elif flag == "--dtype":
            self.dtype = take()
        elif flag == "--checkpointDir":
            self.checkpointDir = take()
        elif flag == "--checkpointEvery":
            self.checkpointEvery = int(take())
        elif flag == "--journal":
            self.journal = take()
            if self.journal not in ("auto", "on", "off"):
                self.printUsage(1)
        elif flag == "--journalMaxMb":
            self.journalMaxMb = int(take())
            if self.journalMaxMb <= 0:
                self.printUsage(1)
        elif flag == "--history":
            self.history = take()
            if self.history not in ("auto", "on", "off"):
                self.printUsage(1)
        elif flag == "--historyMaxMb":
            self.historyMaxMb = int(take())
            if self.historyMaxMb <= 0:
                self.printUsage(1)
        elif flag == "--perfGuard":
            self.perfGuard = take()
            if self.perfGuard not in ("warn", "off"):
                self.printUsage(1)
        elif flag == "--perfGuardRatio":
            self.perfGuardRatio = float(take())
            if self.perfGuardRatio <= 1.0:
                self.printUsage(1)
        elif flag == "--profileDir":
            self.profileDir = take()
        elif flag == "--trace":
            self.trace = take()
        elif flag == "--traceMaxMb":
            self.traceMaxMb = int(take())
        elif flag == "--blackbox":
            self.blackbox = take()
            if self.blackbox not in ("on", "off"):
                self.printUsage(1)
        elif flag == "--superBatch":
            self.superBatch = int(take())
        elif flag == "--wirePack":
            self.wirePack = take()
            if self.wirePack not in ("auto", "stacked", "group"):
                self.printUsage(1)
        elif flag == "--wireCodec":
            self.wireCodec = take()
            if self.wireCodec not in ("auto", "off", "dict"):
                self.printUsage(1)
        elif flag == "--wireAssemble":
            self.wireAssemble = take()
            if self.wireAssemble not in ("auto", "on", "off"):
                self.printUsage(1)
        elif flag == "--featurizeNative":
            self.featurizeNative = take()
            if self.featurizeNative not in ("auto", "on", "off"):
                self.printUsage(1)
        elif flag == "--recycleAfterMb":
            self.recycleAfterMb = int(take())
        elif flag == "--elastic":
            self.elastic = take()
            if self.elastic not in ("off", "on"):
                self.printUsage(1)
        elif flag == "--elasticEvictTicks":
            self.elasticEvictTicks = int(take())
        elif flag == "--elasticEvictSkewMs":
            self.elasticEvictSkewMs = float(take())
        elif flag == "--elasticRejoin":
            self.elasticRejoin = take()
            if self.elasticRejoin not in ("off", "on"):
                self.printUsage(1)
        elif flag == "--tenants":
            self.tenants = int(take())
            if self.tenants < 1:
                self.printUsage(1)
        elif flag == "--tenantKey":
            self.tenantKey = take()
            if self.tenantKey not in ("hash", "lang"):
                self.printUsage(1)
        elif flag == "--maxQueueRows":
            self.maxQueueRows = int(take())
        elif flag == "--shedPolicy":
            self.shedPolicy = take()
            if self.shedPolicy not in ("block", "shed-oldest"):
                self.printUsage(1)
        elif flag == "--sentinel":
            self.sentinel = take()
            if self.sentinel not in ("on", "off"):
                self.printUsage(1)
        elif flag == "--sentinelRollbacks":
            self.sentinelRollbacks = int(take())
        elif flag == "--sentinelWindow":
            self.sentinelWindow = int(take())
        elif flag == "--servePort":
            self.servePort = int(take())
        elif flag == "--serveBatchRows":
            self.serveBatchRows = int(take())
            if self.serveBatchRows < 1:
                self.printUsage(1)
        elif flag == "--serveMaxWaitMs":
            self.serveMaxWaitMs = float(take())
        elif flag == "--serveDepth":
            self.serveDepth = int(take())
            if self.serveDepth < 1:
                self.printUsage(1)
        elif flag == "--servePromoteEvery":
            self.servePromoteEvery = float(take())
        elif flag == "--abtest":
            self.abtest = take()
            if self.abtest not in ("on", "off"):
                self.printUsage(1)
        elif flag == "--routerPort":
            self.routerPort = int(take())
        elif flag == "--replicas":
            self.replicas = take()
        elif flag == "--routePolicy":
            self.routePolicy = take()
            if self.routePolicy not in ("p99", "hash"):
                self.printUsage(1)
        elif flag == "--modelWatch":
            self.modelWatch = take()
            if self.modelWatch not in ("on", "off"):
                self.printUsage(1)
        elif flag == "--modelWatchWindow":
            self.modelWatchWindow = int(take())
        elif flag == "--freshness":
            self.freshness = take()
            if self.freshness not in ("on", "off"):
                self.printUsage(1)
        elif flag == "--freshnessSloMs":
            self.freshnessSloMs = float(take())
            if self.freshnessSloMs < 0:
                self.printUsage(1)
        elif flag == "--servingStaleSloS":
            self.servingStaleSloS = float(take())
            if self.servingStaleSloS < 0:
                self.printUsage(1)
        elif flag == "--faultEvery":
            self.faultEvery = int(take())
        elif flag == "--chaos":
            self.chaos = take()
        elif flag == "--webTimeout":
            self.webTimeout = float(take())
        elif flag in ("--help", "-h"):
            self.printUsage(0)
        else:
            self.printUsage(1)
        return self.parse(rest[1:])

    def printUsage(self, exit_code: int) -> None:
        print(self.usage)
        raise SystemExit(exit_code)

    # -- derived ------------------------------------------------------------
    def effective_wire(self) -> str:
        """Resolve ``--wire auto`` (the default) to the measured-best wire
        for this configuration: RAGGED whenever the device hashes in a
        back-to-back regime (the headline/bench path — +14% paired on
        object ingest, +28% from blocks, packed for another +11.4%,
        sharded on every layout since r4/r5); PADDED for host hashing (the
        ragged wire ships raw code units by definition) and for WALL-CLOCK
        streaming (--seconds > 0): the ragged units bucket is
        data-dependent, so it cannot pre-compile before the stream starts
        (apps/common.warmup_compile) — a live run would stall ~30 s on its
        first batch — while wall-clock intervals are latency-dominated and
        wire bytes don't bind there. Explicit ``--wire ragged``/``padded``
        always wins; explicit ragged with --hashOn host is rejected at
        source construction (apps/common.build_source)."""
        if self.wire != "auto":
            return self.wire
        if self.hashOn != "device" or self.seconds > 0:
            return "padded"
        return "ragged"

    def effective_block_wire(self) -> bool:
        """Resolve ``--blockWire``: whether block sources should parse
        through the zero-copy wire emitter (raw bytes → ragged-wire units
        in one C pass, features/native.parse_tweet_block_wire). ``auto``
        (the default) follows the effective wire: the emitter produces the
        RAGGED wire's unit representation (narrow uint8 units), so it is
        on exactly when the stream ships ragged; the padded wire keeps the
        legacy ParsedBlock parser (its C pad copy reads uint16). The
        batches are byte-identical either way — this flag moves work, not
        semantics (tests/test_blockwire.py) — and a library without the
        emitter degrades to the legacy parser on its own
        (features/native.py seam)."""
        if self.blockWire != "auto":
            return self.blockWire == "on"
        return self.effective_wire() == "ragged"

    def effective_wire_pack(self) -> str:
        """Resolve ``--wirePack auto`` to the measured-default superbatch
        wire layout. The coalesced group wire (one contiguous buffer per K
        batches, uint16-delta offsets) is bit-identical to the stacked wire
        and composes the two measured transfer facts (bandwidth improves
        with size; packing the lean wire paid +11.4%), but the r2/r3 law —
        measure in the target regime before shipping a wire/dispatch
        change — holds the default at STACKED until the tunnel-regime bench
        clears (tools/bench_superwire.py; BENCHMARKS.md "Lean wire v2"
        records the CPU control, which is wire-insensitive by design).
        Explicit ``--wirePack group``/``stacked`` always wins — except the
        contradictory ``--wirePack stacked --wireCodec dict``, which is
        rejected below: the codec lives on the PACKED wire forms
        (compression compounds the per-array-overhead trap that made
        packing the lean-wire default), so a stacked superbatch wire would
        silently ship the group's batches uncompressed."""
        if self.effective_wire_codec() == "dict":
            if self.wirePack == "stacked":
                raise ValueError(
                    "--wirePack stacked contradicts --wireCodec dict: the "
                    "codec rides the packed one-buffer wire (use "
                    "--wirePack group, or drop the codec)"
                )
            return "group"
        if self.wirePack != "auto":
            return self.wirePack
        return "stacked"

    def effective_wire_codec(self) -> str:
        """Resolve ``--wireCodec auto`` to the measured-default units
        codec. ``dict`` (the digram codec, features/wirecodec.py) is only
        meaningful on the ragged raw-units wire — explicit ``dict`` with a
        padded/host-hash wire is rejected, like explicit ragged with
        ``--hashOn host``. ``auto`` follows the wirePack precedent: OFF
        until the tunnel-regime paired verdict clears (the r2/r3 law —
        measure in the target regime before shipping a wire change;
        BENCHMARKS.md "Compressed wire" records the modeled-transport
        paired win and the standing auto decision)."""
        if self.wireCodec in ("off", "auto"):
            return "off"
        if self.effective_wire() != "ragged":
            raise ValueError(
                "--wireCodec dict needs the ragged raw-units wire "
                "(--wire ragged, or auto with --hashOn device and "
                "--seconds 0)"
            )
        return "dict"

    def effective_journal(self) -> bool:
        """Resolve ``--journal auto`` (the default): the durable intake
        journal is ON exactly when ``--checkpointDir`` is set — the replay
        cursor lives in verified checkpoint meta, so without checkpoints
        there is nothing exact to resume from (and the flag's whole point
        is the crash-equals-clean differential, tests/test_journal.py).
        Explicit ``on``/``off`` wins; explicit ``on`` without a checkpoint
        directory is rejected at install (apps/common.install_journal) —
        the journal needs a directory and a cursor authority. ``off`` is
        bit-exact pre-journal behavior: every hook no-ops."""
        if self.journal != "auto":
            return self.journal == "on"
        return bool(self.checkpointDir)

    def effective_history(self) -> bool:
        """Resolve ``--history auto`` (the default): the telemetry
        historian is ON exactly when ``--checkpointDir`` is set — its
        segments and the perfGuard baseline live under the checkpoint
        directory, so without one there is nowhere durable to append.
        Explicit ``on``/``off`` wins; explicit ``on`` without a checkpoint
        directory is rejected at install (apps/common.install_historian).
        ``off`` is bit-exact pre-historian behavior: the sample hook
        no-ops (tests/test_history.py byte-compares weights)."""
        if self.history != "auto":
            return self.history == "on"
        return bool(self.checkpointDir)

    def effective_max_queue_rows(self) -> int:
        """Resolve ``--maxQueueRows``: explicit > 0 wins; 0 (the default)
        sizes the bound from the batch size — 8 pinned row buckets is deep
        enough that the fill gate and a ``--superBatch`` group never
        starve, shallow enough that a stalled consumer bounds host RSS at
        ~8 batches of parsed rows. Without a pinned bucket there is no
        batch size to derive from, so 0 stays unbounded (as does an
        explicit -1)."""
        if self.maxQueueRows > 0:
            return self.maxQueueRows
        if self.maxQueueRows < 0:
            return 0
        return 8 * self.batchBucket if self.batchBucket > 0 else 0

    def local_shards(self) -> int | None:
        """Parse Spark-style local[N] master hints; None means use all devices."""
        m = self.master
        if m.startswith("local[") and m.endswith("]"):
            inner = m[len("local[") : -1]
            if inner != "*":
                try:
                    return max(1, int(inner))
                except ValueError:
                    return None
        return None

    def validate_master(self) -> None:
        """Resolve --master into the runtime it names. ``local``/``local[N]``
        stay single-host; ``twtml://host:port`` is the cluster form (fills
        --coordinator); anything else — notably the reference's
        ``spark://host:port`` — is REJECTED: this runtime cannot honor it,
        and silently running single-host would be worse (VERDICT r2)."""
        m = self.master
        if m == "local" or (m.startswith("local[") and m.endswith("]")):
            return
        if m.startswith("twtml://"):
            addr = m[len("twtml://"):].rstrip("/")
            if not addr:
                raise SystemExit("--master twtml:// needs host:port")
            if self.coordinator and self.coordinator != addr:
                raise SystemExit(
                    f"--master {m} conflicts with --coordinator "
                    f"{self.coordinator}"
                )
            self.coordinator = addr
            return
        raise SystemExit(
            f"unsupported --master {m!r}: this is the TPU-native runtime — "
            "use local[N] for single-host, or twtml://host:port (equivalently "
            "--coordinator host:port --numProcesses N --processId I) for a "
            "multi-host jax.distributed group"
        )

    def multihost(self) -> "tuple[str, int, int] | None":
        """(coordinator, num_processes, process_id) when a multi-host group
        is requested; None for single-host runs. Called after
        ``validate_master`` so twtml:// masters are folded in."""
        if not self.coordinator:
            if self.numProcesses > 0 or self.processId >= 0:
                # half-specified cluster coordinates silently running
                # single-host would double-train the stream and race
                # checkpoint writers — reject, like bad --master schemes
                raise SystemExit(
                    "--numProcesses/--processId need --coordinator "
                    "host:port (or --master twtml://host:port)"
                )
            return None
        if self.numProcesses < 2 or self.processId < 0:
            raise SystemExit(
                "--coordinator requires --numProcesses >= 2 and "
                "--processId >= 0 (one unique id per process)"
            )
        if self.processId >= self.numProcesses:
            raise SystemExit(
                f"--processId {self.processId} out of range for "
                f"--numProcesses {self.numProcesses}"
            )
        return self.coordinator, self.numProcesses, self.processId
