"""Process-RSS watchdog for long-running loops.

Why this exists: the r3 20-minute soak measured host RSS growing
proportionally to UPLOADED BYTES (~4–6 MB per 65k-tweet pass) through the
tunnel-attached TPU transport, while the identical pipeline on the CPU
backend stayed flat — the retention is in the axon tunnel client's
host-side transfer buffers, not in framework allocations (BENCHMARKS.md
"Endurance soaks", tools/soak.py). The framework cannot free another
library's buffers, so the guard is operational: sample RSS cheaply on a
batch cadence, warn with the diagnosis and the workaround when growth
passes a threshold, and keep warning at each further threshold step. The
workaround is bounded process lifetime — checkpoint-restart is cheap here
(``--checkpointDir``/``--checkpointEvery`` resume exactly,
apps/common.AppCheckpoint), so a supervisor can recycle the process
before the leak matters. Locally-attached runtimes never trip it.
"""

from __future__ import annotations

import os
import resource

from .logging import get_logger

log = get_logger("utils.rss")


def rss_mb() -> float:
    """Current resident set size in MB (statm is a no-syscall read on
    Linux; ru_maxrss — the high-water mark — is the portable fallback).

    Linux-only assumptions in the fallback: ru_maxrss is KB on Linux but
    BYTES on macOS (where this would over-report ~1000×), and a high-water
    mark can never shrink the way the statm reading can. Harmless on this
    rig; gate on sys.platform before reusing elsewhere."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGESIZE") / 1e6
    except Exception:
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def slope_mb_per_min(samples) -> float:
    """Least-squares slope of ``(t_seconds, rss_mb)`` samples in MB/min —
    the tools/soak.py leak-rate estimator, shared so the live
    ``host.rss_slope_mb_per_min`` gauge and the offline soak report agree
    on the math. 0.0 until two samples exist or all timestamps coincide."""
    pts = list(samples)
    if len(pts) < 2:
        return 0.0
    xs = [t / 60.0 for t, _ in pts]
    ys = [m for _, m in pts]
    n = float(len(pts))
    mx = sum(xs) / n
    my = sum(ys) / n
    var = sum((x - mx) ** 2 for x in xs)
    if var == 0.0:
        return 0.0
    cov = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    return cov / var


class RssWatchdog:
    """``tick()`` once per batch; samples every ``sample_every`` ticks and
    warns when RSS has grown ``warn_growth_mb`` beyond the first sample
    (then again at each further ``warn_growth_mb`` of growth).

    ``TWTML_RSS_WARN_MB`` overrides the threshold; 0 disables the warning
    (sampling still happens so callers can read ``last_mb``)."""

    def __init__(
        self, warn_growth_mb: float | None = None, sample_every: int = 64
    ):
        if warn_growth_mb is None:
            warn_growth_mb = float(os.environ.get("TWTML_RSS_WARN_MB", 2048))
        self.warn_growth_mb = warn_growth_mb
        self.sample_every = max(1, sample_every)
        self.last_mb: float | None = None
        self.warn_count = 0
        self._base: float | None = None
        self._next_warn = warn_growth_mb
        self._ticks = 0

    def tick(self) -> None:
        self._ticks += 1
        if self._ticks % self.sample_every:
            return
        cur = rss_mb()
        self.last_mb = cur
        try:
            # observability side-channel: the per-N-batches RSS sample lands
            # in the metrics registry so the dashboard/bench see it live
            from ..telemetry import metrics as _metrics

            _metrics.get_registry().gauge("host.rss_mb").set(round(cur, 1))
        except Exception:  # lawcheck: disable=TW005 -- telemetry side-channel publish: the RSS gauge must never kill the recycle watchdog (Try-parity)
            pass
        if self._base is None:
            self._base = cur
            return
        growth = cur - self._base
        if self.warn_growth_mb > 0 and growth >= self._next_warn:
            log.warning(
                "process RSS grew %.0f MB since start (now %.0f MB). On the "
                "tunnel-attached TPU transport this matches the known "
                "axon-client transfer-buffer retention (grows with uploaded "
                "bytes; the same pipeline is flat on CPU — BENCHMARKS.md r3 "
                "soak). Workaround for long-lived runs: bound process "
                "lifetime via checkpoint-restart (--checkpointDir + "
                "--checkpointEvery resume exactly).",
                growth, cur,
            )
            self.warn_count += 1
            self._next_warn = growth + self.warn_growth_mb
