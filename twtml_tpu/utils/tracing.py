"""Profiling/tracing hooks (SURVEY.md §5.1: absent in the reference — Spark's
UI was the de-facto profiler; here jax.profiler is first-class).

``Tracer(profile_dir)`` wraps jax.profiler.start_trace/stop_trace with a
no-op mode when disabled, so apps can call it unconditionally:

    tracer = Tracer(conf.profileDir)
    tracer.start()
    ... training ...
    tracer.stop()

Traces are TensorBoard-compatible (xplane) under ``profile_dir``; on TPU they
include device timelines and XLA op breakdowns.
"""

from __future__ import annotations

from . import get_logger

log = get_logger("tracing")


class Tracer:
    def __init__(self, profile_dir: str = ""):
        self.profile_dir = profile_dir
        self._active = False

    @property
    def enabled(self) -> bool:
        return bool(self.profile_dir)

    def start(self) -> None:
        if not self.enabled or self._active:
            return
        import jax

        jax.profiler.start_trace(self.profile_dir)
        self._active = True
        log.info("jax.profiler trace started → %s", self.profile_dir)

    def stop(self) -> None:
        if not self._active:
            return
        import jax

        jax.profiler.stop_trace()
        self._active = False
        log.info("jax.profiler trace written → %s", self.profile_dir)

    def __enter__(self) -> "Tracer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def annotate(name: str):
    """Named region visible in trace timelines (TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
