"""The wall-clock seam: one place where TWTML_NOW_MS pins time.

PR 4's sentinel acceptance test and the serving parity tests work by
bit-replaying runs, which only holds if every clock that feeds features or
batch identity is pinnable. The featurizer reads ``TWTML_NOW_MS`` at
construction (features/featurizer.py); lockstep, sentinel, and serving
code must read the SAME seam instead of ``time.time()`` directly — the
lawcheck rule TW006 enforces that statically.

``time.monotonic()`` is unaffected: pure intervals (deadlines, rate
windows, backoff) should stay monotonic and are not part of replay
identity.
"""

from __future__ import annotations

import os
import time


def now_ms() -> int:
    """Epoch milliseconds, pinned by TWTML_NOW_MS when set (the
    deterministic-replay seam shared with Featurizer.from_conf)."""
    env = os.environ.get("TWTML_NOW_MS", "")
    if env:
        # a malformed pin raises, like featurizer.from_conf on the same
        # value — silently falling back to the wall clock would un-pin a
        # replay that believes itself pinned
        return int(env)
    return int(time.time() * 1000)


def now_s() -> float:
    """Epoch seconds through the same seam (lockstep batch timestamps)."""
    return now_ms() / 1000.0
