from .rounding import round_half_up
from .logging import get_logger

__all__ = ["round_half_up", "get_logger"]
