from .rounding import round_half_up
from .logging import get_logger
from .clock import now_ms, now_s
from .backend import (
    force_virtual_cpu_devices,
    set_cpu_device_count_hint,
    shard_map,
)

__all__ = [
    "round_half_up",
    "get_logger",
    "now_ms",
    "now_s",
    "force_virtual_cpu_devices",
    "set_cpu_device_count_hint",
    "shard_map",
]
