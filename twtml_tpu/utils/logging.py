"""Logging setup mirroring the reference's two-channel scheme:
root WARN -> stderr, framework logger DEBUG-able
(spark/src/main/resources/log4j.properties:1-17).
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    root = logging.getLogger()
    if not root.handlers:
        root.addHandler(handler)
        root.setLevel(logging.WARNING)
    level = os.environ.get("TWTML_LOG", "INFO").upper()
    logging.getLogger("twtml_tpu").setLevel(getattr(logging, level, logging.INFO))
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    _configure()
    if not name.startswith("twtml_tpu"):
        name = "twtml_tpu." + name
    return logging.getLogger(name)
