"""Shared measurement loop for the benchmarks (bench.py, tools/bench_suite.py).

The double-buffered pipeline under test: featurize chunk k+1 on a host
thread while the device runs chunk k (SURVEY.md §7 hard part (c) — hiding
host featurization latency behind device steps).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

WARMUP_STEPS = 2


def measure_pipeline(
    model,
    featurize: Callable,
    chunks: Sequence,
    warmup_steps: int = WARMUP_STEPS,
) -> dict:
    """Run every chunk through featurize → model.step with one-chunk
    prefetch; returns {"tweets_per_sec", "seconds", "batches", "final_mse"}.
    ``featurize(chunk)`` must return a device-ready batch; ``model.step``
    must return a StepOutput (its ``mse`` is used for the final sync)."""
    n = sum(len(c) for c in chunks)

    warm = featurize(chunks[0])
    for _ in range(warmup_steps):
        model.step(warm)

    t0 = time.perf_counter()
    last = None
    with ThreadPoolExecutor(max_workers=1) as pool:
        pending = pool.submit(featurize, chunks[0])
        for nxt in chunks[1:]:
            batch = pending.result()
            pending = pool.submit(featurize, nxt)
            last = model.step(batch)
        last = model.step(pending.result())
    last.mse.block_until_ready()
    dt = time.perf_counter() - t0
    return {
        "tweets_per_sec": n / dt,
        "seconds": dt,
        "batches": len(chunks),
        "final_mse": float(last.mse),
    }
