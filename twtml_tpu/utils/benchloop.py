"""Shared measurement loop for the benchmarks (bench.py, tools/bench_suite.py).

The pipeline under test is the streaming hot path: featurize chunk k+1 on a
host thread while the device runs chunk k (SURVEY.md §7 hard part (c) —
hiding host featurization latency behind device steps). Two measured-on-TPU
policies baked in:

- **Per-step sync.** Each step's stats are fetched before the next dispatch,
  exactly like the real streaming loop (telemetry consumes every batch's
  Stats, SessionStats.scala:22-34). It is also required for honest timing
  over a remote-tunnel device: even a depth-2 dispatch queue floods the
  transport and collapses throughput ~2x (measured).
- **Prefetch pays whenever the device sync is not host-CPU work.** On an
  accelerator backend ``block_until_ready`` is GIL-released transport/IO
  wait, so a featurize thread overlaps with it even on a single-CPU host
  (measured 2x). Only on the CPU backend with one usable CPU does the
  worker thread purely add GIL churn — the loop runs inline there.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

WARMUP_STEPS = 2


def _usable_cpus() -> int:
    """CPUs this process may actually run on (affinity/cgroup aware)."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


def _run_once(model, featurize, chunks, prefetch: bool):
    """One timed pass; returns (elapsed seconds, last StepOutput).

    The pass ends with a REAL host fetch of the last step's mse: on this
    build's tunnel transport ``block_until_ready`` does not wait for device
    execution (BENCHMARKS.md), and the model's weights chain through every
    step, so one scalar fetch at the end is the cheapest way to make the
    timed window include actual completion of the whole pass."""
    t0 = time.perf_counter()
    if prefetch:
        with ThreadPoolExecutor(max_workers=1) as pool:
            pending = pool.submit(featurize, chunks[0])
            for nxt in chunks[1:]:
                batch = pending.result()
                pending = pool.submit(featurize, nxt)
                model.step(batch).mse.block_until_ready()
            last = model.step(pending.result())
    else:
        last = None
        for chunk in chunks:
            last = model.step(featurize(chunk))
            last.mse.block_until_ready()
    float(last.mse)  # force completion inside the timed window
    return time.perf_counter() - t0, last


def measure_passes(
    run_pass: Callable,
    *,
    repeats: int = 1,
    time_budget_s: float | None = None,
    settled_after: int = 0,
):
    """Best-of-N measurement core: call ``run_pass() -> (seconds, last)``
    until ``repeats`` passes ran, then keep going while ``time_budget_s``
    lasts unless ``settled_after`` consecutive passes failed to beat the
    best by >2% — the stall-riding policy shared by every benchmark (the
    accelerator tunnel stalls in multi-second bursts; one pass is never
    trusted). Returns (best_seconds, last_output, passes)."""
    t_start = time.perf_counter()
    best_dt, final, passes, since_improve = None, None, 0, 0
    while True:
        dt, last = run_pass()
        passes += 1
        improved = best_dt is None or dt < best_dt * 0.98
        best_dt = dt if best_dt is None else min(dt, best_dt)
        since_improve = 0 if improved else since_improve + 1
        final = last
        if passes < max(1, repeats):
            continue
        if time_budget_s is None:
            break
        if settled_after and since_improve >= settled_after:
            break
        if time.perf_counter() - t_start >= time_budget_s:
            break
    return best_dt, final, passes


def measure_pipeline(
    model,
    featurize: Callable,
    chunks: Sequence,
    warmup_steps: int = WARMUP_STEPS,
    repeats: int = 1,
    prefetch: bool | None = None,
    time_budget_s: float | None = None,
    settled_after: int = 0,
) -> dict:
    """Run every chunk through featurize → model.step; returns
    {"tweets_per_sec", "seconds", "batches", "final_mse", "passes"}.

    ``featurize(chunk)`` must return a device-ready batch; ``model.step``
    must return a StepOutput (its ``mse`` is the per-step sync point).
    ``repeats`` > 1 re-runs the whole pass and reports the fastest one —
    the sustained-capability number, robust to transport jitter (the tunnel
    to a remote accelerator stalls in multi-second bursts, sometimes
    minutes long). ``time_budget_s`` keeps adding passes (beyond
    ``repeats``) while the budget lasts, and ``settled_after`` > 0 stops
    early once that many consecutive passes fail to beat the best by >2% —
    together they ride out a stall window without burning time when the
    transport is healthy. When the model exposes ``reset()`` its weights
    are zeroed before every timed pass, so each pass is the identical
    single-streaming-pass program and ``final_mse`` is
    repeat-count-independent.
    """
    n = sum(len(c) for c in chunks)
    if prefetch is None:
        import jax

        prefetch = jax.default_backend() != "cpu" or _usable_cpus() > 1
    resettable = hasattr(model, "reset")

    warm = featurize(chunks[0])
    for _ in range(warmup_steps):
        model.step(warm).mse.block_until_ready()

    def run_pass():
        if resettable:
            model.reset()
        return _run_once(model, featurize, chunks, prefetch)

    best_dt, last, passes = measure_passes(
        run_pass,
        repeats=repeats,
        time_budget_s=time_budget_s,
        settled_after=settled_after,
    )
    return {
        "tweets_per_sec": n / best_dt,
        "seconds": best_dt,
        "batches": len(chunks),
        "final_mse": float(last.mse),  # identical across passes w/ reset()
        "passes": passes,
    }
