"""Shared measurement loop for the benchmarks (bench.py, tools/bench_suite.py).

The pipeline under test is the streaming hot path: featurize chunk k+1 on a
host thread while the device runs chunk k (SURVEY.md §7 hard part (c) —
hiding host featurization latency behind device steps). Measured-on-TPU
policies baked in (r2 — the transport's behavior changed since round 1 and
the r1 policy notes no longer hold):

- **Dispatch freely, fetch once per pass.** On this build's tunnel
  transport, ``block_until_ready`` is NOT a cheap sync: with per-step
  argument uploads in flight it forces a ~70 ms round trip per call
  (32-step pass: ~2.5 s synced vs ~0.25 s dispatched), while plain
  dispatches pipeline. Conversely it does not reliably wait either (a
  4096³ matmul "completes" in 18 µs by that clock). So a timed pass issues
  every dispatch without syncing and ends with ONE real host fetch of the
  last step's mse — the weights chain through every step, so that single
  scalar closes the window over actual completion of the whole pass.
- **Prefetch pays whenever the device step is not host-CPU work.** A
  featurize thread overlaps with dispatch/transfer waits even on a
  single-CPU host. Only on the CPU backend with one usable CPU does the
  worker thread purely add GIL churn — the loop runs inline there.
"""

from __future__ import annotations

import os
import statistics
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

WARMUP_STEPS = 2


def _fetch_mse(out) -> float:
    """The ONE data-dependent completion fetch closing a timed pass. A
    multi-tenant StepOutput carries an [M] mse vector — still one host
    fetch of one small array; the last element depends on every tenant's
    chained weights, so it closes the window the same way."""
    import numpy as np

    return float(np.asarray(out.mse).ravel()[-1])


def _usable_cpus() -> int:
    """CPUs this process may actually run on (affinity/cgroup aware)."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


def _run_once(model, featurize, chunks, prefetch: bool):
    """One timed pass; returns (elapsed seconds, last StepOutput). Dispatch
    freely, one real fetch at the end — see the module docstring."""
    dt, last, _ = _run_once_timed(model, featurize, chunks, prefetch)
    return dt, last


def _run_once_timed(model, featurize, chunks, prefetch: bool):
    """``_run_once`` plus the completion-fetch seconds as a third element —
    the fetch is timed separately so the tunnel-health monitor can classify
    the pass (telemetry/metrics.py): a stalled transport shows up as a
    multi-second completion fetch."""
    t0 = time.perf_counter()
    if prefetch:
        with ThreadPoolExecutor(max_workers=1) as pool:
            pending = pool.submit(featurize, chunks[0])
            for nxt in chunks[1:]:
                batch = pending.result()
                pending = pool.submit(featurize, nxt)
                model.step(batch)
            last = model.step(pending.result())
    else:
        last = None
        for chunk in chunks:
            last = model.step(featurize(chunk))
    t_fetch = time.perf_counter()
    _fetch_mse(last)  # force completion inside the timed window
    t_end = time.perf_counter()
    return t_end - t0, last, t_end - t_fetch


def measure_passes(
    run_pass: Callable,
    *,
    repeats: int = 1,
    time_budget_s: float | None = None,
    settled_after: int = 0,
):
    """Best-of-N measurement core: call ``run_pass() -> (seconds, last)``
    until ``repeats`` passes ran, then keep going while ``time_budget_s``
    lasts unless ``settled_after`` consecutive passes failed to beat the
    best by >2% — the stall-riding policy shared by every benchmark (the
    accelerator tunnel stalls in multi-second bursts; one pass is never
    trusted). Returns (best_seconds, last_output, pass_times) —
    ``pass_times`` holds every pass's seconds, so callers can report
    best/median/pass-count and round-over-round numbers explain themselves."""
    t_start = time.perf_counter()
    best_dt, final, since_improve = None, None, 0
    times: list[float] = []
    while True:
        dt, last = run_pass()
        times.append(dt)
        improved = best_dt is None or dt < best_dt * 0.98
        best_dt = dt if best_dt is None else min(dt, best_dt)
        since_improve = 0 if improved else since_improve + 1
        final = last
        if len(times) < max(1, repeats):
            continue
        if time_budget_s is None:
            break
        if settled_after and since_improve >= settled_after:
            break
        if time.perf_counter() - t_start >= time_budget_s:
            break
    return best_dt, final, times


def measure_pipeline(
    model,
    featurize: Callable,
    chunks: Sequence,
    warmup_steps: int = WARMUP_STEPS,
    repeats: int = 1,
    prefetch: bool | None = None,
    time_budget_s: float | None = None,
    settled_after: int = 0,
) -> dict:
    """Run every chunk through featurize → model.step; returns
    {"tweets_per_sec", "seconds", "batches", "final_mse", "passes"}.

    ``featurize(chunk)`` must return a device-ready batch; ``model.step``
    must return a StepOutput (its ``mse`` is fetched ONCE at the end of each
    pass — the per-pass completion point; there is deliberately no per-step
    sync, see the module docstring). Returns {"tweets_per_sec",
    "median_tweets_per_sec", "seconds", "batches", "final_mse", "passes"}.
    ``repeats`` > 1 re-runs the whole pass and reports the fastest one —
    the sustained-capability number, robust to transport jitter (the tunnel
    to a remote accelerator stalls in multi-second bursts, sometimes
    minutes long). ``time_budget_s`` keeps adding passes (beyond
    ``repeats``) while the budget lasts, and ``settled_after`` > 0 stops
    early once that many consecutive passes fail to beat the best by >2% —
    together they ride out a stall window without burning time when the
    transport is healthy. When the model exposes ``reset()`` its weights
    are zeroed before every timed pass, so each pass is the identical
    single-streaming-pass program and ``final_mse`` is
    repeat-count-independent.
    """
    n = sum(len(c) for c in chunks)
    if prefetch is None:
        import jax

        prefetch = jax.default_backend() != "cpu" or _usable_cpus() > 1
    resettable = hasattr(model, "reset")

    warm = featurize(chunks[0])
    for _ in range(warmup_steps):
        # completion fetch, not block_until_ready: warmup must fully drain
        # before the first timed pass (module docstring)
        _fetch_mse(model.step(warm))

    # per-pass health classification: the completion-fetch latency is the
    # pass's transport sample; phase counts in the output say how much of
    # the budget sat in a degraded window (the tunnel's ~10-min phases)
    from ..telemetry.metrics import TunnelHealthMonitor

    health = TunnelHealthMonitor()

    def run_pass():
        if resettable:
            model.reset()
        dt, last, fetch_s = _run_once_timed(model, featurize, chunks, prefetch)
        health.observe(fetch_s)
        return dt, last

    best_dt, last, times = measure_passes(
        run_pass,
        repeats=repeats,
        time_budget_s=time_budget_s,
        settled_after=settled_after,
    )
    median_dt = statistics.median(times)
    return {
        "tweets_per_sec": n / best_dt,
        "median_tweets_per_sec": n / median_dt,
        "seconds": best_dt,
        "batches": len(chunks),
        "final_mse": _fetch_mse(last),  # identical across passes w/ reset()
        "passes": len(times),
        "health": health.summary(),
    }
