"""Half-up rounding, host and device versions.

The reference rounds every reported metric and every prediction with
BigDecimal HALF_UP (Utils.scala:4-6, used at LinearRegression.scala:57,63-65),
i.e. ties round away from zero (2.5 -> 3, -2.5 -> -3), unlike Python's
built-in banker's rounding. The device version is used inside jit so the MSE
is computed over *rounded* predictions exactly like the reference (§2.5 of
SURVEY.md: "MSE is computed on rounded predictions").
"""

from __future__ import annotations

import decimal


def round_half_up(x: float) -> float:
    """Scalar host-side HALF_UP rounding (ties away from zero).

    Uses decimal to match BigDecimal exactly on values adjacent to ties
    (e.g. 0.49999999999999994 rounds to 0, where float ``floor(x+0.5)``
    would give 1).
    """
    return float(
        decimal.Decimal(x).quantize(decimal.Decimal(1), rounding=decimal.ROUND_HALF_UP)
    )


def jnp_round_half_up(x):
    """Device-side HALF_UP rounding; safe under jit (no data-dependent flow).

    Note: computed as ``floor(x+0.5)`` / ``ceil(x-0.5)`` in device floats, which
    differs from BigDecimal on tie-adjacent values below float resolution
    (e.g. 0.49999999999999994). Acceptable inside the jit metric path; host-side
    reporting uses the exact ``round_half_up`` above.
    """
    import jax.numpy as jnp

    return jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5))
