"""Monotonic run ids + config fingerprints (ISSUE 20 satellite).

Nothing linked a BENCH_*.json row to the run that produced it, or one
historian segment to the next run appending after it. Two small joins fix
that:

- ``next_run_id()`` — a machine-local monotonically-increasing integer,
  persisted in a small counter file under an ``fcntl`` lock (concurrent
  bench subprocesses each get a distinct id). ``TWTML_RUN_ID_FILE``
  overrides the location (tests; per-checkout counters).
- ``config_fingerprint(conf_or_dict)`` — a short stable hash over the
  SCALAR config values, so "same config, different run" and "same run id
  family, different config" are both one string comparison across bench
  rows, historian run headers, and perfGuard baselines.

Host-side stdlib only; no jax anywhere near this module.
"""

from __future__ import annotations

import hashlib
import os
import tempfile

ENV_RUN_ID_FILE = "TWTML_RUN_ID_FILE"


def _counter_path() -> str:
    override = os.environ.get(ENV_RUN_ID_FILE, "")
    if override:
        return override
    return os.path.join(tempfile.gettempdir(), "twtml-run-id")


def next_run_id() -> int:
    """Allocate the next machine-local run id (1, 2, 3, ...). The counter
    file is read-increment-written under an exclusive ``flock`` so parallel
    launches never collide; an unreadable counter restarts at 1 rather than
    failing the run (ids are a join key, not a correctness invariant)."""
    path = _counter_path()
    try:
        import fcntl

        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            raw = os.read(fd, 64).decode("ascii", "replace").strip()
            try:
                current = int(raw)
            except ValueError:
                current = 0
            nxt = current + 1
            os.lseek(fd, 0, os.SEEK_SET)
            os.ftruncate(fd, 0)
            os.write(fd, str(nxt).encode("ascii"))
            return nxt
        finally:
            os.close(fd)  # releases the flock too
    except OSError:
        return 1


def config_fingerprint(conf) -> str:
    """12-hex-char stable hash over the scalar config values. Accepts a
    Config-like object (``vars()`` is taken) or a plain dict; private
    attrs, callables and non-scalars are skipped so the fingerprint only
    moves when a knob a human set moves."""
    d = conf if isinstance(conf, dict) else vars(conf)
    items = sorted(
        (k, v) for k, v in d.items()
        if not k.startswith("_") and isinstance(v, (str, int, float, bool))
    )
    blob = "\n".join(f"{k}={v!r}" for k, v in items).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:12]
