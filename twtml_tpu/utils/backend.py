"""Backend/platform selection helpers.

One place for the "force an n-device virtual CPU mesh" dance used by the
driver entry (``__graft_entry__.dryrun_multichip``) and the CLI backend
selector (``apps.linear_regression.select_backend``): both need to set
``jax_num_cpu_devices`` *before* any backend initialization and degrade
gracefully when one is already live. tests/conftest.py deliberately does not
import this (it must configure jax before the repo is even on sys.path), but
follows the same recipe.
"""

from __future__ import annotations


def backends_initialized() -> bool | None:
    """True/False when jax can report whether a backend is initialized in
    this process (after which device-count configs can no longer change);
    None when the probe (a jax-internal symbol, no stability guarantee) is
    unavailable — the helpers below then fall back to public-API behavior:
    attempt the ``jax_num_cpu_devices`` update first and catch the
    RuntimeError jax raises for it post-init (``jax_platforms`` never
    raises, so callers that only flip the platform must verify the outcome
    via ``jax.default_backend()`` instead)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:  # lawcheck: disable=TW005 -- documented probe contract: None means 'jax-internal symbol unavailable', callers fall back to public-API behavior (docstring above)
        return None


def shard_map():
    """The shard_map entry point across jax versions: top-level
    ``jax.shard_map`` where it exists (newer jax), the experimental module
    otherwise (the 0.4.3x line) — same keyword surface
    (``mesh``/``in_specs``/``out_specs``) either way."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    import functools

    from jax.experimental.shard_map import shard_map as _sm

    # check_rep=False: the 0.4.x replication checker false-positives on the
    # scan-carry + psum pattern our superbatch programs use ("mismatched
    # replication types"); it is a static lint, not a semantic change, and
    # later jax versions accept the same programs with checking on
    return functools.partial(_sm, check_rep=False)


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside a shard_map body, across
    jax versions: ``lax.axis_size`` where it exists, the axis environment on
    the 0.4.3x line. Always a Python int (shape arithmetic depends on it)."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    from jax._src import core

    return int(core.get_axis_env().axis_sizes[axis_name])


def pcast_varying(x, axis_name):
    """``lax.pcast(..., to="varying")`` where it exists (the new shard_map
    varying-manual-axes system); identity on older jax, whose experimental
    shard_map (run with ``check_rep=False`` — see ``shard_map``) has no
    replication types to convert between."""
    from jax import lax

    fn = getattr(lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axis_name, to="varying")


def set_host_device_count_flag(n_devices: int) -> None:
    """Pre-init fallback for jax builds without the ``jax_num_cpu_devices``
    config option (it landed after the 0.4.3x line this image may carry):
    the classic ``XLA_FLAGS --xla_force_host_platform_device_count`` route,
    which the CPU backend reads at initialization. Replaces any existing
    count flag so repeated calls converge instead of appending."""
    import os

    flag = f"--xla_force_host_platform_device_count={n_devices}"
    parts = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    parts.append(flag)
    os.environ["XLA_FLAGS"] = " ".join(parts)


def _set_cpu_device_count(n_devices: int) -> bool:
    """``jax_num_cpu_devices`` when this jax has it, XLA_FLAGS otherwise.
    Returns False when a live backend makes the change impossible."""
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
        return True
    except AttributeError:
        # older jax: no such config option — the env-var route below works
        # as long as no backend is initialized (callers checked)
        set_host_device_count_flag(n_devices)
        return True
    except RuntimeError:
        # probe was unavailable and a backend with a different CPU device
        # count is already live
        return False


def force_virtual_cpu_devices(n_devices: int) -> bool:
    """Switch jax to an ``n_devices``-device virtual CPU backend.

    The virtual CPU mesh compiles and executes the same
    Mesh/shard_map/psum program structure the TPU path uses, which is how
    multi-chip sharding is validated on hosts without n real chips.

    Returns True when the configuration was applied; False when a backend was
    already initialized (the config is then left untouched and the caller
    should use whatever devices exist).
    """
    import jax

    if backends_initialized():
        return False
    if not _set_cpu_device_count(n_devices):
        return False
    jax.config.update("jax_platforms", "cpu")
    return True


def set_cpu_device_count_hint(n_devices: int) -> bool:
    """Set the CPU device count without forcing the platform (the local[N]
    hint: only affects runs where the CPU backend wins platform selection).
    Returns False if a backend is already initialized, leaving it untouched."""
    if backends_initialized():
        return False
    return _set_cpu_device_count(n_devices)
