"""Backend/platform selection helpers.

One place for the "force an n-device virtual CPU mesh" dance used by the
driver entry (``__graft_entry__.dryrun_multichip``) and the CLI backend
selector (``apps.linear_regression.select_backend``): both need to set
``jax_num_cpu_devices`` *before* any backend initialization and degrade
gracefully when one is already live. tests/conftest.py deliberately does not
import this (it must configure jax before the repo is even on sys.path), but
follows the same recipe.
"""

from __future__ import annotations


def backends_initialized() -> bool | None:
    """True/False when jax can report whether a backend is initialized in
    this process (after which device-count configs can no longer change);
    None when the probe (a jax-internal symbol, no stability guarantee) is
    unavailable — the helpers below then fall back to public-API behavior:
    attempt the ``jax_num_cpu_devices`` update first and catch the
    RuntimeError jax raises for it post-init (``jax_platforms`` never
    raises, so callers that only flip the platform must verify the outcome
    via ``jax.default_backend()`` instead)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return None


def force_virtual_cpu_devices(n_devices: int) -> bool:
    """Switch jax to an ``n_devices``-device virtual CPU backend.

    The virtual CPU mesh compiles and executes the same
    Mesh/shard_map/psum program structure the TPU path uses, which is how
    multi-chip sharding is validated on hosts without n real chips.

    Returns True when the configuration was applied; False when a backend was
    already initialized (the config is then left untouched and the caller
    should use whatever devices exist).
    """
    import jax

    if backends_initialized():
        return False
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
        jax.config.update("jax_platforms", "cpu")
        return True
    except RuntimeError:
        # probe was unavailable and a backend with a different CPU device
        # count is already live
        return False


def set_cpu_device_count_hint(n_devices: int) -> bool:
    """Set the CPU device count without forcing the platform (the local[N]
    hint: only affects runs where the CPU backend wins platform selection).
    Returns False if a backend is already initialized, leaving it untouched."""
    import jax

    if backends_initialized():
        return False
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
        return True
    except RuntimeError:
        return False
