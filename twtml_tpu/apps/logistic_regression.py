"""Streaming logistic-regression entry point (BASELINE config #3: binary
sentiment on the tweet stream).

Same pipeline shape as the linear app (filter → featurize → fused
predict-then-train → stats) with the label swapped to the lexicon sentiment
of the original tweet (features/sentiment.py) and the logistic learner
(models/logistic.py). Reported ``mse`` over hard 0/1 predictions is the
misclassification rate.

Run: ``python -m twtml_tpu.apps.logistic_regression --source replay \
      --replayFile tests/data/tweets.jsonl --seconds 1``
"""

from __future__ import annotations

import sys

import numpy as np

from ..config import ConfArguments
from ..features.featurizer import Featurizer
from ..features.sentiment import (
    sentiment_label,
    sentiment_labels,
    sentiment_labels_from_units,
)
from ..models.logistic import StreamingLogisticRegressionWithSGD
from ..streaming.context import StreamingContext
from ..telemetry.session_stats import SessionStats
from ..utils import get_logger, round_half_up
from .common import (
    AppCheckpoint,
    DivergenceSentinel,
    ProcessRecycler,
    attach_super_batcher,
    build_model,
    build_source,
    init_distributed,
    install_blackbox,
    install_chaos,
    install_historian,
    install_journal,
    install_trace,
    journal_boot_replay,
    select_backend,
    warmup_compile,
)

log = get_logger("apps.logistic")


def run(conf: ConfArguments, max_batches: int = 0) -> dict:
    lead = init_distributed(conf)  # before any backend use (apps/common)
    session = SessionStats(conf).open() if lead else None
    select_backend(conf)
    featurizer = Featurizer.from_conf(conf)
    featurizer.label_fn = sentiment_label
    featurizer.batch_label_fn = sentiment_labels  # C hot path, same labels
    featurizer.unit_label_fn = sentiment_labels_from_units  # block ingest
    # mesh-sharded automatically on several devices / --master local[N],
    # exactly like the flagship app (the logistic residual rides the same
    # sharded step)
    model, row_multiple = build_model(conf, StreamingLogisticRegressionWithSGD)
    import jax

    lockstep = jax.process_count() > 1
    install_trace(conf)
    install_chaos(conf)
    install_blackbox(conf)  # crash flight recorder (apps/common)
    install_journal(conf)  # durable intake journal (--journal, apps/common)
    install_historian(conf)  # telemetry historian (--history, apps/common)

    ssc = StreamingContext(
        batch_interval=conf.seconds,
        max_queue_rows=conf.effective_max_queue_rows(),
        shed_policy=conf.shedPolicy,
    )
    stream = ssc.source_stream(
        build_source(conf, allow_block=True), featurizer,
        row_bucket=conf.batchBucket, token_bucket=conf.tokenBucket,
        row_multiple=row_multiple,
        device_hash=conf.hashOn == "device",
        ragged=conf.effective_wire() == "ragged",
    )
    # tenant count in the run record: callers (bench suite, tests) can see
    # how many models this run's one jit program trained
    totals = {
        "count": 0, "batches": 0,
        "tenants": int(getattr(model, "num_tenants", 1) or 1),
    }

    # checkpoint/resume — same upgrade as the flagship app (SURVEY.md §5.4)
    ckpt = AppCheckpoint(
        conf,
        get_state=lambda: model.latest_weights,
        set_state=model.set_initial_weights,
        totals=totals,
        lead=lead,
    )
    # journal boot recovery — same replay-exact resume as the flagship app
    journal_boot_replay(conf, ssc, ckpt, totals)

    recycler = ProcessRecycler(conf, ckpt, totals)

    # divergence sentinel — same guard as the flagship app (apps/common)
    sentinel = DivergenceSentinel(
        conf, model, ckpt, ssc, lead=lead, totals=totals
    )

    # model watch — same drift/trend plane as the flagship app
    from .common import ModelWatchGuard

    modelwatch = ModelWatchGuard(conf, ckpt, totals, lead=lead)

    # freshness plane — same lineage/watermark/SLO plane as the flagship app
    from ..telemetry import freshness as _freshness
    from .common import FreshnessGuard

    _freshness.configure(conf)
    freshness_guard = FreshnessGuard(conf, ckpt, totals, lead=lead)

    def handle(out, batch, _batch_time, at_boundary=True) -> None:
        b = int(out.count)
        totals["count"] += b
        totals["batches"] += 1
        err_rate = float(out.mse)  # 0/1 preds → MSE == misclassification rate
        if lead:
            # per-row series are lead-local (followers don't fetch
            # predictions) and can be empty when the lead's own shard had
            # no valid rows this batch — the GLOBAL stats above still hold
            valid = batch.mask.astype(bool)
            real = batch.label[valid].astype(np.float64)
            pred = np.asarray(out.predictions)[valid].astype(np.float64)
            rates = (
                f"({real.mean():.2f}, {pred.mean():.2f})"
                if real.size else "(-, -)"
            )
            print(
                f"count: {totals['count']}  batch: {b}  "
                f"errRate: {err_rate:.3f}  posRate (real, pred): {rates}",
                flush=True,
            )
            session.update(
                totals["count"], b,
                round_half_up(err_rate * 100),  # percent for the int dashboard
                round_half_up(float(out.real_stdev) * 100),
                round_half_up(float(out.pred_stdev) * 100),
                real, pred,
            )
        ckpt.maybe_save(totals, at_boundary)
        recycler.check(at_boundary)
        if max_batches and totals["batches"] >= max_batches:
            ssc.request_stop()

    # elastic membership plane (--elastic on, apps/common.attach_elastic)
    from .common import attach_elastic, elastic_exit

    elastic_plane = attach_elastic(conf, ssc, model, stream, ckpt, totals)

    flush_group, group_k = attach_super_batcher(
        conf, stream, model, handle,
        stop_requested=lambda: ssc.stop_requested,
        max_dispatch=(
            max(1, max_batches - totals["batches"]) if max_batches else 0
        ),
        abort=ssc.request_abort,  # fetch-watchdog aborts fail the run loudly
        sentinel=sentinel,
        modelwatch=modelwatch,
        elastic=elastic_plane,
        freshness=freshness_guard,
    )
    warmup_compile(stream, model, super_batch=group_k)
    ssc.start(lockstep=lockstep)
    try:
        ssc.await_termination()
    except KeyboardInterrupt:
        pass
    finally:
        ssc.stop()
        flush_group()  # drain a partial superbatch group
        if session is not None:
            session.publish_metrics()  # final dashboard-panel snapshot
        from ..telemetry import trace as pipeline_trace

        pipeline_trace.uninstall()  # flush + close the --trace file
        ckpt.final_save(totals)
        from ..streaming import journal as _journal_mod
        from ..telemetry import historian as _historian_mod

        # after the final save (it stamps the journal cursor): close the
        # segment files and clear the module face so a later run() in the
        # same process starts clean
        _journal_mod.uninstall()
        # perfGuard baseline stamps on CLEAN shutdown only
        if not ssc.failed:
            _historian_mod.stamp_baseline()
        _historian_mod.uninstall()
    if ssc.failed:
        elastic_exit(failed=True)
        raise RuntimeError(
            "run aborted by a runtime guard — lockstep peer loss, a fetch "
            "watchdog abort, or the divergence sentinel (see critical log "
            "above); progress up to the failure is checkpointed"
        )
    elastic_exit(failed=False)
    return totals


def main(argv=None) -> None:
    conf = (
        ConfArguments()
        .setAppName("twitter-stream-ml-logistic-regression")
        .parse(list(sys.argv[1:] if argv is None else argv))
    )
    totals = run(conf)
    log.info("done: %s tweets in %s batches", totals["count"], totals["batches"])


if __name__ == "__main__":
    main()
