"""Streaming linear-regression entry point — the flagship application.

Wires the same pipeline as the reference's ``LinearRegression.main``
(LinearRegression.scala:12-91): config → session stats → featurizer → model →
streaming context → source → per-batch predict/stats/train → run. The
reference's two registered outputs (stats ``foreachRDD`` then ``trainOn``)
collapse into one fused device step that scores with pre-update weights and
trains in the same XLA program.

Run: ``python -m twtml_tpu.apps.linear_regression --source replay \
      --replayFile tests/data/tweets.jsonl --seconds 1``
"""

from __future__ import annotations

import sys

import numpy as np

from ..config import ConfArguments
from ..features.featurizer import Featurizer
from ..streaming.context import StreamingContext
from ..telemetry.session_stats import SessionStats
from ..utils import get_logger, round_half_up

# shared app runtime (apps/common.py); re-exported here because this is the
# flagship entry other modules historically import the helpers from
from .common import (  # noqa: F401
    AppCheckpoint,
    ProcessRecycler,
    attach_super_batcher,
    build_model,
    build_source,
    init_distributed,
    install_blackbox,
    install_chaos,
    install_historian,
    install_journal,
    install_trace,
    select_backend,
    warmup_compile,
)

log = get_logger("apps.linear")


def run(conf: ConfArguments, max_batches: int = 0) -> dict:
    # multi-host group formation MUST precede any backend use (apps/common)
    lead = init_distributed(conf)

    log.info("Initializing session stats...")
    # one telemetry session per RUN, not per host: the lead publishes the
    # global stats (they are psum-identical on every host); followers train
    session = SessionStats(conf).open() if lead else None

    log.info("Initializing TPU-native streaming model...")
    select_backend(conf)
    featurizer = Featurizer.from_conf(conf)
    model, row_multiple = build_model(conf)
    import jax

    lockstep = jax.process_count() > 1
    install_trace(conf)
    install_chaos(conf)
    # crash flight recorder: every abort path dumps a post-mortem bundle
    # next to the checkpoint dir (apps/common.install_blackbox)
    install_blackbox(conf)
    # durable intake journal (--journal, auto-on with --checkpointDir):
    # every recovery path below replays from it instead of counting loss
    install_journal(conf)
    # telemetry historian (--history, auto-on with --checkpointDir):
    # durable long-horizon time series at the stats-publish cadence
    install_historian(conf)

    log.info("Initializing streaming context... %s sec/batch", conf.seconds)
    ssc = StreamingContext(
        batch_interval=conf.seconds,
        # bounded intake backpressure (--maxQueueRows/--shedPolicy):
        # the queue was the last unbounded buffer in the pipeline
        max_queue_rows=conf.effective_max_queue_rows(),
        shed_policy=conf.shedPolicy,
    )
    stream = ssc.source_stream(
        build_source(conf, allow_block=True), featurizer,
        row_bucket=conf.batchBucket, token_bucket=conf.tokenBucket,
        row_multiple=row_multiple,
        device_hash=conf.hashOn == "device",
        ragged=conf.effective_wire() == "ragged",
    )

    # tenant count in the run record: callers (bench suite, tests) can see
    # how many models this run's one jit program trained
    totals = {
        "count": 0, "batches": 0,
        "tenants": int(getattr(model, "num_tenants", 1) or 1),
    }

    # checkpoint/resume (upgrade over the reference, SURVEY.md §5.4)
    ckpt = AppCheckpoint(
        conf,
        get_state=lambda: model.latest_weights,
        set_state=model.set_initial_weights,
        totals=totals,
        lead=lead,
    )

    # journal boot recovery (kill -9 / watchdog-abort restart): replay the
    # rows past the restored checkpoint's cursor and fast-forward the
    # source past everything journaled — resume is replay-exact
    from .common import journal_boot_replay

    journal_boot_replay(conf, ssc, ckpt, totals)

    # --recycleAfterMb: bounded process lifetime (checkpoint + exact-resume
    # re-exec) once RSS crosses the ceiling — the actionable form of the
    # RSS watchdog's diagnosis (apps/common.ProcessRecycler)
    recycler = ProcessRecycler(conf, ckpt, totals)

    # divergence sentinel (--sentinel, default on): non-finite per-batch
    # stats → skip the batch, roll back to the last verified-finite
    # checkpoint, abort cleanly after N rollbacks (apps/common)
    from .common import DivergenceSentinel, ModelWatchGuard

    sentinel = DivergenceSentinel(
        conf, model, ckpt, ssc, lead=lead, totals=totals
    )

    # model watch (--modelWatch, default on): drift/loss-trend telemetry
    # from the in-step quality vector riding the existing stats fetch;
    # sustained alert forces a verified-checkpoint save (apps/common)
    modelwatch = ModelWatchGuard(conf, ckpt, totals, lead=lead)

    # freshness plane (--freshness, default on): event-time watermarks +
    # per-batch critical-path lineage stamped at seams the pipeline already
    # crosses — zero added fetches/collectives; a sustained --freshnessSloMs
    # breach forces one verified checkpoint per episode (apps/common)
    from ..telemetry import freshness as _freshness
    from .common import FreshnessGuard

    _freshness.configure(conf)
    freshness_guard = FreshnessGuard(conf, ckpt, totals, lead=lead)

    from ..utils.tracing import Tracer

    tracer = Tracer(conf.profileDir)

    def handle(out, batch, _batch_time, at_boundary=True) -> None:
        b = int(out.count)
        totals["count"] += b
        totals["batches"] += 1
        mse = round_half_up(float(out.mse))
        real_stdev = round_half_up(float(out.real_stdev))
        pred_stdev = round_half_up(float(out.pred_stdev))
        if lead:
            # the reference's debug channel (LinearRegression.scala:67-74);
            # stats are global (psum over the data axis) so one host prints.
            # Per-row series are lead-local (followers don't even fetch
            # predictions, parallel/distributed.py) and may be empty when
            # the lead's own shard had no valid rows this batch.
            valid = batch.mask.astype(bool)
            real = batch.label[valid].astype(np.float64)
            pred = np.asarray(out.predictions)[valid].astype(np.float64)
            print(
                f"count: {totals['count']}  batch: {b}  mse: {mse}  "
                f"stdev (real, pred): ({int(real_stdev)}, {int(pred_stdev)})",
                flush=True,
            )
            session.update(
                totals["count"], b, mse, real_stdev, pred_stdev, real, pred
            )
        ckpt.maybe_save(totals, at_boundary)
        recycler.check(at_boundary)
        if max_batches and totals["batches"] >= max_batches:
            ssc.request_stop()

    # elastic membership plane (--elastic on): host loss degrades capacity
    # instead of killing the run; a recovered host rejoins at an epoch
    # boundary (apps/common.attach_elastic)
    from .common import attach_elastic, elastic_exit

    elastic_plane = attach_elastic(conf, ssc, model, stream, ckpt, totals)

    flush_group, group_k = attach_super_batcher(
        conf, stream, model, handle,
        stop_requested=lambda: ssc.stop_requested,
        max_dispatch=(
            max(1, max_batches - totals["batches"]) if max_batches else 0
        ),
        abort=ssc.request_abort,  # fetch-watchdog aborts fail the run loudly
        sentinel=sentinel,
        modelwatch=modelwatch,
        elastic=elastic_plane,
        freshness=freshness_guard,
    )

    warmup_compile(stream, model, super_batch=group_k)

    log.info("Starting the streaming computation...")
    tracer.start()
    import time as _time

    t_stream = _time.perf_counter()
    ssc.start(lockstep=lockstep)
    try:
        ssc.await_termination()
    except KeyboardInterrupt:
        pass
    finally:
        ssc.stop()
        flush_group()  # drain a partial superbatch group before final state
        # the post-warmup streaming window (start → last batch drained):
        # what a steady-state rate should be computed over — session init,
        # model build, and the warmup compile are startup, not streaming
        # (the suite's twitter_live config reads this, VERDICT r3 #4)
        totals["stream_seconds"] = _time.perf_counter() - t_stream
        tracer.stop()
        if session is not None:
            # final metrics snapshot so the dashboard panel ends current
            session.publish_metrics()
        from ..telemetry import trace as pipeline_trace

        pipeline_trace.uninstall()  # flush + close the --trace file
        ckpt.final_save(totals)
        from ..streaming import journal as _journal_mod
        from ..telemetry import historian as _historian_mod

        # after the final save (it stamps the journal cursor): close the
        # segment files and clear the module face so a later run() in the
        # same process starts clean
        _journal_mod.uninstall()
        # perfGuard baseline stamps on CLEAN shutdown only — a guard-
        # aborted run's degraded stage costs must not become the next
        # run's "healthy" baseline
        if not ssc.failed:
            _historian_mod.stamp_baseline()
        _historian_mod.uninstall()
    if ssc.failed:
        # elastic runs leave via a hard exit either way (abandoned-epoch
        # teardown during interpreter finalization is unsafe)
        elastic_exit(failed=True)
        raise RuntimeError(
            "run aborted by a runtime guard — lockstep peer loss, a fetch "
            "watchdog abort, or the divergence sentinel (see critical log "
            "above); progress up to the failure is checkpointed"
        )
    elastic_exit(failed=False)
    return totals


def main(argv=None) -> None:
    conf = (
        ConfArguments()
        .setAppName("twitter-stream-ml-linear-regression")
        .parse(list(sys.argv[1:] if argv is None else argv))
    )
    totals = run(conf)
    log.info("done: %s tweets in %s batches", totals["count"], totals["batches"])


if __name__ == "__main__":
    main()
