"""Streaming linear-regression entry point — the flagship application.

Wires the same pipeline as the reference's ``LinearRegression.main``
(LinearRegression.scala:12-91): config → session stats → featurizer → model →
streaming context → source → per-batch predict/stats/train → run. The
reference's two registered outputs (stats ``foreachRDD`` then ``trainOn``)
collapse into one fused device step that scores with pre-update weights and
trains in the same XLA program.

Run: ``python -m twtml_tpu.apps.linear_regression --source replay \
      --replayFile tests/data/tweets.jsonl --seconds 1``
"""

from __future__ import annotations

import sys

import numpy as np

from ..config import ConfArguments
from ..features.featurizer import Featurizer
from ..models.linear import StreamingLinearRegressionWithSGD
from ..streaming.context import StreamingContext
from ..streaming.sources import ReplayFileSource, Source, SyntheticSource
from ..telemetry.session_stats import SessionStats
from ..utils import get_logger, round_half_up

log = get_logger("apps.linear")


def select_backend(conf) -> None:
    """--backend {auto,tpu,cpu}: auto keeps jax's platform choice (TPU when
    attached); cpu forces the host backend (the reference's local[*] analog,
    ConfArguments.scala:54-56)."""
    import jax

    from ..utils import set_cpu_device_count_hint

    shards = conf.local_shards()
    if shards:
        # honor the local[N] hint before any backend initialization; it only
        # affects the CPU platform, so it's harmless when TPU wins auto
        if not set_cpu_device_count_hint(shards):
            log.warning("backend already initialized; local[%d] hint dropped", shards)
    if conf.backend == "cpu":
        # jax_platforms silently no-ops when a backend is already live, so
        # verify the outcome instead of guessing the pre-state (and this
        # first jax.default_backend() call initializes cpu when it did work)
        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                "--backend cpu requested but a non-cpu backend is already "
                "initialized in this process"
            )
    elif conf.backend == "tpu":
        kinds = {d.platform for d in jax.devices()}
        if "cpu" in kinds and len(kinds) == 1:
            raise RuntimeError("--backend tpu requested but only CPU devices present")


def build_source(
    conf,
    allow_block: bool = False,
    block_interval: "tuple[int, int] | None" = None,
) -> Source:
    """``allow_block``: set by entry points whose pipelines consume
    ParsedBlocks (linear: default labels; logistic: unit_label_fn; k-means:
    numeric columns, which passes ``block_interval`` to override the
    parser's retweet-count filter — it keeps ALL retweets)."""
    if conf.ingest == "block" and not allow_block:
        raise SystemExit(
            "--ingest block is not wired for this entry point; "
            "use --ingest object"
        )
    if conf.ingest == "block" and conf.source != "replay":
        raise SystemExit("--ingest block requires --source replay")
    if conf.source == "replay":
        if not conf.replayFile:
            raise SystemExit("--source replay requires --replayFile <path.jsonl>")
        if conf.ingest == "block":
            from ..streaming.sources import BlockReplayFileSource

            if conf.replaySpeed:
                raise SystemExit(
                    "--ingest block replays as fast as possible; "
                    "drop --replaySpeed or use --ingest object"
                )
            if conf.hashOn != "device":
                raise SystemExit(
                    "--ingest block ships raw code units (device hashing); "
                    "--hashOn host requires --ingest object"
                )
            begin, end = (
                block_interval
                if block_interval is not None
                else (conf.numRetweetBegin, conf.numRetweetEnd)
            )
            source: Source = BlockReplayFileSource(
                conf.replayFile, num_retweet_begin=begin, num_retweet_end=end
            )
            return _wrap_faults(source, conf)
        source = ReplayFileSource(conf.replayFile, speed=conf.replaySpeed)
    elif conf.source == "synthetic":
        source = SyntheticSource(rate=conf.replaySpeed or 0.0)
    elif conf.source == "twitter":
        from ..streaming.twitter import TwitterSource

        source = TwitterSource.from_properties()
    else:
        raise SystemExit(f"unknown --source {conf.source!r}")
    return _wrap_faults(source, conf)


def _wrap_faults(source: Source, conf) -> Source:
    if conf.faultEvery > 0:
        from ..streaming.faults import FaultInjectingSource

        # finite replay files need the crash cap to avoid livelock (each
        # restart re-reads from the start); unbounded sources keep crashing
        source = FaultInjectingSource(
            source,
            crash_every=conf.faultEvery,
            max_crashes=3 if conf.source == "replay" else 0,
        )
    return source


def build_model(conf):
    """Single-device fused learner on one chip; mesh-sharded learner when the
    backend exposes several devices (or local[N] caps a virtual CPU mesh) —
    the CLI face of BASELINE config #5's data-parallel scale-up. Returns
    (model, required row multiple for batches)."""
    import jax

    shards = conf.local_shards()
    n_devices = len(jax.devices())
    n_data = min(shards, n_devices) if shards else n_devices
    if n_data > 1:
        from ..parallel import ParallelSGDModel, make_mesh

        mesh = make_mesh(num_data=n_data, devices=jax.devices()[:n_data])
        log.info("mesh-sharded training: %d-way data parallel", n_data)
        return ParallelSGDModel.from_conf(conf, mesh), n_data
    return StreamingLinearRegressionWithSGD.from_conf(conf), 1


def warmup_compile(stream, model) -> None:
    """Pre-compile the step for the known batch shape BEFORE the stream
    starts, so the first wall-clock micro-batch doesn't swallow the whole
    compile-time backlog (~30 s on a cold TPU chip, during which a live
    source keeps producing). Only possible when --batchBucket AND
    --tokenBucket pin the full XLA program shape (read from the stream's
    own configuration — the single source of truth). The warm batch comes
    from the stream's OWN featurize dispatch (``featurize_empty``) so it
    compiles exactly the program the stream will run; an all-padding batch
    is semantically a no-op for the learner (zero-sample iterations leave
    weights untouched)."""
    if stream.row_bucket <= 0 or stream.token_bucket <= 0:
        return
    import time as _time

    t0 = _time.perf_counter()
    model.step(stream.featurize_empty())
    log.info(
        "pre-compiled the train step for buckets (%d, %d) in %.1fs",
        stream.row_bucket, stream.token_bucket, _time.perf_counter() - t0,
    )


def run(conf: ConfArguments, max_batches: int = 0) -> dict:
    log.info("Initializing session stats...")
    session = SessionStats(conf).open()

    log.info("Initializing TPU-native streaming model...")
    select_backend(conf)
    featurizer = Featurizer.from_conf(conf)
    model, row_multiple = build_model(conf)

    log.info("Initializing streaming context... %s sec/batch", conf.seconds)
    ssc = StreamingContext(batch_interval=conf.seconds)
    stream = ssc.source_stream(
        build_source(conf, allow_block=True), featurizer,
        row_bucket=conf.batchBucket, token_bucket=conf.tokenBucket,
        row_multiple=row_multiple,
        device_hash=conf.hashOn == "device",
    )

    totals = {"count": 0, "batches": 0}

    # checkpoint/resume (upgrade over the reference, SURVEY.md §5.4)
    ckpt = None
    if conf.checkpointDir:
        from ..checkpoint import Checkpointer

        ckpt = Checkpointer(conf.checkpointDir)
        restored = ckpt.restore()
        if restored is not None:
            weights, meta = restored
            model.set_initial_weights(weights)
            totals["count"] = int(meta.get("count", 0))
            totals["batches"] = int(meta.get("batches", 0))
            log.info(
                "resumed from checkpoint step %s (count=%s)",
                meta.get("step"), totals["count"],
            )

    from ..utils.tracing import Tracer

    tracer = Tracer(conf.profileDir)
    last_saved = {"step": totals["batches"]}

    def on_batch(batch, _batch_time) -> None:
        if batch.num_valid == 0:
            log.debug("batch: 0")
            return
        out = model.step(batch)
        b = int(out.count)
        totals["count"] += b
        totals["batches"] += 1
        mse = round_half_up(float(out.mse))
        real_stdev = round_half_up(float(out.real_stdev))
        pred_stdev = round_half_up(float(out.pred_stdev))
        valid = batch.mask.astype(bool)
        real = batch.label[valid].astype(np.float64)
        pred = np.asarray(out.predictions)[valid].astype(np.float64)
        # the reference's debug channel (LinearRegression.scala:67-74)
        print(
            f"count: {totals['count']}  batch: {b}  mse: {mse}  "
            f"stdev (real, pred): ({int(real_stdev)}, {int(pred_stdev)})",
            flush=True,
        )
        session.update(
            totals["count"], b, mse, real_stdev, pred_stdev, real, pred
        )
        if ckpt is not None and conf.checkpointEvery > 0 and (
            totals["batches"] % conf.checkpointEvery == 0
        ):
            ckpt.save(
                totals["batches"], model.latest_weights,
                {"count": totals["count"], "batches": totals["batches"]},
            )
            last_saved["step"] = totals["batches"]
        if max_batches and totals["batches"] >= max_batches:
            ssc.request_stop()

    stream.foreach_batch(on_batch)

    warmup_compile(stream, model)

    log.info("Starting the streaming computation...")
    tracer.start()
    ssc.start()
    try:
        ssc.await_termination()
    except KeyboardInterrupt:
        pass
    finally:
        ssc.stop()
        tracer.stop()
        if ckpt is not None and totals["batches"] != last_saved["step"]:
            ckpt.save(
                totals["batches"], model.latest_weights,
                {"count": totals["count"], "batches": totals["batches"]},
            )
    return totals


def main(argv=None) -> None:
    conf = (
        ConfArguments()
        .setAppName("twitter-stream-ml-linear-regression")
        .parse(list(sys.argv[1:] if argv is None else argv))
    )
    totals = run(conf)
    log.info("done: %s tweets in %s batches", totals["count"], totals["batches"])


if __name__ == "__main__":
    main()
