"""Serving entry point — the read path as a product (ISSUE 9 / ROADMAP 1).

Boots the existing dashboard web server IN-PROCESS with a ServingPlane
attached (``POST /api/predict`` + ``GET /api/serving``), promotes the newest
servable snapshot from ``--checkpointDir`` (verified + quality stamp
ok/warn — the ``tools/model_report.py --gate`` predicate), keeps promoting
as the trainer writes new checkpoints (hot-swap between dispatches, never
tearing an in-flight batch), and publishes the ``Serving`` telemetry view on
a fixed cadence.

Deployment shape: the TRAIN process writes verified checkpoints; THIS
process reads them off disk and owns the query traffic — the handoff is the
filesystem, so serving adds zero host fetches and zero collectives to the
train path (the PR 1/5 law, asserted by counting in tests/test_serving.py).
Run both against the same ``--checkpointDir``:

    python -m twtml_tpu.apps.linear_regression --checkpointDir ck \
        --checkpointEvery 64 ...
    python -m twtml_tpu.apps.serve --checkpointDir ck --servePort 8888

    curl -s localhost:8888/api/predict -d '{"rows": [{"text": "hello"}]}'
"""

from __future__ import annotations

import sys
import threading
import time

from ..config import ConfArguments
from ..utils import get_logger
from .common import install_blackbox, install_chaos, install_trace, select_backend

log = get_logger("apps.serve")

PUBLISH_EVERY_S = 2.0


def run(conf: ConfArguments, started=None, stop_event=None,
        max_seconds: float = 0.0) -> dict:
    """Boot snapshot → plane → promoter → web server; serve until
    ``stop_event``/SIGINT/``max_seconds``. ``started(server, plane,
    promoter)`` fires once the front door is live (the test hook). Returns
    the final serving stats view."""
    if conf.multihost() is not None:
        raise SystemExit(
            "the serve entry point is single-host: scale reads by running "
            "N serve processes against replicas of the checkpoint directory"
        )
    if not conf.checkpointDir:
        raise SystemExit(
            "--checkpointDir is required: serving promotes verified "
            "checkpoint snapshots (train with --checkpointDir/"
            "--checkpointEvery to produce them)"
        )
    select_backend(conf)
    install_trace(conf)
    install_chaos(conf)
    install_blackbox(conf)

    from ..serving import ServingPlane, SnapshotPromoter, load_servable
    from ..telemetry.web_client import WebClient
    from ..web.server import Server

    snapshot, reason = load_servable(conf.checkpointDir)
    if snapshot is None:
        raise SystemExit(f"no servable snapshot: {reason}")
    log.info(
        "initial snapshot: step %d, %d tenant(s) — %s",
        snapshot.step, snapshot.num_tenants, reason,
    )
    engine = None
    if getattr(conf, "abtest", "off") == "on":
        # champion/challenger (ISSUE 11): the tenant-stack snapshot's
        # variants ride ONE mirrored predict program — the champion
        # answers, challengers shadow-score, and per-tenant quality stamps
        # auto-promote the champion pointer through the is_promotable gate
        if snapshot.num_tenants < 2:
            raise SystemExit(
                "--abtest on needs a tenant-stack checkpoint "
                f"({snapshot.num_tenants} tenant(s) found): train with "
                "--tenants M >= 2 so the snapshot carries M variants"
            )
        from ..serving.abtest import ChampionEngine

        import jax.numpy as jnp

        engine = ChampionEngine(
            num_text_features=conf.numTextFeatures,
            num_tenants=snapshot.num_tenants,
            tenant_key=getattr(conf, "tenantKey", "hash"),
            dtype=jnp.dtype(getattr(conf, "dtype", "float32")),
        )
    plane = ServingPlane.from_conf(conf, snapshot, engine=engine)
    log.info("pre-compiling the predict program...")
    plane.warmup()
    plane.start()
    promoter = SnapshotPromoter(
        conf.checkpointDir, plane,
        poll_s=float(getattr(conf, "servePromoteEvery", 5.0) or 5.0),
    ).start()
    server = Server(port=conf.servePort).attach_serving(plane)
    server.start_background()
    port = server._runner.addresses[0][1]
    web = WebClient(f"http://127.0.0.1:{port}",
                    timeout=float(getattr(conf, "webTimeout", 2.0)))
    log.info("serving front door live: POST /api/predict on port %d", port)
    if started is not None:
        started(server, plane, promoter)

    t0 = time.monotonic()
    stop_event = stop_event or threading.Event()
    try:
        while not stop_event.is_set():
            if max_seconds and time.monotonic() - t0 >= max_seconds:
                break
            if plane.failed:
                break
            stop_event.wait(PUBLISH_EVERY_S)
            try:
                # the Serving view rides the same additive jsonClass wire
                # as every dashboard payload (cache + websocket broadcast)
                web.serving(plane.stats())
            except Exception:
                log.debug("serving publish failed", exc_info=True)
    except KeyboardInterrupt:
        pass
    finally:
        promoter.stop()
        plane.stop()
        stats = plane.stats()
        server.stop()
        from ..telemetry import trace as pipeline_trace

        pipeline_trace.uninstall()
    if plane.failed:
        raise RuntimeError(
            "serving plane aborted by the fetch watchdog (wedged transport); "
            "in-flight requests were rejected, not hung — see critical log"
        )
    log.info(
        "serve session done: %s requests, %s rows, qps %s",
        stats["requests"], stats["rows"], stats["qps"],
    )
    return stats


def main(argv=None) -> None:
    conf = (
        ConfArguments()
        .setAppName("twitter-stream-ml-serve")
        .parse(list(sys.argv[1:] if argv is None else argv))
    )
    run(conf)


if __name__ == "__main__":
    main()
