"""Shared app runtime: backend selection, source construction, and mesh-aware
model construction for every entry point.

The reference's one-flag cluster story (``--master local[N]`` / cluster
masters, ConfArguments.scala:95-98) applies to ALL its entry points because
Spark owns the runtime. Here the equivalent lives in ``build_model``: any
SGD-family app scales from one chip to a data-parallel device mesh by
constructing its learner through it (apps/linear_regression.py,
apps/logistic_regression.py; k-means has its own mesh-aware model,
models/kmeans.py), with the CLI face unchanged.
"""

from __future__ import annotations

from ..models.linear import StreamingLinearRegressionWithSGD
from ..streaming.sources import ReplayFileSource, Source, SyntheticSource
from ..utils import get_logger

log = get_logger("apps.common")


def select_backend(conf) -> None:
    """--backend {auto,tpu,cpu}: auto keeps jax's platform choice (TPU when
    attached); cpu forces the host backend (the reference's local[*] analog,
    ConfArguments.scala:54-56)."""
    import jax

    from ..utils import set_cpu_device_count_hint

    shards = conf.local_shards()
    if shards:
        # honor the local[N] hint before any backend initialization; it only
        # affects the CPU platform, so it's harmless when TPU wins auto
        if not set_cpu_device_count_hint(shards):
            log.warning("backend already initialized; local[%d] hint dropped", shards)
    if conf.backend == "cpu":
        # jax_platforms silently no-ops when a backend is already live, so
        # verify the outcome instead of guessing the pre-state (and this
        # first jax.default_backend() call initializes cpu when it did work)
        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                "--backend cpu requested but a non-cpu backend is already "
                "initialized in this process"
            )
    elif conf.backend == "tpu":
        kinds = {d.platform for d in jax.devices()}
        if "cpu" in kinds and len(kinds) == 1:
            raise RuntimeError("--backend tpu requested but only CPU devices present")


def build_source(
    conf,
    allow_block: bool = False,
    block_interval: "tuple[int, int] | None" = None,
) -> Source:
    """``allow_block``: set by entry points whose pipelines consume
    ParsedBlocks (linear: default labels; logistic: unit_label_fn; k-means:
    numeric columns, which passes ``block_interval`` to override the
    parser's retweet-count filter — it keeps ALL retweets)."""
    if conf.ingest == "block" and not allow_block:
        raise SystemExit(
            "--ingest block is not wired for this entry point; "
            "use --ingest object"
        )
    if conf.ingest == "block" and conf.source != "replay":
        raise SystemExit("--ingest block requires --source replay")
    if conf.source == "replay":
        if not conf.replayFile:
            raise SystemExit("--source replay requires --replayFile <path.jsonl>")
        if conf.ingest == "block":
            from ..streaming.sources import BlockReplayFileSource

            if conf.replaySpeed:
                raise SystemExit(
                    "--ingest block replays as fast as possible; "
                    "drop --replaySpeed or use --ingest object"
                )
            if conf.hashOn != "device":
                raise SystemExit(
                    "--ingest block ships raw code units (device hashing); "
                    "--hashOn host requires --ingest object"
                )
            begin, end = (
                block_interval
                if block_interval is not None
                else (conf.numRetweetBegin, conf.numRetweetEnd)
            )
            source: Source = BlockReplayFileSource(
                conf.replayFile, num_retweet_begin=begin, num_retweet_end=end
            )
            return _wrap_faults(source, conf)
        source = ReplayFileSource(conf.replayFile, speed=conf.replaySpeed)
    elif conf.source == "synthetic":
        source = SyntheticSource(rate=conf.replaySpeed or 0.0)
    elif conf.source == "twitter":
        from ..streaming.twitter import TwitterSource

        source = TwitterSource.from_properties()
    else:
        raise SystemExit(f"unknown --source {conf.source!r}")
    return _wrap_faults(source, conf)


def _wrap_faults(source: Source, conf) -> Source:
    if conf.faultEvery > 0:
        from ..streaming.faults import FaultInjectingSource

        # finite replay files need the crash cap to avoid livelock (each
        # restart re-reads from the start); unbounded sources keep crashing
        source = FaultInjectingSource(
            source,
            crash_every=conf.faultEvery,
            max_crashes=3 if conf.source == "replay" else 0,
        )
    return source


def mesh_shape(conf) -> int:
    """Data-axis size the conf + attached devices call for: the number of
    visible devices, capped by the ``--master local[N]`` hint."""
    import jax

    shards = conf.local_shards()
    n_devices = len(jax.devices())
    return min(shards, n_devices) if shards else n_devices


def build_mesh(conf, what: str = "training"):
    """The one-flag cluster story: the ('data',) mesh the conf calls for, or
    None when a single device (or local[1]) keeps execution unsharded. Every
    entry point routes through here so device selection / local[N] capping
    can never diverge between apps."""
    n_data = mesh_shape(conf)
    if n_data <= 1:
        return None
    import jax

    from ..parallel import make_mesh

    log.info("mesh-sharded %s: %d-way data parallel", what, n_data)
    return make_mesh(num_data=n_data, devices=jax.devices()[:n_data])


def build_model(conf, model_cls=StreamingLinearRegressionWithSGD):
    """Single-device fused learner on one chip; mesh-sharded learner when the
    backend exposes several devices (or local[N] caps a virtual CPU mesh) —
    the CLI face of BASELINE config #5's data-parallel scale-up, for ANY
    SGD-family learner (the class's residual/prediction knobs carry over to
    the sharded step). Returns (model, required row multiple for batches)."""
    mesh = build_mesh(conf, what=f"training ({model_cls.__name__})")
    if mesh is not None:
        from ..parallel import ParallelSGDModel

        model = ParallelSGDModel.from_conf(
            conf, mesh,
            residual_fn=model_cls.residual_fn,
            prediction_fn=model_cls.prediction_fn,
            round_predictions=model_cls.round_predictions,
        )
        return model, model.num_data
    return model_cls.from_conf(conf), 1


class AppCheckpoint:
    """``--checkpointDir``/``--checkpointEvery`` wiring shared by every entry
    point (model checkpoint/resume is this framework's upgrade over the
    reference, SURVEY.md §5.4 — a restarted reference job begins from
    zeros). Restores state + counters at startup, saves on a cadence-
    crossing test at weight-current boundaries (so ``--superBatch`` groups
    snap to the first boundary at/after each cadence point instead of
    stretching to lcm), and saves final state at shutdown.

    ``get_state()`` returns the checkpointable arrays (flat dict or one
    array); ``set_state(state)`` restores them into the model."""

    def __init__(self, conf, get_state, set_state, totals: dict):
        self._ckpt = None
        self._get_state = get_state
        self.every = int(getattr(conf, "checkpointEvery", 0) or 0)
        if not conf.checkpointDir:
            self._last = 0
            return
        from ..checkpoint import Checkpointer

        self._ckpt = Checkpointer(conf.checkpointDir)
        restored = self._ckpt.restore()
        if restored is not None:
            state, meta = restored
            set_state(state)
            totals["count"] = int(meta.get("count", 0))
            totals["batches"] = int(meta.get("batches", 0))
            log.info(
                "resumed from checkpoint step %s (count=%s)",
                meta.get("step"), totals["count"],
            )
        self._last = totals["batches"]

    def _save(self, totals: dict) -> None:
        self._ckpt.save(
            totals["batches"], self._get_state(),
            {"count": totals["count"], "batches": totals["batches"]},
        )
        self._last = totals["batches"]

    def maybe_save(self, totals: dict, at_boundary: bool = True) -> None:
        """Cadence save — call per batch from the app's handler."""
        if self._ckpt is not None and at_boundary and self.every > 0 and (
            totals["batches"] - self._last >= self.every
        ):
            self._save(totals)

    def final_save(self, totals: dict) -> None:
        """Shutdown save when anything advanced past the last save."""
        if self._ckpt is not None and totals["batches"] != self._last:
            self._save(totals)


class SuperBatcher:
    """Group K featurized micro-batches into ONE device dispatch
    (``model.step_many``: a lax.scan of the ordinary train step) and re-emit
    each batch's StepOutput to ``handle`` in order.

    Why: in replay/back-to-back regimes every per-batch stats fetch costs a
    full transport round trip (~100 ms through this build's TPU tunnel —
    BENCHMARKS.md), capping the telemetry-on path at ~17k tweets/s; fetching
    K batches' stats as one array lifts that ~K× (measured ~17k → ~100k at
    K=8, batch 2048). Semantics are unchanged: batch boundaries, per-batch
    stats, predict-then-train ordering, and final weights are bitwise those
    of K sequential ``step`` calls (tests/test_superbatch.py). Requires
    pinned batch buckets (every grouped batch must share one shape).

    ``handle(out, batch, batch_time)`` receives plain-numpy per-batch
    outputs; call ``flush()`` after the stream terminates to drain a
    partial final group.

    Only contiguous SAME-SHAPE batches group (one compiled scan program): a
    batch that overflowed a pinned bucket, or flipped the units wire dtype,
    flushes the pending group first and starts its own — it is never
    silently dropped, and partial groups run as plain steps (identical
    math, no one-off scan compiles at odd lengths)."""

    def __init__(self, model, k: int, handle):
        self.model = model
        self.k = k
        self.handle = handle
        self._buf: list = []
        self._sig = None

    @staticmethod
    def _signature(batch):
        return (type(batch),) + tuple((a.shape, a.dtype) for a in batch)

    def on_batch(self, batch, batch_time) -> None:
        sig = self._signature(batch)
        if self._buf and sig != self._sig:
            self.flush()  # shape/dtype changed: close the group, never drop
        self._sig = sig
        self._buf.append((batch, batch_time))
        if len(self._buf) >= self.k:
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        import jax

        from ..features.batch import stack_batches
        from ..models.base import StepOutput

        group, self._buf = self._buf, []
        if len(group) < self.k:
            # partial group (tail, or a shape change): plain steps — the
            # same math, and no fresh scan compile for a one-off length
            for batch, t in group:
                out = jax.device_get(self.model.step(batch))
                self.handle(out, batch, t, at_boundary=True)
            return
        outs = self.model.step_many(stack_batches([b for b, _ in group]))
        host = jax.device_get(outs)  # ONE transfer for all K batches' stats
        last = len(group) - 1
        for k, (batch, t) in enumerate(group):
            self.handle(
                StepOutput(*(f[k] for f in host)), batch, t,
                at_boundary=(k == last),
            )


def attach_super_batcher(conf, stream, model, handle):
    """Wire the app's per-batch ``handle(out, batch, t, at_boundary)`` to the
    stream: plain step-then-handle by default, grouped through a
    SuperBatcher when ``--superBatch K`` applies. Returns
    ``(flush, effective_k)`` — the app must invoke ``flush`` after
    termination (drains a partial final group) and may pass ``effective_k``
    to ``warmup_compile`` so the scan program pre-compiles too.

    ``at_boundary`` is True whenever the model's weights are current as of
    this batch (always, except mid-group under a superbatch) — the guard for
    side effects that read ``model.latest_weights``, e.g. checkpoints.

    Group-granular caps: a whole group dispatches as one program, so a
    ``max_batches``-style stop lands on the first group boundary at/after
    the cap (up to K−1 extra batches, deterministic — the documented
    trade of the flag).

    The flag applies only to back-to-back regimes (``--seconds 0``): under a
    wall clock it would delay live telemetry by K intervals, so it downgrades
    with a warning. Grouped batches must share one XLA shape, which pinned
    buckets guarantee — unpinned buckets are an error, matching the
    pre-compile contract (``warmup_compile``)."""
    k = int(getattr(conf, "superBatch", 1) or 1)
    if k > 1 and conf.seconds > 0:
        log.warning(
            "--superBatch %d ignored: wall-clock streaming (--seconds %s) "
            "would delay live stats by %d intervals", k, conf.seconds, k,
        )
        k = 1
    if k > 1 and (stream.row_bucket <= 0 or stream.token_bucket <= 0):
        raise ValueError(
            "--superBatch needs pinned shapes: set --batchBucket and "
            "--tokenBucket so every grouped batch compiles to one program"
        )

    import jax

    def skip_empty(fn):
        def cb(batch, t):
            if batch.num_valid == 0:
                log.debug("batch: 0")
                return
            fn(batch, t)

        return cb

    if k <= 1:
        def per_batch(batch, t):
            # ONE host transfer for the whole StepOutput: the handlers read
            # every field, and sequential scalar fetches each pay a full
            # transport round trip (BENCHMARKS.md telemetry regime)
            out = jax.device_get(model.step(batch))
            handle(out, batch, t, at_boundary=True)

        stream.foreach_batch(skip_empty(per_batch))
        return (lambda: None), 1

    batcher = SuperBatcher(model, k, handle)
    stream.foreach_batch(skip_empty(batcher.on_batch))
    return batcher.flush, k


def warmup_compile(stream, model, super_batch: int = 1) -> None:
    """Pre-compile the step for the known batch shape BEFORE the stream
    starts, so the first wall-clock micro-batch doesn't swallow the whole
    compile-time backlog (~30 s on a cold TPU chip, during which a live
    source keeps producing). Only possible when --batchBucket AND
    --tokenBucket pin the full XLA program shape (read from the stream's
    own configuration — the single source of truth). The warm batch comes
    from the stream's OWN featurize dispatch (``featurize_empty``) so it
    compiles exactly the program the stream will run; an all-padding batch
    is semantically a no-op for the learner (zero-sample iterations leave
    weights untouched)."""
    if stream.row_bucket <= 0 or stream.token_bucket <= 0:
        return
    import time as _time

    import numpy as np

    from ..features.batch import UnitBatch

    t0 = _time.perf_counter()
    empty = stream.featurize_empty()
    variants = [empty]
    if isinstance(empty, UnitBatch) and empty.units.dtype == np.uint8:
        # the units wire dtype is per-batch metadata (uint8 iff every row
        # is ASCII — featurizer._pad_ragged_units): warm BOTH programs so
        # a stream's first non-ASCII tweet doesn't stall mid-flight
        variants.append(empty._replace(units=empty.units.astype(np.uint16)))
    for v in variants:
        model.step(v)
    if super_batch > 1:
        # --superBatch dispatches a scanned program too: warm it for the
        # same shapes/dtypes so the first full group doesn't stall
        from ..features.batch import stack_batches

        for v in variants:
            model.step_many(stack_batches([v] * super_batch))
    log.info(
        "pre-compiled the train step for buckets (%d, %d) in %.1fs",
        stream.row_bucket, stream.token_bucket, _time.perf_counter() - t0,
    )
