"""Shared app runtime: backend selection, source construction, and mesh-aware
model construction for every entry point.

The reference's one-flag cluster story (``--master local[N]`` / cluster
masters, ConfArguments.scala:95-98) applies to ALL its entry points because
Spark owns the runtime. Here the equivalent lives in ``build_model``: any
SGD-family app scales from one chip to a data-parallel device mesh by
constructing its learner through it (apps/linear_regression.py,
apps/logistic_regression.py; k-means has its own mesh-aware model,
models/kmeans.py), with the CLI face unchanged.
"""

from __future__ import annotations

from ..models.linear import StreamingLinearRegressionWithSGD
from ..streaming.sources import ReplayFileSource, Source, SyntheticSource
from ..utils import get_logger

log = get_logger("apps.common")


def select_backend(conf) -> None:
    """--backend {auto,tpu,cpu}: auto keeps jax's platform choice (TPU when
    attached); cpu forces the host backend (the reference's local[*] analog,
    ConfArguments.scala:54-56)."""
    import jax

    from ..utils import set_cpu_device_count_hint

    shards = conf.local_shards()
    if shards:
        # honor the local[N] hint before any backend initialization; it only
        # affects the CPU platform, so it's harmless when TPU wins auto
        if not set_cpu_device_count_hint(shards):
            log.warning("backend already initialized; local[%d] hint dropped", shards)
    if conf.backend == "cpu":
        # jax_platforms silently no-ops when a backend is already live, so
        # verify the outcome instead of guessing the pre-state (and this
        # first jax.default_backend() call initializes cpu when it did work)
        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                "--backend cpu requested but a non-cpu backend is already "
                "initialized in this process"
            )
    elif conf.backend == "tpu":
        kinds = {d.platform for d in jax.devices()}
        if "cpu" in kinds and len(kinds) == 1:
            raise RuntimeError("--backend tpu requested but only CPU devices present")


def build_source(
    conf,
    allow_block: bool = False,
    block_interval: "tuple[int, int] | None" = None,
) -> Source:
    """``allow_block``: set by entry points whose pipelines consume
    ParsedBlocks (linear: default labels; logistic: unit_label_fn; k-means:
    numeric columns, which passes ``block_interval`` to override the
    parser's retweet-count filter — it keeps ALL retweets)."""
    if conf.ingest == "block" and not allow_block:
        raise SystemExit(
            "--ingest block is not wired for this entry point; "
            "use --ingest object"
        )
    if conf.ingest == "block" and conf.source != "replay":
        raise SystemExit("--ingest block requires --source replay")
    if conf.source == "replay":
        if not conf.replayFile:
            raise SystemExit("--source replay requires --replayFile <path.jsonl>")
        if conf.ingest == "block":
            from ..streaming.sources import BlockReplayFileSource

            if conf.replaySpeed:
                raise SystemExit(
                    "--ingest block replays as fast as possible; "
                    "drop --replaySpeed or use --ingest object"
                )
            if conf.hashOn != "device":
                raise SystemExit(
                    "--ingest block ships raw code units (device hashing); "
                    "--hashOn host requires --ingest object"
                )
            begin, end = (
                block_interval
                if block_interval is not None
                else (conf.numRetweetBegin, conf.numRetweetEnd)
            )
            source: Source = BlockReplayFileSource(
                conf.replayFile, num_retweet_begin=begin, num_retweet_end=end
            )
            return _wrap_faults(source, conf)
        source = ReplayFileSource(conf.replayFile, speed=conf.replaySpeed)
    elif conf.source == "synthetic":
        source = SyntheticSource(rate=conf.replaySpeed or 0.0)
    elif conf.source == "twitter":
        from ..streaming.twitter import TwitterSource

        source = TwitterSource.from_properties()
    else:
        raise SystemExit(f"unknown --source {conf.source!r}")
    return _wrap_faults(source, conf)


def _wrap_faults(source: Source, conf) -> Source:
    if conf.faultEvery > 0:
        from ..streaming.faults import FaultInjectingSource

        # finite replay files need the crash cap to avoid livelock (each
        # restart re-reads from the start); unbounded sources keep crashing
        source = FaultInjectingSource(
            source,
            crash_every=conf.faultEvery,
            max_crashes=3 if conf.source == "replay" else 0,
        )
    return source


def mesh_shape(conf) -> int:
    """Data-axis size the conf + attached devices call for: the number of
    visible devices, capped by the ``--master local[N]`` hint."""
    import jax

    shards = conf.local_shards()
    n_devices = len(jax.devices())
    return min(shards, n_devices) if shards else n_devices


def build_mesh(conf, what: str = "training"):
    """The one-flag cluster story: the ('data',) mesh the conf calls for, or
    None when a single device (or local[1]) keeps execution unsharded. Every
    entry point routes through here so device selection / local[N] capping
    can never diverge between apps."""
    n_data = mesh_shape(conf)
    if n_data <= 1:
        return None
    import jax

    from ..parallel import make_mesh

    log.info("mesh-sharded %s: %d-way data parallel", what, n_data)
    return make_mesh(num_data=n_data, devices=jax.devices()[:n_data])


def build_model(conf, model_cls=StreamingLinearRegressionWithSGD):
    """Single-device fused learner on one chip; mesh-sharded learner when the
    backend exposes several devices (or local[N] caps a virtual CPU mesh) —
    the CLI face of BASELINE config #5's data-parallel scale-up, for ANY
    SGD-family learner (the class's residual/prediction knobs carry over to
    the sharded step). Returns (model, required row multiple for batches)."""
    mesh = build_mesh(conf, what=f"training ({model_cls.__name__})")
    if mesh is not None:
        from ..parallel import ParallelSGDModel

        model = ParallelSGDModel.from_conf(
            conf, mesh,
            residual_fn=model_cls.residual_fn,
            prediction_fn=model_cls.prediction_fn,
            round_predictions=model_cls.round_predictions,
        )
        return model, model.num_data
    return model_cls.from_conf(conf), 1


def warmup_compile(stream, model) -> None:
    """Pre-compile the step for the known batch shape BEFORE the stream
    starts, so the first wall-clock micro-batch doesn't swallow the whole
    compile-time backlog (~30 s on a cold TPU chip, during which a live
    source keeps producing). Only possible when --batchBucket AND
    --tokenBucket pin the full XLA program shape (read from the stream's
    own configuration — the single source of truth). The warm batch comes
    from the stream's OWN featurize dispatch (``featurize_empty``) so it
    compiles exactly the program the stream will run; an all-padding batch
    is semantically a no-op for the learner (zero-sample iterations leave
    weights untouched)."""
    if stream.row_bucket <= 0 or stream.token_bucket <= 0:
        return
    import time as _time

    import numpy as np

    from ..features.batch import UnitBatch

    t0 = _time.perf_counter()
    empty = stream.featurize_empty()
    model.step(empty)
    if isinstance(empty, UnitBatch) and empty.units.dtype == np.uint8:
        # the units wire dtype is per-batch metadata (uint8 iff every row
        # is ASCII — featurizer._pad_ragged_units): warm BOTH programs so
        # a stream's first non-ASCII tweet doesn't stall mid-flight
        model.step(empty._replace(units=empty.units.astype(np.uint16)))
    log.info(
        "pre-compiled the train step for buckets (%d, %d) in %.1fs",
        stream.row_bucket, stream.token_bucket, _time.perf_counter() - t0,
    )
