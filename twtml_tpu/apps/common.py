"""Shared app runtime: backend selection, source construction, and mesh-aware
model construction for every entry point.

The reference's one-flag cluster story (``--master local[N]`` / cluster
masters, ConfArguments.scala:95-98) applies to ALL its entry points because
Spark owns the runtime. Here the equivalent lives in ``build_model``: any
SGD-family app scales from one chip to a data-parallel device mesh by
constructing its learner through it (apps/linear_regression.py,
apps/logistic_regression.py; k-means has its own mesh-aware model,
models/kmeans.py), with the CLI face unchanged.
"""

from __future__ import annotations

from ..models.linear import StreamingLinearRegressionWithSGD
from ..streaming import faults as _faults
from ..streaming import journal as _journal
from ..streaming.sources import ReplayFileSource, Source, SyntheticSource
from ..telemetry import blackbox as _blackbox
from ..telemetry import freshness as _freshness
from ..telemetry import lineage as _lineage
from ..telemetry import metrics as _metrics
from ..telemetry import modelwatch as _modelwatch
from ..telemetry import sideband as _sideband
from ..telemetry import trace as _trace
from ..utils import get_logger

log = get_logger("apps.common")

# fetch-watchdog policy (see FetchWatchdog): the deadline derives from the
# health monitor's rolling fetch RTT — generous multiples, because tunnel
# stalls legitimately burst for minutes and a re-issue only helps a LOST
# request, not a stalled transport
FETCH_DEADLINE_MULT = 25.0
FETCH_DEADLINE_MIN_S = 30.0
FETCH_DEADLINE_MAX_S = 180.0
FETCH_RETRIES = 3


class FetchAbort(RuntimeError):
    """The fetch watchdog exhausted its retries: the run is aborting."""


def init_distributed(conf) -> bool:
    """The cluster face of every entry point (the reference's one-flag story:
    ``--master spark://host:port`` runs the same main on a cluster,
    ConfArguments.scala:95-98, README.md:44-55). Validates --master (bad
    schemes are rejected, not ignored), and when ``--coordinator``/
    ``twtml://`` asks for a multi-host group, joins it via
    ``parallel.distributed.initialize`` — which MUST happen before anything
    initializes the XLA backend, so apps call this first.

    ``--elastic on`` routes group formation through the elastic runtime
    instead (parallel/elastic.py): epoch-addressed custom clients whose
    dead-peer reaction is OURS (the lockstep watchdog + membership plane),
    not the coordination service's process-kill. A RESTARTED host finds a
    live run via the lead's beacon and parks for admission at the next
    epoch boundary — rejoining a mid-flight fleet with the same CLI that
    launched it.

    Returns True when this process should own telemetry/prints (the lead —
    process 0, or any single-host run)."""
    conf.validate_master()
    mh = conf.multihost()
    if mh is None:
        return True
    if conf.backend == "cpu":
        # cross-process CPU collectives need gloo selected BEFORE the
        # backend initializes (jax 0.4.x wires it to the distributed
        # client at backend creation) — and the jax.process_index() probe
        # at the end of THIS function is the first backend init. Without
        # this, the documented multi-host CLI dies at its first
        # collective with "Multiprocess computations aren't implemented
        # on the CPU backend" (the test harness had set the flag by hand
        # since PR 1, which is why only raw CLI runs ever hit it).
        import jax

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    coordinator, num_processes, process_id = mh
    if getattr(conf, "elastic", "off") == "on":
        return _init_elastic(conf, coordinator, num_processes, process_id)
    from ..parallel.distributed import initialize

    initialize(coordinator, num_processes, process_id)
    import jax

    return jax.process_index() == 0


def _init_elastic(conf, coordinator: str, num_processes: int,
                  process_id: int) -> bool:
    """Elastic group formation. Cold start: everyone forms epoch 0 with
    the full launch membership. A restarted host (the run is already live
    and this uid is not — or no longer — a member) parks at the beacon
    and joins at the epoch boundary the lead commits for it."""
    import os as _os
    import time as _time

    from ..parallel import elastic as _elastic

    if num_processes is None or process_id is None:
        raise SystemExit(
            "--elastic on needs explicit --numProcesses/--processId (or a "
            "twtml:// master with both): elastic membership has no "
            "cluster-env auto-detection"
        )
    host, _, port = coordinator.rpartition(":")
    runtime = _elastic.install_runtime(
        host or "127.0.0.1", int(port), process_id
    )
    launch_members = list(range(num_processes))
    if not getattr(conf, "checkpointDir", ""):
        log.warning(
            "--elastic on without --checkpointDir: membership changes "
            "re-synchronize from the lead's LIVE state instead of a "
            "verified on-disk checkpoint (reduced rollback guarantee)"
        )
    if process_id == 0 and runtime.beacon is not None:
        # cold start: uid 0 owns the beacon and leads the launch
        runtime.beacon.publish("forming", 0, launch_members)
        runtime.form(0, launch_members)
        import jax

        return jax.process_index() == 0
    # uid 0 with beacon=None is a RESTARTED ex-lead: an elected successor
    # owns the beacon port now, so it rejoins through the same follower
    # hello/park path as everyone else — demotion is losing the bind
    client = runtime.beacon_client()
    deadline = _time.monotonic() + _elastic._init_timeout_s()
    hello = None
    while _time.monotonic() < deadline:
        hello = client.request("hello", process_id)
        if hello is not None:
            break
        _time.sleep(0.5)
    if hello is None:
        raise SystemExit(
            f"--elastic on: the lead's membership beacon at "
            f"{host}:{runtime.beacon_port} never answered — is the lead up?"
        )
    # the answering beacon names the CURRENT lead (post-election it is the
    # winner's uid, not 0); a restarted ex-lead adopts its successor here
    runtime.set_lead(int(hello.get("lead_uid", 0)))
    if hello["state"] == "forming":
        runtime.form(0, launch_members)
    else:
        # live run: this is a RESTARTED host — park for admission
        log.warning(
            "elastic: run already live at epoch %d (members %s); parking "
            "this host (uid %d) for admission at the next epoch boundary",
            hello["epoch"], hello["members"], process_id,
        )
        joined = False
        park_deadline = _time.monotonic() + float(
            _os.environ.get("TWTML_ELASTIC_PARK_TIMEOUT_S", "") or 120.0
        )
        while _time.monotonic() < park_deadline:
            client.request("join", process_id)
            state = client.request("hello", process_id) or {}
            plan = (client.request("plan", process_id) or {}).get("plan")
            if plan and process_id in plan.get("members", []) and (
                plan["epoch"] > state.get("epoch", -1)
            ):
                # the admission plan names the lead that committed it (it
                # may have changed during the park window)
                runtime.set_lead(int(plan.get("lead_uid", runtime.lead_uid)))
                runtime.joined_late = True
                runtime.form(plan["epoch"], plan["members"])
                joined = True
                break
            _time.sleep(0.5)
        if not joined:
            raise SystemExit(
                "elastic: admission never committed within the park "
                "window (is --elasticRejoin off on the lead, or the "
                "group idle?)"
            )
    import jax

    return jax.process_index() == 0


def select_backend(conf) -> None:
    """--backend {auto,tpu,cpu}: auto keeps jax's platform choice (TPU when
    attached); cpu forces the host backend (the reference's local[*] analog,
    ConfArguments.scala:54-56)."""
    import jax

    from ..utils import set_cpu_device_count_hint

    if getattr(conf, "dtype", "float32") == "float64":
        # without this, jnp silently downcasts f64 → f32 and the flag lies.
        # f64 is the CPU verification dtype (the reference's Java doubles,
        # LinearRegression.scala:32); TPU hardware has no f64 path, and the
        # operating-dtype policy (BENCHMARKS.md "Operating dtype") shows
        # f32 curves match f64 to well under the dashboard's rounding.
        if conf.backend != "cpu":
            raise SystemExit(
                "--dtype float64 runs on the CPU backend only (TPU has no "
                "f64 hardware path); add --backend cpu"
            )
        jax.config.update("jax_enable_x64", True)
    shards = conf.local_shards()
    if shards:
        # honor the local[N] hint before any backend initialization; it only
        # affects the CPU platform, so it's harmless when TPU wins auto
        if not set_cpu_device_count_hint(shards):
            log.warning("backend already initialized; local[%d] hint dropped", shards)
    if conf.backend == "cpu":
        # jax_platforms silently no-ops when a backend is already live, so
        # verify the outcome instead of guessing the pre-state (and this
        # first jax.default_backend() call initializes cpu when it did work)
        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                "--backend cpu requested but a non-cpu backend is already "
                "initialized in this process"
            )
    elif conf.backend == "tpu":
        kinds = {d.platform for d in jax.devices()}
        if "cpu" in kinds and len(kinds) == 1:
            raise RuntimeError("--backend tpu requested but only CPU devices present")


def install_trace(conf) -> None:
    """``--trace PATH`` wiring shared by every entry point: activate the
    pipeline tracer (telemetry/trace.py). Multi-host runs suffix the path
    with the process index — every host traces its own pipeline; a shared
    path would clobber. Call after ``select_backend`` (reading the process
    count may initialize the backend)."""
    path = getattr(conf, "trace", "")
    if not path:
        return
    import jax

    if jax.process_count() > 1:
        path = f"{path}.p{jax.process_index()}"
    # size rotation (--traceMaxMb, default 256): a 600 s bench / multi-hour
    # soak must not grow the JSONL without bound — PATH.1 keeps the
    # previous segment, trace_report stitches them
    _trace.install(
        path,
        max_bytes=int(getattr(conf, "traceMaxMb", 256) or 0) * 1024 * 1024,
    )


def install_chaos(conf) -> None:
    """``--chaos SPEC`` wiring shared by every entry point: activate the
    seeded transport-fault injector (streaming/faults.py) over the
    fetch/step/web injection points. Multi-host note: injections are
    PER-HOST (each process parses the same spec with its own call
    counters); a step error on one host exercises the lockstep abort
    machinery exactly like a real host-local failure."""
    spec = getattr(conf, "chaos", "")
    if not spec:
        return
    try:
        _faults.install_chaos(spec)
    except ValueError as exc:
        raise SystemExit(f"bad --chaos spec: {exc}")


def install_blackbox(conf) -> None:
    """``--blackbox`` (default on) wiring shared by every entry point:
    activate the crash flight recorder (telemetry/blackbox.py). The bundle
    lands NEXT TO the checkpoint directory — the one place a post-crash
    operator already looks — or the tempdir when checkpoints are off. A
    SIGTERM dumps too (kill -TERM mid-soak leaves evidence). Call after
    ``select_backend`` (the process index may initialize the backend)."""
    if getattr(conf, "blackbox", "on") != "on":
        return
    import os as _os
    import tempfile as _tempfile

    import jax

    ckpt_dir = getattr(conf, "checkpointDir", "")
    out_dir = (
        _os.path.dirname(_os.path.abspath(ckpt_dir))
        if ckpt_dir else _tempfile.gettempdir()
    )
    cfg = {
        k: v for k, v in vars(conf).items()
        if not k.startswith("_conf") and isinstance(v, (str, int, float, bool))
    }
    cfg["_appName"] = conf.appName()
    _blackbox.install(
        config=cfg, out_dir=out_dir, process_index=jax.process_index()
    )
    _blackbox.install_signal_handler()


def install_journal(conf) -> None:
    """``--journal`` wiring shared by the FeatureStream entry points
    (linear/logistic; the k-means raw path has no featurize seam to
    journal at): open this host's durable intake journal
    (streaming/journal.py) so the seam in streaming/context.py appends.
    Per-host directories under ``--checkpointDir`` — the journal records
    THIS host's post-shard intake, keyed by the elastic uid (stable across
    epochs and restarts) or the launch process id, so a restarted host
    finds its own records. Call after ``init_distributed`` (needs the
    process identity) and before the StreamingContext is built."""
    if not conf.effective_journal():
        # a journal left installed by an earlier run() in the same process
        # (tests, embedded uses) would journal THIS run's seam too and
        # leak its committed-cursor pairing — --journal off must be
        # bit-exact pre-journal behavior
        _journal.uninstall()
        return
    if not getattr(conf, "checkpointDir", ""):
        raise SystemExit(
            "--journal on requires --checkpointDir: the replay cursor "
            "lives in verified checkpoint meta (use --journal auto to "
            "follow the checkpoint flag)"
        )
    import os as _os

    from ..parallel.elastic import get_runtime as _get_elastic_runtime

    runtime = _get_elastic_runtime()
    if runtime is not None:
        suffix = f"-u{runtime.uid}"
    else:
        import jax

        suffix = (
            f"-p{jax.process_index()}" if jax.process_count() > 1 else ""
        )
    _journal.install(
        _os.path.join(conf.checkpointDir, f"journal{suffix}"),
        max_mb=int(getattr(conf, "journalMaxMb", 512) or 512),
    )


def install_historian(conf) -> None:
    """``--history`` wiring shared by every entry point: open this host's
    telemetry historian (telemetry/historian.py) so the SessionStats
    publish seam samples into it. Per-host directories under
    ``--checkpointDir`` (the journal's keying: elastic uid, or the launch
    process id) — a restarted host appends after its own recovered tail,
    so one directory accumulates a multi-run timeline and the perfGuard
    baseline round-trips between runs. Call after ``init_distributed``."""
    if not conf.effective_history():
        # a historian left installed by an earlier run() in the same
        # process (tests, embedded uses) would sample THIS run's publish
        # ticks too — --history off must be bit-exact pre-historian
        from ..telemetry import historian as _historian

        _historian.uninstall()
        return
    if not getattr(conf, "checkpointDir", ""):
        raise SystemExit(
            "--history on requires --checkpointDir: the historian "
            "segments and the --perfGuard baseline live under it (use "
            "--history auto to follow the checkpoint flag)"
        )
    import os as _os

    from ..parallel.elastic import get_runtime as _get_elastic_runtime
    from ..telemetry import historian as _historian
    from ..utils.runid import config_fingerprint, next_run_id

    runtime = _get_elastic_runtime()
    if runtime is not None:
        suffix = f"-u{runtime.uid}"
    else:
        import jax

        suffix = (
            f"-p{jax.process_index()}" if jax.process_count() > 1 else ""
        )
    _historian.configure(
        _os.path.join(conf.checkpointDir, f"history{suffix}"),
        max_mb=int(getattr(conf, "historyMaxMb", 256) or 256),
        perf_guard=getattr(conf, "perfGuard", "warn") == "warn",
        guard_ratio=float(getattr(conf, "perfGuardRatio", 1.5) or 1.5),
        run_id=next_run_id(),
        fingerprint=config_fingerprint(conf),
    )


def build_source(
    conf,
    allow_block: bool = False,
    block_interval: "tuple[int, int] | None" = None,
) -> Source:
    """``allow_block``: set by entry points whose pipelines consume
    ParsedBlocks (linear: default labels; logistic: unit_label_fn; k-means:
    numeric columns, which passes ``block_interval`` to override the
    parser's retweet-count filter — it keeps ALL retweets)."""
    import jax

    multihost = jax.process_count() > 1
    if multihost and conf.source == "twitter" and conf.ingest == "block":
        # the block parser keeps no per-tweet ids, and ids are the only
        # shard key a live stream has (IdShardedSource) — refuse the
        # combination rather than silently double-train
        raise SystemExit(
            "multi-host live Twitter intake shards by tweet id, which "
            "--ingest block does not carry; use --ingest object"
        )
    if conf.effective_wire() == "ragged":
        if conf.hashOn != "device":
            raise SystemExit(
                "--wire ragged is a device-hash wire format; "
                "it requires --hashOn device"
            )
    if conf.ingest == "block" and not allow_block:
        raise SystemExit(
            "--ingest block is not wired for this entry point; "
            "use --ingest object"
        )
    if conf.ingest == "block" and conf.source not in ("replay", "twitter"):
        raise SystemExit("--ingest block requires --source replay or twitter")
    if conf.ingest == "block" and conf.hashOn != "device":
        raise SystemExit(
            "--ingest block ships raw code units (device hashing); "
            "--hashOn host requires --ingest object"
        )
    if conf.source == "replay":
        if not conf.replayFile:
            raise SystemExit("--source replay requires --replayFile <path.jsonl>")
        if conf.ingest == "block":
            from ..streaming.sources import BlockReplayFileSource

            if conf.replaySpeed:
                raise SystemExit(
                    "--ingest block replays as fast as possible; "
                    "drop --replaySpeed or use --ingest object"
                )
            begin, end = (
                block_interval
                if block_interval is not None
                else (conf.numRetweetBegin, conf.numRetweetEnd)
            )
            # multi-host: byte-range shard of the file per host — each host
            # parses ONLY its shard (SURVEY §2.4 L0: deserialization ships
            # to every executor), so config #1's native loader feeds
            # cluster runs too (r5; was a SystemExit)
            source: Source = BlockReplayFileSource(
                conf.replayFile, num_retweet_begin=begin, num_retweet_end=end,
                # zero-copy wire emitter (--blockWire): raw bytes → ragged
                # wire units in one C pass, byte-identical batches
                wire=conf.effective_block_wire(),
                shard_index=jax.process_index() if multihost else 0,
                shard_count=jax.process_count() if multihost else 1,
            )
            return _wrap_faults(source, conf)
        source = ReplayFileSource(conf.replayFile, speed=conf.replaySpeed)
    elif conf.source == "synthetic":
        source = SyntheticSource(rate=conf.replaySpeed or 0.0)
    elif conf.source == "twitter":
        from ..streaming.twitter import BlockTwitterSource, TwitterSource

        if conf.ingest == "block":
            # live block ingest (r5): raw stream lines batch into byte
            # blocks for the native C parser — no per-tweet Python objects
            # between the socket and the featurizer (closes most of the
            # config-#2 full-app vs protocol-stage gap, BENCHMARKS.md)
            begin, end = (
                block_interval
                if block_interval is not None
                else (conf.numRetweetBegin, conf.numRetweetEnd)
            )
            source = BlockTwitterSource.from_properties(
                num_retweet_begin=begin, num_retweet_end=end,
                wire=conf.effective_block_wire(),
            )
            return _wrap_faults(source, conf)
        source = TwitterSource.from_properties()
        if multihost:
            from ..streaming.sources import IdShardedSource

            # live streams shard by tweet id (id ≡ processId mod N): every
            # host opens its own connection (duplicated ingress — tens of
            # KB/s at real stream rates) and keeps a disjoint residue
            # slice, so no tweet trains twice (r5; was a SystemExit)
            return _wrap_faults(
                IdShardedSource(
                    source, jax.process_index(), jax.process_count()
                ),
                conf,
            )
    else:
        raise SystemExit(f"unknown --source {conf.source!r}")
    if multihost:
        from ..streaming.sources import ShardedSource

        source = ShardedSource(
            source, jax.process_index(), jax.process_count()
        )
    return _wrap_faults(source, conf)


def _wrap_faults(source: Source, conf) -> Source:
    if conf.faultEvery > 0:
        from ..streaming.faults import FaultInjectingSource

        # finite replay files need the crash cap to avoid livelock (each
        # restart re-reads from the start); unbounded sources keep crashing
        source = FaultInjectingSource(
            source,
            crash_every=conf.faultEvery,
            max_crashes=3 if conf.source == "replay" else 0,
        )
    return source


def mesh_shape(conf) -> int:
    """Data-axis size the conf + attached devices call for: the number of
    visible devices, capped by the ``--master local[N]`` hint."""
    import jax

    shards = conf.local_shards()
    n_devices = len(jax.devices())
    return min(shards, n_devices) if shards else n_devices


def build_mesh(conf, what: str = "training"):
    """The one-flag cluster story: the ('data',) mesh the conf calls for, or
    None when a single device (or local[1]) keeps execution unsharded. Every
    entry point routes through here so device selection / local[N] capping
    can never diverge between apps.

    Multi-host runs span the WHOLE process group's devices; jax.devices()
    is process-major, so the 1D data axis is automatically process-aligned
    (the topology per-host intake sharding requires,
    parallel/distributed.py)."""
    import jax

    if jax.process_count() > 1:
        from ..parallel import make_mesh

        if conf.local_shards():
            log.warning("--master local[N] hint ignored in a multi-host run")
        log.info(
            "multi-host %s: %d processes, %d global devices",
            what, jax.process_count(), jax.device_count(),
        )
        return make_mesh(num_data=jax.device_count(), devices=jax.devices())
    n_data = mesh_shape(conf)
    if n_data <= 1:
        return None

    from ..parallel import make_mesh

    log.info("mesh-sharded %s: %d-way data parallel", what, n_data)
    return make_mesh(num_data=n_data, devices=jax.devices()[:n_data])


def build_model(conf, model_cls=StreamingLinearRegressionWithSGD):
    """Single-device fused learner on one chip; mesh-sharded learner when the
    backend exposes several devices (or local[N] caps a virtual CPU mesh) —
    the CLI face of BASELINE config #5's data-parallel scale-up, for ANY
    SGD-family learner (the class's residual/prediction knobs carry over to
    the sharded step). Returns (model, required row multiple for batches).

    ``--tenants M`` (> 1) swaps in the multi-tenant model plane
    (parallel/tenants.TenantStackModel): M stacked models in ONE jit
    program sharing one wire and ONE stacked stats fetch per tick — the
    marginal tenant costs device FLOPs (µs), not tunnel round trips (the
    r2 law). Composes with the data mesh (rows P(data), tenant axis
    replicated); the cross-process tenants-on-model-axis layout is driven
    at the library level (tests/test_distributed_multiprocess.py) — the
    app-level multi-host wiring keeps its single-model plane for now."""
    import jax as _jax

    # --wireAssemble: the fused one-pass native pack (r17) is a process-
    # wide seam — every packer (plain / mesh / multi-host / tenant) rides
    # it through features/batch.py, so one configure covers them all
    from ..features import assemble as _assemble

    _assemble.configure(getattr(conf, "wireAssemble", "auto") or "auto")
    # --featurizeNative: the one-pass fused featurize (r18) is the same
    # kind of process-wide seam — both ingest paths ride it through the
    # featurizer, so one configure covers object and block streams
    from ..features import featurize_native as _ffz

    _ffz.configure(getattr(conf, "featurizeNative", "auto") or "auto")

    tenants = int(getattr(conf, "tenants", 1) or 1)
    # TWTML_FORCE_TENANT_PLANE=1 routes even --tenants 1 through the
    # stacked program — the app-level M=1 differential-parity hook (the
    # default path stays the plain single-model plane: a 1-tenant stream
    # must not pay the routing split)
    import os as _os

    force_plane = _os.environ.get("TWTML_FORCE_TENANT_PLANE") == "1"
    if tenants > 1 or (force_plane and tenants == 1):
        if getattr(conf, "tenantKey", "hash") == "lang" and conf.hashOn != "device":
            raise SystemExit(
                "--tenantKey lang routes on raw code units; it requires "
                "--hashOn device"
            )
        from ..parallel.tenants import TenantStackModel

        if _jax.process_count() > 1:
            # app-level tenant fleet (r16, PR 7 REMAINING b; ragged wire
            # lifted in r20): the tenant stack behind per-host sharded
            # intake on the 1D process-aligned data mesh — the global
            # tenant wire assembles on the row axis like the stacked
            # superbatch wire, ONE pooled fetch per tick, and the elastic
            # membership plane rebuilds it across epochs like the
            # single-model plane. Ragged tenant parts agree one shared
            # per-shard bucket fleet-wide (a single allgather-max per
            # batch — MultiHostTenantModel._stack_ragged_parts).
            from ..parallel.tenants import MultiHostTenantModel

            mesh = build_mesh(
                conf, what=f"tenant fleet ({model_cls.__name__})"
            )

            def tenant_rebuilder(new_mesh):
                return TenantStackModel.from_conf(
                    conf, new_mesh,
                    residual_fn=model_cls.residual_fn,
                    prediction_fn=model_cls.prediction_fn,
                    round_predictions=model_cls.round_predictions,
                )

            inner = tenant_rebuilder(mesh)
            model = MultiHostTenantModel(
                inner, mesh, rebuilder=tenant_rebuilder
            )
            log.info(
                "multi-tenant model FLEET: %d tenants across %d hosts, "
                "key=%s, stacked wire", tenants, _jax.process_count(),
                model.tenant_key,
            )
            return model, max(1, inner.num_data // _jax.process_count())
        mesh = build_mesh(conf, what=f"tenant plane ({model_cls.__name__})")
        model = TenantStackModel.from_conf(
            conf, mesh,
            residual_fn=model_cls.residual_fn,
            prediction_fn=model_cls.prediction_fn,
            round_predictions=model_cls.round_predictions,
        )
        log.info(
            "multi-tenant model plane: %d tenants, key=%s, wire=%s",
            tenants, model.tenant_key, model.wire_pack,
        )
        return model, (mesh.shape[mesh.axis_names[0]] if mesh else 1)
    mesh = build_mesh(conf, what=f"training ({model_cls.__name__})")
    codec = getattr(conf, "effective_wire_codec", lambda: "off")()
    if mesh is not None:
        from ..parallel import ParallelSGDModel

        def sgd_rebuilder(new_mesh):
            if new_mesh is None:
                # an elastic fleet shrunk to one host with one device:
                # build_mesh legitimately says "unsharded", but the
                # MultiHost wrapper's step/pack surface needs A mesh — a
                # 1-device data mesh is the same math (shard_map over one
                # shard) and keeps every holder of the wrapper working
                import jax as _jax_inner

                from ..parallel import make_mesh

                new_mesh = make_mesh(
                    num_data=1, devices=_jax_inner.devices()[:1]
                )
            return ParallelSGDModel.from_conf(
                conf, new_mesh,
                residual_fn=model_cls.residual_fn,
                prediction_fn=model_cls.prediction_fn,
                round_predictions=model_cls.round_predictions,
            )

        model = sgd_rebuilder(mesh)
        import jax

        if jax.process_count() > 1:
            from ..parallel.distributed import MultiHostSGDModel

            # the app featurizes only THIS host's rows: its local batch
            # must divide this host's share of the data axis. The codec
            # bucket (r16, groups in r20) is agreed on the SAME alignment
            # allgather the raw bucket already pays — zero new collectives;
            # for --superBatch groups, prepare() records each batch's
            # agreed bucket and the group pack combines them arithmetically.
            mh = MultiHostSGDModel(model, mesh, rebuilder=sgd_rebuilder)
            mh.wire_codec = codec if codec == "dict" else ""
            return mh, max(1, model.num_data // jax.process_count())
        # single-process mesh: the mesh packs compress per shard segment
        # (parallel/sharding.py pack_for_wire / pack_group_for_wire)
        model.wire_codec = codec if codec == "dict" else ""
        return model, model.num_data
    return model_cls.from_conf(conf), 1


def state_checksum(state) -> str:
    """CRC of a checkpointable state (flat dict or one array) — logged at
    recycle-save and at restore so a recycled run's logs PROVE the
    post-restart weights are bit-identical to the pre-exec save
    (tests/test_recycler.py asserts the two lines match)."""
    import zlib

    import numpy as np

    arrs = state if isinstance(state, dict) else {"state": state}
    crc = 0
    for key in sorted(arrs):
        a = np.ascontiguousarray(np.asarray(arrs[key]))
        crc = zlib.crc32(
            a.tobytes(),
            zlib.crc32(f"{key}:{a.dtype}:{a.shape}".encode(), crc),
        )
    return f"{crc:08x}"


class AppCheckpoint:
    """``--checkpointDir``/``--checkpointEvery`` wiring shared by every entry
    point (model checkpoint/resume is this framework's upgrade over the
    reference, SURVEY.md §5.4 — a restarted reference job begins from
    zeros). Restores state + counters at startup, saves on a cadence-
    crossing test at weight-current boundaries (so ``--superBatch`` groups
    snap to the first boundary at/after each cadence point instead of
    stretching to lcm), and saves final state at shutdown.

    ``get_state()`` returns the checkpointable arrays (flat dict or one
    array); ``set_state(state)`` restores them into the model.

    Multi-host: only the lead (``lead=True``) WRITES the fleet directory
    (concurrent writers against one directory would race), and restore is
    LEAD-AUTHORITATIVE — after the local restore attempt, the lead's
    state/counters are broadcast to every process, so a follower without
    the lead's filesystem (no shared storage) still resumes consistently
    instead of silently training from zeros against resumed peers.

    Elastic fleets (r20): every NON-lead host shadow-saves the same
    verified archives into its own ``standby-u<uid>/`` subdirectory on the
    same cadence — training is psum-identical, so the archives are
    bit-identical to the lead's. That is the any-host-can-restore
    discipline lead election relies on: ``promote()`` flips authority
    after a won election and the new lead resyncs the fleet from its OWN
    verified archives (no shared storage assumed). Broadcast sourcing
    follows ``_lead`` (not hardcoded process 0), so authority tracks the
    elected lead whatever its epoch pid is."""

    def __init__(self, conf, get_state, set_state, totals: dict,
                 lead: bool = True):
        self._ckpt = None
        self._get_state = get_state
        self._set_state = set_state
        from ..parallel.elastic import get_runtime as _get_elastic_runtime

        runtime = _get_elastic_runtime()
        self._elastic = runtime is not None
        self._lead = runtime.is_lead if self._elastic else lead
        self._shadow = self._elastic and not self._lead
        self.every = int(getattr(conf, "checkpointEvery", 0) or 0)
        self.restored_meta = None
        if not conf.checkpointDir:
            self._last = 0
            return
        from ..checkpoint import Checkpointer

        ckpt_dir = conf.checkpointDir
        if self._shadow:
            import os as _os

            ckpt_dir = _os.path.join(
                conf.checkpointDir, f"standby-u{runtime.uid}"
            )
            log.info(
                "elastic standby checkpoints: this host shadow-saves "
                "verified archives into %s (any-host-can-restore)",
                ckpt_dir,
            )
        self._ckpt = Checkpointer(ckpt_dir)
        restored = self._ckpt.restore()
        # this host's OWN restored meta (followers restore their shadow
        # archives): the intake journal's boot replay reads its cursor
        # stamp from here (journal_boot_replay) — per-host, never the
        # broadcast (each host replays its own journal)
        self.restored_meta = restored[1] if restored is not None else None
        if restored is not None:
            state, meta = restored
            set_state(state)
            totals["count"] = int(meta.get("count", 0))
            totals["batches"] = int(meta.get("batches", 0))
            log.info(
                "resumed from checkpoint step %s (count=%s, state crc %s)",
                meta.get("step"), totals["count"], state_checksum(state),
            )
        import jax

        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils

            # every process contributes its own (structurally identical)
            # state; all receive the LEAD's — the lead is the fleet-dir
            # writer, so its view of the checkpoint is the truth. Source
            # by _lead, not process 0: after an election the lead's epoch
            # pid is whatever the member sort gives it.
            meta_arr, state = multihost_utils.broadcast_one_to_all((
                np.array(
                    [int(restored is not None),
                     totals["count"], totals["batches"]], np.int64,
                ),
                get_state(),
            ), is_source=bool(self._lead))
            # unconditional: a follower restoring a STALE local checkpoint
            # while the lead starts fresh must also converge on the lead
            set_state(jax.tree_util.tree_map(np.asarray, state))
            totals["count"] = int(meta_arr[1])
            totals["batches"] = int(meta_arr[2])
            if int(meta_arr[0]) and restored is None:
                log.info(
                    "resumed from the lead's broadcast checkpoint "
                    "(count=%s)", totals["count"],
                )
            # every host logs the post-broadcast crc: an elastic rejoiner's
            # first-tick weights must BIT-match the lead's, and matching
            # crc lines across hosts are the assertable proof
            log.info(
                "multi-host state synchronized from the lead (count=%s, "
                "state crc %s)", totals["count"],
                state_checksum(self._get_state()),
            )
        self._last = totals["batches"]

    def _save(self, totals: dict) -> None:
        if not self._lead and not self._shadow:
            self._last = totals["batches"]  # keep cadence bookkeeping aligned
            return
        j = _journal.get()
        if j is not None and not j.save_allowed:
            # mid-replay: the weights already re-trained past the rollback
            # cursor, but the committed cursor cannot advance until the
            # final replayed batch delivers — a save now would stamp a
            # cursor whose replay double-trains on crash-restore. Defer;
            # _last stays put so the cadence retries next boundary.
            log.info(
                "checkpoint save deferred at batch %s: journal replay "
                "still draining (retries next boundary)",
                totals["batches"],
            )
            return
        meta = {"count": totals["count"], "batches": totals["batches"]}
        # quality stamp (ISSUE 8): every verified checkpoint records the
        # model-health picture at save time — the promotion-gate substrate
        # the serving plane reads (tools/model_report.py renders history)
        quality = _modelwatch.snapshot_for_checkpoint()
        if quality is not None:
            meta["quality"] = quality
        # freshness stamp (ISSUE 16): the event-lag/watermark picture at
        # save time, so checkpoint history carries the staleness story
        fresh = _freshness.snapshot_for_checkpoint()
        if fresh is not None:
            meta["freshness"] = fresh
        # journal cursor stamp (ISSUE 19): saves run at weight-current
        # boundaries on the thread that featurizes, so every record with
        # id < cursor is inside the state being saved — the replay-exact
        # resume point for rollback/resync/restart (streaming/journal.py)
        jstamp = _journal.snapshot_for_checkpoint()
        if jstamp is not None:
            meta["journal"] = jstamp
        self._ckpt.save(totals["batches"], self._get_state(), meta)
        self._last = totals["batches"]
        if jstamp is not None:
            # bounded disk: segments retire once covered by EVERY retained
            # verified archive (a fallback restore can land on the oldest)
            oldest = self._ckpt.oldest_meta()
            covered = ((oldest or {}).get("journal") or {}).get("cursor")
            if covered is not None:
                _journal.get().retire_covered(int(covered))
        # sticky flight-recorder context: a post-mortem bundle names the
        # checkpoint a restart will resume from (telemetry/blackbox.py)
        _blackbox.note(
            "last_checkpoint",
            {"step": totals["batches"], "count": totals["count"]},
        )

    def maybe_save(self, totals: dict, at_boundary: bool = True) -> None:
        """Cadence save — call per batch from the app's handler."""
        if self._ckpt is not None and at_boundary and self.every > 0 and (
            totals["batches"] - self._last >= self.every
        ):
            self._save(totals)

    def final_save(self, totals: dict) -> None:
        """Shutdown save when anything advanced past the last save."""
        if self._ckpt is not None and totals["batches"] != self._last:
            self._save(totals)

    def save_now(self, totals: dict) -> bool:
        """Unconditional save (the recycler's pre-exec snapshot). Returns
        False when no checkpoint dir is configured."""
        if self._ckpt is None:
            return False
        self._save(totals)
        return True

    def own_journal_stamp(self, batches: int) -> "dict | None":
        """This host's journal cursor for the agreed rollback point: the
        newest LOCAL archive's stamp, valid only when its ``batches``
        matches the lead-agreed value (cadence saves are psum-aligned, so
        lead and shadow archives land on the same batch indices; a stale
        or missing local archive — fresh joiner, pre-journal history —
        returns None and the caller falls back to counted loss). Local
        disk read only: zero added fetches, zero added collectives."""
        if self._ckpt is None:
            return None
        meta = self._ckpt.latest_meta()
        if meta is None or int(meta.get("batches", -1)) != int(batches):
            return None
        return meta.get("journal")

    def adopt_replay_totals(self, totals: dict, count, batches) -> None:
        """Reset the run counters to a rollback point whose rows a journal
        replay is about to re-ingest: the replayed rows re-count through
        the unchanged handler path, so the final ledger matches an
        unfailed run (the crash-equals-clean differential). Keeps the
        cadence bookkeeping aligned so post-replay saves fire on the same
        boundaries as a clean run."""
        totals["count"] = int(count)
        totals["batches"] = int(batches)
        self._last = totals["batches"]

    def promote(self) -> None:
        """Elastic lead handoff: this host won an election. Its standby
        archives become the fleet's checkpoint lineage — future saves
        continue into the same (formerly standby) directory, and the next
        ``resync_from_verified`` restores from them and broadcasts with
        this host as the source. Idempotent."""
        if self._lead:
            return
        self._lead = True
        self._shadow = False
        if self._ckpt is not None:
            log.warning(
                "checkpoint authority PROMOTED after lead election: this "
                "host's verified archives in %s are the fleet lineage now",
                self._ckpt.directory,
            )
        from ..telemetry import blackbox as _blackbox

        _blackbox.record(
            "checkpoint_promoted",
            directory=getattr(self._ckpt, "directory", ""),
        )

    def resync_from_verified(self, totals: dict) -> bool:
        """Elastic epoch re-synchronization (r16): every member of a
        just-formed epoch converges on the LEAD's state + counters — its
        newest verified on-disk checkpoint when one exists (the documented
        rollback guarantee: a clean commit saves at the boundary first, so
        it loses nothing; a rescue rolls back at most --checkpointEvery
        batches), else its live weights (checkpoints off — survivors are
        psum-identical anyway, and a joiner still inherits the truth).
        Rolled-back rows are counted (``elastic.rows_rolled_back``), never
        silent. Single-process epochs (a fleet shrunk to one host) restore
        locally with no collective. Returns False only when there is
        neither a checkpoint nor a multi-host broadcast to sync from (the
        degenerate 1-host/no-disk case — state simply continues)."""
        import jax

        restored = (
            self._ckpt.restore()
            if self._ckpt is not None and self._lead else None
        )
        old_count = int(totals.get("count", 0))

        def adopt(state, count, batches) -> None:
            self._set_state(state)
            totals["count"] = int(count)
            totals["batches"] = int(batches)
            self._last = totals["batches"]
            rolled = max(0, old_count - totals["count"])
            if rolled:
                _metrics.get_registry().counter(
                    "elastic.rows_rolled_back"
                ).inc(rolled)
            log.warning(
                "elastic resync: state from the lead's %s (count=%d, "
                "batches=%d, state crc %s)%s",
                "verified checkpoint" if restored is not None or not (
                    self._lead
                ) else "live weights",
                totals["count"], totals["batches"], state_checksum(state),
                f" — {rolled} row(s) of post-checkpoint progress rolled "
                f"back (counted)" if rolled else "",
            )

        if jax.process_count() <= 1:
            if restored is None:
                return False
            state, meta = restored
            adopt(state, meta.get("count", 0), meta.get("batches", 0))
            return True
        import numpy as np
        from jax.experimental import multihost_utils

        state = self._get_state()
        count, batches = totals.get("count", 0), totals.get("batches", 0)
        if restored is not None:
            state = restored[0]
            count = restored[1].get("count", 0)
            batches = restored[1].get("batches", 0)
        meta_arr, state = multihost_utils.broadcast_one_to_all((
            np.array([1, count, batches], np.int64), state,
        ), is_source=bool(self._lead))
        adopt(
            jax.tree_util.tree_map(np.asarray, state),
            int(meta_arr[1]), int(meta_arr[2]),
        )
        return True

    def rollback_to_verified(self) -> "dict | None":
        """Restore the newest VERIFIED (checksummed, finite) checkpoint
        into the model — the divergence sentinel's recovery hook. Returns
        the checkpoint meta, or None when no verified checkpoint exists
        (checkpoints off, empty dir, or every archive corrupt/non-finite).

        Multi-host: lead-authoritative like the startup restore — the lead
        restores from disk and its state broadcasts to every process (a
        follower has no checkpoint files). All hosts MUST call this on the
        same tick (the sentinel guarantees it: stats are psum-global and
        deliveries deterministic, verified by the rollback count riding
        the cadence allgather), because the broadcast is a collective."""
        restored = (
            self._ckpt.restore() if self._ckpt is not None else None
        )
        if restored is not None:
            _blackbox.note(
                "last_verified_rollback",
                {"step": restored[1].get("step")},
            )
        import jax

        if jax.process_count() <= 1:
            if restored is None:
                return None
            state, meta = restored
            self._set_state(state)
            return meta
        import numpy as np
        from jax.experimental import multihost_utils

        ok = int(restored is not None) if self._lead else 0
        # EVERY host fetches its current state first: the broadcast needs a
        # structurally identical pytree per process, and get_state itself
        # may be a collective (MultiHostSGDModel.latest_weights allgathers)
        # — the lead must participate too, then its disk state wins
        state = self._get_state()
        count = batches = 0
        if self._lead and restored is not None:
            state = restored[0]
            count = int(restored[1].get("count", 0))
            batches = int(restored[1].get("batches", 0))
        # the flags carry the agreed (count, batches) rollback point on the
        # SAME broadcast — a follower needs it to locate its OWN journal
        # cursor for replay (own_journal_stamp); zero added collectives
        flag, state = multihost_utils.broadcast_one_to_all((
            np.array([ok, count, batches], np.int64), state,
        ), is_source=bool(self._lead))
        if not int(flag[0]):
            return None
        self._set_state(jax.tree_util.tree_map(np.asarray, state))
        if self._lead and restored is not None:
            return restored[1]
        return {
            "broadcast": True,
            "count": int(flag[1]),
            "batches": int(flag[2]),
        }


def journal_replay_rollback(ssc, ckpt: AppCheckpoint, totals: dict, meta,
                            where: str) -> "int | None":
    """Re-ingest every journaled row after the rollback point ``meta``
    names — the conversion of a counted-loss site into a replay-exact one
    (ISSUE 19). Returns rows replayed (0 when the cursor was already at
    the tail), or None when replay was impossible (journal off, or no
    local cursor for the agreed point) — the caller keeps its counted-loss
    accounting then.

    ``meta`` is the rollback target's checkpoint meta: a full local meta
    (single-host / lead), a broadcast stub carrying (count, batches) (a
    follower locates its OWN cursor via ``own_journal_stamp``), or None —
    no verified checkpoint existed, the model was reset to initial zeros,
    and the WHOLE journal replays from cursor 0 (crash-equals-clean holds
    even before the first save).

    Host-side only: disk reads + queue putbacks at the FRONT (row order
    preserved; the replayed rows re-cross the unchanged featurize path
    under append suppression). Multi-host replay rides the existing
    lockstep cadence — a host with fewer replayed rows dispatches
    all-padding ticks per the lockstep invariant; ZERO new collectives,
    zero added fetches."""
    j = _journal.get()
    if j is None:
        return None
    if meta is None:
        count = batches = 0
        stamp = {"cursor": 0, "rows": 0}
    else:
        count = int(meta.get("count", 0))
        batches = int(meta.get("batches", 0))
        stamp = meta.get("journal")
        if stamp is None:
            stamp = ckpt.own_journal_stamp(batches)
    if stamp is None:
        log.warning(
            "journal: no local cursor for the agreed rollback point "
            "(batches=%d) after %s — rows stay counted as lost, not "
            "replayed (stale/missing local archive or pre-journal "
            "history)", batches if meta is not None else -1, where,
        )
        return None
    cursor = int(stamp["cursor"])
    # an EARLIER replay still draining (a storm re-poisons a replayed row,
    # or a reform lands mid-drain) is superseded by this one — its cursor
    # is at or below the old one, so its items re-cover the stale rows
    # still parked at the queue front. Remove them before the new putback
    # or the overlap trains twice.
    stale = j.cancel_pending_replay()
    if stale:
        queued = ssc._drain(0)
        qrows = sum(getattr(s, "rows", 1) for s in queued)
        keep = (
            _journal.IntakeJournal._split_items(queued, stale)
            if qrows > stale else []
        )
        ssc._putback(keep)
        log.warning(
            "journal: superseded an in-progress replay — dropped %d stale "
            "queued row(s) the new replay from cursor %d re-covers",
            min(stale, qrows), cursor,
        )
    items, rows = j.replay_from(cursor)
    ssc._putback(items)
    ckpt.adopt_replay_totals(totals, count, batches)
    _blackbox.record(
        "journal_replay", where=where, rows=rows, cursor=cursor,
        count=count, batches=batches,
    )
    log.warning(
        "journal: replayed %d row(s) from cursor %d after %s — counters "
        "reset to (count=%d, batches=%d); recovery is replay-exact, zero "
        "rows lost", rows, cursor, where, count, batches,
    )
    return rows


def journal_boot_replay(conf, ssc, ckpt: AppCheckpoint, totals: dict) -> int:
    """Boot half of journal recovery (watchdog-abort restart, kill -9,
    recycle): every row this host ever journaled is either inside the
    restored checkpoint (id < cursor) or re-enqueued here from the journal
    (id >= cursor), and the deterministic source fast-forwards past ALL of
    them (``SkipRowsSource``) instead of re-producing from the top. Call
    after ``AppCheckpoint`` restores and before the stream starts."""
    j = _journal.get()
    if j is None:
        return 0
    from ..parallel import elastic as _elastic

    rt = _elastic.get_runtime()
    if rt is not None and rt.joined_late:
        # this host's pre-departure coverage moved to its adopters when
        # the fleet reformed without it — replaying (or fast-forwarding
        # past) its old journal would double-train adopted rows
        j.reset()
        log.warning(
            "journal: reset on late join — this host's pre-departure "
            "rows belong to their adopters now; boot replay skipped"
        )
        return 0
    meta = getattr(ckpt, "restored_meta", None)
    stamp = (meta or {}).get("journal")
    if meta is not None and stamp is None:
        log.warning(
            "journal: the restored checkpoint carries no journal cursor "
            "(pre-journal archive) — boot replay skipped; the source "
            "re-produces from its top as a bare checkpoint-restart would"
        )
        return 0
    if meta is not None and int(meta.get("batches", -1)) != int(
        totals.get("batches", 0)
    ):
        # multi-host: the lead's broadcast moved the counters away from
        # this host's own archive — its cursor no longer names the
        # adopted state, so an exact replay is off the table
        log.warning(
            "journal: local archive (batches=%s) disagrees with the "
            "adopted counters (batches=%s) — boot replay skipped",
            meta.get("batches"), totals.get("batches"),
        )
        return 0
    cursor = int(stamp["cursor"]) if stamp is not None else 0
    skip_rows = j.rows_total
    items, rows = j.replay_from(cursor)
    ssc._putback(items)
    # fast-forward only sources that RE-PRODUCE the same rows on restart
    # (replay file, seeded synthetic) — a live stream never re-produces,
    # so skipping would drop fresh rows, not duplicates
    fast_forward = skip_rows if conf.source != "twitter" else 0
    if fast_forward:
        from ..streaming.sources import SkipRowsSource

        ssc._source = SkipRowsSource(ssc._source, fast_forward)
    _blackbox.record(
        "journal_replay", where="boot", rows=rows, cursor=cursor,
        count=int(totals.get("count", 0)),
        batches=int(totals.get("batches", 0)),
    )
    if skip_rows or rows:
        log.warning(
            "journal: boot resume — %d journaled row(s), %d fast-forwarded "
            "at the source (%d inside the restored checkpoint, %d replayed "
            "from cursor %d); zero rows lost, zero rows double-trained",
            skip_rows, fast_forward, skip_rows - rows, rows, cursor,
        )
    return rows


class DivergenceSentinel:
    """Non-finite-state guard at the model boundary (``--sentinel``, default
    on): one poisoned batch (NaN/Inf labels, adversarial features) drives
    the fused predict-then-train step's weights non-finite in a single
    update, and — before this guard — silently destroyed the model AND,
    within ``keep_last`` cadence saves, every checkpoint the resume path
    relies on.

    **Zero added host fetches** (the r2/r3 measurement law — asserted by
    tests the way the ``--trace`` tests are): the finiteness check reads
    ONLY the StepOutput scalars the pipeline already fetched per batch
    (mse/stdevs — NaN labels or NaN weights propagate into all of them
    through the on-device stats reduction). Healthy-path cost is three
    ``math.isfinite`` calls per batch (paired-neutral on the CPU control,
    BENCHMARKS.md).

    On a non-finite delivery: the batch is SKIPPED (never handed to the
    app handler — its stats are garbage; the dispatch slot is refunded so
    max-batches caps don't under-train), the model rolls back to the last
    VERIFIED-finite checkpoint (``AppCheckpoint.rollback_to_verified``;
    without ``--checkpointDir`` it resets to the reference's initial
    zeros), and ``model.rollbacks`` counts it. Consecutive non-finite
    deliveries are ONE episode — batches already dispatched against the
    poisoned weights drain through as tainted skips without re-rolling
    back — and the first finite delivery closes it. After
    ``--sentinelRollbacks`` rollbacks within ``--sentinelWindow`` batches
    the run aborts CLEANLY via the existing ``ssc.request_abort`` path
    (checkpointed shutdown, non-zero exit): a stream that keeps poisoning
    the model is an operator problem, not a retry problem.

    PARITY: on the healthy path the sentinel observes and never touches
    reference semantics; it only ever SKIPS batches whose state is
    non-finite — a regime where the reference would train garbage forever
    (PARITY.md).

    Multi-host: stats are psum-global and deliveries deterministic, so
    every host reaches the same verdict at the same delivered batch and
    performs the same rollback (the checkpoint broadcast inside
    ``rollback_to_verified`` is collective). The cumulative rollback count
    rides the per-tick cadence allgather (``ssc.rollback_count_fn``) so
    the group VERIFIES it rolled back the same steps instead of assuming
    it."""

    def __init__(self, conf, model, ckpt: AppCheckpoint, ssc,
                 lead: bool = True, totals: "dict | None" = None):
        self.enabled = getattr(conf, "sentinel", "on") == "on"
        self.max_rollbacks = int(getattr(conf, "sentinelRollbacks", 3) or 0)
        self.window = max(1, int(getattr(conf, "sentinelWindow", 512) or 1))
        self._model = model
        self._ckpt = ckpt
        self._ssc = ssc
        self._lead = lead
        # run counters, for the journal-replay conversion (ISSUE 19): a
        # replayed rollback resets them to the checkpoint so the re-counted
        # rows end at the clean-run ledger; None (legacy callers) keeps
        # the counted-loss path
        self._totals = totals
        # rows of the current episode replayed (vs counted lost): set per
        # rollback by _rollback, read by admit's loss accounting
        self._replaying = False
        self._num_features = int(getattr(conf, "numTextFeatures", 1000))
        self._tainted = False
        self._delivered = 0
        self._rollback_points: list[int] = []
        self._pipeline = None
        reg = _metrics.get_registry()
        self._nonfinite_count = reg.counter("model.nonfinite_batches")
        self._rollback_count = reg.counter("model.rollbacks")
        self._rows_lost = reg.counter("model.rows_lost")
        if self.enabled:
            # the rollback count rides the lockstep cadence allgather so
            # multi-host groups verify they rolled back the same steps
            ssc.rollback_count_fn = lambda: len(self._rollback_points)

    def bind(self, pipeline) -> None:
        """Attach the fetch pipeline/batcher whose ``refund_dispatch``
        keeps max-batches caps exact when a batch is skipped."""
        self._pipeline = pipeline

    @property
    def rollbacks(self) -> int:
        return len(self._rollback_points)

    @staticmethod
    def _finite(out) -> bool:
        import math

        # the already-fetched per-batch scalars: NaN labels hit mse and
        # real_stdev immediately; NaN WEIGHTS (a poisoned prior batch) hit
        # pred_stdev/mse through the predictions — between them every
        # non-finite state the fused step can reach is visible without
        # touching the device
        return (
            math.isfinite(float(out.mse))
            and math.isfinite(float(out.real_stdev))
            and math.isfinite(float(out.pred_stdev))
        )

    def admit(self, out, batch) -> bool:
        """Per-delivery gate (wired by ``attach_super_batcher``): True →
        hand the batch to the app handler; False → skipped (non-finite
        state; rollback/abort already handled here)."""
        self._delivered += 1
        if self._finite(out):
            if self._tainted:
                log.warning(
                    "divergence sentinel: finite stats resumed at "
                    "delivered batch %d — rollback recovered the model",
                    self._delivered,
                )
                self._tainted = False
            return True
        self._nonfinite_count.inc()
        rows = int(out.count) if hasattr(out, "count") else 0
        if self._pipeline is not None:
            self._pipeline.refund_dispatch()
        if self._tainted:
            # same episode: a batch dispatched against the poisoned
            # weights before the rollback took effect drains through
            if not self._replaying:
                self._rows_lost.inc(rows)
            log.warning(
                "divergence sentinel: skipping tainted in-flight batch "
                "(delivered %d, %d rows)%s", self._delivered, rows,
                " — rows re-ingest via journal replay"
                if self._replaying else "",
            )
            return False
        self._tainted = True
        self._rollback()
        if not self._replaying:
            # no journal (or no usable cursor): the skipped rows are lost,
            # counted — the pre-journal ledger
            self._rows_lost.inc(rows)
        return False

    def _rollback(self) -> None:
        self._rollback_count.inc()
        self._rollback_points.append(self._delivered)
        _trace.get().instant(
            "sentinel_rollback", delivered=self._delivered,
            episode=len(self._rollback_points),
        )
        _blackbox.record(
            "sentinel_rollback", delivered=self._delivered,
            episode=len(self._rollback_points),
        )
        meta = self._ckpt.rollback_to_verified()
        # journal-replay conversion (ISSUE 19): re-ingest every row after
        # the rollback point instead of skipping it — the sentinel site's
        # half of the crash-equals-clean differential. Legacy callers
        # (no totals) and --journal off keep the counted-loss behavior.
        replayed = None
        if self._totals is not None:
            replayed = journal_replay_rollback(
                self._ssc, self._ckpt, self._totals, meta,
                where="sentinel rollback",
            )
        self._replaying = replayed is not None
        if meta is not None:
            log.error(
                "divergence sentinel: NON-FINITE model state at delivered "
                "batch %d — rolled back to verified checkpoint step %s and "
                "skipping the poisoning batch (rollback #%d)",
                self._delivered, meta.get("step", "?"),
                len(self._rollback_points),
            )
        else:
            # nothing to roll back to: reset to the reference's initial
            # state (zeros, LinearRegression.scala:32) — progress is lost
            # but the stream keeps training, which beats NaN forever
            import numpy as np

            from ..features.batch import NUM_NUMBER_FEATURES

            self._model.set_initial_weights(np.zeros(
                (self._num_features + NUM_NUMBER_FEATURES,), np.float32,
            ))
            log.error(
                "divergence sentinel: NON-FINITE model state at delivered "
                "batch %d and no verified checkpoint — model RESET to "
                "initial zeros (rollback #%d); add --checkpointDir to "
                "preserve progress across rollbacks",
                self._delivered, len(self._rollback_points),
            )
        in_window = [
            p for p in self._rollback_points
            if self._delivered - p < self.window
        ]
        if self.max_rollbacks and len(in_window) >= self.max_rollbacks:
            _metrics.get_registry().counter("model.sentinel_aborts").inc()
            _blackbox.record(
                "sentinel_abort", rollbacks=len(in_window),
                window=self.window,
            )
            log.critical(
                "divergence sentinel: %d rollbacks within %d batches — the "
                "stream keeps poisoning the model; aborting the run "
                "cleanly (the shutdown path flushes a final checkpoint "
                "and the process exits non-zero)",
                len(in_window), self.window,
            )
            self._ssc.request_abort()


class ModelWatchGuard:
    """``--modelWatch`` delivery adapter (ISSUE 8): feeds the host-side
    model watcher (telemetry/modelwatch.py) from the quality leaf the
    pipeline ALREADY fetched inside the StepOutput — zero added host
    fetches, zero added collectives, exactly like the sentinel's
    finiteness check — and implements the sentinel EARLY-WARNING hook:
    when the watcher holds ``alert`` for ``--modelWatchWindow`` delivered
    batches, it emits a blackbox event + counter and forces ONE immediate
    verified-checkpoint save per episode (warn-only: the sentinel's
    non-finite rollback machine is untouched — an alerting-but-finite
    model keeps training, it just leaves a restorable snapshot + evidence
    behind before things possibly get worse).

    Multi-host: the quality vector is psum-global, so every host derives
    the same verdict on the same delivered batch; the forced save is
    lead-only inside ``AppCheckpoint`` like every other save."""

    def __init__(self, conf, ckpt: "AppCheckpoint | None", totals: dict,
                 lead: bool = True):
        self.enabled = getattr(conf, "modelWatch", "on") == "on"
        self.window = max(1, int(getattr(conf, "modelWatchWindow", 8) or 1))
        self._ckpt = ckpt
        self._totals = totals
        self._lead = lead
        self._saved_episode = False
        self._alert_saves = _metrics.get_registry().counter(
            "model.alert_checkpoints"
        )

    def observe(self, out, at_boundary: bool = True) -> None:
        """Per-delivery hook (wired OUTSIDE the tenant adapter in
        ``attach_super_batcher``, so the tenant plane's raw [M, Q] quality
        leaf is visible here — per-tenant drift for free)."""
        if not self.enabled or getattr(out, "quality", None) is None:
            return
        import numpy as np

        counts = np.atleast_1d(np.asarray(out.count, np.float64))
        if float(counts.sum()) <= 0:
            return  # an all-padding / globally-empty tick carries no data
        verdict = _modelwatch.record_tick(
            np.asarray(out.quality, np.float64), counts,
            np.asarray(out.mse, np.float64),
        )
        if verdict["level"] != "alert":
            self._saved_episode = False
            return
        if (
            verdict["alert_run"] >= self.window
            and not self._saved_episode
            and at_boundary  # save_now reads weights — they must be current
        ):
            self._saved_episode = True
            self._alert_saves.inc()
            _blackbox.record(
                "modelwatch_alert_checkpoint",
                batches=self._totals.get("batches", 0),
                drift=round(verdict["drift_score"], 3),
                trend=round(verdict["loss_trend"], 4),
            )
            saved = self._ckpt.save_now(self._totals) if (
                self._ckpt is not None
            ) else False
            log.warning(
                "model watch: ALERT held for %d batches (drift z=%.2f, "
                "loss trend %+.1f%%) — %s (early warning only; training "
                "continues, the sentinel still owns rollback)",
                verdict["alert_run"], verdict["drift_score"],
                verdict["loss_trend"] * 100.0,
                "forced a verified-checkpoint save"
                if saved else "no checkpoint dir configured, evidence "
                "recorded to the flight recorder only",
            )


class FreshnessGuard:
    """``--freshness`` delivery adapter (ISSUE 16): pops the batch's lineage
    record at fetch delivery (telemetry/freshness.py — pure host arithmetic
    over stamps the seams already took; zero added host fetches, zero added
    collectives like the sentinel/model-watch checks) and implements the
    ``--freshnessSloMs`` early-warning hook in the ModelWatchGuard shape:
    when the event→delivery lag stays over the SLO for a sustained run, the
    plane emits the blackbox event + counter and this guard forces ONE
    verified-checkpoint save per breach episode (warn-only — a stale-but-
    healthy model keeps training; it just leaves a restorable snapshot
    behind from BEFORE the backlog grew).

    Wired OUTERMOST in ``attach_super_batcher`` so every delivery — even
    ticks the sentinel skips or the multihost filter drops as globally
    empty — advances the lineage FIFO; the FIFOs stay aligned with the
    dispatch order exactly because nothing upstream can swallow a
    delivery before this hook sees it."""

    def __init__(self, conf, ckpt: "AppCheckpoint | None" = None,
                 totals: "dict | None" = None, lead: bool = True):
        self.enabled = getattr(conf, "freshness", "on") == "on"
        self._ckpt = ckpt
        self._totals = totals if totals is not None else {}
        self._lead = lead
        self._saved_episode = False
        self._slo_saves = _metrics.get_registry().counter(
            "freshness.slo_checkpoints"
        )

    def observe(self, out, at_boundary: bool = True) -> None:
        if not self.enabled:
            return
        verdict = _freshness.record_delivery()
        if verdict is None:
            return
        if not verdict["breach"]:
            self._saved_episode = False
            return
        if (
            verdict["in_episode"]
            and not self._saved_episode
            and at_boundary  # save_now reads weights — they must be current
        ):
            self._saved_episode = True
            self._slo_saves.inc()
            saved = self._ckpt.save_now(self._totals) if (
                self._ckpt is not None
            ) else False
            log.warning(
                "freshness guard: event lag %.0f ms over SLO for %d "
                "batches (critical edge: %s) — %s (warn-only; training "
                "continues, the sentinel still owns rollback)",
                verdict["event_lag_ms"], verdict["breach_run"],
                verdict["critical"] or "?",
                "forced a verified-checkpoint save"
                if saved else "no checkpoint dir configured, evidence "
                "recorded to the flight recorder only",
            )


class ProcessRecycler:
    """``--recycleAfterMb``: bounded process lifetime as a MECHANISM, not
    just a diagnosis (VERDICT r4 #7 — the RSS watchdog warns about the
    axon-client transfer-buffer retention but could not act). When process
    RSS crosses the configured ABSOLUTE ceiling, the next weights-current
    batch boundary checkpoints and re-execs the process in place
    (``os.execv`` — same interpreter, same argv, same environment).
    Restore is exact (``AppCheckpoint``: weights + counters resume
    bit-identically), so the recycle is invisible to the learning
    trajectory; a live source simply reconnects and continues, while a
    replay source restarts its file exactly as a manual
    checkpoint-restart would (the flag targets long-lived LIVE/tunnel
    deployments — the regime the retention affects).

    Refused multi-host (one host exec'ing would desert the lockstep group;
    recycle the whole group externally) and without ``--checkpointDir``
    (nothing to resume from). ``TWTML_RECYCLE_MAX`` caps recycles per
    process lineage (the count rides the ``TWTML_RECYCLES`` env var across
    execs); unbounded by default."""

    def __init__(self, conf, ckpt: AppCheckpoint, totals: dict,
                 sample_every: int = 1):
        import os as _os

        self.threshold = float(getattr(conf, "recycleAfterMb", 0) or 0)
        self._ticks = 0
        # sample on every boundary by default: rss_mb is a ~µs statm read
        # and boundaries are already sparse in back-to-back mode (the
        # attach_super_batcher cadence); TWTML_RECYCLE_SAMPLE_EVERY remains
        # the test hook pinning WHICH boundary recycles
        self._sample_every = max(
            1,
            int(_os.environ.get("TWTML_RECYCLE_SAMPLE_EVERY", sample_every)),
        )
        if self.threshold <= 0:
            return
        import jax

        if jax.process_count() > 1:
            raise SystemExit(
                "--recycleAfterMb is single-host: a multi-host lockstep "
                "group cannot lose a member mid-collective — recycle the "
                "whole group externally on the RSS watchdog's warning"
            )
        if not getattr(conf, "checkpointDir", ""):
            raise SystemExit(
                "--recycleAfterMb needs --checkpointDir (a recycle is "
                "checkpoint + re-exec; without a checkpoint the restart "
                "would train from zeros)"
            )
        self._ckpt = ckpt
        self._totals = totals
        self._lineage = int(_os.environ.get("TWTML_RECYCLES", "0") or 0)
        self._max = int(_os.environ.get("TWTML_RECYCLE_MAX", "0") or 0)
        self._capped_warned = False

    def check(self, at_boundary: bool = True) -> None:
        """Call per batch from the app handler, AFTER the cadence
        checkpoint logic. Samples RSS every ``sample_every`` ticks; only a
        weights-current boundary may recycle (the snapshot must include
        this batch)."""
        if self.threshold <= 0 or not at_boundary:
            return
        self._ticks += 1
        if self._ticks % self._sample_every:
            return
        from ..utils.rss import rss_mb

        cur = rss_mb()
        if cur < self.threshold:
            return
        if self._max and self._lineage >= self._max:
            if not self._capped_warned:
                self._capped_warned = True
                log.warning(
                    "RSS %.0f MB over the --recycleAfterMb ceiling but "
                    "TWTML_RECYCLE_MAX=%d reached; running on", cur, self._max,
                )
            return
        self._recycle(cur)

    def _recycle(self, cur_mb: float) -> None:
        import os as _os
        import sys as _sys

        self._ckpt.save_now(self._totals)
        main = _sys.modules.get("__main__")
        spec = getattr(main, "__spec__", None)
        if spec is not None and spec.name:
            argv = [_sys.executable, "-m", spec.name] + _sys.argv[1:]
        else:
            argv = [_sys.executable] + _sys.argv
        log.warning(
            "process RSS %.0f MB crossed --recycleAfterMb %.0f: "
            "checkpointed at batch %d (count=%d, state crc %s) and "
            "re-exec'ing (recycle #%d of this lineage). Resume is exact.",
            cur_mb, self.threshold, self._totals["batches"],
            self._totals["count"],
            state_checksum(self._ckpt._get_state()),
            self._lineage + 1,
        )
        _os.environ["TWTML_RECYCLES"] = str(self._lineage + 1)
        for h in list(log.handlers) or []:
            try:
                h.flush()
            except Exception:  # lawcheck: disable=TW005 -- best-effort log flush immediately before execv; a sick handler must not stop the recycle
                pass
        _sys.stdout.flush()
        _sys.stderr.flush()
        _os.execv(_sys.executable, argv)


class FetchWatchdog:
    """Deadline + bounded-retry + clean-abort guard over the pooled host
    fetches (FetchPipeline / SuperBatcher).

    Why it is safe to retry: a ``device_get`` through this transport is an
    RTT-bound REQUEST, not a wait-for-arrival (BENCHMARKS.md r3) — the
    device arrays stay resident, so a fetch that missed its deadline or
    raised can simply be RE-ISSUED; a duplicate concurrent get reads the
    same bytes. The deadline derives from the health monitor's rolling
    fetch RTT (``FETCH_DEADLINE_MULT`` × median, clamped to
    [``FETCH_DEADLINE_MIN_S``, ``FETCH_DEADLINE_MAX_S``]) — deliberately
    generous, because tunnel stalls legitimately burst for minutes and a
    retry only helps a LOST request, not a stalled transport.

    After ``retries`` re-issues the run aborts CLEANLY instead of the
    pre-guard behavior (an untimed ``future.result()`` = a silent permanent
    hang): the abort hook marks the run failed and stops the stream, the
    app's shutdown path flushes a final checkpoint, and the process exits
    non-zero with a critical log line.

    Env overrides (ops/test hooks): ``TWTML_FETCH_DEADLINE_S`` pins a fixed
    deadline; ``TWTML_FETCH_RETRIES`` overrides the retry budget.
    Constructor args win over both."""

    def __init__(self, health, abort=None, deadline_s: float = 0.0,
                 retries: "int | None" = None):
        import os as _os

        self._health = health
        self._abort = abort
        self.deadline_s = deadline_s or float(
            _os.environ.get("TWTML_FETCH_DEADLINE_S", "0") or 0
        )
        self.retries = (
            retries if retries is not None
            else int(_os.environ.get("TWTML_FETCH_RETRIES", FETCH_RETRIES))
        )
        reg = _metrics.get_registry()
        self._retry_count = reg.counter("fetch.retries")
        self._abort_count = reg.counter("fetch.aborts")
        self.aborted = False

    def deadline(self) -> float:
        if self.deadline_s > 0:
            return self.deadline_s
        med_s = self._health.median_ms() / 1e3
        if med_s <= 0:
            # no samples yet (first fetch of the run): be maximally patient
            return FETCH_DEADLINE_MAX_S
        return min(
            max(FETCH_DEADLINE_MULT * med_s, FETCH_DEADLINE_MIN_S),
            FETCH_DEADLINE_MAX_S,
        )

    def await_result(self, future, reissue):
        """Blocking wait for a pooled fetch future under the deadline;
        ``reissue()`` must submit a fresh fetch of the same device output
        and return its future."""
        from concurrent.futures import TimeoutError as _FutTimeout

        attempts = 0
        while True:
            deadline = self.deadline()
            try:
                return future.result(timeout=deadline)
            except _FutTimeout:
                why = f"made no progress within its {deadline:.1f}s deadline"
            except Exception as exc:  # lawcheck: disable=TW005 -- not a swallow: the failure is captured into `why` and drives the watchdog's retry/abort machine below
                why = f"failed ({exc!r})"
            attempts += 1
            if attempts > self.retries:
                self.aborted = True
                self._abort_count.inc()
                _trace.get().instant("fetch_abort", attempts=attempts)
                _blackbox.record("fetch_abort", attempts=attempts, why=why)
                log.critical(
                    "pooled stats fetch %s after %d attempt(s); aborting "
                    "the run — the stream stops and the shutdown path "
                    "flushes a final checkpoint (FetchWatchdog)",
                    why, attempts,
                )
                if self._abort is not None:
                    self._abort()
                raise FetchAbort(
                    f"pooled fetch {why} after {attempts} attempts"
                )
            self._retry_count.inc()
            _blackbox.record("fetch_retry", attempt=attempts, why=why)
            log.warning(
                "pooled stats fetch %s; re-issuing (retry %d/%d — a "
                "device_get is an RTT-bound request, a duplicate is safe)",
                why, attempts, self.retries,
            )
            future = reissue()


_codec_fallback_warned = False


def _dispatch_lease(wire, *batches):
    """The arena lease(s) a dispatch must hold until its fetch delivers:
    the packed wire's own lease plus the featurize-stage lease riding
    each unpacked batch (the one-pass native featurizer, r18, leases its
    output arrays from the same arena). Identity-deduplicating — an
    unpacked dispatch sees the same object through both views."""
    from ..features.arena import chain_leases

    return chain_leases(
        getattr(wire, "_lease", None),
        *(getattr(b, "_lease", None) for b in batches),
    )


def _record_wire_codec(wire, requested: str) -> None:
    """Per-pack codec telemetry (r15 satellite): the compressed-units
    split from ``features/batch.wire_composition`` → the
    ``wire.units_compressed_bytes`` + ``wire.codec_ratio`` gauges on
    /api/metrics (dashboard "wire ratio" tile). A pack that REQUESTED the
    codec but shipped raw (non-ASCII-widened units, or an incompressible
    batch) is the loud per-batch fallback: counted in
    ``wire.codec_fallbacks`` and warned once per process. Pure layout
    math — no array reads, no fetches."""
    global _codec_fallback_warned
    if not requested or requested == "off":
        return
    from ..features.batch import wire_composition

    comp = wire_composition(wire)
    reg = _metrics.get_registry()
    phys = comp.get("units_compressed")
    if phys is None:
        reg.counter("wire.codec_fallbacks").inc()
        reg.gauge("wire.codec_ratio").set(1.0)
        reg.gauge("wire.units_compressed_bytes").set(comp.get("units", 0))
        if not _codec_fallback_warned:
            _codec_fallback_warned = True
            log.warning(
                "wire codec requested but this batch shipped RAW "
                "(non-ASCII-widened units or incompressible) — counted "
                "in wire.codec_fallbacks; further fallbacks are silent"
            )
        return
    reg.gauge("wire.units_compressed_bytes").set(phys)
    if phys:
        reg.gauge("wire.codec_ratio").set(
            round(comp["units"] / phys, 3)
        )


class SuperBatcher:
    """Group K featurized micro-batches into ONE device dispatch
    (``model.step_many``: a lax.scan of the ordinary train step) and re-emit
    each batch's StepOutput to ``handle`` in order.

    Why: in replay/back-to-back regimes every per-batch stats fetch costs a
    full transport round trip (~100 ms through this build's TPU tunnel —
    BENCHMARKS.md), capping the telemetry-on path at ~17k tweets/s. The
    scan fetches K batches' stats as one array (~K×), and r3 additionally
    POOLS the group fetches (``fetch_depth`` concurrent in-order
    ``device_get``s, the FetchPipeline mechanism): measured, the combined
    form beats either lever alone — 6.7× vs sync in its window vs 4.5×
    for pooled singles (tools/bench_telemetry.py ``super8_pool4``).
    Semantics are unchanged: batch boundaries, per-batch stats,
    predict-then-train ordering, and final weights are bitwise those of K
    sequential ``step`` calls (tests/test_superbatch.py). Requires pinned
    batch buckets (every grouped batch must share one shape).

    ``handle(out, batch, batch_time)`` receives plain-numpy per-batch
    outputs in order; ``at_boundary`` is True only when the model's
    weights are current as of that batch (group tail with nothing newer
    dispatched — drains at ``boundary_every`` cadence points keep
    checkpoint saves correct). ``max_dispatch`` caps trained batches at
    group granularity (the documented up-to-K−1 overshoot). Call
    ``flush()`` after the stream terminates.

    Only contiguous SAME-SHAPE batches group (one compiled scan program): a
    batch that overflowed a pinned bucket, or flipped the units wire dtype,
    closes the pending group first and starts its own — it is never
    silently dropped, and partial groups run as plain steps (identical
    math, no one-off scan compiles at odd lengths). The ragged wire groups
    too (r5): its data-dependent units bucket is part of the shape
    signature, so only same-bucket batches share a scan program (totals
    concentrate tightly — steady state is one or two buckets).

    ``deterministic`` (multi-host mode) disables the opportunistic
    already-done early emit, exactly like FetchPipeline's: handler side
    effects then fire only at points driven by the dispatch counter, which
    advances identically on every lockstep host.

    ``wire_pack="group"`` (Lean wire v2, ``--wirePack``) coalesces each
    full group's K ragged batches into ONE contiguous buffer
    (``features/batch.pack_ragged_group`` — mesh/multi-host models lay it
    out per shard via ``pack_group_for_wire``) uploaded by a single
    main-thread put, instead of the stacked wire's K-per-field arrays; the
    scanned program unpacks the segments in-jit, so the math and the
    per-batch stats stay bitwise identical (tests/test_superwire.py).
    Partial groups then pack their single batches through the k=1
    one-buffer wire (``pack_for_wire``/``pack_batch``) for the same lean
    layout. Grouping is already by shape signature, so the group layout is
    a pure function of (signature, K) — one compiled program per group
    shape, exactly like the stacked wire."""

    def __init__(self, model, k: int, handle, fetch_depth: int = 4,
                 boundary_every: int = 0, max_dispatch: int = 0,
                 deterministic: bool = False, abort=None,
                 fetch_deadline_s: float = 0.0,
                 fetch_retries: "int | None" = None,
                 wire_pack: str = "stacked",
                 wire_codec: str = ""):
        from concurrent.futures import ThreadPoolExecutor

        self.model = model
        self.k = k
        self.handle = handle
        self.fetch_depth = max(1, fetch_depth)
        self.max_dispatch = max_dispatch
        self.deterministic = deterministic
        if wire_pack not in ("stacked", "group"):
            raise ValueError(f"wire_pack must be 'stacked' or 'group', got {wire_pack!r}")
        self.wire_pack = wire_pack
        # compressed units wire (--wireCodec, r15): forwarded to the plain
        # features/batch packers below; model-aware packers carry their own
        # ``wire_codec`` attribute (parallel/sharding.py, tenants.py)
        self.wire_codec = wire_codec
        # model-aware coalesced/group packers (mesh models shard the one
        # buffer; multi-host models assemble it globally); plain models use
        # the features/batch host packers
        self._group_packer = getattr(model, "pack_group_for_wire", None)
        self._single_packer = getattr(model, "pack_for_wire", None)
        # cadence drains count DISPATCHED BATCHES (partial groups included),
        # honoring the pre-r3 contract: the first boundary at/after each
        # cadence point
        self.boundary_every = boundary_every
        self._last_boundary = 0
        # model-aware host transfers (MultiHostSGDModel localizes the
        # lead's predictions inside the pooled fetch); plain models use
        # jax.device_get
        self._fetch_many = getattr(model, "fetch_output_many", None)
        self._fetch_one = getattr(model, "fetch_output", None)
        # observability: timed group fetches feed the tunnel-health monitor
        # (one fetch REQUEST per group — fetch.count counts requests, so a
        # K-group still increments by 1)
        self._registry = _metrics.get_registry()
        self._health = _metrics.get_health_monitor()
        self._fetch_count = self._registry.counter("fetch.count")
        self._fetch_hist = self._registry.histogram("fetch.latency_s")
        self._depth_gauge = self._registry.gauge("fetch.queue_depth")
        self._refund_count = self._registry.counter("fetch.refunds")
        self._pool = ThreadPoolExecutor(
            max_workers=self.fetch_depth,
            thread_name_prefix="twtml-group-fetch",
        )
        # deadline/retry/abort guard over every pooled group fetch — the
        # pre-guard future.result() was a silent permanent hang on a
        # wedged tunnel (FetchWatchdog)
        self._watchdog = FetchWatchdog(
            self._health, abort=abort,
            deadline_s=fetch_deadline_s, retries=fetch_retries,
        )
        self._buf: list = []
        self._sig = None
        self._inflight: list = []  # [(future, group, outs)] oldest first
        self._dispatched = 0
        # checkpoint cadence runs on its own MONOTONIC counter, exactly as
        # in FetchPipeline: a refund_dispatch adjusts only the cap
        # accounting and must not drift the boundary cadence (r5 review —
        # the same r3 advisor finding, re-introduced here)
        self._cadence = 0

    @staticmethod
    def _signature(batch):
        # tree_flatten, not tuple(batch): the ragged wire's batch is not a
        # NamedTuple, and its static aux (row_len, shard alignment) must be
        # part of the one-compiled-program signature — the treedef carries
        # both the class and the aux
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(batch)
        return (treedef,) + tuple((a.shape, str(a.dtype)) for a in leaves)

    def on_batch(self, batch, batch_time) -> None:
        if self._watchdog.aborted:
            return  # fetch abort in flight: nothing more may train
        if self.max_dispatch and self._dispatched >= self.max_dispatch:
            # cap reached: deliver what trained so the handler-side stop
            # fires (see FetchPipeline), train nothing more
            self._drain()
            return
        sig = self._signature(batch)
        if self._buf and sig != self._sig:
            self._close_group()  # shape/dtype changed: close, never drop
        self._sig = sig
        self._buf.append((batch, batch_time))
        if len(self._buf) >= self.k:
            self._close_group()

    def _emit_group(self) -> None:
        from ..models.base import StepOutput

        future, group, outs, lease = self._inflight.pop(0)
        try:
            host = self._watchdog.await_result(
                future,
                lambda: self._pool.submit(
                    self._timed_fetch_many, outs, len(group)
                ),
            )
        except FetchAbort:
            # the group trained but its outputs are gone with the wedged
            # tunnel: refund the cap slots so every dispatched batch is
            # either delivered to the handler or refunded (flush refunds
            # the remaining in-flight groups the same way); the wire
            # buffer's arena lease is discarded, never reused — the
            # dispatch may still execute on the wedged backend
            if lease is not None:
                lease.discard()
            for _ in group:
                self.refund_dispatch()
            raise
        last = len(group) - 1
        # _buf is provably empty at every emit site, so the pipeline being
        # drained is the whole weights-current condition
        boundary_ok = not self._inflight
        for k, (batch, t) in enumerate(group):
            self.handle(
                # a multi-host follower's predictions field is None (the
                # lead owns per-row telemetry) — pass None through
                StepOutput(*(
                    None if f is None else f[k] for f in host
                )),
                batch, t,
                at_boundary=(k == last and boundary_ok),
            )
        if lease is not None:
            # fetch delivered ⇒ the dispatch consumed its wire bytes;
            # retired AFTER the handlers (the lease may chain the group
            # batches' featurize-stage arrays — see FetchPipeline)
            lease.retire()

    def _timed_fetch_many(self, outs, group_len: int):
        """Timed pooled group fetch — see FetchPipeline._timed_fetch."""
        import time as _time

        import jax

        fetch = self._fetch_many or jax.device_get
        t0 = _time.perf_counter()
        _faults.perturb("fetch")  # --chaos: inside the timed window, so
        # injected stalls feed the health monitor like real ones
        host = fetch(outs)
        dt = _time.perf_counter() - t0
        self._fetch_count.inc()
        self._fetch_hist.observe(dt)
        self._health.observe(dt)
        _sideband.record_stage("fetch", dt)
        tr = _trace.get()
        if tr.enabled:
            tr.complete("fetch", t0, dt, depth=self.fetch_depth,
                        group=group_len)
        return host

    def _timed_fetch_one(self, out_dev):
        """Single-batch pooled fetch (the partial-group path), timed like
        ``_timed_fetch_many``."""
        import time as _time

        import jax

        fetch = self._fetch_one or jax.device_get
        t0 = _time.perf_counter()
        _faults.perturb("fetch")
        host = fetch(out_dev)
        dt = _time.perf_counter() - t0
        self._fetch_count.inc()
        self._fetch_hist.observe(dt)
        self._health.observe(dt)
        _sideband.record_stage("fetch", dt)
        tr = _trace.get()
        if tr.enabled:
            tr.complete("fetch", t0, dt, depth=1)
        return host

    def refund_dispatch(self) -> None:
        """Give back one ``max_dispatch`` slot (multi-host globally-empty
        batches — see FetchPipeline.refund_dispatch)."""
        self._dispatched -= 1
        self._refund_count.inc()

    def _drain(self) -> None:
        while self._inflight:
            self._emit_group()

    def drain(self) -> None:
        """Deliver every in-flight group NOW without dispatching more —
        the elastic membership plane calls this before a group re-forms
        (nothing may stay in flight across a backend rebuild; buffered
        undispatched batches are host-side and survive untouched)."""
        self._drain()

    def drain_discard(self, why: str) -> int:
        """Rescue-path drain (elastic detach, ``clean=False``): a peer
        died mid-step, so in-flight groups' collectives are POISONED —
        see FetchPipeline.drain_discard. Discards every in-flight group
        (cap slots refunded, leases discarded, rows counted in
        ``elastic.rows_discarded_inflight``); buffered UNDISPATCHED
        batches stay — they are host-side, never touched a collective,
        and train correctly against the rolled-back state after the
        reform. Returns the discarded row count."""
        if not self._inflight:
            return 0
        groups, rows = len(self._inflight), 0
        for future, group, _outs, lease in self._inflight:
            future.cancel()  # not-yet-started fetches never run
            for batch, _t in group:
                rows += int(getattr(batch, "num_valid", 0) or 0)
                self.refund_dispatch()
            if lease is not None:
                lease.discard()  # the dead-peer dispatch may still run
        self._inflight.clear()
        self._depth_gauge.set(0)
        self._registry.counter("elastic.rows_discarded_inflight").inc(rows)
        log.warning(
            "elastic rescue: discarded %d in-flight group(s) (~%d row(s))"
            " — %s; the resync restores the verified checkpoint, so these"
            " rolled-back rows are counted in "
            "elastic.rows_discarded_inflight, never awaited", groups, rows,
            why,
        )
        return rows

    def _coalesce(self, batch) -> bool:
        """Whether this batch rides the coalesced one-buffer wire (group
        mode, ragged wire, and a model whose jit program unpacks it)."""
        from ..features.batch import RaggedUnitBatch

        return (
            self.wire_pack == "group"
            and isinstance(batch, RaggedUnitBatch)
            and getattr(self.model, "accepts_packed", False)
        )

    def _group_wire(self, batches):
        """The step_many wire for one full group: the coalesced one-buffer
        pack (ONE main-thread put; uint16-delta offsets) in group mode, the
        stacked K-per-field arrays otherwise — bit-identical math either
        way (tests/test_superwire.py)."""
        from ..features.batch import (
            pack_ragged_group, stack_batches, wire_nbytes,
        )
        import time as _time

        t0 = _time.perf_counter()
        if not self._coalesce(batches[0]):
            wire = stack_batches(batches)
            _sideband.record_stage("wire_pack", _time.perf_counter() - t0)
            return wire
        packer = self._group_packer or (
            lambda bs: pack_ragged_group(bs, codec=self.wire_codec or None)
        )
        tr = _trace.get()
        if tr.enabled:
            with tr.span(
                "wire_pack", mode="group", batches=len(batches)
            ) as sp:
                wire = packer(batches)
                sp.add(wire_bytes=wire_nbytes(wire))
        else:
            wire = packer(batches)
        _record_wire_codec(wire, self._codec_requested())
        _sideband.record_stage("wire_pack", _time.perf_counter() - t0)
        return wire

    def _codec_requested(self) -> str:
        """The codec this batcher's wire is SUPPOSED to carry — the
        pipeline-level setting for the plain packers, the model's own
        attribute for model-aware packers (they pack with it directly)."""
        if self._group_packer or self._single_packer:
            return getattr(self.model, "wire_codec", "") or ""
        return self.wire_codec

    def _close_group(self) -> None:
        if not self._buf:
            return
        group, self._buf = self._buf, []
        if len(group) < self.k:
            # partial group (tail, or a shape change): plain steps — the
            # same math, and no fresh scan compile for a one-off length.
            # Earlier groups must emit first (strict batch order), and the
            # max_dispatch cap binds here exactly like on full groups.
            # In group mode the singles still ride the k=1 one-buffer wire
            # (pack_for_wire / pack_batch), so a partial tail keeps the
            # coalesced layout's lean offsets.
            self._drain()
            tr = _trace.get()
            for batch, t in group:
                if self.max_dispatch and self._dispatched >= self.max_dispatch:
                    return
                import time as _time

                wire = batch
                if self._coalesce(batch):
                    from ..features.batch import pack_batch

                    packer = self._single_packer or (
                        lambda b: pack_batch(
                            b, codec=self.wire_codec or None
                        )
                    )
                    t0 = _time.perf_counter()
                    if tr.enabled:
                        with tr.span("wire_pack", mode="single"):
                            wire = packer(batch)
                    else:
                        wire = packer(batch)
                    _sideband.record_stage(
                        "wire_pack", _time.perf_counter() - t0
                    )
                    _record_wire_codec(wire, self._codec_requested())
                t0 = _time.perf_counter()
                _faults.perturb("step")  # --chaos dispatch injection
                out_dev = self.model.step(wire)
                dt = _time.perf_counter() - t0
                _sideband.record_stage("dispatch", dt)
                _lineage.mark_dispatch()
                if tr.enabled:
                    tr.complete("dispatch", t0, dt)
                # dispatch-time accounting, as on the grouped path; if the
                # awaited fetch aborts, the slot is refunded (the batch
                # trained but was never delivered — cap accounting follows
                # deliveries, same rule as _emit_group/flush)
                self._dispatched += 1
                self._cadence += 1
                # same watchdog as the pooled paths (the fetch rides the
                # pool so the deadline can fire; awaited immediately, so
                # the partial path stays effectively synchronous)
                lease = _dispatch_lease(wire, batch)
                try:
                    out = self._watchdog.await_result(
                        self._pool.submit(self._timed_fetch_one, out_dev),
                        lambda: self._pool.submit(
                            self._timed_fetch_one, out_dev
                        ),
                    )
                except FetchAbort:
                    if lease is not None:
                        lease.discard()  # wedged dispatch: no reuse
                    self.refund_dispatch()
                    raise
                self.handle(out, batch, t, at_boundary=True)
                if lease is not None:
                    lease.retire()  # after the handler — see _emit_one
            return
        # backpressure + timeliness, as in FetchPipeline (the already-done
        # probe is wall-clock-dependent, so deterministic/multi-host mode
        # skips it — emits then happen only at counter-driven points)
        while len(self._inflight) >= self.fetch_depth or (
            not self.deterministic
            and self._inflight and self._inflight[0][0].done()
        ):
            self._emit_group()
        wire = self._group_wire([b for b, _ in group])
        import time as _time

        tr = _trace.get()
        t0 = _time.perf_counter()
        _faults.perturb("step")  # --chaos dispatch injection
        outs = self.model.step_many(wire)
        dt = _time.perf_counter() - t0
        _sideband.record_stage("dispatch", dt)
        _lineage.mark_dispatch(len(group))
        if tr.enabled:
            tr.complete("dispatch", t0, dt, group=len(group),
                        depth=len(self._inflight))
        self._inflight.append(
            (self._pool.submit(self._timed_fetch_many, outs, len(group)),
             group, outs, _dispatch_lease(wire, *(b for b, _ in group)))
        )
        self._depth_gauge.set(len(self._inflight))
        self._dispatched += len(group)
        self._cadence += len(group)
        if self.boundary_every and (
            self._cadence - self._last_boundary >= self.boundary_every
        ):
            self._drain()  # cadence point: weights current for checkpoints
            self._last_boundary = self._cadence

    def flush(self) -> None:
        try:
            self._close_group()  # a partial tail drains inflight itself
            self._drain()
        except FetchAbort:
            # already logged + the abort hook fired; the app's shutdown
            # path owns the final checkpoint flush — never raise into it
            if self._inflight or self._buf:
                # refund the dispatched-but-undelivered batches riding the
                # dropped in-flight groups (they trained, but their outputs
                # are gone with the wedged tunnel — cap accounting follows
                # deliveries; buffered batches never dispatched, nothing to
                # refund there)
                for _future, group, _outs, lease in self._inflight:
                    if lease is not None:
                        lease.discard()  # wedged dispatches: no reuse
                    for _ in group:
                        self.refund_dispatch()
                log.warning(
                    "dropping %d in-flight group(s) and %d buffered "
                    "batch(es) after the fetch abort",
                    len(self._inflight), len(self._buf),
                )
                self._inflight.clear()
                self._buf.clear()
        finally:
            # shutdown in a finally: an exception re-raised from
            # future.result() during the drain must not leak the executor
            self._pool.shutdown(wait=False)


class FetchPipeline:
    """Depth-D concurrent stats fetch for back-to-back regimes: the main
    thread dispatches ``model.step(batch)`` and hands each StepOutput's
    host fetch to a small thread pool; completed outputs are consumed IN
    ORDER on the main thread.

    Why: the per-batch stats fetch through this build's TPU tunnel is a
    ~70–100 ms RTT-bound REQUEST — a one-batch-lagged fetch measured
    NEUTRAL (0.996×; starting the copy early doesn't shorten the request),
    but CONCURRENT ``device_get``s pipeline the transport: measured
    **6.2× paired** at depth 8, batch 2048 (17k → 108k median tweets/s,
    tools/bench_telemetry.py; BENCHMARKS.md). Dispatch and ``device_put``
    stay on the main thread — the measured r2 throughput collapse is
    put-specific; gets from worker threads are exactly what the 6.2×
    measurement exercised.

    Semantics vs the synchronous path: per-batch stats identical and in
    order; ``at_boundary`` is True only when nothing newer has been
    dispatched (pipeline drained — end of stream, or a ``boundary_every``
    cadence drain so checkpoint saves still see current weights, exactly
    like the superbatch's group boundaries); ``max_dispatch`` caps how
    many batches may train, so max-batches stops stay EXACT (the cap is
    enforced before dispatch, not discovered after). ``flush()`` after
    stream termination drains the tail.

    ``deterministic`` (multi-host mode) disables the opportunistic
    already-done early emit: handler side effects (request_stop,
    empty-global refunds) then fire only at DETERMINISTIC points — the
    depth backpressure, cadence drains, cap drains, and flush — all driven
    by the dispatch counter, which advances identically on every lockstep
    host. With the opportunistic emit, one host could see a stop/refund a
    tick earlier than a peer (wall-clock-dependent ``done()``), exit the
    lockstep loop early, and leave the peer blocked in its next
    collective (r3 advisor finding)."""

    def __init__(self, model, handle, depth: int = 8, stop_requested=None,
                 boundary_every: int = 0, max_dispatch: int = 0,
                 pack: bool = False, deterministic: bool = False,
                 abort=None, fetch_deadline_s: float = 0.0,
                 fetch_retries: "int | None" = None,
                 wire_codec: str = ""):
        from concurrent.futures import ThreadPoolExecutor

        self.model = model
        self.handle = handle
        self.depth = max(1, depth)
        # compressed units wire (--wireCodec, r15): forwarded to the plain
        # pack_batch below; model-aware packers carry their own attribute
        self.wire_codec = wire_codec
        # one-buffer wire: measured +11.4% paired on the ragged wire
        # through this transport (per-ARRAY request overhead stops hiding
        # once the wire is lean); handlers still receive the UNPACKED
        # batch. The pack itself is model-aware (r5): mesh models lay the
        # buffer out PER SHARD so the data axis can shard it
        # (ParallelSGDModel.pack_for_wire), multi-host models additionally
        # assemble the global buffer from every host's local shard segments
        # (MultiHostSGDModel.pack_for_wire); plain models use the
        # field-major features/batch.pack_batch
        self.pack = pack
        self._packer = getattr(model, "pack_for_wire", None)
        self.deterministic = deterministic
        self._stop_requested = stop_requested
        self.boundary_every = boundary_every
        self.max_dispatch = max_dispatch
        # model-aware host transfer (MultiHostSGDModel.fetch_output defers
        # the lead's prediction localization into the pooled fetch); plain
        # models use jax.device_get
        self._fetch = getattr(model, "fetch_output", None)
        self._pool = ThreadPoolExecutor(
            max_workers=self.depth, thread_name_prefix="twtml-stats-fetch"
        )
        # observability (side-channel only): every pooled fetch is timed and
        # fed to the tunnel-health monitor + fetch-latency histogram; no
        # extra host fetch is ever issued — the timing wraps the ONE fetch
        # this pipeline already makes per batch
        self._registry = _metrics.get_registry()
        self._health = _metrics.get_health_monitor()
        self._fetch_count = self._registry.counter("fetch.count")
        self._fetch_hist = self._registry.histogram("fetch.latency_s")
        self._depth_gauge = self._registry.gauge("fetch.queue_depth")
        self._refund_count = self._registry.counter("fetch.refunds")
        # deadline/retry/abort guard over every pooled fetch — the
        # pre-guard future.result() in _emit_one was a silent permanent
        # hang on a wedged tunnel (FetchWatchdog)
        self._watchdog = FetchWatchdog(
            self._health, abort=abort,
            deadline_s=fetch_deadline_s, retries=fetch_retries,
        )
        self._pending: list = []  # [(future, out, batch, t)] oldest first
        self._head_since = None  # poll()'s head-fetch deadline bookkeeping
        self._dispatched = 0
        # checkpoint cadence runs on its own MONOTONIC counter: a
        # refund_dispatch must not make the cap accounting pass a cadence
        # point twice or skip it (r3 advisor finding)
        self._cadence = 0
        self._last_boundary = 0

    def _timed_fetch(self, out):
        """The pooled host fetch, timed for the tunnel-health monitor and
        the ``fetch`` trace stage. This wraps the ONE fetch the pipeline
        already makes per batch — instrumentation never adds a
        ``device_get`` (BENCHMARKS.md measurement integrity)."""
        import time as _time

        import jax

        fetch = self._fetch or jax.device_get
        t0 = _time.perf_counter()
        _faults.perturb("fetch")  # --chaos: inside the timed window, so
        # injected stalls feed the health monitor like real ones
        host = fetch(out)
        dt = _time.perf_counter() - t0
        self._fetch_count.inc()
        self._fetch_hist.observe(dt)
        self._health.observe(dt)
        _sideband.record_stage("fetch", dt)
        tr = _trace.get()
        if tr.enabled:
            tr.complete("fetch", t0, dt, depth=self.depth)
        return host

    def _emit_one(self) -> None:
        future, out, batch, t, lease = self._pending.pop(0)
        try:
            host = self._watchdog.await_result(
                future, lambda: self._pool.submit(self._timed_fetch, out)
            )
        except FetchAbort:
            # the dispatch may still execute on the wedged backend: never
            # donate its wire buffer back for reuse (features/arena.py)
            if lease is not None:
                lease.discard()
            raise
        self.handle(host, batch, t, at_boundary=not self._pending)
        if lease is not None:
            # fetch delivered ⇒ the dispatch consumed its wire bytes: the
            # arena lease retires to the pool. AFTER the handler — the
            # lease may chain the batch's featurize-stage arrays (r18),
            # which delivery handlers still read (tenant re-routing,
            # per-batch stats), and a prefetching featurize thread must
            # not be handed the buffer while they do
            lease.retire()

    def _drain(self) -> None:
        while self._pending:
            self._emit_one()

    def on_batch(self, batch, t) -> None:
        import jax

        if self._watchdog.aborted:
            return  # fetch abort in flight: nothing more may train
        stop = self._stop_requested
        if stop is not None and stop():
            return  # stop requested: nothing more may train
        if self.max_dispatch and self._dispatched >= self.max_dispatch:
            # cap reached: later batches must not train — but whatever DID
            # train must still be delivered NOW, or the handler-side stop
            # (max-batches → request_stop) never fires and an unbounded
            # live source keeps batching forever
            self._drain()
            return
        # backpressure + timeliness: block down to depth-1 in flight, then
        # opportunistically consume whatever already finished (skipped in
        # deterministic/multi-host mode — see the class docstring)
        while len(self._pending) >= self.depth or (
            not self.deterministic
            and self._pending and self._pending[0][0].done()
        ):
            self._emit_one()
            if stop is not None and stop():
                return  # the cap landed on an emitted batch: do not dispatch
        tr = _trace.get()
        import time as _time

        if self.pack:
            from ..features.batch import pack_batch

            packer = self._packer or (
                lambda b: pack_batch(b, codec=self.wire_codec or None)
            )
            t0 = _time.perf_counter()
            if tr.enabled:
                from ..features.batch import wire_nbytes

                with tr.span("wire_pack", mode="single") as sp:
                    wire = packer(batch)
                    sp.add(wire_bytes=wire_nbytes(wire))
            else:
                wire = packer(batch)
            _sideband.record_stage("wire_pack", _time.perf_counter() - t0)
            _record_wire_codec(
                wire,
                (getattr(self.model, "wire_codec", "") or "")
                if self._packer else self.wire_codec,
            )
        else:
            wire = batch
        # argument uploads ride the dispatch on this transport (no
        # separate device_put on the single-host hot path); timed
        # unconditionally for the sideband's upload attribution, with the
        # --chaos injection INSIDE the window so injected dispatch stalls
        # attribute like real ones
        t0 = _time.perf_counter()
        _faults.perturb("step")  # --chaos dispatch injection
        out = self.model.step(wire)  # dispatch on the MAIN thread
        dt = _time.perf_counter() - t0
        _sideband.record_stage("dispatch", dt)
        _lineage.mark_dispatch()
        if tr.enabled:
            tr.complete("dispatch", t0, dt, depth=len(self._pending))
        self._pending.append(
            (self._pool.submit(self._timed_fetch, out), out, batch, t,
             _dispatch_lease(wire, batch))
        )
        self._depth_gauge.set(len(self._pending))
        self._dispatched += 1
        self._cadence += 1
        if self.boundary_every and (
            self._cadence - self._last_boundary >= self.boundary_every
        ):
            self._drain()  # cadence point: weights current for checkpoints
            self._last_boundary = self._cadence

    def refund_dispatch(self) -> None:
        """Give back one ``max_dispatch`` slot — called by handlers that
        SKIP a delivered batch (multi-host globally-empty batches: they
        dispatch for collective alignment but must not count toward a
        max-batches cap, or capped runs under-train)."""
        self._dispatched -= 1
        self._refund_count.inc()

    def drain(self) -> None:
        """Deliver every pending output NOW without dispatching more —
        the elastic membership plane calls this before a group re-forms
        (nothing may stay in flight across a backend rebuild)."""
        self._drain()

    def drain_discard(self, why: str) -> int:
        """Rescue-path drain (elastic detach, ``clean=False``): a peer
        died mid-step, so any in-flight output's collectives are POISONED
        — their buffer definition events fail permanently
        (FAILED_PRECONDITION "Gloo all-reduce failed"), and awaiting them
        just burns the fetch watchdog's re-issues before it aborts the
        whole run (measured on the 2-host lead-kill storm,
        tools/chaos_fleet.py). The reform restores the lead's verified
        checkpoint anyway, so the rescue DISCARDS in-flight outputs
        instead of awaiting them: cap slots refunded (every dispatched
        batch is either delivered or refunded), arena leases discarded
        (the dead-peer dispatch may still touch its wire buffer — never
        reuse), and the rolled-back rows counted loudly in
        ``elastic.rows_discarded_inflight``. Clean commits keep the
        lossless ``drain()``. Returns the discarded row count."""
        if not self._pending:
            return 0
        n, rows = len(self._pending), 0
        for future, _out, batch, _t, lease in self._pending:
            future.cancel()  # not-yet-started fetches never run
            rows += int(getattr(batch, "num_valid", 0) or 0)
            self.refund_dispatch()
            if lease is not None:
                lease.discard()
        self._pending.clear()
        self._depth_gauge.set(0)
        self._registry.counter("elastic.rows_discarded_inflight").inc(rows)
        log.warning(
            "elastic rescue: discarded %d in-flight batch output(s) "
            "(~%d row(s)) — %s; the resync restores the verified "
            "checkpoint, so these rolled-back rows are counted in "
            "elastic.rows_discarded_inflight, never awaited", n, rows, why,
        )
        return rows

    @property
    def pending_fetches(self) -> int:
        """In-flight pooled fetches (the serving plane's idle loop reads
        this to pick its poll cadence)."""
        return len(self._pending)

    def poll(self) -> None:
        """Emit any already-completed in-order results WITHOUT dispatching —
        the serving plane's idle tick, so predictions deliver promptly when
        no new request arrives to trigger the on_batch emit path. Skipped
        in deterministic (multi-host lockstep) mode for the same reason the
        opportunistic early emit is: wall-clock-dependent ``done()`` must
        not drive side effects there.

        The watchdog deadline holds here too: a head fetch that outlives
        it with NO follow-up traffic (the idle-server wedged-tunnel case)
        is emitted through the BLOCKING path, whose watchdog re-issues and
        eventually aborts — without this, a stalled fetch on a quiet
        serving plane would hang its clients until the next request."""
        if self.deterministic:
            return
        while self._pending and self._pending[0][0].done():
            self._emit_one()
        if not self._pending:
            self._head_since = None
            return
        import time as _time

        head = self._pending[0][0]
        now = _time.monotonic()
        since = getattr(self, "_head_since", None)
        if since is None or since[0] is not head:
            self._head_since = (head, now)
            return
        if now - since[1] > self._watchdog.deadline():
            self._head_since = None
            self._emit_one()  # blocking: the watchdog owns it from here

    def flush(self) -> None:
        try:
            self._drain()
        except FetchAbort:
            # already logged + the abort hook fired; the app's shutdown
            # path owns the final checkpoint flush — never raise into it
            if self._pending:
                log.warning(
                    "dropping %d undelivered batch output(s) after the "
                    "fetch abort", len(self._pending),
                )
                for _f, _o, _b, _t, lease in self._pending:
                    if lease is not None:
                        lease.discard()  # wedged dispatches: no reuse
                self._pending.clear()
        finally:
            # shutdown in a finally: an exception re-raised from
            # future.result() during the drain must not leak the executor
            self._pool.shutdown(wait=False)


def _rebalance_intake(source, old_members, new_members, my_uid: int,
                      reason: str) -> None:
    """Intake rebalance across an elastic membership change. Departed
    hosts' residue classes are adopted round-robin by survivors (exact
    going-forward coverage — streaming/sources.py); a REJOINED host's
    handling is source-kind-aware: live id-sharded streams hand its
    residues back (ids are position-free), replay index shards keep them
    with the adopters (the rejoiner becomes a hot standby — re-reading its
    file shard from zero would double-train). Sources with no residue
    surface (block byte-range shards) lose the departed range, counted."""
    sharded = source
    while sharded is not None and not hasattr(sharded, "adopt_residues"):
        sharded = getattr(sharded, "inner", None)
    departed = sorted(u for u in old_members if u not in new_members)
    rejoined = sorted(u for u in new_members if u not in old_members)
    reg = _metrics.get_registry()
    if sharded is None:
        if departed:
            reg.counter("elastic.shards_lost").inc(len(departed))
            log.warning(
                "elastic: this source kind cannot adopt departed shard(s) "
                "%s — their remaining rows are lost (counted in "
                "elastic.shards_lost)", departed,
            )
        return
    survivors = sorted(u for u in new_members if u in old_members)
    for i, uid in enumerate(departed):
        owner = survivors[i % len(survivors)] if survivors else -1
        if owner == my_uid:
            sharded.adopt_residues([uid])
    from ..streaming.sources import IdShardedSource

    if rejoined and isinstance(sharded, IdShardedSource):
        # live stream: the rejoiner's fresh connection resumes its id
        # residues from now — adopters release them (position-free keys)
        sharded.release_residues(rejoined)
    if my_uid in rejoined and not isinstance(sharded, IdShardedSource):
        # replay standby: contribute all-padding batches; the adopters own
        # the residues and the weights stay bit-synchronized regardless
        sharded.residues.clear()
        log.warning(
            "elastic: rejoined a replay-sharded run as a hot standby "
            "(index shards are position-bound; residues stay with their "
            "adopters)"
        )


def attach_elastic(conf, ssc, model, stream, ckpt, totals):
    """``--elastic on`` wiring: build the membership plane over the
    elastic runtime formed in ``init_distributed`` and install it on the
    streaming context. The two transition callbacks close over the whole
    app stack so a membership change is a full re-provisioning:

    detach — drain the fetch pipeline (nothing in flight across a backend
    rebuild; a RESCUE discards in-flight outputs instead — a dead peer
    poisons their collectives, ``drain_discard``), on a CLEAN commit
    checkpoint at the boundary (loss-free), then abandon the epoch's
    process group;

    attach — form the new epoch, rebuild the mesh + model in place,
    re-synchronize state/counters from the lead (broadcast of its verified
    checkpoint — the PR 4 path), rebalance intake shards across the new
    membership, and pre-compile the step for the new world so the first
    post-reform tick doesn't stall.

    Returns the plane (or None when the run is not elastic); pass it to
    ``attach_super_batcher`` so the pipeline drain hook binds."""
    import jax

    from ..parallel import elastic as _elastic
    from ..streaming.membership import MembershipPlane

    runtime = _elastic.get_runtime()
    if runtime is None or jax.process_count() <= 1:
        return None
    source = ssc._source
    if runtime.joined_late:
        # a restarted host admitted into a LIVE run: its replay-index
        # residues were adopted by the incumbents when it departed —
        # re-reading its file shard from zero would double-train, so it
        # contributes as a hot standby (live id-sharded sources keep their
        # residues: the incumbents release them, _rebalance_intake)
        from ..streaming.sources import IdShardedSource

        sharded = source
        while sharded is not None and not hasattr(sharded, "adopt_residues"):
            sharded = getattr(sharded, "inner", None)
        if sharded is not None and not isinstance(sharded, IdShardedSource):
            sharded.residues.clear()
            log.warning(
                "elastic: joined a live replay-sharded run as a hot "
                "standby (residues stay with their adopters)"
            )
    st: dict = {
        "pipeline": None, "group_k": 1,
        "old_members": list(runtime.members),
    }

    def detach(clean: bool) -> None:
        st["old_members"] = list(runtime.members)
        pipe = st.get("pipeline")
        if pipe is not None:
            if clean:
                pipe.drain()
            else:
                # a rescue: the dead peer poisoned any in-flight step's
                # collectives — discard them (rows counted, resync rolls
                # them back) instead of awaiting permanently-failed
                # buffers into a watchdog abort
                pipe.drain_discard("a peer died mid-step")
        if clean:
            # every member is alive and synchronized at a clean commit
            # tick: the lead snapshots HERE so the resync after formation
            # restores exactly the pre-transition state — zero loss
            ckpt.save_now(totals)
        runtime.abandon()

    def attach(plan: dict, reason: str) -> None:
        runtime.form(plan["epoch"], plan["members"])
        if runtime.is_lead:
            # a won election lands here: checkpoint authority moves to
            # this host BEFORE the resync broadcast below, so the fleet
            # restores from the WINNER's verified archives (idempotent —
            # an incumbent lead is already promoted)
            ckpt.promote()
        mesh = build_mesh(conf, what=f"elastic epoch {plan['epoch']}")
        model.rebuild(mesh)
        if reason == "rejoin":
            # a rejoiner's queued rows predate its absence; the adopters
            # own that coverage now — training them would double-train
            dropped = sum(
                getattr(s, "rows", 1) for s in ssc._drain(0)
            )
            if dropped:
                _metrics.get_registry().counter(
                    "elastic.rows_dropped_rejoin"
                ).inc(dropped)
                log.warning(
                    "elastic: dropped %d stale queued row(s) on rejoin "
                    "(counted in elastic.rows_dropped_rejoin)", dropped,
                )
        pre_resync = (int(totals["count"]), int(totals["batches"]))
        ckpt.resync_from_verified(totals)
        # journal-replay conversion (ISSUE 19): after the fleet converges
        # on the lead-agreed rollback point, every host re-ingests ITS OWN
        # journaled rows past its cursor — the in-flight rows a rescue
        # discarded (drain_discard) and the post-checkpoint rows the
        # resync rolled back. Replay rides the lockstep cadence (dry hosts
        # dispatch all-padding); ZERO new collectives. A REJOINER instead
        # resets its journal: its pre-departure coverage moved to the
        # adopters (_rebalance_intake), so replaying it would double-train.
        if _journal.get() is not None:
            # the reform discarded the fetch pipeline's in-flight
            # deliveries wholesale (drain_discard above): their dispatch
            # tokens would strand and desync every later pairing — drop
            # them; the replay below re-covers their rows
            _journal.get().clear_inflight()
            rejoined = set(plan["members"]) - set(st["old_members"])
            if runtime.uid in rejoined:
                _journal.get().reset()
                log.warning(
                    "journal: reset on rejoin — this host's pre-departure "
                    "rows belong to their adopters now"
                )
            else:
                stub = {
                    "count": totals["count"], "batches": totals["batches"],
                }
                if (totals["count"], totals["batches"]) == pre_resync:
                    # nothing rolled back: the resync adopted weights that
                    # cover exactly the delivered batches (the lead's live
                    # weights when no verified checkpoint exists yet, or a
                    # clean-commit save at the current boundary). This
                    # host's COMMITTED delivery cursor is that same point
                    # — no archive lookup needed, so the first reform can
                    # precede the first save and still replay the
                    # discarded in-flight rows instead of counting them.
                    stub["journal"] = (
                        _journal.get().snapshot_for_checkpoint()
                    )
                journal_replay_rollback(
                    ssc, ckpt, totals, stub, where=f"elastic {reason}",
                )
        _rebalance_intake(
            source, st["old_members"], plan["members"], runtime.uid, reason,
        )
        warmup_compile(stream, model, super_batch=st["group_k"])

    plane = MembershipPlane(
        runtime, detach, attach,
        evict_ticks=int(getattr(conf, "elasticEvictTicks", 0) or 0),
        evict_skew_ms=float(getattr(conf, "elasticEvictSkewMs", 250.0)),
        rejoin=getattr(conf, "elasticRejoin", "on") == "on",
    )
    plane._bind_box = st  # attach_super_batcher fills st["pipeline"]
    ssc.membership = plane
    log.info(
        "elastic membership plane ACTIVE: epoch %d, members %s, "
        "evict after %s gating tick(s), rejoin %s",
        runtime.epoch, runtime.members,
        plane.evict_ticks or "∞", "on" if plane.rejoin else "off",
    )
    return plane


def elastic_exit(failed: bool = False) -> None:
    """Elastic runs must leave via a hard exit (abandoned-epoch teardown
    during interpreter finalization LOG(FATAL)s — parallel/elastic.py);
    no-op without an elastic runtime. Call as the LAST line of an app's
    run path, after checkpoints and telemetry have flushed."""
    from ..parallel import elastic as _elastic

    runtime = _elastic.get_runtime()
    if runtime is None:
        return
    log.info(
        "elastic run complete (epoch %d, %d reform(s)); hard exit %d",
        runtime.epoch, len(runtime._graveyard), 1 if failed else 0,
    )
    runtime.finalize_exit(1 if failed else 0)


def attach_super_batcher(conf, stream, model, handle, stop_requested=None,
                         max_dispatch: int = 0, abort=None, sentinel=None,
                         modelwatch=None, elastic=None, freshness=None):
    """Wire the app's per-batch ``handle(out, batch, t, at_boundary)`` to the
    stream: plain step-then-handle by default, grouped through a
    SuperBatcher when ``--superBatch K`` applies. Returns
    ``(flush, effective_k)`` — the app must invoke ``flush`` after
    termination (drains a partial final group) and may pass ``effective_k``
    to ``warmup_compile`` so the scan program pre-compiles too.

    ``at_boundary`` is True whenever the model's weights are current as of
    this batch (always, except mid-group under a superbatch) — the guard for
    side effects that read ``model.latest_weights``, e.g. checkpoints.

    ``stop_requested``: optional predicate (the app's
    ``ssc.stop_requested``) that lets the fetch pipeline honor a
    max-batches stop; ``max_dispatch`` additionally caps how many batches
    may ever train (exact max-batches under the concurrent fetch pipeline
    — see FetchPipeline).

    Group-granular caps: a whole group dispatches as one program, so a
    ``max_batches``-style stop lands on the first group boundary at/after
    the cap (up to K−1 extra batches, deterministic — the documented
    trade of the flag).

    The flag applies only to back-to-back regimes (``--seconds 0``): under a
    wall clock it would delay live telemetry by K intervals, so it downgrades
    with a warning. Grouped batches must share one XLA shape, which pinned
    buckets guarantee — unpinned buckets are an error, matching the
    pre-compile contract (``warmup_compile``)."""
    import jax

    from ..utils.rss import RssWatchdog

    # RSS watchdog on the batch cadence: the long-running loops are where
    # the axon-client transfer-buffer retention accumulates (utils/rss.py)
    watchdog = RssWatchdog()
    guarded_handle = handle

    def handle(out, batch, t, at_boundary=True):  # noqa: F811
        watchdog.tick()
        # journal committed-cursor advance (ISSUE 19): the INNERMOST
        # wrapper — only batches every admission filter accepted (no
        # sentinel skip, no globally-empty no-op) reach here, so the
        # popped dispatch token is safe to commit. BEFORE the app handler:
        # a checkpoint save inside this very delivery must stamp a cursor
        # that covers this batch.
        _j = _journal.get()
        if _j is not None:
            _j.note_delivered()
        guarded_handle(out, batch, t, at_boundary=at_boundary)

    if sentinel is not None and sentinel.enabled:
        # divergence gate between the fetch and the app handler: a
        # non-finite delivery is skipped (rollback handled inside admit);
        # wrapped INSIDE the multi-host empty-batch filter below, so the
        # gate only ever sees batches with rows
        sentinel_inner = handle

        def handle(out, batch, t, at_boundary=True):  # noqa: F811
            if not sentinel.admit(out, batch):
                return
            sentinel_inner(out, batch, t, at_boundary=at_boundary)

    # a tenant-plane model (any M, the forced M=1 differential included)
    # carries num_tenants; plain models don't
    num_tenants = int(getattr(model, "num_tenants", 0) or 0)
    if num_tenants >= 1:
        # multi-tenant model plane: the OUTERMOST delivery wrapper — the
        # fetched [M, ...] StepOutput records the per-tenant view
        # (telemetry/tenants.py, from arrays already on the host — zero
        # added fetches) and collapses to ONE batch-level StepOutput in
        # original row order for the pre-existing chain (sentinel,
        # session stats, checkpoints). M=1 passes through bit-exact.
        import numpy as np

        from ..parallel.tenants import aggregate_tenant_output
        from ..telemetry import tenants as _tenants

        tenant_inner = handle

        def handle(out, batch, t, at_boundary=True):  # noqa: F811
            _tenants.record_tick(
                np.asarray(out.count, np.int64),
                np.asarray(out.mse, np.float64),
            )
            tenant_inner(
                aggregate_tenant_output(out, batch, model), batch, t,
                at_boundary=at_boundary,
            )

    if modelwatch is not None and modelwatch.enabled:
        # model-watch adapter (ISSUE 8), wrapped OUTSIDE the tenant
        # aggregation so it reads the RAW StepOutput — the tenant plane's
        # stacked [M, Q] quality leaf gives per-tenant drift for free;
        # pure host bookkeeping on arrays the fetch already delivered
        mw_inner = handle

        def handle(out, batch, t, at_boundary=True):  # noqa: F811
            modelwatch.observe(out, at_boundary=at_boundary)
            mw_inner(out, batch, t, at_boundary=at_boundary)

    multihost = jax.process_count() > 1
    k = int(getattr(conf, "superBatch", 1) or 1)
    if k > 1 and num_tenants >= 1:
        log.warning(
            "--superBatch %d ignored with --tenants %d: the tenant stack "
            "already amortizes the per-tick stats fetch across its %d "
            "models (scanning K groups of M tenants is future work)",
            k, num_tenants, num_tenants,
        )
        k = 1
    if k > 1 and conf.seconds > 0:
        log.warning(
            "--superBatch %d ignored: wall-clock streaming (--seconds %s) "
            "would delay live stats by %d intervals", k, conf.seconds, k,
        )
        k = 1
    if k > 1 and (stream.row_bucket <= 0 or stream.token_bucket <= 0):
        raise ValueError(
            "--superBatch needs pinned shapes: set --batchBucket and "
            "--tokenBucket so every grouped batch compiles to one program"
        )
    if multihost and (stream.row_bucket <= 0 or stream.token_bucket <= 0):
        raise SystemExit(
            "multi-host runs need pinned shapes: set --batchBucket and "
            "--tokenBucket (every host must dispatch the same collective "
            "program every tick, including all-padding batches)"
        )
    if elastic is not None:
        elastic._bind_box["group_k"] = k  # reform warmup re-compiles k too

    def skip_empty(fn):
        if multihost:
            # a host whose interval/shard came up empty must STILL dispatch
            # its all-padding batch — the other hosts' collectives wait on
            # its program (streaming/context._lockstep_loop)
            return fn

        def cb(batch, t):
            if batch.num_valid == 0:
                log.debug("batch: 0")
                _lineage.drop_newest()  # the shed batch never dispatches
                _js = _journal.get()
                if _js is not None:
                    _js.drop_newest()  # un-push its dispatch token too
                return
            fn(batch, t)

        return cb

    if multihost:
        # the LOCAL batch can't gate the step (collectives above), but a
        # GLOBALLY empty batch (every row filtered out on every host) must
        # not surface to the app — single-host runs skip those pre-step.
        # It must not consume a max-batches slot either (refund below, set
        # once the pipeline exists).
        import numpy as _np

        inner_handle = handle
        pipeline_ref: list = []

        def handle(out, batch, t, at_boundary=True):  # noqa: F811
            # the tenant fleet delivers an [M]-stacked count; a batch is
            # globally empty only when EVERY tenant's share is
            if int(_np.asarray(out.count).sum()) == 0:
                log.debug("batch: 0 (global)")
                if pipeline_ref:
                    pipeline_ref[0].refund_dispatch()
                return
            inner_handle(out, batch, t, at_boundary=at_boundary)

    if freshness is not None and freshness.enabled:
        # freshness adapter (ISSUE 16), the OUTERMOST delivery wrapper:
        # every delivered batch — including ones the sentinel skips or the
        # multihost filter drops as globally empty — must pop its lineage
        # record, or the dispatch-ordered FIFO desynchronizes
        fresh_inner = handle

        def handle(out, batch, t, at_boundary=True):  # noqa: F811
            freshness.observe(out, at_boundary=at_boundary)
            fresh_inner(out, batch, t, at_boundary=at_boundary)

    if _journal.get() is not None:
        # journal dispatch-token pop (ISSUE 19): the OUTERMOST delivery
        # wrapper — every delivered batch, including ones the sentinel
        # skips or the multihost filter drops as globally empty, must pop
        # its token in dispatch order or the committed-cursor pairing
        # desynchronizes (the commit itself happens in the innermost
        # wrapper above, so filtered batches pop without committing)
        journal_pop_inner = handle

        def handle(out, batch, t, at_boundary=True):  # noqa: F811
            _jp = _journal.get()
            if _jp is not None:
                _jp.pop_dispatch()
            journal_pop_inner(out, batch, t, at_boundary=at_boundary)

    # cadence drains exist for checkpoint saves only: without a
    # checkpointDir each drain would stall the fetch pipelining for a
    # no-op save (one rule for both the k=1 and superbatch paths)
    boundary_every = (
        int(getattr(conf, "checkpointEvery", 0) or 0)
        if getattr(conf, "checkpointDir", "")
        else 0
    )
    if int(getattr(conf, "recycleAfterMb", 0) or 0) > 0 and not boundary_every:
        # --recycleAfterMb can only act at weights-current boundaries; in
        # back-to-back mode with no --checkpointEvery the pipeline would
        # otherwise never drain mid-stream and the flag would be silently
        # inert (r5 review) — impose a default recycle-check cadence
        boundary_every = 64

    # the ragged wire additionally ships as ONE packed buffer (measured
    # +11.4% paired — per-array request overhead stops hiding once the
    # wire is lean; bit-identical unpack inside the jit step). Since r5
    # every layout packs: mesh models lay the buffer out per shard and
    # multi-host models assemble it globally (pack_for_wire), so the fast
    # path survives every deployment shape.
    pack = bool(getattr(stream, "ragged", False)) and getattr(
        model, "accepts_packed", False
    )
    # compressed units wire (--wireCodec dict, r15): rides exactly the
    # packed wire forms (pack_batch / the coalesced group wire / the mesh
    # per-shard packs — compression compounds the per-array-overhead trap
    # that made packing the lean-wire default). Model-aware packers carry
    # their own wire_codec attribute (set in build_model / from_conf);
    # this value drives the pipeline-level plain packers.
    wire_codec = ""
    if pack:
        _codec = getattr(conf, "effective_wire_codec", lambda: "off")()
        wire_codec = _codec if _codec == "dict" else ""

    if k <= 1:
        if conf.seconds <= 0:
            # back-to-back: concurrent in-order stats fetches pipeline the
            # transport round trip (measured 6.2x paired at depth 8 —
            # FetchPipeline); checkpoint cadence points drain the pipeline
            # so saves see current weights. Multi-host runs emit only at
            # deterministic points so stop/refund side effects land on the
            # same tick on every lockstep host.
            pipe = FetchPipeline(
                model, handle, stop_requested=stop_requested,
                boundary_every=boundary_every,
                max_dispatch=max_dispatch,
                pack=pack,
                deterministic=multihost,
                abort=abort,
                wire_codec=wire_codec,
            )
            if multihost:
                pipeline_ref.append(pipe)  # empty-batch refunds (above)
            if sentinel is not None:
                sentinel.bind(pipe)  # skipped batches refund their cap slot
            if elastic is not None:
                elastic._bind_box["pipeline"] = pipe  # reform drain hook
            stream.foreach_batch(skip_empty(pipe.on_batch))
            return pipe.flush, 1

        def per_batch(batch, t):
            # wall-clock streaming: ONE synchronous host transfer for the
            # whole StepOutput (sequential scalar fetches each pay a full
            # round trip). The fetch is ~2% of a 5 s interval; a lagged
            # fetch here would delay live dashboard stats a full interval
            # for nothing.
            import time as _time

            tr = _trace.get()
            if pack:
                from ..features.batch import pack_batch

                packer = getattr(model, "pack_for_wire", None) or (
                    lambda b: pack_batch(b, codec=wire_codec or None)
                )
                tp = _time.perf_counter()
                if tr.enabled:
                    with tr.span("wire_pack", mode="single"):
                        wire = packer(batch)
                else:
                    wire = packer(batch)
                _sideband.record_stage(
                    "wire_pack", _time.perf_counter() - tp
                )
                _record_wire_codec(
                    wire,
                    (getattr(model, "wire_codec", "") or "")
                    if getattr(model, "pack_for_wire", None)
                    else wire_codec,
                )
            else:
                wire = batch
            lease = _dispatch_lease(wire, batch)
            td = _time.perf_counter()
            _faults.perturb("step")  # --chaos dispatch injection
            out = model.step(wire)
            d_dt = _time.perf_counter() - td
            _sideband.record_stage("dispatch", d_dt)
            _lineage.mark_dispatch()
            if tr.enabled:
                tr.complete("dispatch", td, d_dt)
            fetch = getattr(model, "fetch_output", None) or jax.device_get
            t0 = _time.perf_counter()
            _faults.perturb("fetch")
            out = fetch(out)
            dt = _time.perf_counter() - t0
            reg = _metrics.get_registry()
            reg.counter("fetch.count").inc()
            reg.histogram("fetch.latency_s").observe(dt)
            _metrics.get_health_monitor().observe(dt)
            _sideband.record_stage("fetch", dt)
            if tr.enabled:
                tr.complete("fetch", t0, dt, depth=1)
            handle(out, batch, t, at_boundary=True)
            if lease is not None:
                lease.retire()  # synchronous fetch: dispatch consumed it
                # (after the handler — the lease may chain the batch's
                # featurize-stage arrays, r18)

        stream.foreach_batch(skip_empty(per_batch))
        return (lambda: None), 1

    batcher = SuperBatcher(
        model, k, handle,
        boundary_every=boundary_every,
        max_dispatch=max_dispatch,
        deterministic=multihost,
        abort=abort,
        # the coalesced one-buffer group wire applies exactly where the
        # k=1 pack does (ragged wire + a model that unpacks in-jit);
        # --wirePack auto resolves to the measured default
        # (config.effective_wire_pack, BENCHMARKS.md "Lean wire v2")
        wire_pack=(
            "group"
            if pack and getattr(
                conf, "effective_wire_pack", lambda: "stacked"
            )() == "group"
            else "stacked"
        ),
        wire_codec=wire_codec,
    )
    if multihost:
        pipeline_ref.append(batcher)  # empty-batch refunds (above)
    if sentinel is not None:
        sentinel.bind(batcher)  # skipped batches refund their cap slot
    if elastic is not None:
        elastic._bind_box["pipeline"] = batcher  # reform drain hook
    # grouping needs every batch in its FINAL layout before the shape
    # signature/stacking: mesh and multi-host models shard-align ragged
    # batches (and harmonize the wire dtype across hosts) in prepare()
    prepare = getattr(model, "prepare", None)
    if prepare is None:
        on_batch = batcher.on_batch
    else:
        def on_batch(batch, t):
            batcher.on_batch(prepare(batch), t)

    stream.foreach_batch(skip_empty(on_batch))
    return batcher.flush, k


def warmup_compile(stream, model, super_batch: int = 1) -> None:
    """Pre-compile the step for the known batch shape BEFORE the stream
    starts, so the first wall-clock micro-batch doesn't swallow the whole
    compile-time backlog (~30 s on a cold TPU chip, during which a live
    source keeps producing). Only possible when --batchBucket AND
    --tokenBucket pin the full XLA program shape (read from the stream's
    own configuration — the single source of truth). The warm batch comes
    from the stream's OWN featurize dispatch (``featurize_empty``) so it
    compiles exactly the program the stream will run; an all-padding batch
    is semantically a no-op for the learner (zero-sample iterations leave
    weights untouched)."""
    if stream.row_bucket <= 0 or stream.token_bucket <= 0:
        return
    import time as _time

    import numpy as np

    from ..features.batch import UnitBatch

    if getattr(stream, "ragged", False):
        # the ragged wire's units-buffer bucket is DATA-dependent (Σ row
        # lengths, rounded to RAGGED_UNIT_MULTIPLE) — an all-padding batch
        # compiles the minimum bucket, not the one real batches will hit,
        # so full pre-compilation is impossible here. Say so instead of
        # logging a readiness that isn't real; the first real batch
        # compiles in-flight (totals concentrate tightly, so steady state
        # is one or two buckets). Live wall-clock streams that cannot
        # afford that stall should use --wire padded.
        log.info(
            "--wire ragged: units bucket is data-dependent; the first real "
            "batch compiles its program in-flight (pre-compile n/a)"
        )
        return
    t0 = _time.perf_counter()
    empty = stream.featurize_empty()
    variants = [empty]
    if isinstance(empty, UnitBatch) and empty.units.dtype == np.uint8:
        # the units wire dtype is per-batch metadata (uint8 iff every row
        # is ASCII — featurizer._pad_ragged_units): warm BOTH programs so
        # a stream's first non-ASCII tweet doesn't stall mid-flight
        variants.append(empty._replace(units=empty.units.astype(np.uint16)))
    for v in variants:
        model.step(v)
    if super_batch > 1:
        # --superBatch dispatches a scanned program too: warm it for the
        # same shapes/dtypes so the first full group doesn't stall
        from ..features.batch import stack_batches

        for v in variants:
            model.step_many(stack_batches([v] * super_batch))
    log.info(
        "pre-compiled the train step for buckets (%d, %d) in %.1fs",
        stream.row_bucket, stream.token_bucket, _time.perf_counter() - t0,
    )
