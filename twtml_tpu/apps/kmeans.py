"""Streaming k-means entry point (reference: KMeans.scala:49-170).

Pipeline kept equivalent: retweets only (``isRetweet`` — NO retweet-interval
filter here, unlike the linear app, KMeans.scala:77-80), featurized to the
dense pair (original's retweetCount, original's followersCount)
(KMeans.scala:19-33), per-batch StandardScaler(false, true), manual
``update(scaled, decayFactor, timeUnit)`` on a k=3 half-life-5-batches model
with random 2-d centers (KMeans.scala:69-73,103-105), then per-batch debug
output of centers and assignments (KMeans.scala:118-127). The live cluster
scatter chart the reference sketches and leaves commented out
(KMeans.scala:89,129-132) is implemented here: per-batch points + predicted
cluster labels stream to a Lightning scatter viz, best-effort like all
telemetry.

Run: ``python -m twtml_tpu.apps.kmeans --source replay --replayFile ...``
"""

from __future__ import annotations

import queue
import sys
import threading

import jax
import numpy as np

from ..config import ConfArguments
from ..features.batch import pad_row_count
from ..features.featurizer import Status
from ..models.kmeans import StreamingKMeans
from ..ops.scaler import standard_scale
from ..streaming.context import StreamingContext
from ..streaming.sources import Source
from ..telemetry.lightning import CHART_MAX_POINTS, Lightning
from ..utils import get_logger
from .common import (
    AppCheckpoint,
    ProcessRecycler,
    build_mesh,
    build_source,
    init_distributed,
    install_blackbox,
    install_chaos,
    install_historian,
    install_trace,
    select_backend,
)

log = get_logger("apps.kmeans")

NUM_DIMENSIONS = 2  # KMeans.scala:57
NUM_CLUSTERS = 3  # KMeans.scala:58
CHART_FAILURE_LIMIT = 5  # consecutive append failures before giving up


def _start_chart_worker(conf) -> "queue.Queue":
    """Daemon thread owning every Lightning call for the cluster chart.
    Returns the frame queue (drop-oldest, depth 2); the worker creates the
    session + scatter viz, then streams frames, giving up for good after
    CHART_FAILURE_LIMIT consecutive failures."""
    q: "queue.Queue" = queue.Queue(maxsize=2)

    def _worker() -> None:
        try:
            lgn = Lightning(host=conf.lightning)
            lgn.create_session(conf.appName())
            viz = lgn.scatter_streaming([], [])
            log.info(
                "lightning cluster chart: %s/visualizations/%s",
                conf.lightning, viz.id,
            )
        except Exception as exc:
            log.warning("lightning unavailable (%s); cluster chart disabled", exc)
            return
        failures = 0
        while failures < CHART_FAILURE_LIMIT:
            x, y, label = q.get()
            try:
                lgn.scatter_streaming(x, y, label=label, viz=viz)
                failures = 0
            except Exception as exc:
                failures += 1
                log.debug("lightning append failed (%s)", exc)
        log.warning("cluster chart disabled after repeated append failures")

    threading.Thread(target=_worker, daemon=True).start()
    return q


def featurize(status: Status) -> np.ndarray:
    """Dense (retweetCount, followersCount) of the original tweet
    (KMeans.scala:19-33)."""
    original = status.retweeted_status
    return np.array(
        [float(original.retweet_count), float(original.followers_count)],
        dtype=np.float32,
    )


def run(conf: ConfArguments, max_batches: int = 0, wall_clock: bool = True) -> dict:
    if getattr(conf, "elastic", "off") == "on":
        # the k-means plane's raw-stream handler owns its own global
        # assembly; the elastic rebuild contract (model.rebuild + the
        # broadcast resync) is wired for the SGD-family apps only
        raise SystemExit(
            "--elastic on is wired for the SGD entry points (linear, "
            "logistic); the k-means plane keeps the abort-on-peer-loss "
            "behavior for now"
        )
    lead = init_distributed(conf)  # every entry point forms the group
    select_backend(conf)
    install_trace(conf)
    install_chaos(conf)
    install_blackbox(conf)  # crash flight recorder (apps/common)
    install_historian(conf)  # telemetry historian (--history, apps/common)
    multihost = jax.process_count() > 1
    if multihost and conf.batchBucket <= 0:
        raise SystemExit(
            "multi-host k-means needs --batchBucket: every host must "
            "dispatch the same fixed-shape collective program each tick"
        )
    # k-means keeps ALL retweets (isRetweet only, NO retweet-count interval —
    # KMeans.scala:77-80): block ingest overrides the parser's interval
    # filter; isRetweet filtering is inherent (rows without a
    # retweeted_status never emit)
    source: Source = build_source(
        conf, allow_block=True, block_interval=(0, 2**62)
    )

    # the scatter chart KMeans.scala:86-96 sets up (and :129-132 appends to,
    # commented out there) — best-effort, training survives telemetry
    # outages. ALL chart network IO (create + per-batch appends) lives on one
    # daemon thread behind a drop-oldest queue: urlopen's timeout doesn't
    # bound DNS resolution, so neither startup nor the batch loop may ever
    # wait on the resolver; a slow chart just skips frames. One chart per
    # RUN: the lead owns it (multi-host followers train silently).
    chart_q = _start_chart_worker(conf) if lead else None

    # mesh-sharded clustering on several devices / --master local[N]: rows
    # shard over 'data', per-center sums psum over ICI (models/kmeans.py)
    model = (
        StreamingKMeans(mesh=build_mesh(conf, what="clustering"))
        .set_k(NUM_CLUSTERS)
        .set_half_life(5, "batches")
        .set_random_centers(NUM_DIMENSIONS, 0.0)
    )
    scale = jax.jit(standard_scale)
    ssc = StreamingContext(
        batch_interval=conf.seconds,
        # bounded intake backpressure — same guard as the SGD apps; the
        # k-means stream has no SGD sentinel (its state is decayed
        # averages, not gradient-updated weights)
        max_queue_rows=conf.effective_max_queue_rows(),
        shed_policy=conf.shedPolicy,
    )
    totals = {"count": 0, "batches": 0}

    # checkpoint/resume of the cluster state — same upgrade as the SGD apps
    # (SURVEY.md §5.4); state = centers + per-center decay weights
    ckpt = AppCheckpoint(
        conf,
        get_state=lambda: {
            "centers": model.latest_centers,
            "weights": np.asarray(model.cluster_weights),
        },
        set_state=lambda st: model.set_initial_centers(
            st["centers"], st["weights"]
        ),
        totals=totals,
        lead=lead,
    )
    recycler = ProcessRecycler(conf, ckpt, totals)

    # multi-host: the fixed per-host row shape (lockstep drains cap at it)
    local_bucket = (
        pad_row_count(
            conf.batchBucket, conf.batchBucket,
            max(1, model.num_data // jax.process_count()),
        )
        if multihost
        else 0
    )

    from ..utils.rss import RssWatchdog

    watchdog = RssWatchdog()  # axon-client retention guard (utils/rss.py)

    def on_batch_multihost(statuses: list[Status], _batch_time) -> None:
        """Per-host sharded k-means batch: local rows → one global
        row-sharded point matrix (`host_local_rows_to_global`), the
        per-batch StandardScaler computed GLOBALLY (jit over the global
        array — XLA inserts the mean/var collectives), and the mesh
        update's per-center psums span every host. A host with no rows
        still dispatches (all-padding — the update is a state no-op when
        the GLOBAL batch is empty, models/kmeans.py)."""
        from jax.experimental import multihost_utils

        from ..parallel.distributed import (
            host_local_rows_to_global,
            local_rows,
        )

        retweets = [s for s in statuses if s.is_retweet]
        if len(retweets) > local_bucket:
            log.error(
                "dropping %d rows over --batchBucket in multi-host "
                "lockstep (raise --batchBucket)",
                len(retweets) - local_bucket,
            )
            retweets = retweets[:local_bucket]
        n = len(retweets)
        pts = np.zeros((local_bucket, NUM_DIMENSIONS), np.float32)
        if n:
            pts[:n] = np.stack([featurize(s) for s in retweets])
        mask = np.zeros((local_bucket,), np.float32)
        mask[:n] = 1.0
        g_pts = host_local_rows_to_global(pts, model.mesh)
        g_mask = host_local_rows_to_global(mask, model.mesh)
        scaled_g = scale(g_pts, g_mask)
        assign = model.update(scaled_g, g_mask)[:n]  # this host's rows
        centers = model.latest_centers
        sl = local_rows(scaled_g)[:n]
        pred = (
            np.argmin(
                ((sl[:, None, :] - centers[None]) ** 2).sum(-1), axis=1
            )
            if n
            else np.zeros((0,), np.int64)
        )
        # ONE tiny allgather agrees global count + global cluster sizes
        # (every host calls it — lockstep keeps the order aligned)
        agg = multihost_utils.process_allgather(
            np.concatenate(
                [[n], np.bincount(pred, minlength=NUM_CLUSTERS)]
            ).astype(np.int64)
        ).sum(axis=0)
        n_global, sizes = int(agg[0]), agg[1:]
        if n_global == 0:
            log.debug("batch: 0 (global)")  # the update was a state no-op
            return
        totals["count"] += n_global
        totals["batches"] += 1
        watchdog.tick()
        if lead:
            print(
                f"count: {totals['count']}  batch: {n_global}  "
                f"centers: {np.round(centers, 3).tolist()}  "
                f"sizes: {sizes.tolist()}",
                flush=True,
            )
            log.debug("assignments: %s", assign.tolist())
            m = min(n, CHART_MAX_POINTS)
            try:
                chart_q.put_nowait((sl[:m, 0], sl[:m, 1], pred[:m]))
            except queue.Full:
                pass
        ckpt.maybe_save(totals)
        recycler.check()
        if max_batches and totals["batches"] >= max_batches:
            ssc.request_stop()

    def _rows_for(n: int) -> int:
        """The central padding policy (features/batch.py): power-of-two
        bucket, rounded to the mesh's data-axis multiple."""
        return pad_row_count(n, 0, model.num_data)

    def on_batch(statuses: list[Status], _batch_time) -> None:
        from ..features.blocks import COL_FOLLOWERS, COL_LABEL, ParsedBlock, merge_blocks

        if statuses and isinstance(statuses[0], ParsedBlock):
            # block ingest: both k-means dimensions are numeric columns —
            # the whole featurization is one vectorized slice
            block = merge_blocks(statuses)
            n = block.rows
            if n == 0:
                log.debug("batch: 0")
                return
            rows = _rows_for(n)
            pts = np.zeros((rows, NUM_DIMENSIONS), np.float32)
            pts[:n, 0] = block.numeric[:, COL_LABEL]
            pts[:n, 1] = block.numeric[:, COL_FOLLOWERS]
        else:
            retweets = [s for s in statuses if s.is_retweet]  # KMeans.scala:77-80
            if not retweets:
                log.debug("batch: 0")
                return
            n = len(retweets)
            rows = _rows_for(n)
            pts = np.zeros((rows, NUM_DIMENSIONS), np.float32)
            pts[:n] = np.stack([featurize(s) for s in retweets])
        mask = np.zeros((rows,), np.float32)
        mask[:n] = 1.0
        scaled = np.asarray(scale(pts, mask))
        assign = model.update(scaled, mask)[:n]
        pred = model.predict(scaled[:n])
        totals["count"] += n
        totals["batches"] += 1
        watchdog.tick()
        centers = model.latest_centers
        print(
            f"count: {totals['count']}  batch: {n}  "
            f"centers: {np.round(centers, 3).tolist()}  "
            f"sizes: {np.bincount(pred, minlength=NUM_CLUSTERS).tolist()}",
            flush=True,
        )
        log.debug("assignments: %s", assign.tolist())
        # subsample like session_stats.py: don't pay a multi-MB JSON encode
        # per batch at bench-scale batch sizes; drop the frame if the chart
        # worker is behind (latest batch wins)
        m = min(n, CHART_MAX_POINTS)
        try:
            chart_q.put_nowait((scaled[:m, 0], scaled[:m, 1], pred[:m]))
        except queue.Full:
            pass
        ckpt.maybe_save(totals)
        recycler.check()
        if max_batches and totals["batches"] >= max_batches:
            ssc.request_stop()

    # --batchBucket caps back-to-back drains in single-host mode too, so
    # replay batching is deterministic (and the multi-host fixed shape)
    ssc.raw_stream(
        source,
        row_bucket=local_bucket if multihost else max(0, conf.batchBucket),
    ).foreach_batch(on_batch_multihost if multihost else on_batch)
    try:
        if wall_clock or multihost:
            # multi-host always uses the lockstep scheduler (collective
            # cadence agreement), whatever the batch interval
            ssc.start(lockstep=multihost)
            try:
                ssc.await_termination()
            except KeyboardInterrupt:
                pass
            finally:
                ssc.stop()
        else:
            ssc.run_to_completion()
    finally:
        # like the sibling apps: the shutdown save must survive a handler
        # exception or Ctrl-C (run_to_completion raises on the main thread)
        from ..telemetry import trace as pipeline_trace

        pipeline_trace.uninstall()  # flush + close the --trace file
        ckpt.final_save(totals)
        from ..telemetry import historian as _historian_mod

        # perfGuard baseline stamps on CLEAN shutdown only
        if not ssc.failed:
            _historian_mod.stamp_baseline()
        _historian_mod.uninstall()
    if ssc.failed:
        raise RuntimeError(
            "run aborted by a runtime guard — lockstep peer loss or a fetch "
            "watchdog abort (see critical log above); progress up to the "
            "failure is checkpointed"
        )
    return totals


def main(argv=None) -> None:
    conf = (
        ConfArguments()
        .setAppName("twitter-stream-ml-kmeans")
        .parse(list(sys.argv[1:] if argv is None else argv))
    )
    totals = run(conf)
    log.info("done: %s tweets in %s batches", totals["count"], totals["batches"])


if __name__ == "__main__":
    main()
