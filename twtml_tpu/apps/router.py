"""Fleet front door — N serve replicas behind ONE router (ISSUE 11).

Boots the dashboard web server with a ``FleetRouter`` attached instead of a
model: ``POST /api/predict`` forwards each request to a replica per
``--routePolicy`` (least-p99 or consistent-hash), a failing replica is
drained/ejected behind a jittered backoff while its traffic retries on the
others, and ``GET /api/fleet`` serves the live fleet view (also broadcast
on the jsonClass wire for dashboards, next to a Metrics snapshot carrying
``router.retries``/``fleet.replica_ejections``).

Deployment shape (the horizontal read axis, ROADMAP item 2): ONE trainer
writes verified checkpoints; N serve replicas each poll that directory
through their own ``SnapshotPromoter`` (they promote independently but
converge on the same stamped step — ``is_promotable`` is one predicate);
THIS process owns the front door and no model, so it boots in milliseconds
and adds zero device work to the host:

    python -m twtml_tpu.apps.serve --checkpointDir ck --servePort 8888
    python -m twtml_tpu.apps.serve --checkpointDir ck --servePort 8889
    python -m twtml_tpu.apps.router --routerPort 8899 \
        --replicas http://127.0.0.1:8888,http://127.0.0.1:8889

    curl -s localhost:8899/api/predict -d '{"rows": [{"text": "hello"}]}'

jax-free on purpose: the router never imports the model layer, so the one
host core stays with the replicas' featurize/dispatch work.
"""

from __future__ import annotations

import sys
import threading
import time

from ..config import ConfArguments
from ..utils import get_logger

log = get_logger("apps.router")

PUBLISH_EVERY_S = 2.0


def run(conf: ConfArguments, started=None, stop_event=None,
        max_seconds: float = 0.0) -> dict:
    """Boot router → web server; route until ``stop_event``/SIGINT/
    ``max_seconds``. ``started(server, router)`` fires once the front door
    is live (the test hook). Returns the final fleet view."""
    urls = [u.strip() for u in (conf.replicas or "").split(",") if u.strip()]
    if not urls:
        raise SystemExit(
            "--replicas is required: the router fronts serve replicas "
            "(comma-separated base URLs, e.g. "
            "--replicas http://127.0.0.1:8888,http://127.0.0.1:8889)"
        )
    from ..serving.fleet import FleetRouter
    from ..telemetry import metrics as _metrics
    from ..telemetry.web_client import WebClient
    from ..web.server import Server

    router = FleetRouter(
        urls,
        policy=getattr(conf, "routePolicy", "p99"),
        # forwards must outlive a replica's own watchdog-bounded fetch path
        timeout=max(float(getattr(conf, "webTimeout", 2.0)), 30.0),
    ).start()
    server = Server(port=conf.routerPort).attach_fleet(router)
    server.start_background()
    port = server._runner.addresses[0][1]
    web = WebClient(f"http://127.0.0.1:{port}",
                    timeout=float(getattr(conf, "webTimeout", 2.0)))
    log.info(
        "fleet front door live: POST /api/predict on port %d over %d "
        "replica(s), policy=%s", port, len(urls), router.policy,
    )
    if started is not None:
        started(server, router)

    t0 = time.monotonic()
    stop_event = stop_event or threading.Event()
    try:
        while not stop_event.is_set():
            if max_seconds and time.monotonic() - t0 >= max_seconds:
                break
            stop_event.wait(PUBLISH_EVERY_S)
            try:
                # the Fleet view + a Metrics snapshot (router.retries /
                # fleet.replica_ejections land on /api/metrics) ride the
                # same additive jsonClass wire as every dashboard payload
                web.fleet(router.stats())
                snap = _metrics.get_registry().snapshot()
                web.metrics(
                    snap.get("counters", {}), snap.get("gauges", {}),
                    {}, snap.get("histograms", {}),
                )
            except Exception:
                log.debug("fleet publish failed", exc_info=True)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
        stats = router.stats()
        server.stop()
    log.info(
        "router session done: %s requests, %s retries, %s ejections",
        stats["requests"], stats["retries"], stats["ejections"],
    )
    return stats


def main(argv=None) -> None:
    conf = (
        ConfArguments()
        .setAppName("twitter-stream-ml-router")
        .parse(list(sys.argv[1:] if argv is None else argv))
    )
    run(conf)


if __name__ == "__main__":
    main()
