"""Last-value API cache with config persistence (reference: ApiCache.scala).

Holds the most recent Stats and Config JSON. Only Config survives restarts:
it is backed up to ``{tmpdir}/twtml-web.json`` on every cacheConfig
(ApiCache.scala:27-31,54-56) and restored at boot unless ``-nocache``
(Main.scala:12-14) — so reconnecting dashboards can re-embed their charts
while stats restart from zero (SURVEY.md §2.5 "Stats survive only in memory;
Config survives restarts").
"""

from __future__ import annotations

import os
import tempfile

import collections
import json as _json

from ..telemetry.api_types import (
    Config, Fleet, Freshness, History, Hosts, Metrics, ModelHealth, Series,
    Serving, Stats, Tenants, decode, encode,
)
from ..utils import get_logger

log = get_logger("web.cache")

BACKUP_FILE = os.path.join(tempfile.gettempdir(), "twtml-web.json")

# rolling chart history: enough for a few minutes of batches on a dashboard
SERIES_WINDOW = 64


class ApiCache:
    def __init__(self, backup_file: str = BACKUP_FILE):
        self.backup_file = backup_file
        self._stats = Stats()
        self._config = Config()
        self._metrics = Metrics()
        self._hosts = Hosts()
        self._tenants = Tenants()
        self._model = ModelHealth()
        self._serving = Serving()
        self._fleet = Fleet()
        self._freshness = Freshness()
        self._history = History()
        self._series: collections.deque[Series] = collections.deque(
            maxlen=SERIES_WINDOW
        )

    def config(self) -> str:
        return encode(self._config)

    def stats(self) -> str:
        return encode(self._stats)

    def metrics(self) -> str:
        """Latest pipeline-metrics snapshot (in-memory only, like Stats)."""
        return encode(self._metrics)

    def hosts(self) -> str:
        """Latest per-host lockstep sideband view (in-memory only)."""
        return encode(self._hosts)

    def tenants(self) -> str:
        """Latest per-tenant model-plane view (in-memory only)."""
        return encode(self._tenants)

    def model(self) -> str:
        """Latest model-health view (in-memory only, like Stats)."""
        return encode(self._model)

    def serving(self) -> str:
        """Latest serving-plane view (in-memory only, like Stats)."""
        return encode(self._serving)

    def fleet(self) -> str:
        """Latest read-fleet view (in-memory only, like Stats)."""
        return encode(self._fleet)

    def freshness(self) -> str:
        """Latest end-to-end freshness view (in-memory only, like Stats)."""
        return encode(self._freshness)

    def history(self) -> str:
        """Latest telemetry-historian view (in-memory only, like Stats)."""
        return encode(self._history)

    def series(self) -> str:
        """Recent Series messages as a JSON array (chart backfill for
        dashboards that connect mid-run; in-memory only, like Stats)."""
        from dataclasses import asdict

        return _json.dumps(
            [{"jsonClass": s.json_class, **asdict(s)} for s in self._series]
        )

    def cache(self, json_text: str) -> None:
        """Dispatch on the jsonClass hint (ApiCache.scala:41-48); unknown
        payloads are logged and dropped."""
        try:
            data = decode(json_text)
        except Exception:
            # log-and-drop contract (ApiCache.scala:47): a malformed payload
            # must never 500 a POST or tear down a websocket
            log.error("json not recognized: %s", json_text)
            return
        if isinstance(data, Stats):
            log.debug("caching stats")
            self._stats = data
        elif isinstance(data, Metrics):
            self._metrics = data
        elif isinstance(data, Hosts):
            self._hosts = data
        elif isinstance(data, Tenants):
            self._tenants = data
        elif isinstance(data, ModelHealth):
            self._model = data
        elif isinstance(data, Serving):
            self._serving = data
        elif isinstance(data, Fleet):
            self._fleet = data
        elif isinstance(data, Freshness):
            self._freshness = data
        elif isinstance(data, History):
            self._history = data
        elif isinstance(data, Series):
            self._series.append(data)
        else:
            log.debug("caching config")
            self._config = data
            self.backup()

    def backup(self) -> None:
        with open(self.backup_file, "w", encoding="utf-8") as fh:
            fh.write(self.config())

    def restore(self) -> None:
        try:
            with open(self.backup_file, encoding="utf-8") as fh:
                self.cache(fh.read())
        except Exception:  # lawcheck: disable=TW005 -- reference Try parity: best-effort restore, ApiCache.scala:50-52
            pass
