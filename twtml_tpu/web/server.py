"""Dashboard web server (reference: twtml-web's Socko server, Server.scala +
ApiHandler.scala).

Same route surface and broadcast semantics as the reference:

- ``POST /api``        → cache payload, respond ``{"status":"OK"}``, broadcast
                         the raw JSON to every live websocket
                         (ApiHandler.scala:50-57);
- ``GET /api/config``  → cached Config JSON (ApiHandler.scala:38-42);
- ``GET /api/stats``   → cached Stats JSON (ApiHandler.scala:44-48);
- ``WS /api``          → on connect, push the cached Config to the new socket
                         (ApiHandler.scala:68-73); every inbound frame is
                         cached and broadcast to ALL sockets including the
                         sender (ApiHandler.scala:59-67);
- ``GET /``            → dashboard index, ``GET /*`` → static assets
                         (Server.scala:54-59), 404 otherwise.

Netty/Akka actors become one asyncio event loop (aiohttp); the per-message
fire-once actor pattern is just a coroutine per request. ``start_background``
runs the loop in a daemon thread so tests and the training CLI can embed the
server in-process — the pattern the reference's WebTestSuite used by calling
Main.main directly (WebTestSuite.scala:22).
"""

from __future__ import annotations

import asyncio
import json
import mimetypes
import threading
from importlib import resources as _res

from aiohttp import WSMsgType, web

from ..utils import get_logger
from .cache import ApiCache

log = get_logger("web.server")

OK = json.dumps({"status": "OK"})


class Server:
    def __init__(self, port: int = 8888, host: str = "0.0.0.0",
                 cache: ApiCache | None = None):
        self.port = port
        self.host = host
        self.cache = cache if cache is not None else ApiCache()
        self._websockets: set[web.WebSocketResponse] = set()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner: web.AppRunner | None = None
        self._started = threading.Event()
        self._assets = _res.files("twtml_tpu.web").joinpath("assets")
        # serving front door (ISSUE 9): a ServingPlane attached by the
        # serve entry point makes POST /api/predict live; without one the
        # route answers 503 (this process has no model)
        self._serving = None
        # fleet front door (ISSUE 11): a FleetRouter attached by the router
        # entry point makes POST /api/predict a forwarding proxy over the
        # replica fleet and GET /api/fleet a LIVE router view
        self._fleet = None

    def attach_serving(self, plane) -> "Server":
        """Attach a ``serving.ServingPlane``: POST /api/predict submits to
        its coalescer and awaits the pipelined result future."""
        self._serving = plane
        return self

    def attach_fleet(self, router) -> "Server":
        """Attach a ``serving.FleetRouter``: POST /api/predict forwards to
        a replica per the route policy (failed replicas eject + retry on
        another — the client never sees a single replica's death), and
        GET /api/fleet answers with live router stats."""
        self._fleet = router
        return self

    # -- handlers ------------------------------------------------------------
    async def _post_api(self, request: web.Request) -> web.StreamResponse:
        text = await request.text()
        log.debug("http - post data %s", text)
        self.cache.cache(text)
        await self._broadcast(text)
        return web.Response(text=OK, content_type="application/json")

    async def _get_config(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.config(), content_type="application/json")

    async def _get_stats(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.stats(), content_type="application/json")

    async def _get_series(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.series(), content_type="application/json")

    async def _get_metrics(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.metrics(), content_type="application/json")

    async def _get_hosts(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.hosts(), content_type="application/json")

    async def _get_tenants(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.tenants(),
                            content_type="application/json")

    async def _get_model(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.model(),
                            content_type="application/json")

    async def _get_serving(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.serving(),
                            content_type="application/json")

    async def _get_freshness(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.freshness(),
                            content_type="application/json")

    async def _get_history(self, request: web.Request) -> web.StreamResponse:
        return web.Response(text=self.cache.history(),
                            content_type="application/json")

    async def _get_fleet(self, request: web.Request) -> web.StreamResponse:
        # a router process answers LIVE (the view is plain host bookkeeping
        # under a lock); any other process serves the cached additive view
        if self._fleet is not None:
            view = {"jsonClass": "Fleet", **self._fleet.stats()}
            return web.Response(text=json.dumps(view),
                                content_type="application/json")
        return web.Response(text=self.cache.fleet(),
                            content_type="application/json")

    async def _post_predict(self, request: web.Request) -> web.StreamResponse:
        """The serving front door: coalesced, pipelined inference from the
        attached plane's device-resident snapshot. Errors are JSON with an
        ``error`` field — 503 when no plane is attached or the plane
        aborted (wedged transport → watchdog abort, never a hang), 400 on a
        malformed request body."""
        def fail(status: int, message: str) -> web.Response:
            return web.Response(
                text=json.dumps({"error": message}), status=status,
                content_type="application/json",
            )

        if self._fleet is not None:
            # fleet front door: forward the raw body off the event loop
            # (urllib blocks; the executor bounds concurrency) — replica
            # failures retry/eject inside the router, so a client only
            # sees 503 when the whole fleet is down this instant
            body = await request.read()
            loop = asyncio.get_event_loop()
            # the router's OWN forward pool: asyncio's default executor is
            # cpu+4 threads — 5 on the one-core host, which would cap a
            # whole fleet at ~one replica's in-flight budget (measured,
            # BENCHMARKS.md "Read fleet")
            status, payload = await loop.run_in_executor(
                getattr(self._fleet, "executor", None),
                self._fleet.predict, body,
            )
            return web.Response(
                body=payload, status=status,
                content_type="application/json",
            )
        plane = self._serving
        if plane is None:
            return fail(503, "serving not enabled on this server "
                             "(start via twtml_tpu.apps.serve or route a "
                             "fleet via twtml_tpu.apps.router)")
        try:
            payload = json.loads(await request.text())
            rows = payload["rows"] if isinstance(payload, dict) else payload
            if not isinstance(rows, list):
                raise ValueError("body must be {\"rows\": [...]} ")
            statuses = plane.statuses_from_rows(rows)
        except (ValueError, KeyError, TypeError) as exc:
            return fail(400, f"bad predict request: {exc}")
        try:
            # the plane's future resolves from the pipelined fetch pool;
            # wrap_future bridges it into this event loop. The
            # FetchWatchdog bounds how long it can possibly take.
            result = await asyncio.wrap_future(plane.submit(statuses))
        except ValueError as exc:  # oversized request
            return fail(400, str(exc))
        except Exception as exc:
            return fail(503, str(exc))
        return web.Response(
            text=json.dumps({
                "predictions": result["predictions"],
                "snapshotStep": result["snapshot_step"],
                "servedRows": len(result["predictions"]),
                # dispatch-time snapshot age (ISSUE 16): how stale the
                # weights that scored THIS response were; -1 from planes
                # predating the freshness stamp (fleet replicas mid-roll)
                "modelStalenessS": result.get("model_staleness_s", -1.0),
            }),
            content_type="application/json",
        )

    async def _ws_api(self, request: web.Request) -> web.StreamResponse:
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        self._websockets.add(ws)
        log.debug("websocket connected (%d live)", len(self._websockets))
        try:
            await ws.send_str(self.cache.config())  # WsStartHandler behavior
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    self.cache.cache(msg.data)
                    await self._broadcast(msg.data)
                elif msg.type == WSMsgType.ERROR:
                    break
        finally:
            self._websockets.discard(ws)
        return ws

    async def _broadcast(self, text: str) -> None:
        """Fan a frame out to every dashboard (webSocketConnections.writeText
        equivalent); dead sockets are dropped silently."""
        for ws in list(self._websockets):
            try:
                await ws.send_str(text)
            except Exception:
                self._websockets.discard(ws)

    async def _index(self, request: web.Request) -> web.StreamResponse:
        return self._static_file("index.html")

    async def _static(self, request: web.Request) -> web.StreamResponse:
        rel = request.match_info["path"]
        return self._static_file(rel)

    def _static_file(self, rel: str) -> web.StreamResponse:
        # join segment-by-segment with every segment vetted: a single
        # joinpath("/abs/path") would DISCARD the assets base entirely
        # (pathlib semantics; "D:" does the same on Windows) and serve
        # arbitrary filesystem paths. Control chars (e.g. %00) would raise
        # from is_file() → 500; they 404 here instead.
        parts = rel.split("/")
        if any(
            p in ("", ".", "..")
            or "\\" in p
            or ":" in p
            or any(ord(c) < 32 for c in p)
            for p in parts
        ):
            raise web.HTTPNotFound
        parent = self._assets
        for p in parts[:-1]:
            parent = parent.joinpath(p)
        target = parent.joinpath(parts[-1])
        if rel.endswith(".js") and not rel.endswith(".min.js"):
            # dist builds ship minified assets (tools/jsminify.py via
            # scripts/build_dist.sh — the reference's sbt-uglify analog,
            # web/build.sbt:25-39): serve file.min.js when present, so the
            # dashboard loads the minified bundle without URL changes.
            # Staleness guard for dev trees: a leftover (gitignored)
            # .min.js older than an edited source must not shadow the fix;
            # when mtimes are unavailable (zip deploys — immutable), the
            # minified file wins.
            minified = parent.joinpath(parts[-1][:-3] + ".min.js")
            if minified.is_file():
                try:
                    import os as _os

                    fresh = _os.path.getmtime(str(minified)) >= (
                        _os.path.getmtime(str(target))
                    )
                except OSError:
                    fresh = True
                if fresh:
                    target = minified
        if not target.is_file():
            raise web.HTTPNotFound
        ctype, _ = mimetypes.guess_type(rel)
        return web.Response(body=target.read_bytes(),
                            content_type=ctype or "application/octet-stream")

    # -- lifecycle -----------------------------------------------------------
    def _build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/api", self._ws_api)  # websocket handshake
        app.router.add_post("/api", self._post_api)
        app.router.add_get("/api/config", self._get_config)
        app.router.add_get("/api/stats", self._get_stats)
        app.router.add_get("/api/series", self._get_series)  # chart backfill
        app.router.add_get("/api/metrics", self._get_metrics)  # observability
        app.router.add_get("/api/hosts", self._get_hosts)  # lockstep fleet view
        app.router.add_get("/api/tenants", self._get_tenants)  # model plane
        app.router.add_get("/api/model", self._get_model)  # model health
        app.router.add_get("/api/serving", self._get_serving)  # serve plane
        app.router.add_get("/api/fleet", self._get_fleet)  # read fleet
        app.router.add_get("/api/freshness", self._get_freshness)  # e2e lag
        app.router.add_get("/api/history", self._get_history)  # historian
        app.router.add_post("/api/predict", self._post_predict)  # front door
        app.router.add_get("/", self._index)
        app.router.add_get("/{path:.+}", self._static)
        return app

    async def _start_async(self) -> None:
        self._runner = web.AppRunner(self._build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        log.info("Open your browser and navigate to http://%s:%d",
                 self.host, self.port)

    async def _stop_async(self) -> None:
        for ws in list(self._websockets):
            try:
                await ws.close()
            except Exception:  # lawcheck: disable=TW005 -- best-effort websocket close on shutdown; a dead client must not wedge server stop
                pass
        if self._runner is not None:
            await self._runner.cleanup()

    def start_background(self) -> "Server":
        """Run the server loop in a daemon thread; returns once listening."""
        def runner():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(self._start_async())
            self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=runner, name="twtml-web", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("web server failed to start")
        return self

    def stop(self) -> None:
        if self._loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(self._stop_async(), self._loop)
        try:
            fut.result(timeout=5)
        except Exception:  # lawcheck: disable=TW005 -- best-effort bounded shutdown: a wedged event loop is abandoned (daemon thread) rather than hanging the app exit
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)

    def run_forever(self) -> None:
        """Foreground mode for the standalone process (web.main)."""
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        loop.run_until_complete(self._start_async())
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(self._stop_async())
