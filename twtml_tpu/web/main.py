"""Standalone web-server entry (reference: Main.scala:7-23).

``python -m twtml_tpu.web.main [-nocache]`` — restores the persisted Config
unless ``-nocache`` is given, honors the ``PORT`` env var (Heroku
compatibility, Server.scala:66), and stops cleanly on SIGINT/SIGTERM (the
reference's JVM shutdown hook)."""

from __future__ import annotations

import os
import signal
import sys

from ..utils import get_logger
from .cache import ApiCache
from .server import Server

log = get_logger("web.main")


def main(argv: list[str] | None = None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    cache = ApiCache()
    if "-nocache" not in args:
        cache.restore()

    port = int(os.environ.get("PORT", "8888"))
    server = Server(port=port, cache=cache)

    def shutdown(_sig, _frame):
        log.info("shutting down")
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, shutdown)
    try:
        server.run_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
