from .cache import ApiCache
from .server import Server

__all__ = ["ApiCache", "Server"]
