// Manual test harness logic (reference: web/src/main/assets/js/test.js).
(function () {
  "use strict";

  let wsOn = false;

  function log(json) {
    const row = document.getElementById("log").insertRow(1);
    row.insertCell().textContent = new Date().toLocaleTimeString();
    row.insertCell().textContent = JSON.stringify(json);
  }

  document.addEventListener("DOMContentLoaded", () => {
    api.bind(log);

    document.getElementById("wsToggle").addEventListener("click", (ev) => {
      wsOn = !wsOn;
      if (wsOn) api.websocketOn(); else api.websocketOff();
      ev.target.textContent = "websocket: " + (wsOn ? "on" : "off");
    });

    document.getElementById("postConfig").addEventListener("click", () => {
      api.postConfig(
        document.getElementById("cfgId").value,
        document.getElementById("cfgHost").value,
        document.getElementById("cfgViz").value.split(",").map((s) => s.trim()),
      );
    });

    document.getElementById("postStats").addEventListener("click", () => {
      api.postStats(
        Number(document.getElementById("stCount").value),
        Number(document.getElementById("stBatch").value),
        Number(document.getElementById("stMse").value),
        Number(document.getElementById("stReal").value),
        Number(document.getElementById("stPred").value),
      );
    });
  });
})();
