// Browser API client (reference: web/src/main/assets/js/api.js — same
// responsibilities, rebuilt on native WebSocket/fetch instead of
// jquery-atmosphere): websocket with 5s auto-reconnect, HTTP fallbacks for
// posting, jsonClass-discriminated payload builders, and a simple event bus.
(function (global) {
  "use strict";

  const api = {
    ws: null,
    listeners: [],
    reconnectDelayMs: 5000,
    _wantOpen: false,

    bind(fn) { this.listeners.push(fn); },

    _dispatch(json) {
      for (const fn of this.listeners) {
        try { fn(json); } catch (e) { console.error(e); }
      }
    },

    _wsUrl() {
      const proto = location.protocol === "https:" ? "wss:" : "ws:";
      return proto + "//" + location.host + "/api";
    },

    websocketOn() {
      this._wantOpen = true;
      const sock = new WebSocket(this._wsUrl());
      this.ws = sock;
      sock.onmessage = (ev) => {
        try { this._dispatch(JSON.parse(ev.data)); }
        catch (e) { console.error("bad frame", ev.data); }
      };
      sock.onopen = () => this._dispatch({ jsonClass: "_Socket", open: true });
      sock.onclose = () => {
        this._dispatch({ jsonClass: "_Socket", open: false });
        if (this._wantOpen) {
          setTimeout(() => this.websocketOn(), this.reconnectDelayMs);
        }
      };
    },

    websocketOff() {
      this._wantOpen = false;
      if (this.ws) this.ws.close();
    },

    _wsReady() {
      return this.ws && this.ws.readyState === WebSocket.OPEN;
    },

    // POST via websocket when live, HTTP otherwise (reference api.js:65-79)
    post(payload) {
      const text = JSON.stringify(payload);
      if (this._wsReady()) {
        this.ws.send(text);
        return Promise.resolve();
      }
      return fetch("/api", {
        method: "POST",
        headers: { "content-type": "application/json" },
        body: text,
      });
    },

    postConfig(id, host, viz) {
      return this.post({ jsonClass: "Config", id, host, viz });
    },

    postStats(count, batch, mse, realStddev, predStddev) {
      return this.post({ jsonClass: "Stats", count, batch, mse, realStddev, predStddev });
    },

    getConfig() { return fetch("/api/config").then((r) => r.json()); },
    getStats() { return fetch("/api/stats").then((r) => r.json()); },

    guid() {
      return "xxxxxxxx-xxxx-4xxx-yxxx-xxxxxxxxxxxx".replace(/[xy]/g, (c) => {
        const r = (Math.random() * 16) | 0;
        return (c === "x" ? r : (r & 0x3) | 0x8).toString(16);
      });
    },
  };

  global.api = api;
})(window);
