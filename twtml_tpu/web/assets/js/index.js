// Dashboard logic (reference: web/src/main/assets/js/index.js — dispatch on
// jsonClass; Config rebuilds the chart iframes, Stats updates the counters).
(function () {
  "use strict";

  const ids = ["count", "batch", "mse", "realStddev", "predStddev"];
  let chart = null;
  let backfilled = false;
  const pendingSeries = [];

  function onConfig(json) {
    for (const id of ids) document.getElementById(id).textContent = "0";
    if (chart) chart.clear();
    document.getElementById("session").textContent = json.id || "—";
    const graphs = document.getElementById("graphs");
    graphs.replaceChildren();
    for (const vizId of json.viz || []) {
      // the reference embeds Lightning charts via pym
      // (js/index.js:35-43: host + "/visualizations/" + id + "/pym")
      const frame = document.createElement("iframe");
      frame.src = json.host + "/visualizations/" + vizId + "/pym";
      frame.title = "viz " + vizId;
      graphs.appendChild(frame);
    }
  }

  function onStats(json) {
    for (const id of ids) {
      document.getElementById(id).textContent = Number(json[id]).toLocaleString();
    }
  }

  function onMetrics(json) {
    // pipeline observability panel (telemetry/metrics.py snapshot)
    const counters = json.counters || {};
    const gauges = json.gauges || {};
    const health = json.health || {};
    const phase = health.phase || "—";
    const badge = document.getElementById("tunnelPhase");
    badge.textContent = phase;
    badge.classList.toggle("healthy", phase === "healthy");
    badge.classList.toggle("degraded", phase === "degraded");
    document.getElementById("rttMs").textContent =
      String(health.rtt_ms || 0);
    document.getElementById("phaseFlips").textContent =
      String(health.transitions || 0);
    document.getElementById("wireMb").textContent =
      (Number(counters["wire.bytes"] || 0) / 1e6).toFixed(1);
    // compressed-wire ratio (--wireCodec): raw/compressed units bytes of
    // the latest packed batch; 1.00 = codec off or shipping raw
    document.getElementById("wireRatio").textContent =
      (Number(gauges["wire.codec_ratio"] || 1)).toFixed(2);
    // pooled wire arena (r17): outstanding leases and cumulative pool
    // recycles (wire.arena_* — features/arena.py)
    document.getElementById("arenaPool").textContent =
      String(gauges["wire.arena_in_use"] || 0) + " · " +
      String(counters["wire.arena_recycled"] || 0);
    document.getElementById("rssMb").textContent =
      String(gauges["host.rss_mb"] || 0);
    // continuous leak-rate gauge (utils/rss.py least-squares slope over
    // publish-tick samples — the soak estimator, live)
    document.getElementById("rssSlope").textContent =
      Number(gauges["host.rss_slope_mb_per_min"] || 0).toFixed(2);
    // ingest event-time lag (streaming/sources.py sampled gauge, ms → s);
    // "—" until a replay/live source records one
    const ingestLag = gauges["ingest.event_time_lag_ms"];
    document.getElementById("ingestLag").textContent =
      ingestLag === undefined ? "—" : (Number(ingestLag) / 1000).toFixed(1);
    document.getElementById("fetchDepth").textContent =
      String(gauges["fetch.queue_depth"] || 0);
    // ingest/state robustness (bounded queue + divergence sentinel)
    // block-parse throughput (ingest.parse_tweets_per_s, tweets/s -> k/s):
    // the bottleneck ladder's parse rung, live
    document.getElementById("parseRate").textContent =
      (Number(gauges["ingest.parse_tweets_per_s"] || 0) / 1000).toFixed(0);
    document.getElementById("queueRows").textContent =
      String(gauges["ingest.queue_rows"] || 0);
    document.getElementById("rowsShed").textContent =
      String(counters["ingest.rows_shed"] || 0);
    const rb = document.getElementById("rollbacks");
    rb.textContent = String(counters["model.rollbacks"] || 0);
    rb.classList.toggle("degraded", (counters["model.rollbacks"] || 0) > 0);
    // durable intake journal: rows re-ingested by replay recovery (the
    // crash-equals-clean counter — nonzero means a recovery replayed
    // instead of counting rows lost)
    document.getElementById("journalReplayed").textContent =
      String(counters["journal.replayed_rows"] || 0);
    // derived latency quantiles (Histogram.snapshot p95, seconds → ms)
    const hist = (json.histograms || {})["fetch.latency_s"] || {};
    document.getElementById("fetchP95").textContent =
      (Number(hist.p95 || 0) * 1000).toFixed(1);
  }

  function onHosts(json) {
    // per-host lockstep tiles (telemetry/sideband.py): one tile per host,
    // the straggler attributor's pick highlighted with its ladder stage
    const straggler = document.getElementById("straggler");
    const gating = Number(json.straggler) >= 0;
    straggler.textContent = gating
      ? "host " + json.straggler + (json.stage ? " · " + json.stage : "")
      : "—";
    straggler.classList.toggle("degraded", gating);
    document.getElementById("tickSkew").textContent =
      String(json.skewMs || 0);
    // elastic membership (streaming/membership.py): epoch + live host
    // count + the current lead (moves at a won election), cumulative
    // churn; "—" when the run is not elastic
    const elastic = Number(json.epoch) >= 0;
    document.getElementById("elasticEpoch").textContent = elastic
      ? json.epoch + " · " + (json.liveHosts || 0) + " host" +
        ((json.liveHosts || 0) === 1 ? "" : "s") +
        (Number(json.leadUid) >= 0 ? " · lead " + json.leadUid : "")
      : "—";
    document.getElementById("elasticChurn").textContent = elastic
      ? (json.departed || 0) + " / " + (json.rejoined || 0)
      : "—";
    const panel = document.getElementById("hostsPanel");
    panel.replaceChildren();
    for (const h of json.hosts || []) {
      const tile = document.createElement("div");
      tile.className = "stat";
      const isGating = gating && h.host === json.straggler;
      if (isGating) tile.classList.add("gating");
      const label = document.createElement("div");
      label.className = "label";
      label.textContent = "host " + h.host + (isGating ? " · gating" : "");
      const value = document.createElement("div");
      value.className = "value";
      value.textContent = Number(h.tick_prep_ms || 0).toFixed(0) + " ms";
      tile.appendChild(label);
      tile.appendChild(value);
      panel.appendChild(tile);
    }
  }

  function onTenants(json) {
    // per-tenant model-plane tiles (telemetry/tenants.py): one tile per
    // tenant with its last-batch rows + mse; the gating tenant (most rows
    // this tick — where the shared row bucket binds first) highlighted
    var tenants = json.tenants || [];
    document.getElementById("tenantsActive").textContent =
      tenants.length ? String(json.active || 0) + " / " + tenants.length : "—";
    const panel = document.getElementById("tenantsPanel");
    panel.replaceChildren();
    for (const t of tenants) {
      const tile = document.createElement("div");
      tile.className = "stat";
      const isGating = Number(json.gating) >= 0 && t.tenant === json.gating;
      if (isGating) tile.classList.add("gating");
      const label = document.createElement("div");
      label.className = "label";
      label.textContent = "tenant " + t.tenant + (isGating ? " · gating" : "");
      const value = document.createElement("div");
      value.className = "value";
      value.textContent =
        Number(t.rows || 0).toLocaleString() +
        (t.mse >= 0 ? " · mse " + Math.round(Number(t.mse)) : "");
      tile.appendChild(label);
      tile.appendChild(value);
      panel.appendChild(tile);
    }
  }

  function onServing(json) {
    // serving-plane tiles (serving/plane.py stats view): QPS + latency
    // quantiles, the active snapshot (step + checkpoint quality level),
    // error count, and per-tenant served-row tiles on the tenant plane
    const hasSnapshot = Number(json.snapshotStep) >= 0;
    document.getElementById("serveQps").textContent = hasSnapshot
      ? Number(json.qps || 0).toFixed(1)
      : "—";
    document.getElementById("serveRows").textContent =
      Number(json.rowsPerSec || 0).toLocaleString();
    document.getElementById("serveP50").textContent =
      Number(json.p50Ms || 0).toFixed(1);
    document.getElementById("serveP99").textContent =
      Number(json.p99Ms || 0).toFixed(1);
    document.getElementById("serveSnapshot").textContent = hasSnapshot
      ? "ckpt-" + json.snapshotStep
      : "—";
    // serving staleness (ISSUE 16): seconds since the active snapshot was
    // installed; the stale badge mirrors the plane's warn-only SLO episode
    const age = Number(json.snapshotAgeS);
    const ageEl = document.getElementById("serveAge");
    ageEl.textContent = hasSnapshot && age >= 0 ? age.toFixed(0) : "—";
    ageEl.classList.toggle("stale", json.level === "stale");
    const levelEl = document.getElementById("serveLevel");
    const level = json.level || "—";
    levelEl.textContent = level;
    levelEl.classList.toggle("ok", level === "ok");
    levelEl.classList.toggle("warn", level === "warn");
    const errs = Number(json.errors || 0);
    const errEl = document.getElementById("serveErrors");
    errEl.textContent = String(errs);
    errEl.classList.toggle("degraded", errs > 0);
    const panel = document.getElementById("servingTenantsPanel");
    panel.replaceChildren();
    for (const t of json.tenants || []) {
      const tile = document.createElement("div");
      tile.className = "stat";
      const label = document.createElement("div");
      label.className = "label";
      label.textContent = "tenant " + t.tenant;
      const value = document.createElement("div");
      value.className = "value";
      value.textContent = Number(t.rows || 0).toLocaleString() + " rows";
      tile.appendChild(label);
      tile.appendChild(value);
      panel.appendChild(tile);
    }
  }

  function onFleet(json) {
    // read-fleet tiles (serving/fleet.py stats view via apps/router.py):
    // policy + router retry/ejection story, the fleet-wide champion on the
    // champion/challenger plane, and one tile per replica (qps + forward
    // p99; an ejected replica is highlighted until its probe recovers it)
    const replicas = json.replicas || [];
    document.getElementById("fleetPolicy").textContent =
      replicas.length ? (json.policy || "—") : "—";
    document.getElementById("fleetRequests").textContent =
      Number(json.requests || 0).toLocaleString();
    const retries = Number(json.retries || 0);
    const retriesEl = document.getElementById("fleetRetries");
    retriesEl.textContent = String(retries);
    retriesEl.classList.toggle("degraded", retries > 0);
    const ejections = Number(json.ejections || 0);
    const ejectionsEl = document.getElementById("fleetEjections");
    ejectionsEl.textContent = String(ejections);
    ejectionsEl.classList.toggle("degraded", ejections > 0);
    document.getElementById("fleetChampion").textContent =
      Number(json.champion) >= 0 ? "tenant " + json.champion : "—";
    const panel = document.getElementById("fleetPanel");
    panel.replaceChildren();
    for (const r of replicas) {
      const tile = document.createElement("div");
      tile.className = "stat";
      if (!r.healthy) tile.classList.add("ejected");
      const label = document.createElement("div");
      label.className = "label";
      label.textContent =
        "replica " + r.replica + (r.healthy ? "" : " · ejected");
      const value = document.createElement("div");
      value.className = "value";
      value.textContent =
        Number(r.qps || 0).toFixed(1) + " qps · p99 " +
        Number(r.p99Ms || 0).toFixed(0) + " ms";
      tile.appendChild(label);
      tile.appendChild(value);
      panel.appendChild(tile);
    }
  }

  function drawLossSpark(values) {
    // rolling per-batch mse sparkline (ModelHealth.mse window)
    const canvas = document.getElementById("lossSpark");
    const ctx = canvas.getContext("2d");
    const w = (canvas.width = canvas.clientWidth || 800);
    const h = (canvas.height = canvas.clientHeight || 60);
    ctx.clearRect(0, 0, w, h);
    if (!values.length) {
      ctx.fillStyle = "rgba(128,128,128,0.6)";
      ctx.font = "11px system-ui";
      ctx.fillText("loss sparkline — waiting for model telemetry…", 8, 14);
      return;
    }
    let lo = Math.min(...values), hi = Math.max(...values);
    if (hi === lo) { hi = lo + 1; }
    ctx.beginPath();
    ctx.strokeStyle = "rgb(29, 78, 216)";
    ctx.lineWidth = 1.4;
    values.forEach((v, i) => {
      const x = (i / Math.max(values.length - 1, 1)) * (w - 10) + 5;
      const y = h - 6 - ((v - lo) / (hi - lo)) * (h - 12);
      i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
    });
    ctx.stroke();
    ctx.fillStyle = "rgba(128,128,128,0.8)";
    ctx.font = "10px system-ui";
    ctx.fillText("mse " + Math.round(values[values.length - 1]), 6, 12);
  }

  function onModelHealth(json) {
    // model & data quality tiles (telemetry/modelwatch.py): graduated
    // health badge, drift z / loss-trend numbers, norm gauges, per-tenant
    // drift tiles on the multi-tenant plane, and the loss sparkline
    const level = json.level || "—";
    const badge = document.getElementById("modelLevel");
    badge.textContent = level;
    badge.classList.toggle("ok", level === "ok");
    badge.classList.toggle("warn", level === "warn");
    badge.classList.toggle("alert", level === "alert");
    document.getElementById("driftScore").textContent =
      Number(json.driftScore || 0).toFixed(1);
    const trend = Number(json.lossTrend || 0);
    document.getElementById("lossTrend").textContent =
      (trend >= 0 ? "+" : "") + (trend * 100).toFixed(0) + "%";
    document.getElementById("weightNorm").textContent =
      Number(json.weightNorm || 0).toFixed(1);
    document.getElementById("updateNorm").textContent =
      Number(json.updateNorm || 0).toFixed(2);
    document.getElementById("driftEpisodes").textContent =
      String(json.episodes || 0);
    const panel = document.getElementById("modelTenantsPanel");
    panel.replaceChildren();
    for (const t of json.tenants || []) {
      const tile = document.createElement("div");
      tile.className = "stat";
      const alerting = t.level === "alert" || t.level === "warn";
      if (alerting) tile.classList.add("alerting");
      const label = document.createElement("div");
      label.className = "label";
      label.textContent = "tenant " + t.tenant;
      const value = document.createElement("div");
      value.className = "value";
      value.textContent =
        (t.level || "ok") + " · z " + Number(t.drift || 0).toFixed(1);
      tile.appendChild(label);
      tile.appendChild(value);
      panel.appendChild(tile);
    }
    drawLossSpark(json.mse || []);
  }

  function drawFreshSpark(values) {
    // rolling watermark-lag sparkline (Freshness.watermark window)
    const canvas = document.getElementById("freshSpark");
    const ctx = canvas.getContext("2d");
    const w = (canvas.width = canvas.clientWidth || 800);
    const h = (canvas.height = canvas.clientHeight || 60);
    ctx.clearRect(0, 0, w, h);
    if (!values.length) {
      ctx.fillStyle = "rgba(128,128,128,0.6)";
      ctx.font = "11px system-ui";
      ctx.fillText("watermark sparkline — waiting for freshness telemetry…", 8, 14);
      return;
    }
    let lo = Math.min(...values), hi = Math.max(...values);
    if (hi === lo) { hi = lo + 1; }
    ctx.beginPath();
    ctx.strokeStyle = "rgb(21, 128, 61)";
    ctx.lineWidth = 1.4;
    values.forEach((v, i) => {
      const x = (i / Math.max(values.length - 1, 1)) * (w - 10) + 5;
      const y = h - 6 - ((v - lo) / (hi - lo)) * (h - 12);
      i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
    });
    ctx.stroke();
    ctx.fillStyle = "rgba(128,128,128,0.8)";
    ctx.font = "10px system-ui";
    ctx.fillText(
      "watermark lag " + Math.round(values[values.length - 1]) + " ms", 6, 12
    );
  }

  function onFreshness(json) {
    // end-to-end freshness tiles (telemetry/freshness.py view): event-time
    // lag percentiles, event→publish lag, the low-watermark lag + its
    // sparkline, the dominant critical-path edge, and the SLO breach count
    const live = Number(json.batches) > 0;
    const ms = (v) => (live && Number(v) >= 0 ? Number(v).toFixed(0) : "—");
    document.getElementById("freshP50").textContent = ms(json.eventLagP50Ms);
    document.getElementById("freshP95").textContent = ms(json.eventLagP95Ms);
    document.getElementById("freshP99").textContent = ms(json.eventLagP99Ms);
    document.getElementById("freshPublish").textContent =
      ms(json.publishLagP95Ms);
    document.getElementById("freshWatermark").textContent =
      ms(json.watermarkLagMs);
    document.getElementById("freshCritical").textContent =
      json.critical || "—";
    const breaches = Number(json.breaches || 0);
    const breachEl = document.getElementById("freshBreaches");
    breachEl.textContent = String(breaches);
    breachEl.classList.toggle("degraded", breaches > 0);
    drawFreshSpark(json.watermark || []);
  }

  function drawHistorySpark(canvasId, values, label, unit, color) {
    // one historian sparkline tile (History.rss / .rtt / .stageMs windows)
    const canvas = document.getElementById(canvasId);
    const ctx = canvas.getContext("2d");
    const w = (canvas.width = canvas.clientWidth || 800);
    const h = (canvas.height = canvas.clientHeight || 44);
    ctx.clearRect(0, 0, w, h);
    if (!values.length) {
      ctx.fillStyle = "rgba(128,128,128,0.6)";
      ctx.font = "11px system-ui";
      ctx.fillText(label + " — waiting for historian samples…", 8, 14);
      return;
    }
    let lo = Math.min(...values), hi = Math.max(...values);
    if (hi === lo) { hi = lo + 1; }
    ctx.beginPath();
    ctx.strokeStyle = color;
    ctx.lineWidth = 1.4;
    values.forEach((v, i) => {
      const x = (i / Math.max(values.length - 1, 1)) * (w - 10) + 5;
      const y = h - 6 - ((v - lo) / (hi - lo)) * (h - 12);
      i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
    });
    ctx.stroke();
    ctx.fillStyle = "rgba(128,128,128,0.8)";
    ctx.font = "10px system-ui";
    ctx.fillText(
      label + " " + values[values.length - 1].toFixed(1) + " " + unit, 6, 12
    );
  }

  function onHistory(json) {
    // telemetry-historian tiles (telemetry/historian.py view): long-horizon
    // RSS / fetch-RTT / per-tick stage-cost sparklines + the perfGuard
    // regression count, from the durable time-series tail
    const live = Number(json.samples) > 0;
    const num = (v, d) => (live ? Number(v).toFixed(d) : "—");
    document.getElementById("histSamples").textContent =
      live ? String(json.samples) : "—";
    document.getElementById("histPhase").textContent = json.phase || "—";
    document.getElementById("histRss").textContent = num(json.rssMb, 0);
    document.getElementById("histSlope").textContent =
      num(json.rssSlopeMbPerMin, 2);
    document.getElementById("histRtt").textContent = num(json.rttMs, 1);
    document.getElementById("histDisk").textContent = num(json.diskMb, 1);
    const regress = Number(json.regressions || 0);
    const regressEl = document.getElementById("histRegressions");
    regressEl.textContent = String(regress);
    regressEl.classList.toggle("degraded", regress > 0);
    document.getElementById("histPhase").classList.toggle(
      "degraded", json.phase === "degraded"
    );
    drawHistorySpark("histRssSpark", json.rss || [], "host rss", "mb",
                     "rgb(180, 83, 9)");
    drawHistorySpark("histRttSpark", json.rtt || [], "fetch rtt", "ms",
                     "rgb(29, 78, 216)");
    drawHistorySpark("histStageSpark", json.stageMs || [],
                     "stage cost / tick", "ms", "rgb(107, 33, 168)");
  }

  function onMessage(json) {
    switch (json.jsonClass) {
      case "Config": onConfig(json); break;
      case "Stats": onStats(json); break;
      case "Metrics": onMetrics(json); break;
      case "Hosts": onHosts(json); break;
      case "Tenants": onTenants(json); break;
      case "ModelHealth": onModelHealth(json); break;
      case "Serving": onServing(json); break;
      case "Fleet": onFleet(json); break;
      case "Freshness": onFreshness(json); break;
      case "History": onHistory(json); break;
      case "Series":
        // live frames buffer until the history backfill lands (ordering)
        if (!backfilled) pendingSeries.push(json);
        else if (chart) chart.push(json);
        break;
      case "_Socket": {
        const badge = document.getElementById("conn");
        badge.textContent = json.open ? "live" : "offline";
        badge.classList.toggle("live", !!json.open);
        break;
      }
    }
  }

  document.addEventListener("DOMContentLoaded", () => {
    chart = new LiveChart(document.getElementById("livechart"));
    chart.draw();
    api.bind(onMessage);
    api.websocketOn();
    api.getStats().then(onStats).catch(() => {});
    // observability panel backfill (latest Metrics snapshot, if any)
    fetch("/api/metrics").then((r) => r.json()).then(onMetrics).catch(() => {});
    // per-host lockstep view backfill (empty hosts[] on single-host runs)
    fetch("/api/hosts").then((r) => r.json()).then(onHosts).catch(() => {});
    // per-tenant model-plane backfill (empty tenants[] single-tenant)
    fetch("/api/tenants").then((r) => r.json()).then(onTenants).catch(() => {});
    // model-health backfill (level "ok", empty sparkline until telemetry)
    fetch("/api/model").then((r) => r.json()).then(onModelHealth).catch(() => {});
    // serving-plane backfill (snapshotStep -1 until a serve process posts)
    fetch("/api/serving").then((r) => r.json()).then(onServing).catch(() => {});
    // read-fleet backfill (empty replicas[] off a router process)
    fetch("/api/fleet").then((r) => r.json()).then(onFleet).catch(() => {});
    // freshness-plane backfill (batches 0 until a training run publishes)
    fetch("/api/freshness").then((r) => r.json()).then(onFreshness).catch(() => {});
    // historian backfill (samples 0 until a --history run publishes)
    fetch("/api/history").then((r) => r.json()).then(onHistory).catch(() => {});
    // backfill the chart from the server's rolling series window, then
    // apply any live frames that arrived while the fetch was in flight
    const flush = () => {
      backfilled = true;
      for (const s of pendingSeries.splice(0)) chart.push(s);
    };
    fetch("/api/series").then((r) => r.json()).then((items) => {
      for (const s of items) chart.push(s);
      flush();
    }).catch(flush);
  });
})();
