// Dashboard logic (reference: web/src/main/assets/js/index.js — dispatch on
// jsonClass; Config rebuilds the chart iframes, Stats updates the counters).
(function () {
  "use strict";

  const ids = ["count", "batch", "mse", "realStddev", "predStddev"];

  function onConfig(json) {
    for (const id of ids) document.getElementById(id).textContent = "0";
    document.getElementById("session").textContent = json.id || "—";
    const graphs = document.getElementById("graphs");
    graphs.replaceChildren();
    for (const vizId of json.viz || []) {
      // the reference embeds Lightning charts via pym
      // (js/index.js:35-43: host + "/visualizations/" + id + "/pym")
      const frame = document.createElement("iframe");
      frame.src = json.host + "/visualizations/" + vizId + "/pym";
      frame.title = "viz " + vizId;
      graphs.appendChild(frame);
    }
  }

  function onStats(json) {
    for (const id of ids) {
      document.getElementById(id).textContent = Number(json[id]).toLocaleString();
    }
  }

  function onMessage(json) {
    switch (json.jsonClass) {
      case "Config": onConfig(json); break;
      case "Stats": onStats(json); break;
      case "_Socket": {
        const badge = document.getElementById("conn");
        badge.textContent = json.open ? "live" : "offline";
        badge.classList.toggle("live", !!json.open);
        break;
      }
    }
  }

  document.addEventListener("DOMContentLoaded", () => {
    api.bind(onMessage);
    api.websocketOn();
    api.getStats().then(onStats).catch(() => {});
  });
})();
