// Built-in live line chart for real vs predicted retweet counts.
// Replaces the reference's external-Lightning iframes (SessionStats.scala:49-52:
// 4 series — real, pred, and their stdev bands, blue/gold) with a
// dependency-free canvas renderer fed by Series messages over the websocket.
(function (global) {
  "use strict";

  const COLORS = {
    real: "rgb(30, 144, 255)",      // SessionStats.scala:16 blue
    pred: "rgb(255, 215, 0)",       // SessionStats.scala:19 gold
    realBand: "rgba(173, 216, 230, 0.5)",
    predBand: "rgba(238, 232, 170, 0.5)",
  };
  const WINDOW = 400; // points kept on screen

  function LiveChart(canvas) {
    this.canvas = canvas;
    this.ctx = canvas.getContext("2d");
    this.real = [];
    this.pred = [];
    this.realStd = [];
    this.predStd = [];
  }

  LiveChart.prototype.push = function (series) {
    const n = Math.min(series.real.length, series.pred.length);
    for (let i = 0; i < n; i++) {
      this.real.push(series.real[i]);
      this.pred.push(series.pred[i]);
      this.realStd.push(series.realStddev);
      this.predStd.push(series.predStddev);
    }
    const drop = this.real.length - WINDOW;
    if (drop > 0) {
      this.real.splice(0, drop);
      this.pred.splice(0, drop);
      this.realStd.splice(0, drop);
      this.predStd.splice(0, drop);
    }
    this.draw();
  };

  LiveChart.prototype.clear = function () {
    this.real = [];
    this.pred = [];
    this.realStd = [];
    this.predStd = [];
    this.draw();
  };

  LiveChart.prototype.draw = function () {
    const ctx = this.ctx;
    const w = (this.canvas.width = this.canvas.clientWidth || 800);
    const h = (this.canvas.height = this.canvas.clientHeight || 360);
    ctx.clearRect(0, 0, w, h);
    const data = this.real.concat(this.pred);
    if (!data.length) {
      ctx.fillStyle = "rgba(128,128,128,0.6)";
      ctx.font = "14px system-ui";
      ctx.fillText("waiting for stream…", 16, 24);
      return;
    }
    let lo = Math.min(...data), hi = Math.max(...data);
    if (hi === lo) { hi = lo + 1; }
    const pad = (hi - lo) * 0.1;
    lo -= pad; hi += pad;
    const sx = (i, len) => (i / Math.max(len - 1, 1)) * (w - 50) + 40;
    const sy = (v) => h - 20 - ((v - lo) / (hi - lo)) * (h - 40);

    // axis labels
    ctx.fillStyle = "rgba(128,128,128,0.8)";
    ctx.font = "11px system-ui";
    ctx.fillText(Math.round(hi), 4, 14);
    ctx.fillText(Math.round(lo), 4, h - 8);

    const drawLine = (values, color, width) => {
      ctx.beginPath();
      ctx.strokeStyle = color;
      ctx.lineWidth = width;
      values.forEach((v, i) => {
        const x = sx(i, values.length), y = sy(v);
        i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
      });
      ctx.stroke();
    };
    drawLine(this.realStd, COLORS.realBand, 1);
    drawLine(this.predStd, COLORS.predBand, 1);
    drawLine(this.real, COLORS.real, 1.6);
    drawLine(this.pred, COLORS.pred, 1.6);

    // legend
    const legend = [
      ["real", COLORS.real], ["predicted", COLORS.pred],
      ["stdev real", COLORS.realBand], ["stdev pred", COLORS.predBand],
    ];
    let x = 50;
    legend.forEach(([label, color]) => {
      ctx.fillStyle = color;
      ctx.fillRect(x, 6, 10, 10);
      ctx.fillStyle = "rgba(128,128,128,0.9)";
      ctx.fillText(label, x + 14, 15);
      x += ctx.measureText(label).width + 40;
    });
  };

  global.LiveChart = LiveChart;
})(window);
