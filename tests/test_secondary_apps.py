"""Secondary model apps: k-means entry (KMeans.scala parity), logistic
sentiment entry (BASELINE config #3), and the per-batch standard scaler."""

import os

import numpy as np
import pytest

from twtml_tpu.config import ConfArguments
from twtml_tpu.features.featurizer import Status
from twtml_tpu.features.sentiment import sentiment_label, sentiment_score
from twtml_tpu.ops.scaler import standard_scale

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")


def test_standard_scaler_matches_mllib_semantics():
    pts = np.array([[1.0, 5.0], [3.0, 5.0], [5.0, 5.0]], np.float32)
    mask = np.ones((3,), np.float32)
    out = np.asarray(standard_scale(pts, mask))
    # col 0: sample std of [1,3,5] = 2 → scaled [0.5, 1.5, 2.5]
    np.testing.assert_allclose(out[:, 0], [0.5, 1.5, 2.5], rtol=1e-6)
    # col 1: zero std → 0.0 (MLlib StandardScalerModel)
    np.testing.assert_allclose(out[:, 1], [0.0, 0.0, 0.0])


def test_standard_scaler_masked_rows_excluded():
    pts = np.array([[1.0, 1.0], [3.0, 1.0], [999.0, 999.0]], np.float32)
    mask = np.array([1.0, 1.0, 0.0], np.float32)
    out = np.asarray(standard_scale(pts, mask))
    assert out[2].tolist() == [0.0, 0.0]  # padding zeroed
    np.testing.assert_allclose(
        out[:2, 0], pts[:2, 0] / np.std(pts[:2, 0], ddof=1), rtol=1e-6
    )


def test_sentiment_labeler():
    assert sentiment_score("I love this great day") > 0
    assert sentiment_score("terrible awful mess") < 0
    pos = Status(retweeted_status=Status(text="what a wonderful result"))
    neg = Status(retweeted_status=Status(text="this is the worst fail"))
    assert sentiment_label(pos) == 1.0
    assert sentiment_label(neg) == 0.0


def conf_for(app_args):
    return ConfArguments().parse([
        "--source", "replay", "--replayFile", DATA, "--seconds", "1",
        "--backend", "cpu",
        "--lightning", "http://127.0.0.1:9", "--twtweb", "http://127.0.0.1:9",
        *app_args,
    ])


def test_kmeans_app_on_replay(capsys):
    from twtml_tpu.apps.kmeans import run

    totals = run(conf_for([]), wall_clock=False)
    # the k-means filter keeps ALL retweets (8 in the fixture), not just the
    # [100,1000] interval the linear app uses
    assert totals["count"] == 8
    out = capsys.readouterr().out
    assert "centers:" in out and "sizes:" in out


def test_logistic_app_on_replay(capsys):
    from twtml_tpu.apps.logistic_regression import run

    totals = run(conf_for([]))
    assert totals["count"] == 6
    out = capsys.readouterr().out
    assert "errRate:" in out


def test_logistic_app_sharded_local4(capsys):
    """--master local[4]: the logistic entry trains through the 4-way
    sharded mesh step (VERDICT r1: every entry point scales from the CLI)."""
    from twtml_tpu.apps.logistic_regression import run

    totals = run(conf_for(["--master", "local[4]"]))
    assert totals["count"] == 6
    assert "errRate:" in capsys.readouterr().out


def test_kmeans_app_sharded_local4(capsys):
    from twtml_tpu.apps.kmeans import run

    totals = run(conf_for(["--master", "local[4]"]), wall_clock=False)
    assert totals["count"] == 8
    assert "centers:" in capsys.readouterr().out


class TestBatchSentiment:
    """The C lexicon scorer (native/fasthash.cpp lexicon_score_batch) must
    label exactly like the per-status Python ground truth."""

    CASES = [
        "good vibes only",
        "this is BAD, really TERRIBLE stuff",
        "GREAT!!! but the problem... isn't awful?",
        "don't hate, it's the best",  # apostrophes inside tokens
        "goodness gracious",  # 'goodness' must NOT match 'good'
        "café terrible",  # non-ASCII row -> python fallback path
        "ΣΙΓΜΑ bad",  # non-ASCII uppercase
        "",  # empty text
        "x" * 500,  # token longer than any lexicon word
        "win-win fail/fail",  # punctuation separators
    ]

    def _statuses(self):
        from twtml_tpu.features.featurizer import Status

        return [
            Status(text="RT", retweeted_status=Status(text=t, retweet_count=200))
            for t in self.CASES
        ]

    def test_matches_per_status_labeler(self):
        import numpy as np

        from twtml_tpu.features.sentiment import sentiment_label, sentiment_labels

        statuses = self._statuses()
        got = sentiment_labels(statuses)
        want = np.array([sentiment_label(s) for s in statuses], np.float32)
        np.testing.assert_array_equal(got, want)

    def test_matches_without_native_library(self, monkeypatch):
        import numpy as np

        from twtml_tpu.features import native
        from twtml_tpu.features.sentiment import sentiment_label, sentiment_labels

        monkeypatch.setattr(native, "lexicon_scores", lambda *a, **k: None)
        statuses = self._statuses()
        got = sentiment_labels(statuses)
        want = np.array([sentiment_label(s) for s in statuses], np.float32)
        np.testing.assert_array_equal(got, want)

    def test_featurizer_batch_label_fn_parity(self):
        import numpy as np

        from twtml_tpu.features.featurizer import Featurizer
        from twtml_tpu.features.sentiment import sentiment_label, sentiment_labels

        slow = Featurizer(now_ms=0, label_fn=sentiment_label)
        fast = Featurizer(
            now_ms=0, label_fn=sentiment_label, batch_label_fn=sentiment_labels
        )
        statuses = self._statuses()
        a = slow.featurize_batch_units(statuses, pre_filtered=True)
        b = fast.featurize_batch_units(statuses, pre_filtered=True)
        np.testing.assert_array_equal(a.label, b.label)
        np.testing.assert_array_equal(a.units, b.units)
