"""End-to-end learning-quality tests on an analytically-known stream
(SURVEY.md §7 stage 3: "RMSE-curve parity tests against an
analytically-known synthetic stream").

The text-dependent stream below has labels the hashed-bigram featurization
CAN express (label ≈ a + b·len(text) is representable since the per-tweet
token-count total equals the bigram count ≈ len−1), so streaming SGD with
progressive validation must drive per-batch RMSE from the label scale down
toward the noise floor. A second test documents the featurization ceiling:
label components driven by followers are invisible through the reference's
hand-scaled ×1e-12 numeric features (SURVEY.md §2.5 "poor-man's
normalization"), so RMSE plateaus at that component's variance — faithful
to the reference's behavior, and the reason BASELINE config #4 introduces
bigger featurization."""

import numpy as np

from twtml_tpu.features.featurizer import Featurizer, Status
from twtml_tpu.models import StreamingLinearRegressionWithSGD
from twtml_tpu.streaming.sources import MultiSource, SyntheticSource

WORDS = "tpu stream learn fast jax mesh shard grad psum tweet".split()


def text_only_batches(n_batches=24, batch=512, seed=5, noise=5.0):
    rng = np.random.default_rng(seed)
    feat = Featurizer(now_ms=1785320000000)
    for _ in range(n_batches):
        statuses = []
        for _ in range(batch):
            text = " ".join(rng.choice(WORDS, size=int(rng.integers(3, 12))))
            label = 100 + 2 * len(text) + rng.normal(0, noise)
            statuses.append(
                Status(
                    text="RT " + text,
                    retweeted_status=Status(
                        text=text, retweet_count=int(max(label, 0))
                    ),
                )
            )
        yield feat.featurize_batch(statuses, row_bucket=batch, pre_filtered=True)


def test_rmse_converges_toward_noise_floor():
    model = StreamingLinearRegressionWithSGD(step_size=0.1, num_iterations=50)
    rmses = [float(model.step(b).mse) ** 0.5 for b in text_only_batches()]
    # progressive validation: first batch is scored with zero weights (RMSE
    # at the label scale), late batches approach the noise floor (σ=5)
    assert rmses[0] > 150
    assert np.mean(rmses[-4:]) < 30
    assert np.mean(rmses[-4:]) < rmses[0] / 5


def test_featurization_ceiling_is_faithful():
    """Follower-driven label variance can't be learned through ×1e-12-scaled
    numeric features — the RMSE plateau sits at that component's scale, far
    above the noise floor (reference quirk preserved, SURVEY.md §2.5)."""
    statuses = list(SyntheticSource(total=8 * 512, seed=5).produce())
    feat = Featurizer(now_ms=1785320000000)
    model = StreamingLinearRegressionWithSGD(step_size=0.1, num_iterations=50)
    rmse = None
    for k in range(8):
        batch = feat.featurize_batch(
            statuses[k * 512 : (k + 1) * 512], row_bucket=512, pre_filtered=True
        )
        rmse = float(model.step(batch).mse) ** 0.5
    assert 150 < rmse < 400  # plateaued at the unlearnable component's stdev


def test_sharded_receivers_feed_one_stream():
    import time

    shards = [SyntheticSource(total=25, seed=s) for s in range(4)]
    multi = MultiSource(shards)
    got = []
    multi.start(got.append)
    deadline = time.time() + 10
    while not multi.exhausted and time.time() < deadline:
        time.sleep(0.01)
    multi.stop()
    assert multi.exhausted
    assert len(got) == 100  # 4 shards × 25 tweets, all delivered


def test_rmse_curve_identical_across_ingest_modes(tmp_path):
    """Streaming 8 micro-batches from a FILE with weights carried across
    batches: the object path and the native block path must produce the
    SAME per-batch MSE curve — the 'identical RMSE curves' acceptance bar
    (BASELINE.md north star) applied to the ingest modes."""
    import json

    from tools.bench_suite import _status_json
    from twtml_tpu.features.blocks import merge_blocks
    from twtml_tpu.streaming.sources import BlockReplayFileSource

    statuses = list(SyntheticSource(total=2048, seed=11).produce())
    path = tmp_path / "stream.jsonl"
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")

    feat = Featurizer(now_ms=1785320000000)
    B = 256

    model_o = StreamingLinearRegressionWithSGD(num_iterations=10)
    curve_o = []
    for i in range(0, 2048, B):
        out = model_o.step(feat.featurize_batch_units(
            statuses[i : i + B], row_bucket=B, unit_bucket=64,
            pre_filtered=True,
        ))
        curve_o.append(float(out.mse))

    block = merge_blocks(list(BlockReplayFileSource(str(path)).produce()))
    assert block.rows == 2048
    model_b = StreamingLinearRegressionWithSGD(num_iterations=10)
    curve_b = []
    for i in range(0, 2048, B):
        sub = type(block)(
            block.numeric[i : i + B],
            block.units[block.offsets[i] : block.offsets[i + B]],
            block.offsets[i : i + B + 1] - block.offsets[i],
            block.ascii[i : i + B],
        )
        out = model_b.step(feat.featurize_parsed_block(
            sub, row_bucket=B, unit_bucket=64
        ))
        curve_b.append(float(out.mse))

    assert len(curve_o) == 8
    np.testing.assert_allclose(curve_o, curve_b, rtol=1e-6)
    assert curve_o[-1] < curve_o[0]  # it actually learns along the curve
