"""Divergence sentinel (ISSUE 4 tentpole, part 2): a poisoned batch drives
the fused predict-then-train step's weights non-finite in ONE update; the
sentinel catches it on the ALREADY-FETCHED per-batch stats (zero added host
fetches — asserted the way the --trace tests do), rolls the model back to
the last verified-finite checkpoint, skips the poisoning batch, and after N
rollbacks in a window aborts cleanly through the ssc.request_abort path.

Acceptance (ISSUE 4): a --chaos 'source.nan(...)' run detects, rolls back,
continues — and its final weights MATCH a clean run over a replay file that
never contained the poisoned batch."""

import json
from types import SimpleNamespace

import numpy as np
import pytest

from twtml_tpu.config import ConfArguments
from twtml_tpu.streaming import faults
from twtml_tpu.telemetry import metrics as _metrics


@pytest.fixture(autouse=True)
def clean_state():
    _metrics.reset_for_tests()
    faults.uninstall_chaos()
    yield
    faults.uninstall_chaos()
    _metrics.reset_for_tests()


# -- unit: the admit()/rollback state machine --------------------------------

def _out(mse=1.0, real=2.0, pred=3.0, count=16):
    return SimpleNamespace(
        mse=mse, real_stdev=real, pred_stdev=pred, count=count
    )


class _FakeCkpt:
    def __init__(self, meta=None):
        self.meta = meta
        self.calls = 0

    def rollback_to_verified(self):
        self.calls += 1
        return self.meta


class _FakeSsc:
    def __init__(self):
        self.aborted = False
        self.rollback_count_fn = None

    def request_abort(self):
        self.aborted = True


class _FakeModel:
    def __init__(self):
        self.set_calls = []

    def set_initial_weights(self, w):
        self.set_calls.append(np.asarray(w))


def _sentinel(conf_args=(), ckpt=None, model=None, ssc=None):
    from twtml_tpu.apps.common import DivergenceSentinel

    conf = ConfArguments().parse(list(conf_args))
    ssc = ssc or _FakeSsc()
    s = DivergenceSentinel(
        conf, model or _FakeModel(), ckpt or _FakeCkpt({"step": 7}), ssc
    )
    return s, ssc


def test_finite_batches_admit_and_cost_nothing_extra():
    s, _ = _sentinel()
    assert s.enabled
    for _ in range(10):
        assert s.admit(_out(), None)
    assert s.rollbacks == 0


def test_nonfinite_rolls_back_once_per_episode_and_skips_tainted():
    ckpt = _FakeCkpt({"step": 4})
    s, ssc = _sentinel(ckpt=ckpt)
    assert s.admit(_out(), None)
    # poisoned batch + two in-flight batches trained on poisoned weights
    assert not s.admit(_out(mse=float("nan")), None)
    assert not s.admit(_out(pred=float("inf")), None)
    assert not s.admit(_out(mse=float("nan")), None)
    assert ckpt.calls == 1  # ONE rollback for the whole episode
    assert s.rollbacks == 1
    # first finite delivery closes the episode; a later NaN is a NEW one
    assert s.admit(_out(), None)
    assert not s.admit(_out(real=float("nan")), None)
    assert ckpt.calls == 2
    assert not ssc.aborted
    reg = _metrics.get_registry()
    assert reg.counter("model.rollbacks").snapshot() == 2
    assert reg.counter("model.nonfinite_batches").snapshot() == 4
    assert reg.counter("model.rows_lost").snapshot() == 4 * 16


def test_no_verified_checkpoint_resets_to_initial_zeros():
    model = _FakeModel()
    s, _ = _sentinel(ckpt=_FakeCkpt(None), model=model)
    assert not s.admit(_out(mse=float("nan")), None)
    assert len(model.set_calls) == 1
    w = model.set_calls[0]
    assert w.shape == (1000 + 4,)  # numTextFeatures default + numeric
    assert not w.any()


def test_rollback_storm_aborts_via_request_abort():
    s, ssc = _sentinel(conf_args=["--sentinelRollbacks", "2",
                                  "--sentinelWindow", "100"])
    assert not s.admit(_out(mse=float("nan")), None)  # rollback 1
    assert s.admit(_out(), None)
    assert not ssc.aborted
    assert not s.admit(_out(mse=float("nan")), None)  # rollback 2 -> abort
    assert ssc.aborted
    assert _metrics.get_registry().counter(
        "model.sentinel_aborts").snapshot() == 1


def test_rollbacks_outside_the_window_do_not_abort():
    s, ssc = _sentinel(conf_args=["--sentinelRollbacks", "2",
                                  "--sentinelWindow", "3"])
    assert not s.admit(_out(mse=float("nan")), None)
    for _ in range(5):  # slide the first rollback out of the window
        assert s.admit(_out(), None)
    assert not s.admit(_out(mse=float("nan")), None)
    assert not ssc.aborted
    assert s.rollbacks == 2


def test_sentinel_off_is_inert():
    s, ssc = _sentinel(conf_args=["--sentinel", "off"])
    assert not s.enabled
    assert ssc.rollback_count_fn is None


def test_rollback_count_rides_the_ssc_hook():
    s, ssc = _sentinel()
    assert ssc.rollback_count_fn() == 0
    s.admit(_out(mse=float("nan")), None)
    assert ssc.rollback_count_fn() == 1


# -- end-to-end acceptance ---------------------------------------------------

CLOSED = "http://127.0.0.1:9"


def _write_lines(path, lines):
    with open(path, "w") as fh:
        for ln in lines:
            fh.write(ln + "\n")


def _corpus(total, seed):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    return [
        json.dumps(_status_json(s))
        for s in SyntheticSource(
            total=total, seed=seed, base_ms=1785320000000
        ).produce()
    ]


def _run_counting_fetches(conf_args):
    """app.run with every jax.device_get counted — the measurement-
    integrity assertion idiom from tests/test_trace.py."""
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()  # lock the conftest backend before local[1]
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    jax.device_get = counting
    try:
        totals = app.run(ConfArguments().parse(list(conf_args)))
    finally:
        jax.device_get = real
    return totals, calls["n"]


BASE = [
    "--source", "replay", "--seconds", "0", "--backend", "cpu",
    "--batchBucket", "16", "--tokenBucket", "64", "--master", "local[1]",
    "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
]


def test_acceptance_nan_chaos_rollback_matches_clean_run(tmp_path, monkeypatch):
    """THE ISSUE 4→19 acceptance path: poison batch 5 of 8 via source.nan,
    detect on the already-fetched stats, roll back to the verified
    checkpoint at batch 4, and RE-INGEST the skipped rows from the intake
    journal (--journal auto follows --checkpointDir). source.nan injects
    at the featurize stage — AFTER the journal seam — so the journaled
    bytes are clean and the trigger's call index never re-fires on the
    replay: the final weights and counters equal a clean run over the SAME
    full file. Crash-equals-clean, zero rows lost."""
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer

    # pin the age-feature clock: the comparison is BIT-exact, and the two
    # runs must featurize identically (same trick as the multi-host tests)
    monkeypatch.setenv("TWTML_NOW_MS", "1785320000000")

    lines = _corpus(8 * 16, seed=51)
    poisoned_file = tmp_path / "poisoned.jsonl"
    _write_lines(poisoned_file, lines)

    d_poison, d_clean = str(tmp_path / "ckp"), str(tmp_path / "ckc")
    totals_p, fetches_p = _run_counting_fetches(
        BASE + ["--replayFile", str(poisoned_file),
                "--checkpointDir", d_poison, "--checkpointEvery", "1",
                "--chaos", "source.nan@5"]
    )
    reg = _metrics.get_registry()
    assert reg.counter("model.rollbacks").snapshot() == 1
    assert reg.counter("model.nonfinite_batches").snapshot() == 1
    # the journal converts the counted loss into a replay: the poisoned
    # batch's 16 rows re-ingest from cursor 4 and train clean
    assert reg.counter("model.rows_lost").snapshot() == 0
    assert reg.counter("journal.replayed_rows").snapshot() == 16
    assert reg.counter("journal.torn_tails").snapshot() == 0
    assert reg.counter("fetch.aborts").snapshot() == 0
    # one fetch per DISPATCHED batch and nothing else: 8 from the file +
    # 1 re-dispatch of the replayed rows — the sentinel and the journal
    # both read only what was already on the host
    assert fetches_p == 9
    # every row trains exactly once: the full-file ledger
    assert totals_p["batches"] == 8
    assert totals_p["count"] == 8 * 16

    _metrics.reset_for_tests()
    faults.uninstall_chaos()  # the injector is process-wide per --chaos run

    totals_c = app.run(ConfArguments().parse(
        BASE + ["--replayFile", str(poisoned_file),
                "--checkpointDir", d_clean, "--checkpointEvery", "1"]
    ))
    assert totals_c["batches"] == 8
    assert totals_c["count"] == 8 * 16

    w_poison, meta_p = Checkpointer(d_poison).restore()
    w_clean, meta_c = Checkpointer(d_clean).restore()
    assert meta_p["count"] == meta_c["count"] == 8 * 16
    # rollback restore is bit-exact, the journaled bytes are the clean
    # pre-poison rows, and replay re-runs them through the unchanged
    # featurize path in order -> identical trajectories
    np.testing.assert_array_equal(w_poison, w_clean)


def test_nan_chaos_zero_fetch_delta_vs_sentinel_off(tmp_path):
    """Healthy path: sentinel on vs off is fetch-count identical (the
    guard never touches the device)."""
    path = tmp_path / "tweets.jsonl"
    _write_lines(path, _corpus(4 * 16, seed=52))
    args = BASE + ["--replayFile", str(path)]
    totals_on, fetches_on = _run_counting_fetches(args)
    _metrics.reset_for_tests()
    totals_off, fetches_off = _run_counting_fetches(
        args + ["--sentinel", "off"]
    )
    assert totals_on["count"] == totals_off["count"] == 4 * 16
    assert fetches_on == fetches_off == 4


def test_nan_chaos_without_checkpoint_resets_and_continues(tmp_path):
    """No --checkpointDir: the rollback target is the reference's initial
    zeros — progress is lost loudly, the stream keeps training."""
    from twtml_tpu.apps import linear_regression as app

    import jax

    jax.devices()
    path = tmp_path / "tweets.jsonl"
    _write_lines(path, _corpus(8 * 16, seed=53))
    totals = app.run(ConfArguments().parse(
        BASE + ["--replayFile", str(path), "--chaos", "source.nan@5"]
    ))
    reg = _metrics.get_registry()
    assert reg.counter("model.rollbacks").snapshot() == 1
    # without a checkpoint cadence the fetch pipeline runs deep: between
    # the poisoned dispatch and its delivery, up to depth-1 more batches
    # trained on NaN weights and drain as tainted skips — how many is
    # wall-clock-dependent (the opportunistic early emit), so assert the
    # closed accounting instead of a fixed count
    lost = int(reg.counter("model.rows_lost").snapshot())
    assert lost >= 16
    assert totals["count"] == 8 * 16 - lost
    assert totals["batches"] == totals["count"] // 16
    assert reg.counter("model.sentinel_aborts").snapshot() == 0


def test_nan_storm_aborts_cleanly_with_finite_checkpoint(tmp_path):
    """Rollback storm (every 2nd batch poisoned, budget 2): the run aborts
    through request_abort — non-zero outcome, critical log, and the final
    checkpoint holds FINITE weights (the rollback restored them before
    the abort; a NaN final save would have been quarantined anyway)."""
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer

    import jax

    jax.devices()
    path = tmp_path / "tweets.jsonl"
    _write_lines(path, _corpus(8 * 16, seed=54))
    ck = str(tmp_path / "ck")
    with pytest.raises(RuntimeError, match="runtime guard"):
        app.run(ConfArguments().parse(
            BASE + ["--replayFile", str(path),
                    "--checkpointDir", ck, "--checkpointEvery", "1",
                    "--chaos", "source.nan@2",
                    "--sentinelRollbacks", "2", "--sentinelWindow", "100"]
        ))
    reg = _metrics.get_registry()
    assert reg.counter("model.rollbacks").snapshot() == 2
    assert reg.counter("model.sentinel_aborts").snapshot() == 1
    restored = Checkpointer(ck).restore()
    assert restored is not None
    state, meta = restored
    assert np.isfinite(np.asarray(state)).all()


def test_superbatch_group_rollback_skips_poisoned_group(tmp_path):
    """--superBatch: the poisoning lands inside a scanned K-group — the
    whole tainted group's deliveries are skipped (the scan chained the NaN
    through the group), the rollback recovers, and the run completes."""
    from twtml_tpu.apps import linear_regression as app

    import jax

    jax.devices()
    path = tmp_path / "tweets.jsonl"
    _write_lines(path, _corpus(8 * 16, seed=55))
    ck = str(tmp_path / "ck")
    totals = app.run(ConfArguments().parse(
        BASE + ["--replayFile", str(path),
                "--checkpointDir", ck, "--checkpointEvery", "2",
                "--superBatch", "2",
                "--chaos", "source.nan@5"]
    ))
    reg = _metrics.get_registry()
    # TWO episodes: batch 5 (featurize call 5) poisons its group (5,6) —
    # both skipped, 32 rows replayed from the batch-4 cursor. The @5
    # trigger is every-5th-call, so the 10th featurize call (batch 8; the
    # replays consumed calls 7-8) poisons AGAIN — rollback to the batch-6
    # save replays batches 7-8. Each replay re-crosses the seam BELOW the
    # injection point and trains clean: the full-file ledger, zero lost.
    assert reg.counter("model.rollbacks").snapshot() == 2
    assert reg.counter("fetch.aborts").snapshot() == 0
    assert totals["batches"] == 8
    assert totals["count"] == 8 * 16
    assert reg.counter("model.rows_lost").snapshot() == 0
    assert reg.counter("journal.replayed_rows").snapshot() == 4 * 16
