"""Superbatch ingest (``step_many``): a lax.scan of K micro-batch steps in
one dispatch must be the SAME computation as K sequential ``step`` calls —
identical final weights and per-batch stats (the scan body is the very same
train_step program, weights chained through it) — for the dense path, the
sparse Gram path, and the logistic residual."""

import numpy as np

from twtml_tpu.features.batch import stack_batches
from twtml_tpu.features.featurizer import Featurizer
from twtml_tpu.models import (
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)
from twtml_tpu.streaming.sources import SyntheticSource


def featurized_batches(n=4, rows=32, f_text=None):
    statuses = list(
        SyntheticSource(total=n * rows, seed=3, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000, **(
        {"num_text_features": f_text} if f_text else {}
    ))
    return [
        feat.featurize_batch_units(
            statuses[i * rows : (i + 1) * rows], row_bucket=rows, pre_filtered=True
        )
        for i in range(n)
    ]


def assert_equivalent(make_model, batches):
    seq = make_model()
    outs = [seq.step(b) for b in batches]
    sup = make_model()
    stacked_out = sup.step_many(stack_batches(batches))
    np.testing.assert_array_equal(sup.latest_weights, seq.latest_weights)
    for k, out in enumerate(outs):
        assert float(stacked_out.mse[k]) == float(out.mse)
        assert float(stacked_out.count[k]) == float(out.count)
        np.testing.assert_array_equal(
            np.asarray(stacked_out.predictions[k]), np.asarray(out.predictions)
        )


def test_dense_superbatch_matches_sequential():
    assert_equivalent(
        lambda: StreamingLinearRegressionWithSGD(num_iterations=10),
        featurized_batches(),
    )


def test_sparse_gram_superbatch_matches_sequential():
    assert_equivalent(
        lambda: StreamingLinearRegressionWithSGD(
            num_text_features=2**14, num_iterations=5, l2_reg=0.1
        ),
        featurized_batches(f_text=2**14),
    )


def test_logistic_superbatch_matches_sequential():
    from twtml_tpu.features.sentiment import sentiment_label, sentiment_labels

    statuses = list(SyntheticSource(total=96, seed=5, base_ms=1785320000000).produce())
    feat = Featurizer(now_ms=1785320000000)
    feat.label_fn = sentiment_label
    feat.batch_label_fn = sentiment_labels
    batches = [
        feat.featurize_batch_units(statuses[i : i + 32], row_bucket=32, pre_filtered=True)
        for i in range(0, 96, 32)
    ]
    assert_equivalent(
        lambda: StreamingLogisticRegressionWithSGD(num_iterations=10), batches
    )


def ragged_batches(n=4, rows=32, f_text=None):
    statuses = list(
        SyntheticSource(total=n * rows, seed=3, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000, **(
        {"num_text_features": f_text} if f_text else {}
    ))
    return [
        feat.featurize_batch_ragged(
            statuses[i * rows : (i + 1) * rows], row_bucket=rows,
            pre_filtered=True,
        )
        for i in range(n)
    ]


def test_ragged_superbatch_matches_sequential():
    """r5 (VERDICT r4 #1c): the ragged wire stacks — [K, N] units scan like
    any leaf with row_len static — and the scan is bitwise the K plain
    steps."""
    assert_equivalent(
        lambda: StreamingLinearRegressionWithSGD(num_iterations=10),
        ragged_batches(),
    )


def test_ragged_stack_rejects_mixed_alignment():
    import pytest

    from twtml_tpu.features.batch import align_ragged_shards

    a, b = ragged_batches(n=2)
    with pytest.raises(ValueError, match="different row_len or shard"):
        stack_batches([a, align_ragged_shards(b, 2)])


def test_mesh_ragged_step_many_matches_sequential():
    """Stacked shard-aligned ragged batches scan on the mesh (both
    layouts), equal to K sequential sharded ragged steps — and to the
    padded wire's weights (the wire is bit-identical)."""
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    batches = ragged_batches(n=4, rows=32)
    for mesh_kw in (dict(num_data=4), dict(num_data=2, num_model=2)):
        mesh = make_mesh(devices=jax.devices()[:4], **mesh_kw)
        seq = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
        outs = [seq.step(shard_batch(b, mesh)) for b in batches]
        sup = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
        aligned = [sup.prepare(b) for b in batches]
        many = sup.step_many(stack_batches(aligned))
        np.testing.assert_array_equal(sup.latest_weights, seq.latest_weights)
        for k, out in enumerate(outs):
            assert float(many.mse[k]) == float(out.mse)
            np.testing.assert_array_equal(
                np.asarray(many.predictions[k]), np.asarray(out.predictions)
            )


def test_superbatcher_groups_ragged_via_prepare():
    """The app grouping path: SuperBatcher over prepare()-aligned ragged
    batches on a mesh — same weights as sequential mesh steps, every batch
    delivered in order."""
    import jax

    from twtml_tpu.apps.common import SuperBatcher
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    batches = ragged_batches(n=5, rows=32)
    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    model = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    seen = []
    batcher = SuperBatcher(
        model, 2,
        lambda out, batch, t, at_boundary: seen.append(float(out.count)),
    )
    for i, b in enumerate(batches):
        batcher.on_batch(model.prepare(b), float(i))
    batcher.flush()
    assert len(seen) == 5  # 2 full groups + a partial tail

    ref = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    for b in batches:
        ref.step(shard_batch(b, mesh))
    np.testing.assert_array_equal(model.latest_weights, ref.latest_weights)


def test_mesh_step_many_matches_sequential():
    """ParallelSGDModel.step_many (scan inside shard_map) equals K
    sequential sharded steps on BOTH mesh layouts — so --superBatch works
    under --master local[N] too."""
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    batches = featurized_batches(n=4, rows=32)
    for mesh_kw in (dict(num_data=4), dict(num_data=2, num_model=2)):
        mesh = make_mesh(devices=jax.devices()[:4], **mesh_kw)
        seq = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
        outs = [seq.step(shard_batch(b, mesh)) for b in batches]
        sup = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
        stacked = shard_batch(stack_batches(batches), mesh)
        many = sup.step_many(stacked)
        np.testing.assert_allclose(
            sup.latest_weights, seq.latest_weights, rtol=1e-6, atol=1e-7
        )
        for k, out in enumerate(outs):
            assert float(many.mse[k]) == float(out.mse)
            np.testing.assert_array_equal(
                np.asarray(many.predictions[k]), np.asarray(out.predictions)
            )


def test_linear_app_superbatch_identical_stats(tmp_path, capsys):
    """The flagship app with --superBatch 3 prints the IDENTICAL per-batch
    stats lines (same batch boundaries, same mse/stdev sequence) and ends
    with identical weights as the plain run — including the partial final
    group drained by the termination flush."""
    import json as _json

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    path = tmp_path / "tweets.jsonl"
    statuses = list(SyntheticSource(total=7 * 16, seed=9, base_ms=1785320000000).produce())
    from tools.bench_suite import _status_json

    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")

    def run(extra):
        conf = ConfArguments().parse(
            [
                "--source", "replay", "--replayFile", str(path),
                "--seconds", "0", "--backend", "cpu",
                "--batchBucket", "16", "--tokenBucket", "64",
                "--master", "local[1]",  # single-device learner: step_many
            ]
            + extra
        )
        capsys.readouterr()
        totals = app.run(conf)
        lines = [
            ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("count:")
        ]
        return totals, lines

    # default wire (auto → ragged, r5) AND the padded escape hatch: the
    # superbatch path must be stats-identical on both
    all_lines = []
    for wire in ([], ["--wire", "padded"]):
        totals_plain, lines_plain = run(wire)
        totals_super, lines_super = run(wire + ["--superBatch", "3"])
        # stream_seconds is wall-clock (r4, for the suite's startup split)
        totals_plain.pop("stream_seconds", None)
        totals_super.pop("stream_seconds", None)
        assert totals_super == totals_plain
        assert lines_super == lines_plain
        assert len(lines_plain) >= 5  # several batches incl. a partial group
        all_lines.append(lines_plain)
    # and the two wires agree with each other (bit-identical features)
    assert all_lines[0] == all_lines[1]


def test_superbatch_requires_pinned_buckets(tmp_path):
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    path = tmp_path / "tweets.jsonl"
    path.write_text("")
    conf = ConfArguments().parse(
        [
            "--source", "replay", "--replayFile", str(path),
            "--seconds", "0", "--backend", "cpu", "--superBatch", "4",
            "--master", "local[1]",
        ]
    )
    import pytest

    with pytest.raises(ValueError, match="superBatch needs pinned shapes"):
        app.run(conf)


def test_mixed_shape_batches_flush_not_drop():
    """A batch with a different shape (bucket overflow / units dtype flip)
    must close the pending group and form its own — every batch trains,
    none is dropped, order preserved."""
    from twtml_tpu.apps.common import SuperBatcher

    small = featurized_batches(n=5, rows=16)
    big = featurized_batches(n=1, rows=32)[0]
    stream = [small[0], small[1], big, small[2], small[3], small[4]]

    model = StreamingLinearRegressionWithSGD(num_iterations=5)
    seen = []
    batcher = SuperBatcher(
        model, 2, lambda out, batch, t, at_boundary: seen.append(
            (batch.mask.shape[0], float(out.count))
        )
    )
    for i, b in enumerate(stream):
        batcher.on_batch(b, float(i))
    batcher.flush()
    assert [rows for rows, _ in seen] == [16, 16, 32, 16, 16, 16]

    ref = StreamingLinearRegressionWithSGD(num_iterations=5)
    for b in stream:
        ref.step(b)
    np.testing.assert_array_equal(model.latest_weights, ref.latest_weights)


def test_partial_tail_uses_plain_steps():
    """Group sizes below K run as plain steps — no scanned program is built
    for one-off lengths."""
    from twtml_tpu.apps.common import SuperBatcher

    batches = featurized_batches(n=3)
    model = StreamingLinearRegressionWithSGD(num_iterations=5)
    emitted = []
    b4 = SuperBatcher(model, 4, lambda o, b, t, at_boundary: emitted.append(o))
    for i, b in enumerate(batches):
        b4.on_batch(b, float(i))
    b4.flush()
    assert len(emitted) == 3
    assert model._scan_step is None  # never compiled a scan

    ref = StreamingLinearRegressionWithSGD(num_iterations=5)
    for b in batches:
        ref.step(b)
    np.testing.assert_array_equal(model.latest_weights, ref.latest_weights)


def test_checkpoint_cadence_crosses_group_boundaries(tmp_path):
    """--checkpointEvery E with --superBatch K saves on the first boundary
    at/after each cadence point (not lcm(K, E))."""
    import json as _json

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    path = tmp_path / "tweets.jsonl"
    statuses = list(SyntheticSource(total=8 * 16, seed=9, base_ms=1785320000000).produce())
    from tools.bench_suite import _status_json

    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")
    ckdir = tmp_path / "ck"
    conf = ConfArguments().parse(
        [
            "--source", "replay", "--replayFile", str(path),
            "--seconds", "0", "--backend", "cpu",
            "--batchBucket", "16", "--tokenBucket", "64",
            "--master", "local[1]", "--superBatch", "3",
            "--checkpointDir", str(ckdir), "--checkpointEvery", "2",
        ]
    )
    app.run(conf)
    from twtml_tpu.checkpoint import Checkpointer

    weights, meta = Checkpointer(str(ckdir)).restore()
    # 8 batches in groups of 3: boundaries at 3, 6, 8(flush); cadence 2 →
    # saves at 3, 6, 8 — the final state is checkpointed
    assert meta["batches"] == 8


def test_cumulative_count_chains_across_stream():
    """step_many is stateful like step: a second call continues the same
    model (weights advance, no reset between superbatches)."""
    batches = featurized_batches(n=4)
    seq = StreamingLinearRegressionWithSGD(num_iterations=5)
    for b in batches:
        seq.step(b)
    sup = StreamingLinearRegressionWithSGD(num_iterations=5)
    sup.step_many(stack_batches(batches[:2]))
    sup.step_many(stack_batches(batches[2:]))
    np.testing.assert_array_equal(sup.latest_weights, seq.latest_weights)


def test_boundary_cadence_immune_to_refunds():
    """``refund_dispatch`` adjusts only the max-batches cap accounting; the
    checkpoint boundary cadence runs on its own MONOTONIC counter (r5 —
    the same r3 advisor finding FetchPipeline fixed with `_cadence`,
    re-found in SuperBatcher by the r5 review: multi-host globally-empty
    refunds must not drift weights-current drains past the configured
    cadence)."""
    from twtml_tpu.apps.common import SuperBatcher

    batches = featurized_batches(n=8)
    flags = []
    model = StreamingLinearRegressionWithSGD(num_iterations=5)
    sb = SuperBatcher(
        model, 2, lambda o, b, t, at_boundary: flags.append(at_boundary),
        boundary_every=4, deterministic=True,
    )
    for i, b in enumerate(batches):
        sb.on_batch(b, 0.0)
        if i == 1:  # two globally-empty refunds right after group 1
            sb.refund_dispatch()
            sb.refund_dispatch()
    sb.flush()
    # cadence 4 over 4 groups of 2: drains after batches 4 and 8, refunds
    # notwithstanding — at_boundary=True lands exactly there
    assert flags == [False, False, False, True, False, False, False, True]
