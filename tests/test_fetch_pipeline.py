"""Concurrent in-order stats fetch (apps/common.FetchPipeline): back-to-back
apps dispatch on the main thread and fetch each batch's StepOutput on a
small pool (measured 6.2x paired over sync fetches through the TPU tunnel
-- BENCHMARKS.md). Semantics must stay the synchronous path's: per-batch
stats in order, at_boundary only with current weights (drains), exact
max-batches caps, tail drained by flush()."""

import json

import numpy as np

from twtml_tpu.apps.common import FetchPipeline
from twtml_tpu.config import ConfArguments
from twtml_tpu.streaming.sources import SyntheticSource


class FakeModel:
    def __init__(self):
        self.dispatched = []

    def step(self, batch):
        self.dispatched.append(batch)
        return {"i": np.asarray(batch)}


def test_emits_in_order_and_flush_drains():
    model, events = FakeModel(), []
    pipe = FetchPipeline(
        model,
        lambda out, b, t, at_boundary: events.append((int(out["i"]), at_boundary)),
        depth=3,
    )
    for i in range(10):
        pipe.on_batch(i, 0.0)
    pipe.flush()
    assert model.dispatched == list(range(10))
    assert [e[0] for e in events] == list(range(10))  # strict order
    # at_boundary True iff the pipeline was empty after the emit (an
    # instant fake model drains opportunistically, so most emits qualify);
    # the final drained batch always does
    assert events[-1][1] is True


def test_max_dispatch_is_exact_and_stop_vetoes():
    model, events = FakeModel(), []
    stop = {"flag": False}

    def handle(out, b, t, at_boundary):
        events.append(int(out["i"]))
        if out["i"] >= 4:
            stop["flag"] = True

    pipe = FetchPipeline(
        model, handle, depth=3,
        stop_requested=lambda: stop["flag"], max_dispatch=5,
    )
    for i in range(20):
        pipe.on_batch(i, 0.0)
    pipe.flush()
    assert model.dispatched == [0, 1, 2, 3, 4]  # the cap, exactly
    assert events == [0, 1, 2, 3, 4]


def test_boundary_every_drains_at_cadence():
    model, events = FakeModel(), []
    pipe = FetchPipeline(
        model,
        lambda out, b, t, at_boundary: events.append((int(out["i"]), at_boundary)),
        depth=4, boundary_every=3,
    )
    for i in range(9):
        pipe.on_batch(i, 0.0)
    pipe.flush()
    boundaries = [i for i, at_b in events if at_b]
    # every 3rd batch is a drain point (weights current for checkpoints)
    assert set(boundaries) >= {2, 5, 8}
    assert [e[0] for e in events] == list(range(9))


def test_linear_app_max_batches_exact_under_fetch_pipeline(tmp_path):
    """The flagship app in back-to-back mode (--seconds 0, where the fetch
    pipeline engages) trains EXACTLY max_batches batches."""
    import jax

    from tools.bench_suite import _status_json
    from twtml_tpu.apps import linear_regression as app

    jax.devices()  # lock the conftest's 8-device backend before local[1]

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=8 * 16, seed=11, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")

    conf = ConfArguments().parse([
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
    ])
    totals = app.run(conf, max_batches=3)
    assert totals["batches"] == 3
    assert totals["count"] == 3 * 16


def test_linear_app_checkpoint_cadence_under_fetch_pipeline(tmp_path):
    """--checkpointDir/--checkpointEvery under the fetch pipeline: cadence
    saves see current weights (the pipeline drains at cadence points), and
    a resumed run continues the counters."""
    import jax

    from tools.bench_suite import _status_json
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer

    jax.devices()

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=6 * 16, seed=12, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")

    ck = str(tmp_path / "ck")
    conf_args = [
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
        "--checkpointDir", ck, "--checkpointEvery", "2",
    ]
    totals = app.run(ConfArguments().parse(conf_args), max_batches=4)
    assert totals["batches"] == 4
    state, meta = Checkpointer(ck).restore()
    assert meta["batches"] == 4
    # resume: counters continue from the checkpoint (batches=4, count=64)
    # and the re-read replay file fast-forwards past the 64 journaled
    # rows the checkpoint covers (r21 exact resume) — only the 2 batches
    # the first run never reached train now: exactly-once over the corpus
    totals2 = app.run(ConfArguments().parse(conf_args))
    assert totals2["batches"] == 4 + 2
    assert totals2["count"] == 64 + 2 * 16


def test_cap_reached_still_delivers_pending_handles():
    """Regression: once max_dispatch is hit, further on_batch calls (an
    unbounded live source keeps producing) must still DELIVER the trained
    batches' handles — that is where the app's request_stop lives; without
    it the stream never learns it should stop."""
    model, events = FakeModel(), []
    pipe = FetchPipeline(
        model, lambda out, b, t, at_boundary: events.append(int(out["i"])),
        depth=8, max_dispatch=2,
    )
    pipe.on_batch(0, 0.0)
    pipe.on_batch(1, 0.0)
    pipe.on_batch(2, 0.0)  # beyond the cap: not trained, but 0 and 1 deliver
    assert model.dispatched == [0, 1]
    assert events == [0, 1]


def test_refund_does_not_perturb_checkpoint_cadence():
    """r3 advisor: cadence runs on a MONOTONIC counter — a refunded
    dispatch slot (multi-host empty-global batches) must not make the
    cadence pass a point twice or skip it."""
    model, events = FakeModel(), []
    pipe = FetchPipeline(
        model,
        lambda out, b, t, at_boundary: events.append((int(out["i"]), at_boundary)),
        depth=4, boundary_every=3, max_dispatch=50,
    )
    for i in range(9):
        pipe.on_batch(i, 0.0)
        pipe.refund_dispatch()  # every batch refunds (worst case)
    pipe.flush()
    boundaries = [i for i, at_b in events if at_b]
    # cadence unchanged by the refunds: every 3rd batch still drains
    assert set(boundaries) >= {2, 5, 8}
    # and the refunds did their own job: the cap accounting went negative-
    # of-dispatch (50-cap never reached, all 9 trained)
    assert [e[0] for e in events] == list(range(9))


def test_deterministic_mode_emits_only_at_deterministic_points():
    """r3 advisor (multi-host): with deterministic=True the opportunistic
    already-done early emit is disabled — deliveries happen only at depth
    backpressure, cadence drains, and flush, i.e. at points driven by the
    dispatch counter (identical on every lockstep host), never by
    wall-clock future completion."""
    import time as _time

    model, events = FakeModel(), []
    pipe = FetchPipeline(
        model,
        lambda out, b, t, at_boundary: events.append(int(out["i"])),
        depth=4, deterministic=True,
    )
    for i in range(4):
        pipe.on_batch(i, 0.0)
        _time.sleep(0.02)  # futures certainly done (instant fake model)...
        # ...yet nothing may emit below the depth watermark
        assert events == []
    pipe.on_batch(4, 0.0)  # 5th dispatch finds depth reached → one emit
    assert events == [0]
    pipe.flush()
    assert events == [0, 1, 2, 3, 4]
