"""Per-shard packed ragged wire (r5): ``pack_ragged_sharded`` lays a
shard-aligned RaggedUnitBatch into ONE buffer whose S equal segments are the
shards, so the mesh data axis shards the single buffer and each device
rebuilds its local batch in-program — the +11.4% packing win (BENCHMARKS.md)
extended to every layout. Parity bar: bit-identical weights vs both the
unpacked ragged wire and the padded units wire on the same mesh."""

import jax
import numpy as np
import pytest

from twtml_tpu.features.batch import (
    RaggedUnitBatch,
    align_ragged_shards,
    pack_ragged_sharded,
    unpack_batch,
)
from twtml_tpu.features.featurizer import Featurizer
from twtml_tpu.parallel import ParallelSGDModel, make_mesh
from twtml_tpu.parallel.sharding import shard_batch
from twtml_tpu.streaming.sources import SyntheticSource


def _ragged_batch(rows=32, f_text=None, seed=3):
    statuses = list(
        SyntheticSource(total=rows, seed=seed, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000, **(
        {"num_text_features": f_text} if f_text else {}
    ))
    return feat.featurize_batch_ragged(
        statuses, row_bucket=rows, pre_filtered=True
    ), feat, statuses


def test_pack_unpack_roundtrip_host():
    rb, _, _ = _ragged_batch()
    aligned = align_ragged_shards(rb, 4)
    pb = pack_ragged_sharded(aligned)
    back = unpack_batch(pb.buffer, pb.layout)
    assert isinstance(back, RaggedUnitBatch)
    assert back.num_shards == 4 and back.row_len == aligned.row_len
    for f in ("units", "offsets", "numeric", "label", "mask"):
        np.testing.assert_array_equal(
            np.asarray(getattr(back, f)), np.asarray(getattr(aligned, f))
        )
    assert pb.num_valid == aligned.num_valid


def test_pack_single_shard_alignment_is_legal():
    # 1-device meshes and the one-shard-per-process topology pack s=1
    rb, _, _ = _ragged_batch(rows=16)
    pb = pack_ragged_sharded(rb)
    back = unpack_batch(pb.buffer, pb.layout)
    np.testing.assert_array_equal(
        np.asarray(back.units), np.asarray(rb.units)
    )
    assert back.num_shards == 1


def test_layout_records_global_shards():
    rb, _, _ = _ragged_batch(rows=16)
    aligned = align_ragged_shards(rb, 2)
    pb = pack_ragged_sharded(aligned, num_shards_out=4)
    assert pb.layout[2][1] == 4


@pytest.mark.parametrize(
    "mesh_kw", [dict(num_data=4), dict(num_data=2, num_model=2)]
)
def test_mesh_packed_step_bit_matches_unpacked(mesh_kw):
    rb, feat, statuses = _ragged_batch(rows=32)
    unit = feat.featurize_batch_units(statuses, row_bucket=32, pre_filtered=True)
    mesh = make_mesh(devices=jax.devices()[:4], **mesh_kw)

    packed = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
    plain = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
    padded = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)

    out_p = packed.step(packed.pack_for_wire(rb))
    out_r = plain.step(shard_batch(rb, mesh))
    out_u = padded.step(unit)

    assert float(out_p.count) == float(out_r.count) == float(out_u.count)
    np.testing.assert_array_equal(
        np.asarray(out_p.predictions), np.asarray(out_r.predictions)
    )
    np.testing.assert_array_equal(packed.latest_weights, plain.latest_weights)
    np.testing.assert_array_equal(packed.latest_weights, padded.latest_weights)


def test_mesh_pack_one_device_mesh():
    rb, _, _ = _ragged_batch(rows=16)
    mesh = make_mesh(num_data=1, devices=jax.devices()[:1])
    m = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
    out = m.step(m.pack_for_wire(rb))
    assert float(out.count) == rb.num_valid


def test_mesh_rejects_flat_pack():
    from twtml_tpu.features.batch import pack_batch

    rb, _, _ = _ragged_batch(rows=16)
    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    m = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
    with pytest.raises(ValueError, match="per-shard packed layout"):
        m.step(pack_batch(rb))


def test_mesh_rejects_mismatched_shard_layout():
    rb, _, _ = _ragged_batch(rows=32)
    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    m = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
    pb = pack_ragged_sharded(align_ragged_shards(rb, 2))
    with pytest.raises(ValueError, match="laid out for 2 shards"):
        m.step(pb)
