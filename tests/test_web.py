"""Web-server integration tests (reference: WebTestSuite.scala:10-42 — boot
the real server in-process and round-trip Config/Stats over real HTTP), plus
websocket broadcast/connect-push semantics the reference only exercised
manually via test.html."""

import asyncio
import json

import pytest

from twtml_tpu.telemetry.api_types import Config, Stats
from twtml_tpu.telemetry.web_client import WebClient
from twtml_tpu.web.cache import ApiCache
from twtml_tpu.web.server import Server

HOST = "127.0.0.1"


@pytest.fixture()
def server(tmp_path):
    cache = ApiCache(backup_file=str(tmp_path / "twtml-web.json"))
    srv = Server(port=0, host=HOST, cache=cache)
    srv.start_background()
    # port 0 → discover the bound port
    port = srv._runner.addresses[0][1]
    yield srv, f"http://{HOST}:{port}", cache
    srv.stop()


def test_http_roundtrip_config_stats(server):
    _, url, _ = server
    client = WebClient(url)
    client.config("100", "http://lightninghost", ["101", "102"])
    client.stats(1000, 10, 2000, 15, 25)
    assert client.get_config() == Config(id="100", host="http://lightninghost",
                                         viz=["101", "102"])
    assert client.get_stats() == Stats(count=1000, batch=10, mse=2000,
                                       realStddev=15, predStddev=25)


def test_defaults_before_any_post(server):
    _, url, _ = server
    client = WebClient(url)
    assert client.get_config() == Config()
    assert client.get_stats() == Stats()


def test_unknown_json_is_dropped(server):
    _, url, _ = server
    import urllib.request

    req = urllib.request.Request(
        url + "/api", data=b'{"jsonClass":"Nope"}',
        headers={"content-type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=2) as resp:
        assert json.loads(resp.read())["status"] == "OK"
    client = WebClient(url)
    assert client.get_stats() == Stats()  # cache untouched


def test_static_dashboard_served(server):
    _, url, _ = server
    import urllib.request

    with urllib.request.urlopen(url + "/", timeout=2) as resp:
        body = resp.read().decode()
    assert "twtml-tpu" in body and 'id="mse"' in body
    with urllib.request.urlopen(url + "/js/api.js", timeout=2) as resp:
        assert b"websocketOn" in resp.read()
    with pytest.raises(Exception):
        urllib.request.urlopen(url + "/definitely-missing", timeout=2)


def test_config_persistence_roundtrip(tmp_path):
    backup = str(tmp_path / "twtml-web.json")
    cache = ApiCache(backup_file=backup)
    cache.cache('{"jsonClass":"Config","id":"a","host":"h","viz":["1"]}')
    cache.cache('{"jsonClass":"Stats","count":5,"batch":1,"mse":2,'
                '"realStddev":3,"predStddev":4}')
    # fresh cache restores Config only (ApiCache.scala:27-31,50-56)
    fresh = ApiCache(backup_file=backup)
    fresh.restore()
    assert json.loads(fresh.config())["id"] == "a"
    assert json.loads(fresh.stats())["count"] == 0


def test_websocket_broadcast_and_connect_push(server):
    _, url, _ = server
    ws_url = url.replace("http://", "ws://") + "/api"
    client = WebClient(url)
    client.config("cfg-1", "http://lightning", ["viz-9"])

    async def scenario():
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.ws_connect(ws_url) as ws1, \
                    session.ws_connect(ws_url) as ws2:
                # on-connect push: cached Config to each new socket
                first1 = json.loads((await ws1.receive(timeout=5)).data)
                first2 = json.loads((await ws2.receive(timeout=5)).data)
                assert first1["jsonClass"] == first2["jsonClass"] == "Config"
                assert first1["id"] == "cfg-1"
                # a frame sent by one socket is broadcast to ALL (incl sender)
                payload = {"jsonClass": "Stats", "count": 7, "batch": 7,
                           "mse": 7, "realStddev": 7, "predStddev": 7}
                await ws1.send_str(json.dumps(payload))
                echo1 = json.loads((await ws1.receive(timeout=5)).data)
                echo2 = json.loads((await ws2.receive(timeout=5)).data)
                assert echo1 == echo2 == payload
        # and an HTTP POST is broadcast to websockets too
        return True

    assert asyncio.run(scenario())
    # the WS frame also updated the HTTP-readable cache
    assert client.get_stats().count == 7


def test_series_roundtrip_and_window(server):
    """Additive Series messages: cached in a rolling window, served at
    /api/series for chart backfill, broadcast like everything else."""
    _, url, cache = server
    client = WebClient(url)
    for k in range(3):
        client.series([float(k), k + 0.5], [k + 1.0, k + 1.5], 10.0, 12.0)
    import urllib.request

    with urllib.request.urlopen(url + "/api/series", timeout=2) as resp:
        items = json.loads(resp.read())
    assert len(items) == 3
    assert items[0]["jsonClass"] == "Series"
    assert items[-1]["real"] == [2.0, 2.5]
    assert items[-1]["realStddev"] == 10.0
    # rolling window bounded
    from twtml_tpu.web.cache import SERIES_WINDOW

    for k in range(SERIES_WINDOW + 10):
        client.series([1.0], [1.0], 0.0, 0.0)
    with urllib.request.urlopen(url + "/api/series", timeout=2) as resp:
        assert len(json.loads(resp.read())) == SERIES_WINDOW


def test_metrics_roundtrip_and_default(server):
    """Additive Metrics messages: cached last-value (in-memory, like Stats),
    served at /api/metrics for the dashboard's observability panel."""
    _, url, _ = server
    import urllib.request

    with urllib.request.urlopen(url + "/api/metrics", timeout=2) as resp:
        empty = json.loads(resp.read())
    assert empty["jsonClass"] == "Metrics"
    assert empty["counters"] == {} and empty["health"] == {}

    client = WebClient(url)
    client.metrics(
        {"pipeline.batches": 12, "wire.bytes": 1234567},
        {"fetch.queue_depth": 3, "host.rss_mb": 512.5},
        {"phase": "degraded", "rtt_ms": 412.0, "transitions": 2},
    )
    with urllib.request.urlopen(url + "/api/metrics", timeout=2) as resp:
        got = json.loads(resp.read())
    assert got["counters"]["pipeline.batches"] == 12
    assert got["gauges"]["host.rss_mb"] == 512.5
    assert got["health"]["phase"] == "degraded"


def test_hosts_roundtrip_and_default(server):
    """Additive Hosts messages (the lockstep fleet view): cached last-value
    like Metrics, served at /api/hosts, unknown to legacy caches."""
    _, url, _ = server
    import urllib.request

    with urllib.request.urlopen(url + "/api/hosts", timeout=2) as resp:
        empty = json.loads(resp.read())
    assert empty["jsonClass"] == "Hosts"
    assert empty["hosts"] == [] and empty["straggler"] == -1

    client = WebClient(url)
    client.hosts(
        [{"host": 0, "tick_prep_ms": 12.0}, {"host": 1, "tick_prep_ms": 140.0}],
        straggler=1, stage="upload", skew_ms=128.0,
    )
    with urllib.request.urlopen(url + "/api/hosts", timeout=2) as resp:
        got = json.loads(resp.read())
    assert got["straggler"] == 1 and got["stage"] == "upload"
    assert got["skewMs"] == 128.0
    assert got["hosts"][1]["tick_prep_ms"] == 140.0


def test_metrics_roundtrip_carries_derived_histograms(server):
    """r8: the Metrics message's additive ``histograms`` field (derived
    p50/p95/p99) round-trips; old payloads without it still decode."""
    _, url, _ = server
    import urllib.request

    client = WebClient(url)
    client.metrics(
        {"pipeline.batches": 3}, {}, {"phase": "healthy"},
        histograms={"fetch.latency_s": {
            "count": 12, "mean": 0.07, "p50": 0.064, "p95": 0.128,
            "p99": 0.256,
        }},
    )
    with urllib.request.urlopen(url + "/api/metrics", timeout=2) as resp:
        got = json.loads(resp.read())
    assert got["histograms"]["fetch.latency_s"]["p95"] == 0.128
    # a legacy Metrics payload (no histograms key) still caches cleanly
    from twtml_tpu.telemetry.api_types import decode

    legacy = decode('{"jsonClass":"Metrics","counters":{},"gauges":{},'
                    '"health":{}}')
    assert legacy.histograms == {}


def test_serving_roundtrip_and_default(server):
    """Additive Serving messages (the serve-plane view): cached last-value
    like Metrics, served at /api/serving, unknown to legacy caches; the
    predict front door answers 503 when no plane is attached."""
    _, url, _ = server
    import urllib.error
    import urllib.request

    with urllib.request.urlopen(url + "/api/serving", timeout=2) as resp:
        empty = json.loads(resp.read())
    assert empty["jsonClass"] == "Serving"
    assert empty["snapshotStep"] == -1 and empty["tenants"] == []

    client = WebClient(url)
    client.serving({
        "qps": 512.5, "rowsPerSec": 8200.0, "p50Ms": 8.2, "p95Ms": 61.0,
        "p99Ms": 84.0, "snapshotStep": 640, "level": "warn",
        "requests": 10000, "rows": 160000, "errors": 2,
        "tenants": [{"tenant": 0, "rows": 90000},
                    {"tenant": 1, "rows": 70000}],
    })
    with urllib.request.urlopen(url + "/api/serving", timeout=2) as resp:
        got = json.loads(resp.read())
    assert got["qps"] == 512.5 and got["p99Ms"] == 84.0
    assert got["snapshotStep"] == 640 and got["level"] == "warn"
    assert got["tenants"][1]["rows"] == 70000

    # POST /api/predict without an attached plane: 503 with a JSON error
    req = urllib.request.Request(
        url + "/api/predict", data=b'{"rows": [{"text": "x"}]}',
        headers={"content-type": "application/json"}, method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req, timeout=2)
    assert exc_info.value.code == 503
    assert "serving" in json.loads(exc_info.value.read())["error"]


def test_history_roundtrip_and_default(server):
    """Additive History messages (the telemetry-historian view): cached
    last-value like Metrics, served at /api/history, unknown fields
    dropped at the client edge (additive-wire discipline)."""
    _, url, _ = server
    import urllib.request

    with urllib.request.urlopen(url + "/api/history", timeout=2) as resp:
        empty = json.loads(resp.read())
    assert empty["jsonClass"] == "History"
    assert empty["samples"] == 0 and empty["rss"] == []

    client = WebClient(url)
    client.history({
        "samples": 12, "runId": 3, "phase": "healthy", "rssMb": 300.5,
        "rssSlopeMbPerMin": 0.4, "rttMs": 71.0, "diskMb": 1.2,
        "regressions": 1, "rss": [299.0, 300.5], "rtt": [70.0, 71.0],
        "stageMs": [4.2, 4.4], "someFutureField": "dropped",
    })
    with urllib.request.urlopen(url + "/api/history", timeout=2) as resp:
        got = json.loads(resp.read())
    assert got["samples"] == 12 and got["rssMb"] == 300.5
    assert got["rss"] == [299.0, 300.5] and got["regressions"] == 1
    assert "someFutureField" not in got


def test_http_post_broadcasts_to_websockets(server):
    _, url, _ = server
    ws_url = url.replace("http://", "ws://") + "/api"

    async def scenario():
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.ws_connect(ws_url) as ws:
                await ws.receive(timeout=5)  # connect push
                WebClient(url).stats(11, 2, 3, 4, 5)
                frame = json.loads((await ws.receive(timeout=5)).data)
                assert frame["jsonClass"] == "Stats" and frame["count"] == 11

    asyncio.run(scenario())


def test_static_handler_rejects_traversal_and_absolute_paths(server):
    """GET //etc/passwd must never serve outside the assets root: pathlib
    joinpath with an absolute segment DISCARDS the base path entirely
    (and 'D:' does the same on Windows; control chars must 404, not 500)."""
    import urllib.error
    import urllib.request

    _, base, _ = server
    ok = urllib.request.urlopen(f"{base}/js/api.js", timeout=3)
    assert ok.status == 200
    for evil in (
        "//etc/passwd", "//root/.ssh/id_rsa", "/a//b", "/a/./b",
        "/D:/secrets.txt", "/js/%00x",
    ):
        try:
            resp = urllib.request.urlopen(base + evil, timeout=3)
            body = resp.read()
            assert b"root:" not in body, f"{evil} leaked a system file"
            raise AssertionError(f"{evil} unexpectedly served ({resp.status})")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404, f"{evil} -> {exc.code}, want 404"
