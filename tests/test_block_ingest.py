"""Native block ingest (native/tweetjson.cpp + features/blocks.py) parity.

The C data-loader must produce byte-identical batches to the Python
ground-truth path (json.loads → Status → filtrate → featurize): same kept
rows, same UTF-16 units (escapes, emoji, surrogates), same numerics and
timestamps. Every test compares against the object path end to end.
"""

import json
import os

import numpy as np
import pytest

from twtml_tpu.features import Featurizer, Status
from twtml_tpu.features.blocks import merge_blocks
from twtml_tpu.streaming.sources import BlockReplayFileSource

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")


def _object_path_batch(path, feat, **kw):
    with open(path, encoding="utf-8") as fh:
        statuses = [Status.from_json(json.loads(l)) for l in fh if l.strip()]
    return feat.featurize_batch_units(statuses, **kw)


def _block_path_batch(path, feat, block_bytes=1 << 20, **kw):
    src = BlockReplayFileSource(path, block_bytes=block_bytes)
    blocks = list(src.produce())
    assert blocks, "no blocks produced"
    return feat.featurize_parsed_block(merge_blocks(blocks), **kw)


def _assert_batches_equal(a, b):
    assert type(a) is type(b)
    np.testing.assert_array_equal(a.units, b.units)
    np.testing.assert_array_equal(a.length, b.length)
    np.testing.assert_allclose(a.numeric, b.numeric, rtol=1e-6)
    np.testing.assert_array_equal(a.label, b.label)
    np.testing.assert_array_equal(a.mask, b.mask)


@pytest.fixture()
def feat():
    return Featurizer(now_ms=1785320000000)


def test_fixture_file_parity(feat):
    obj = _object_path_batch(DATA, feat, row_bucket=16, unit_bucket=128)
    blk = _block_path_batch(DATA, feat, row_bucket=16, unit_bucket=128)
    _assert_batches_equal(obj, blk)


def test_fixture_file_parity_python_fallback(feat, monkeypatch):
    from twtml_tpu.features import native

    monkeypatch.setattr(native, "parse_tweet_block", lambda *a, **k: None)
    obj = _object_path_batch(DATA, feat, row_bucket=16, unit_bucket=128)
    blk = _block_path_batch(DATA, feat, row_bucket=16, unit_bucket=128)
    _assert_batches_equal(obj, blk)


def test_tiny_blocks_carry_across_chunk_boundaries(feat):
    """block_bytes far smaller than a line forces the consumed/carry logic."""
    obj = _object_path_batch(DATA, feat, row_bucket=16, unit_bucket=128)
    blk = _block_path_batch(
        DATA, feat, block_bytes=64, row_bucket=16, unit_bucket=128
    )
    _assert_batches_equal(obj, blk)


ADVERSARIAL = [
    # escapes incl. \uXXXX and an escaped surrogate pair (emoji)
    {"text": "RT", "retweeted_status": {
        "text": "line\\none \"q\" tab\\t \\u00e9 \\ud83d\\ude00 end",
        "retweet_count": 150,
        "user": {"followers_count": 1, "favourites_count": 2, "friends_count": 3},
        "timestamp_ms": "1785310000000"}},
    # raw UTF-8 emoji + CJK, extra nested structures to skip
    {"text": "RT", "extended_entities": {"media": [{"sizes": {"h": 1}}]},
     "retweeted_status": {
        "text": "火 🔥 test",
        "retweet_count": 999,
        "entities": {"urls": [{"indices": [0, 1]}], "hashtags": []},
        "user": {"followers_count": 7, "favourites_count": 0,
                 "friends_count": 9, "description": "nested \"quotes\" {\\n}"},
        "created_at": "Wed Aug 27 13:08:45 +0000 2008"}},
    # boundary values: counts exactly at the [100, 1000] edges
    {"text": "RT", "retweeted_status": {"text": "low edge", "retweet_count": 100,
        "user": {"followers_count": 0, "favourites_count": 0, "friends_count": 0},
        "timestamp_ms": "1785300000000"}},
    {"text": "RT", "retweeted_status": {"text": "high edge", "retweet_count": 1000,
        "user": {"followers_count": 0, "favourites_count": 0, "friends_count": 0},
        "timestamp_ms": "1785300000000"}},
    # filtered out: not a retweet / out of range / null retweeted_status
    {"text": "plain tweet", "retweet_count": 500},
    {"text": "RT", "retweeted_status": {"text": "too hot", "retweet_count": 99999,
        "user": {}}},
    {"text": "RT", "retweeted_status": None},
    # numbers as floats, negative, booleans and nulls in skipped fields
    {"text": "RT", "truncated": False, "coordinates": None,
     "retweeted_status": {"text": "float counts", "retweet_count": 250.0,
        "user": {"followers_count": 123.9, "favourites_count": -1,
                 "friends_count": 0}, "timestamp_ms": 1785311111111}},
    # empty text
    {"text": "RT", "retweeted_status": {"text": "", "retweet_count": 500,
        "user": {"followers_count": 5, "favourites_count": 5, "friends_count": 5},
        "timestamp_ms": "1785312222222"}},
]


def test_adversarial_json_parity(feat, tmp_path):
    path = tmp_path / "adversarial.jsonl"
    path.write_text(
        "\n".join(json.dumps(o) for o in ADVERSARIAL) + "\n", encoding="utf-8"
    )
    obj = _object_path_batch(str(path), feat, row_bucket=8, unit_bucket=64)
    blk = _block_path_batch(str(path), feat, row_bucket=8, unit_bucket=64)
    assert obj.num_valid == 6  # 4 escape/utf8/boundary + float counts + empty
    _assert_batches_equal(obj, blk)


def test_created_at_string_matches_python(feat, tmp_path):
    """The C fixed-format date parse must agree with Python's strptime."""
    path = tmp_path / "dates.jsonl"
    obj = {"text": "RT", "retweeted_status": {
        "text": "dated", "retweet_count": 300,
        "user": {"followers_count": 1, "favourites_count": 1, "friends_count": 1},
        "created_at": "Mon Feb 29 23:59:59 +0130 2016"}}
    path.write_text(json.dumps(obj) + "\n", encoding="utf-8")
    o = _object_path_batch(str(path), feat, row_bucket=8)
    b = _block_path_batch(str(path), feat, row_bucket=8)
    _assert_batches_equal(o, b)
    assert o.numeric[0, 3] != 0  # age feature actually derived from the date


def test_malformed_lines_skipped(feat, tmp_path):
    path = tmp_path / "bad.jsonl"
    good = {"text": "RT", "retweeted_status": {"text": "ok", "retweet_count": 500,
            "user": {"followers_count": 1, "favourites_count": 1,
                     "friends_count": 1}, "timestamp_ms": "1785313333333"}}
    path.write_text(
        json.dumps(good) + "\n" + "{not json}\n" + json.dumps(good) + "\n",
        encoding="utf-8",
    )
    blk = _block_path_batch(str(path), feat, row_bucket=8)
    assert blk.num_valid == 2


def test_linear_app_block_ingest_matches_object(tmp_path, capsys):
    """End to end through the CLI run(): --ingest block == --ingest object."""
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    outputs = {}
    for ingest in ("object", "block"):
        conf = ConfArguments().parse([
            "--source", "replay", "--replayFile", DATA, "--ingest", ingest,
            "--lightning", "http://127.0.0.1:9", "--twtweb", "http://127.0.0.1:9",
            "--backend", "cpu",
        ])
        app.run(conf, max_batches=1)
        outputs[ingest] = [
            l for l in capsys.readouterr().out.splitlines()
            if l.startswith("count:")
        ]
    assert outputs["block"] == outputs["object"]
    assert outputs["block"], "no stats lines captured"


def test_full_text_extended_tweets_parity(feat, tmp_path):
    """Extended-tweet archives store the body in full_text (no text key)."""
    path = tmp_path / "extended.jsonl"
    objs = [
        {"text": "RT", "retweeted_status": {
            "full_text": "the entire extended tweet body, uncut",
            "retweet_count": 400,
            "user": {"followers_count": 2, "favourites_count": 2,
                     "friends_count": 2}, "timestamp_ms": "1785314444444"}},
        # empty text falls through to full_text, like Status.from_json
        {"text": "RT", "retweeted_status": {
            "text": "", "full_text": "fallback body", "retweet_count": 500,
            "user": {"followers_count": 1, "favourites_count": 1,
                     "friends_count": 1}, "timestamp_ms": "1785315555555"}},
        # text wins over full_text when non-empty
        {"text": "RT", "retweeted_status": {
            "text": "short form", "full_text": "long form", "retweet_count": 600,
            "user": {"followers_count": 1, "favourites_count": 1,
                     "friends_count": 1}, "timestamp_ms": "1785316666666"}},
    ]
    path.write_text("\n".join(json.dumps(o) for o in objs) + "\n", "utf-8")
    obj = _object_path_batch(str(path), feat, row_bucket=8, unit_bucket=64)
    blk = _block_path_batch(str(path), feat, row_bucket=8, unit_bucket=64)
    assert obj.num_valid == 3
    _assert_batches_equal(obj, blk)


def test_missing_retweet_count_with_zero_begin(tmp_path):
    """Absent retweet_count coerces to 0 in BOTH paths (Status.from_json
    semantics), so numRetweetBegin=0 keeps the row in both modes."""
    feat0 = Featurizer(now_ms=1785320000000, num_retweet_begin=0)
    path = tmp_path / "nocount.jsonl"
    obj = {"text": "RT", "retweeted_status": {
        "text": "countless", "user": {"followers_count": 1,
        "favourites_count": 1, "friends_count": 1},
        "timestamp_ms": "1785317777777"}}
    path.write_text(json.dumps(obj) + "\n", "utf-8")
    o = _object_path_batch(str(path), feat0, row_bucket=8)
    src = BlockReplayFileSource(str(path), num_retweet_begin=0)
    blocks = list(src.produce())
    b = feat0.featurize_parsed_block(merge_blocks(blocks), row_bucket=8)
    assert o.num_valid == 1
    _assert_batches_equal(o, b)


def test_py_fallback_skips_non_object_json(feat, tmp_path, monkeypatch):
    """Valid JSON that isn't a tweet object must skip, not crash, in the
    Python fallback — matching the C parser's bad-line contract."""
    from twtml_tpu.features import native

    monkeypatch.setattr(native, "parse_tweet_block", lambda *a, **k: None)
    path = tmp_path / "nonobj.jsonl"
    good = {"text": "RT", "retweeted_status": {"text": "ok", "retweet_count": 500,
            "user": {"followers_count": 1, "favourites_count": 1,
                     "friends_count": 1}, "timestamp_ms": "1785318888888"}}
    path.write_text(
        "[1, 2]\n" + json.dumps(good) + "\n\"str\"\n5\n" + json.dumps(good) + "\n",
        encoding="utf-8",
    )
    blk = _block_path_batch(str(path), feat, row_bucket=8)
    assert blk.num_valid == 2


GOOD_LINE = {"text": "RT", "retweeted_status": {"text": "ok", "retweet_count": 500,
             "user": {"followers_count": 1, "favourites_count": 1,
                      "friends_count": 1}, "timestamp_ms": "1785313333333"}}


def _both_paths(path, feat, monkeypatch):
    """(C-path batch, Python-fallback batch) over the same file."""
    from twtml_tpu.features import native

    c = _block_path_batch(str(path), feat, row_bucket=8, unit_bucket=8192)
    with monkeypatch.context() as m:
        m.setattr(native, "parse_tweet_block", lambda *a, **k: None)
        py = _block_path_batch(str(path), feat, row_bucket=8, unit_bucket=8192)
    return c, py


def test_oversized_text_drops_line_both_paths(feat, tmp_path, monkeypatch):
    """ADVICE r1: a retweeted status whose text exceeds the wire-format
    bound (4096 UTF-16 units) is a counted bad line in the C parser AND the
    Python fallback — pinned, documented divergence from object ingest."""
    from twtml_tpu.features.native import MAX_TEXT_UNITS

    over = {"text": "RT", "retweeted_status": {
        "text": "a" * (MAX_TEXT_UNITS + 1), "retweet_count": 500,
        "user": {"followers_count": 1, "favourites_count": 1,
                 "friends_count": 1}}}
    # oversized full_text drops even when a small text would win
    over_full = {"text": "RT", "retweeted_status": {
        "text": "tiny", "full_text": "b" * (MAX_TEXT_UNITS + 100),
        "retweet_count": 500, "user": {"followers_count": 1,
        "favourites_count": 1, "friends_count": 1}}}
    at_bound = {"text": "RT", "retweeted_status": {
        "text": "c" * MAX_TEXT_UNITS, "retweet_count": 500,
        "user": {"followers_count": 1, "favourites_count": 1,
                 "friends_count": 1}, "timestamp_ms": "1785313333333"}}
    path = tmp_path / "oversized.jsonl"
    # duplicate "text" keys: the C scanner caps EVERY occurrence, so an
    # oversized first text drops the line even though dict-wise the small
    # last duplicate wins — the fallback pins the same any-occurrence rule
    dup_text = (
        '{"text": "RT", "retweeted_status": {"text": "'
        + "d" * 4097
        + '", "text": "small wins", "retweet_count": 500, '
        '"user": {"followers_count": 1}}}'
    )
    # duplicate retweeted_status keys: the C parser scans (and caps) the
    # FIRST occurrence too, while dict-wise only the clean last one survives
    dup_rt = (
        '{"text": "RT", "retweeted_status": {"text": "'
        + "e" * 4097
        + '", "retweet_count": 500}, "retweeted_status": {"text": "clean", '
        '"retweet_count": 500, "user": {"followers_count": 1}}}'
    )
    path.write_text(
        "\n".join([json.dumps(o) for o in
                   (GOOD_LINE, over, over_full, at_bound)]
                  + [dup_text, dup_rt, json.dumps(GOOD_LINE)]) + "\n",
        encoding="utf-8",
    )
    c, py = _both_paths(path, feat, monkeypatch)
    # kept: good, at-bound (exactly 4096 units), good — dropped: the two over
    assert c.num_valid == py.num_valid == 3
    _assert_batches_equal(c, py)
    assert int(max(c.length)) == 4096  # the at-bound row kept in full


def test_invalid_utf8_drops_line_both_paths(feat, tmp_path, monkeypatch):
    """ADVICE r1: overlong UTF-8 encodings are malformed in Python's utf-8
    codec (which json.loads(bytes) rides), so the C parser must reject them
    too — but UTF-8-encoded SURROGATES are KEPT by json.loads (it decodes
    bytes with errors='surrogatepass'), so both block paths keep those rows
    as lone UTF-16 units, matching the JVM view (features/hashing.py)."""
    good = json.dumps(GOOD_LINE).encode("utf-8")
    # overlong '/' (0xC0 0xAF) inside the rt text
    overlong = (b'{"text": "RT", "retweeted_status": {"text": "x\xc0\xafy", '
                b'"retweet_count": 500, "user": {"followers_count": 1}}}')
    # overlong NUL (0xC0 0x80) — the classic modified-UTF-8 case
    overlong_nul = (b'{"text": "RT", "retweeted_status": {"text": "x\xc0\x80y", '
                    b'"retweet_count": 500, "user": {"followers_count": 1}}}')
    # out-of-range code point U+110000 (0xF4 0x90 0x80 0x80)
    too_big = (b'{"text": "RT", "retweeted_status": {"text": "x\xf4\x90\x80\x80y", '
               b'"retweet_count": 500, "user": {"followers_count": 1}}}')
    # raw UTF-8-encoded surrogate U+D800 (0xED 0xA0 0x80): KEPT, like json
    surrogate = (b'{"text": "RT", "retweeted_status": {"text": "x\xed\xa0\x80y", '
                 b'"retweet_count": 500, "user": {"followers_count": 1}}}')
    # escaped lone surrogate: valid JSON, kept, exercises the
    # surrogatepass encode in the fallback's encode_texts
    escaped = (b'{"text": "RT", "retweeted_status": {"text": "x\\ud800y", '
               b'"retweet_count": 500, "user": {"followers_count": 1}}}')
    path = tmp_path / "badutf8.jsonl"
    path.write_bytes(
        good + b"\n" + overlong + b"\n" + surrogate + b"\n" + escaped + b"\n"
        + overlong_nul + b"\n" + too_big + b"\n" + good + b"\n"
    )
    c, py = _both_paths(path, feat, monkeypatch)
    # kept: good, raw-surrogate, escaped-surrogate, good
    assert c.num_valid == py.num_valid == 4
    _assert_batches_equal(c, py)
    # both surrogate rows carry the lone 0xD800 unit, not a replacement char
    assert (np.asarray(c.units) == 0xD800).sum() == 2


def test_iter_row_chunks_preserves_rows(feat):
    """The micro-batch slicer (blocks.py iter_row_chunks) must regroup
    arbitrary block boundaries into exact row chunks with identical data."""
    from twtml_tpu.features.blocks import iter_row_chunks, slice_block

    src = BlockReplayFileSource(DATA, block_bytes=256)  # many tiny blocks
    blocks = list(src.produce())
    whole = merge_blocks(blocks)
    for rows in (1, 2, 3, whole.rows, whole.rows + 5):
        chunks = list(iter_row_chunks(iter(blocks), rows))
        assert [c.rows for c in chunks[:-1]] == [rows] * (len(chunks) - 1)
        assert sum(c.rows for c in chunks) == whole.rows
        re = merge_blocks(chunks)
        np.testing.assert_array_equal(re.numeric, whole.numeric)
        np.testing.assert_array_equal(re.units, whole.units)
        np.testing.assert_array_equal(re.offsets, whole.offsets)
        np.testing.assert_array_equal(re.ascii, whole.ascii)
    # slice_block round-trip
    mid = slice_block(whole, 2, 5)
    assert mid.rows == 3
    np.testing.assert_array_equal(mid.numeric, whole.numeric[2:5])
    np.testing.assert_array_equal(
        mid.units, whole.units[whole.offsets[2] : whole.offsets[5]]
    )


def test_merge_blocks_empty_returns_zero_row_block():
    """ADVICE r1: merge_blocks([]) must not crash (a replay file where no
    line passes the filter)."""
    from twtml_tpu.features.blocks import ParsedBlock

    block = merge_blocks([])
    assert isinstance(block, ParsedBlock)
    assert block.rows == 0
    assert block.offsets.tolist() == [0]


def test_block_ingest_rejected_outside_linear_app(tmp_path):
    from twtml_tpu.apps.linear_regression import build_source
    from twtml_tpu.config import ConfArguments

    conf = ConfArguments().parse(
        ["--source", "replay", "--replayFile", DATA, "--ingest", "block"]
    )
    with pytest.raises(SystemExit):
        build_source(conf)  # kmeans/logistic call without allow_block
    assert build_source(conf, allow_block=True) is not None


def test_block_ingest_rejects_host_hashing():
    from twtml_tpu.apps.linear_regression import build_source
    from twtml_tpu.config import ConfArguments

    conf = ConfArguments().parse([
        "--source", "replay", "--replayFile", DATA,
        "--ingest", "block", "--hashOn", "host",
    ])
    with pytest.raises(SystemExit):
        build_source(conf, allow_block=True)


def test_non_numeric_timestamp_keeps_row(feat, tmp_path):
    """A quoted non-numeric timestamp_ms must not desync the parser: the
    row survives with created_ms falling back (parity with Status's
    tolerant _parse_created_at_ms)."""
    path = tmp_path / "badnum.jsonl"
    obj = {"text": "RT", "retweeted_status": {
        "text": "odd timestamp", "retweet_count": 500,
        "user": {"followers_count": 1, "favourites_count": 1,
                 "friends_count": 1}, "timestamp_ms": "not a number"}}
    path.write_text(json.dumps(obj) + "\n", encoding="utf-8")
    o = _object_path_batch(str(path), feat, row_bucket=8)
    b = _block_path_batch(str(path), feat, row_bucket=8)
    assert o.num_valid == b.num_valid == 1
    _assert_batches_equal(o, b)


def test_deeply_nested_json_is_a_bad_line_not_a_crash(feat, tmp_path):
    """~100k nested brackets are well-formed JSON but must not smash the C
    stack — counted bad, stream continues."""
    path = tmp_path / "deep.jsonl"
    good = {"text": "RT", "retweeted_status": {"text": "ok", "retweet_count": 500,
            "user": {"followers_count": 1, "favourites_count": 1,
                     "friends_count": 1}, "timestamp_ms": "1785313333333"}}
    deep = '{"x": ' + "[" * 100000 + "]" * 100000 + "}"
    path.write_text(
        json.dumps(good) + "\n" + deep + "\n" + json.dumps(good) + "\n",
        encoding="utf-8",
    )
    blk = _block_path_batch(str(path), feat, row_bucket=8)
    assert blk.num_valid == 2


@pytest.mark.parametrize("ensure_ascii", [True, False])
def test_fuzzed_unicode_parity(feat, tmp_path, ensure_ascii):
    """Seeded fuzz: random unicode texts (BMP, astral, quotes, escapes,
    controls) serialized with and without \\uXXXX escaping must parse
    identically to the Python path."""
    import random

    rng = random.Random(20260730 + int(ensure_ascii))
    alphabet = (
        [chr(c) for c in range(0x20, 0x7F)]  # printable ASCII incl. " and \\
        + ["\n", "\t", "\r", "\b", "\f"]
        + [chr(rng.randrange(0xA0, 0x2FFF)) for _ in range(40)]  # BMP
        + ["é", "你", "İ", "ẞ"]  # é, 你, İ, ẞ
        + [chr(rng.randrange(0x10000, 0x10400)) for _ in range(10)]  # astral
        + ["\U0001f600", "\U0001f525"]
    )
    def shuffled(d: dict) -> dict:
        items = list(d.items())
        rng.shuffle(items)
        return {
            k: shuffled(v) if isinstance(v, dict) else v for k, v in items
        }

    objs = []
    for i in range(200):
        text = "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 60)))
        objs.append(shuffled({
            "text": "RT wrap",
            "junk": {"nested": [i, None, True, {"deep": [text]}]},
            f"unknown_{rng.randrange(10)}": rng.choice([None, True, 1.5, "s"]),
            "retweeted_status": {
                "text": text,
                "retweet_count": rng.randrange(0, 2000),
                "extra": {"a": [rng.randrange(9)]},
                "user": {
                    "followers_count": rng.randrange(0, 10**9),
                    "favourites_count": rng.randrange(0, 10**6),
                    "friends_count": rng.randrange(0, 10**5),
                    "screen_name": "user_" + str(i),
                },
                "timestamp_ms": str(rng.randrange(10**12, 2 * 10**12)),
            },
        }))
    path = tmp_path / f"fuzz_{ensure_ascii}.jsonl"
    path.write_text(
        "\n".join(json.dumps(o, ensure_ascii=ensure_ascii) for o in objs) + "\n",
        encoding="utf-8",
    )
    obj_b = _object_path_batch(str(path), feat, row_bucket=256, unit_bucket=128)
    blk_b = _block_path_batch(str(path), feat, row_bucket=256, unit_bucket=128)
    assert obj_b.num_valid > 20  # the filter keeps a healthy sample
    _assert_batches_equal(obj_b, blk_b)


def test_logistic_app_block_ingest_matches_object(capsys):
    """The logistic app's block path (unit_label_fn sentiment) must produce
    the same per-batch stats as its object path."""
    from twtml_tpu.apps import logistic_regression as app
    from twtml_tpu.config import ConfArguments

    outputs = {}
    for ingest in ("object", "block"):
        conf = ConfArguments().parse([
            "--source", "replay", "--replayFile", DATA, "--ingest", ingest,
            "--lightning", "http://127.0.0.1:9", "--twtweb", "http://127.0.0.1:9",
            "--backend", "cpu",
        ])
        app.run(conf, max_batches=1)
        outputs[ingest] = [
            l for l in capsys.readouterr().out.splitlines()
            if l.startswith("count:")
        ]
    assert outputs["block"] == outputs["object"]
    assert outputs["block"], "no stats lines captured"


def test_unit_label_fn_parity_on_blocks(feat):
    """sentiment_labels_from_units over a parsed block == per-status
    sentiment labels over the same tweets."""
    import numpy as np

    from twtml_tpu.features.sentiment import (
        sentiment_label,
        sentiment_labels_from_units,
    )

    src = BlockReplayFileSource(DATA)
    block = merge_blocks(list(src.produce()))
    with open(DATA, encoding="utf-8") as fh:
        statuses = [Status.from_json(json.loads(l)) for l in fh if l.strip()]
    kept = [s for s in statuses if feat.filtrate(s)]
    want = np.array([sentiment_label(s) for s in kept], np.float32)
    got = sentiment_labels_from_units(block.units, block.offsets)
    np.testing.assert_array_equal(got, want)


def test_unit_labels_use_original_units_under_accent_normalization(tmp_path):
    """normalize_accents must never leak into labels: stripping 'bàd'→'bad'
    would change a lexicon hit. Labels come from the ORIGINAL units."""
    import numpy as np

    from twtml_tpu.features.sentiment import (
        sentiment_label,
        sentiment_labels_from_units,
    )

    path = tmp_path / "accented.jsonl"
    obj = {"text": "RT", "retweeted_status": {
        "text": "this is bàd news", "retweet_count": 500,
        "user": {"followers_count": 1, "favourites_count": 1,
                 "friends_count": 1}, "timestamp_ms": "1785313333333"}}
    path.write_text(json.dumps(obj) + "\n", encoding="utf-8")
    feat = Featurizer(
        now_ms=1785320000000,
        normalize_accents=True,
        unit_label_fn=sentiment_labels_from_units,
    )
    src = BlockReplayFileSource(str(path))
    batch = feat.featurize_parsed_block(merge_blocks(list(src.produce())))
    with open(path, encoding="utf-8") as fh:
        status = Status.from_json(json.loads(fh.readline()))
    assert batch.label[0] == sentiment_label(status) == 1.0  # 'bàd' ≠ 'bad'


def test_kmeans_app_block_ingest_matches_object(capsys):
    """k-means block path (numeric-column featurization, NO interval
    filter) must print the same per-batch centers as the object path."""
    from twtml_tpu.apps import kmeans as app
    from twtml_tpu.config import ConfArguments

    outputs = {}
    for ingest in ("object", "block"):
        conf = ConfArguments().parse([
            "--source", "replay", "--replayFile", DATA, "--ingest", ingest,
            "--lightning", "http://127.0.0.1:9", "--twtweb", "http://127.0.0.1:9",
            "--backend", "cpu",
        ])
        app.run(conf, max_batches=1, wall_clock=False)
        outputs[ingest] = [
            l for l in capsys.readouterr().out.splitlines()
            if l.startswith("count:")
        ]
    assert outputs["block"] == outputs["object"]
    assert outputs["block"], "no stats lines captured"


def test_warmup_compile_is_a_semantic_noop(capsys):
    """Pinning both buckets pre-compiles the step on an all-padding batch:
    weights stay at zeros and the subsequent real run is unchanged."""
    import numpy as np

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD

    from twtml_tpu.streaming.context import FeatureStream

    conf = ConfArguments().parse(["--batchBucket", "8", "--tokenBucket", "64"])
    feat = Featurizer(now_ms=1785320000000)
    model = StreamingLinearRegressionWithSGD(num_iterations=5)
    stream = FeatureStream(
        feat, row_bucket=conf.batchBucket, token_bucket=conf.tokenBucket,
        device_hash=True,
    )
    app.warmup_compile(stream, model)
    assert np.abs(model.latest_weights).sum() == 0.0  # no-op for the learner

    conf2 = ConfArguments().parse([
        "--source", "replay", "--replayFile", DATA,
        "--batchBucket", "8", "--tokenBucket", "64",
        "--lightning", "http://127.0.0.1:9", "--twtweb", "http://127.0.0.1:9",
        "--backend", "cpu",
    ])
    app.run(conf2, max_batches=1)
    lines = [
        l for l in capsys.readouterr().out.splitlines() if l.startswith("count:")
    ]
    assert lines == ["count: 6  batch: 6  mse: 481105.0  stdev (real, pred): (346, 0)"]


def test_empty_warmup_batch_matches_block_batch_shape(feat):
    """The shape contract warmup relies on in block mode: with the same
    pinned buckets, featurize_batch_units([]) (what featurize_empty emits)
    and featurize_parsed_block (what the stream emits) compile the SAME
    jit program — identical pytree structure, shapes, and dtypes. The units
    wire dtype is per-batch (uint8 for byte-ranged batches, uint16
    otherwise); the warmup's uint8 batch plus its uint16-widened twin (what
    apps/common.warmup_compile steps) must cover every real batch."""
    import jax

    src = BlockReplayFileSource(DATA)
    real = feat.featurize_parsed_block(
        merge_blocks(list(src.produce())), row_bucket=16, unit_bucket=128
    )
    warm = feat.featurize_batch_units([], row_bucket=16, unit_bucket=128)
    assert jax.tree_util.tree_structure(warm) == jax.tree_util.tree_structure(real)
    assert warm.units.dtype == np.uint8  # the canonical warm batch
    assert real.units.dtype in (np.uint8, np.uint16)
    for w, r in zip(warm, real):
        assert w.shape == r.shape
        if w is not warm.units:
            assert w.dtype == r.dtype


def test_fault_injection_counts_tweets_in_blocks():
    """--faultEvery counts TWEETS for block sources too (a block is ~2000
    rows; counting items would make faults thousands of times rarer), and a
    threshold crossed INSIDE a stream's only block still fires — the
    crossing block is lost in flight, like a dropped socket."""
    from twtml_tpu.streaming.faults import FaultInjectingSource, InjectedFault

    def drain(block_bytes):
        src = FaultInjectingSource(
            BlockReplayFileSource(DATA, block_bytes=block_bytes),
            crash_every=3,  # fixture has 6 kept retweets
            max_crashes=1,
        )
        rows, crashed = 0, False
        it = src.produce()
        while True:
            try:
                rows += next(it).rows
            except InjectedFault:
                crashed = True
                break
            except StopIteration:
                break
        return rows, crashed

    # single block holding all 6 tweets: the threshold is inside it
    rows, crashed = drain(1 << 20)
    assert crashed and rows == 0
    # several small blocks: crash still keyed to the tweet count
    rows, crashed = drain(256)
    assert crashed and rows < 6


def test_byte_range_sharding_partitions_rows_exactly(feat):
    """r5 (VERDICT r4 #4): shard_index/shard_count split the file by byte
    range, line-aligned — every kept row lands in exactly one shard and the
    shards' concatenation equals the unsharded parse (each host reads only
    ~1/N of the bytes)."""
    whole = merge_blocks(list(BlockReplayFileSource(DATA).produce()))
    for n in (2, 3, 4):
        shard_blocks = [
            list(BlockReplayFileSource(
                DATA, shard_index=i, shard_count=n, block_bytes=512
            ).produce())
            for i in range(n)
        ]
        merged = merge_blocks([b for blocks in shard_blocks for b in blocks])
        np.testing.assert_array_equal(merged.numeric, whole.numeric)
        np.testing.assert_array_equal(merged.units, whole.units)
        np.testing.assert_array_equal(merged.offsets, whole.offsets)
        np.testing.assert_array_equal(merged.ascii, whole.ascii)


def test_drain_splits_overshooting_blocks():
    """A ParsedBlock bigger than the drain cap splits AT the cap with the
    remainder put back (r5) — capped drains are exactly bucket-sized, which
    multi-host lockstep requires and which pins single-host block batch
    shapes too."""
    from twtml_tpu.streaming.context import StreamingContext
    from twtml_tpu.streaming.sources import QueueSource

    src = BlockReplayFileSource(DATA)
    big = merge_blocks(list(src.produce()))
    assert big.rows >= 4

    ssc = StreamingContext(batch_interval=0)
    ssc.raw_stream(QueueSource(), row_bucket=2)
    ssc._queue.put(big)
    drained = ssc._drain(2)
    assert sum(b.rows for b in drained) == 2
    # remainder is back at the queue FRONT, in order
    rest = ssc._drain(0)
    merged = merge_blocks(drained + [b for b in rest])
    np.testing.assert_array_equal(merged.numeric, big.numeric)
    np.testing.assert_array_equal(merged.units, big.units)
